"""Out-of-core batch runtime (VERDICT r1 missing #7): external merge sort
+ grace hash join — the ``ExternalSorter`` / ``MutableHashTable`` analogs
(``flink-runtime/.../operators/sort/``, ``operators/hash/``).

Tests force a TINY memory budget so the spill paths run on small data,
then assert results identical to the in-memory kernels.
"""

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.dataset.external import ExternalSorter, GraceHashJoin


def test_external_sort_many_runs_matches_inmemory():
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 10_000, 50_000).astype(np.int64)
    vals = rng.random(50_000)
    s = ExternalSorter(["k"], budget_rows=3_000)   # ~17 spilled runs
    for lo in range(0, 50_000, 1_000):
        s.add(RecordBatch({"k": keys[lo:lo + 1_000],
                           "v": vals[lo:lo + 1_000]}))
    out = s.sorted_batch()
    got = np.asarray(out.column("k"))
    assert len(out) == 50_000
    np.testing.assert_array_equal(got, np.sort(keys))
    # payload stays aligned with its key: the (k, v) PAIR multiset is
    # preserved, not just each column's value multiset
    got_pairs = sorted(zip(got.tolist(),
                           np.asarray(out.column("v")).tolist()))
    want_pairs = sorted(zip(keys.tolist(), vals.tolist()))
    assert got_pairs == want_pairs


def test_external_sort_descending_and_streamed_batches():
    keys = np.arange(9_000, dtype=np.int64)
    s = ExternalSorter(["k"], ascending=False, budget_rows=2_000,
                       emit_batch_rows=1_000)
    s.add(RecordBatch({"k": keys}))
    chunks = list(s.merged())
    assert all(len(c) <= 1_000 for c in chunks)
    got = np.concatenate([np.asarray(c.column("k")) for c in chunks])
    np.testing.assert_array_equal(got, keys[::-1])


def test_external_sort_in_memory_tail_only():
    s = ExternalSorter(["k"], budget_rows=1_000_000)
    s.add(RecordBatch({"k": np.array([3, 1, 2], np.int64)}))
    out = s.sorted_batch()
    assert np.asarray(out.column("k")).tolist() == [1, 2, 3]


def test_grace_hash_join_matches_inmemory():
    from flink_tpu.operators.joins import _join_pairs

    rng = np.random.default_rng(9)
    lk = rng.integers(0, 500, 20_000).astype(np.int64)
    rk = rng.integers(0, 500, 5_000).astype(np.int64)
    gj = GraceHashJoin("k", "k", budget_rows=4_000)  # forces bucketing
    gj.add(0, RecordBatch({"k": lk, "lv": np.arange(20_000)}))
    gj.add(1, RecordBatch({"k": rk, "rv": np.arange(5_000)}))
    pairs = []
    for lb, li, rb, ri in gj.join_pairs():
        lks = np.asarray(lb.column("k"))[li]
        lvs = np.asarray(lb.column("lv"))[li]
        rvs = np.asarray(rb.column("rv"))[ri]
        assert (lks == np.asarray(rb.column("k"))[ri]).all()
        pairs.extend(zip(lvs.tolist(), rvs.tolist()))
    li0, ri0 = _join_pairs(lk, rk)
    want = sorted(zip(li0.tolist(), ri0.tolist()))
    assert sorted(pairs) == want


def test_dataset_sort_and_join_use_spill_paths(monkeypatch):
    """The dataset drivers switch to the out-of-core paths above the
    budget; results stay identical to the in-memory kernels."""
    from flink_tpu.dataset.api import ExecutionEnvironment

    rng = np.random.default_rng(3)
    n = 30_000
    keys = rng.integers(0, 2_000, n).astype(np.int64)

    def run():
        env = ExecutionEnvironment()
        ds = env.from_columns({"k": keys, "v": np.arange(n)})
        sorted_rows = ds.sort_partition("k").collect()
        other = env.from_columns({"k": np.arange(0, 2_000, 2),
                                  "w": np.arange(1_000)})
        joined = (env.from_columns({"k": keys, "v": np.arange(n)})
                  .join(other).where("k").equal_to("k").apply().collect())
        return sorted_rows, joined

    in_mem_sorted, in_mem_joined = run()
    monkeypatch.setenv("FLINK_TPU_BATCH_MEMORY_ROWS", "4000")
    sp_sorted, sp_joined = run()
    assert [r["k"] for r in sp_sorted] == [r["k"] for r in in_mem_sorted]
    key_of = lambda r: tuple(sorted(r.items()))  # noqa: E731
    assert sorted(map(key_of, sp_joined)) == sorted(map(key_of,
                                                        in_mem_joined))


def test_grace_hash_join_aliasing_and_skew():
    """Regression: reuse after join_pairs() must not alias sides; a hot key
    (unsplittable skew) still joins correctly via recursive repartition's
    depth cap."""
    from flink_tpu.operators.joins import _join_pairs

    lk = np.zeros(9_000, np.int64)              # ONE hot key
    rk = np.zeros(50, np.int64)
    gj = GraceHashJoin("k", "k", budget_rows=1_000)
    gj.add(0, RecordBatch({"k": lk, "lv": np.arange(9_000)}))
    gj.add(1, RecordBatch({"k": rk, "rv": np.arange(50)}))
    n_pairs = sum(len(li) for _l, li, _r, _ri in gj.join_pairs())
    assert n_pairs == 9_000 * 50
    # reuse: sides must be independent lists
    gj.add(0, RecordBatch({"k": np.array([1], np.int64),
                           "lv": np.array([0])}))
    assert len(gj._right) == 0


def test_external_sort_string_keys_fall_back_to_rowheap():
    s = ExternalSorter(["k"], budget_rows=100)
    words = np.asarray([f"w{i:03d}" for i in range(500)][::-1], object)
    for lo in range(0, 500, 50):
        s.add(RecordBatch({"k": words[lo:lo + 50]}))
    out = s.sorted_batch()
    got = [str(x) for x in np.asarray(out.column("k"))]
    assert got == sorted(str(w) for w in words)


def test_external_sort_descending_uint64_and_int64_min():
    """Regression: the descending gallop merge must not negate keys
    (uint64 overflow; INT64_MIN wraparound)."""
    vals = np.array([5, 2, 9, 2**63 + 7, 0, 13], np.uint64)
    s = ExternalSorter(["k"], ascending=False, budget_rows=2)
    for v in vals:
        s.add(RecordBatch({"k": np.array([v], np.uint64)}))
    out = np.asarray(s.sorted_batch().column("k"))
    np.testing.assert_array_equal(out, np.sort(vals)[::-1])

    imin = np.iinfo(np.int64).min
    vals2 = np.array([3, imin, 7, -5], np.int64)
    s2 = ExternalSorter(["k"], ascending=False, budget_rows=2)
    for v in vals2:
        s2.add(RecordBatch({"k": np.array([v], np.int64)}))
    out2 = np.asarray(s2.sorted_batch().column("k"))
    np.testing.assert_array_equal(out2, np.sort(vals2)[::-1])


def test_grace_join_fast_path_resets_and_cleans(tmp_path):
    import glob
    import tempfile

    gj = GraceHashJoin("k", "k", budget_rows=1_000_000)
    gj.add(0, RecordBatch({"k": np.array([1], np.int64)}))
    gj.add(1, RecordBatch({"k": np.array([1], np.int64)}))
    assert sum(len(li) for _l, li, _r, _ri in gj.join_pairs()) == 1
    # fast path resets sides (reuse must not re-join stale inputs)
    assert gj._left == [] and gj._right == [] and gj._rows == [0, 0]


# ---------------------------------------------------------------------------
# streamed-plan dam breakers (VERDICT r3 next #6)
# ---------------------------------------------------------------------------

def test_incremental_spill_during_add(tmp_path):
    """add() past the budget flushes to bucket files immediately — building
    the join never holds more than ~budget rows in memory."""
    gj = GraceHashJoin("k", "k", budget_rows=1_000,
                       spill_dir=str(tmp_path / "gj"))
    rng = np.random.default_rng(2)
    for lo in range(0, 10_000, 500):
        gj.add(0, RecordBatch({"k": rng.integers(0, 200, 500).astype(np.int64),
                               "v": np.arange(500)}))
    assert gj._spilled
    assert not gj._left and not gj._right       # buffer flushed
    import os
    assert any(f.endswith(".ftb") for f in os.listdir(tmp_path / "gj"))
    gj.add(1, RecordBatch({"k": np.arange(200, dtype=np.int64),
                           "w": np.arange(200)}))
    n = sum(li.size for _l, li, _r, _ri in gj.join_pairs())
    assert n == 10_000                          # every left row matches once


def _streamed_rows(ds):
    rows = []
    for b in ds.stream_batches():
        rows.extend(b.to_rows())
    return rows


def test_streamed_join_matches_materialized():
    from flink_tpu.dataset.api import ExecutionEnvironment

    rng = np.random.default_rng(11)
    env = ExecutionEnvironment()
    l = env.from_columns({"k": rng.integers(0, 50, 3_000).astype(np.int64),
                          "v": np.arange(3_000)})
    r = env.from_columns({"k": rng.integers(0, 50, 800).astype(np.int64),
                          "w": np.arange(800)})
    ds = l.join(r).where("k").equal_to("k").apply()
    mat = ds.collect()
    got = _streamed_rows(ds)

    def key(rows):
        return sorted((int(x["k"]), int(x["v"]), int(x["w"])) for x in rows)

    assert key(got) == key(mat)
    assert len(got) > 3_000                     # duplicates fanned out


def test_streamed_group_reduce_matches_materialized():
    from flink_tpu.dataset.api import ExecutionEnvironment

    rng = np.random.default_rng(12)
    env = ExecutionEnvironment()
    ds0 = env.from_columns({"k": rng.integers(0, 40, 5_000).astype(np.int64),
                            "v": rng.integers(0, 100, 5_000)})

    def fn(key, rows):
        return {"k": int(key), "n": len(rows),
                "s": sum(int(r["v"]) for r in rows)}

    ds = ds0.group_by("k").reduce_group(fn)
    mat = sorted((r["k"], r["n"], r["s"]) for r in ds.collect())
    got = sorted((r["k"], r["n"], r["s"]) for r in _streamed_rows(ds))
    assert got == mat


def test_streamed_join_empty_keeps_schema():
    from flink_tpu.dataset.api import ExecutionEnvironment

    env = ExecutionEnvironment()
    l = env.from_columns({"k": np.arange(5, dtype=np.int64),
                          "v": np.arange(5)})
    r = env.from_columns({"k": np.arange(10, 15, dtype=np.int64),
                          "w": np.arange(5)})
    ds = l.join(r).where("k").equal_to("k").apply()
    batches = list(ds.stream_batches())
    assert sum(len(b) for b in batches) == 0
    # streamed and materialized agree on the empty-result structure
    assert set(batches[-1].columns) == set(ds.collect_batch().columns)


@pytest.mark.slow
def test_stream_plan_join_rss_bounded_beyond_budget(tmp_path):
    """The VERDICT done-criterion: a join LARGER than the row budget runs
    under the streamed plan with bounded peak RSS (VmHWM, hermetic child:
    the inputs would be ~10M rows x 2 columns each if materialized)."""
    import os
    import subprocess
    import sys
    import textwrap

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {root!r})
        import numpy as np
        from flink_tpu.dataset.api import ExecutionEnvironment

        n = 10_000_000
        env = ExecutionEnvironment()
        l = (env.generate_sequence(1, n)
             .map(lambda c: {{"k": np.asarray(c["value"]) % 1_000_000,
                              "v": np.asarray(c["value"])}}))
        r = (env.generate_sequence(1, n)
             .map(lambda c: {{"k": np.asarray(c["value"]) % 1_000_000,
                              "w": np.asarray(c["value"])}}))
        j = l.join(r).where("k").equal_to("k").apply()
        total = 0
        for b in j.stream_batches():
            total += len(b)
        assert total == 100_000_000, total   # 10 x 10 per key
        g = (env.generate_sequence(1, n)
             .map(lambda c: {{"k": np.asarray(c["value"]) % 100_000,
                              "v": np.asarray(c["value"])}})
             .group_by("k")
             .reduce_group(lambda k, rows: {{"k": int(k), "n": len(rows)}}))
        cnt = 0
        for b in g.stream_batches():
            cnt += len(b)
        assert cnt == 100_000, cnt
        with open("/proc/self/status") as f:
            hwm_kb = next(int(line.split()[1]) for line in f
                          if line.startswith("VmHWM:"))
        print("PEAK_MB", hwm_kb / 1024)
    """)
    child_env = dict(os.environ, JAX_PLATFORMS="cpu",
                     FLINK_TPU_BATCH_MEMORY_ROWS=str(1 << 20))
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=900,
                         env=child_env)
    assert "PEAK_MB" in out.stdout, out.stderr[-3000:]
    peak_mb = float(out.stdout.split("PEAK_MB")[1].strip())
    # materialized join inputs alone would be ~320MB + the 100M-row output
    # (~1.6GB); bounded execution stays near baseline + budget chunks
    assert peak_mb < 800, peak_mb


def test_multicolumn_key_join_canonical_across_chunks():
    """Regression: composite keys must encode canonically — per-chunk
    min/max radix packing matched (0,0) with (10,0) across chunks and
    across sides with different value ranges."""
    from flink_tpu.dataset.api import ExecutionEnvironment

    env = ExecutionEnvironment()
    # left a in {0,1,10,11}; right a in {0,10} (different side ranges)
    l = env.from_columns({"a": np.array([0, 1, 10, 11] * 3, np.int64),
                          "b": np.array([0, 0, 0, 0, 1, 1, 1, 1,
                                         2, 2, 2, 2], np.int64),
                          "v": np.arange(12)})
    r = env.from_columns({"a": np.array([0, 10, 0], np.int64),
                          "b": np.array([0, 0, 1], np.int64),
                          "w": np.arange(3)})
    ds = l.join(r).where("a", "b").equal_to("a", "b").apply()
    expected = sorted([(0, 0, 0), (10, 0, 2), (0, 1, 4)])

    def got(rows):
        return sorted((int(x["a"]), int(x["b"]), int(x["v"])) for x in rows)

    assert got(ds.collect()) == expected
    # streamed with a 4-row chunk budget: chunks see disjoint ranges
    import os
    old = os.environ.get("FLINK_TPU_BATCH_MEMORY_ROWS")
    os.environ["FLINK_TPU_BATCH_MEMORY_ROWS"] = "4"
    try:
        assert got(_streamed_rows(ds)) == expected
    finally:
        if old is None:
            del os.environ["FLINK_TPU_BATCH_MEMORY_ROWS"]
        else:
            os.environ["FLINK_TPU_BATCH_MEMORY_ROWS"] = old


def test_multicolumn_distinct_across_chunks():
    from flink_tpu.dataset.api import ExecutionEnvironment
    import os

    env = ExecutionEnvironment()
    ds = env.from_columns({
        "a": np.array([0, 1, 10, 11, 0, 10], np.int64),
        "b": np.array([0, 0, 0, 0, 0, 0], np.int64)}).distinct("a", "b")
    old = os.environ.get("FLINK_TPU_BATCH_MEMORY_ROWS")
    os.environ["FLINK_TPU_BATCH_MEMORY_ROWS"] = "2"
    try:
        rows = _streamed_rows(ds)
    finally:
        if old is None:
            del os.environ["FLINK_TPU_BATCH_MEMORY_ROWS"]
        else:
            os.environ["FLINK_TPU_BATCH_MEMORY_ROWS"] = old
    assert sorted((int(r["a"]), int(r["b"])) for r in rows) == [
        (0, 0), (1, 0), (10, 0), (11, 0)]
