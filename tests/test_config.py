import pytest

from flink_tpu.config.config_option import (Configuration, key,
                                            parse_duration_ms,
                                            parse_memory_bytes)
from flink_tpu.config.options import (CheckpointingOptions, CoreOptions,
                                      ExecutionOptions, StateOptions)


def test_typed_option_defaults():
    conf = Configuration()
    assert conf.get(CoreOptions.MAX_PARALLELISM) == 128
    assert conf.get(ExecutionOptions.MICRO_BATCH_SIZE) == 65536
    assert conf.get(CheckpointingOptions.MODE) == "EXACTLY_ONCE"


def test_set_get_parsing():
    conf = Configuration()
    conf.set(CoreOptions.MAX_PARALLELISM, "256")
    assert conf.get(CoreOptions.MAX_PARALLELISM) == 256
    conf.set(StateOptions.INCREMENTAL, "true")
    assert conf.get(StateOptions.INCREMENTAL) is True
    conf.set(CheckpointingOptions.INTERVAL, "5 s")
    assert conf.get(CheckpointingOptions.INTERVAL) == 5000


def test_duration_and_memory_parsers():
    assert parse_duration_ms("500 ms") == 500
    assert parse_duration_ms("2 min") == 120_000
    assert parse_duration_ms(250) == 250
    assert parse_duration_ms("1.5 s") == 1500
    assert parse_memory_bytes("32 kb") == 32 * 1024
    assert parse_memory_bytes("1g") == 1 << 30
    assert parse_memory_bytes(4096) == 4096


def test_fallback_and_deprecated_keys():
    opt = key("new.key").int_type().default_value(7).with_deprecated_keys("old.key")
    conf = Configuration({"old.key": "42"})
    assert conf.get(opt) == 42
    conf.set(opt, 13)
    assert conf.get(opt) == 13


def test_yaml_loading(tmp_path):
    p = tmp_path / "flink-conf.yaml"
    p.write_text("# comment\npipeline.max-parallelism: 64\nstate.backend: hbm\n")
    conf = Configuration.from_yaml_file(str(p))
    assert conf.get(CoreOptions.MAX_PARALLELISM) == 64
    assert conf.get(StateOptions.BACKEND) == "hbm"


def test_clone_independent():
    a = Configuration({"x": 1})
    b = a.clone()
    b.set("x", 2)
    assert a.get("x") == 1


def test_remove_clears_all_keys():
    opt = key("new.key").int_type().default_value(7).with_deprecated_keys("old.key")
    conf = Configuration({"old.key": "42", "new.key": "43"})
    conf.remove(opt)
    assert conf.get(opt) == 7
    assert not conf.contains(opt)


def test_from_env_dash_keys(monkeypatch):
    monkeypatch.setenv("FLINK_TPU_PIPELINE_MAX__PARALLELISM", "256")
    conf = Configuration.from_env()
    assert conf.get(CoreOptions.MAX_PARALLELISM) == 256
