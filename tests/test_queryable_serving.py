"""Queryable state serving tier (ISSUE-9): snapshot-consistent sharded
reads off the checkpoint stream.

Three layers under test:

1. **Live reads** — fire-time published views (``queryable/view.py``):
   barrier-free, bit-equal to the operator's own fire-time values for
   already-fired panes, on the host/device tiers, at mesh 1 and 2, and
   through a quarantine degrade.
2. **Checkpoint replicas** (``queryable/replica.py``): lookups at the
   last-completed-checkpoint consistency level, sharded by the writer's
   own key-group layout (subtask ranges / mesh slice manifests), with
   staleness gauges and manifest-driven catch-up across rescales; chaos:
   a partitioned replica keeps serving at its advertised staleness and
   re-converges after heal (``Partition(direction=)``), a slow-disk
   storage only delays it (``SlowDisk``).
3. **Serving front end** (``queryable/server.py`` + REST): batched lookup
   protocol (one request, N keys, columnar answer), pooled client with
   eviction + retry/backoff, the unknown-state reply that no longer leaks
   the registered-state list, and the REST state endpoints + panel.
"""

import json
import socket
import socketserver
import struct
import threading
import time
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.queryable import (CheckpointReplica, KvStateRegistry,
                                 QueryableStateClient,
                                 QueryableStateClientPool,
                                 QueryableStateServer, QueryableStateService,
                                 QueryableStateSpec)
from flink_tpu.queryable.replica import REPLICA_FETCH_POINT
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import (FaultInjector, Partition, SlowDisk,
                                     WedgedDevice)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

WINDOW_MS = 1000


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _build_op(emit_tier="host", queryable="agg", mesh_devices=0, **kw):
    kwargs = dict(key_column="k", value_column="v", emit_tier=emit_tier,
                  queryable=queryable, **kw)
    if emit_tier == "host":
        kwargs.setdefault("snapshot_source", "mirror")
    if mesh_devices:
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator
        kwargs.pop("emit_tier")
        kwargs.pop("snapshot_source", None)
        op = MeshWindowAggOperator(
            TumblingEventTimeWindows.of(WINDOW_MS),
            SumAggregator(jnp.float32), mesh=make_mesh(mesh_devices),
            **kwargs)
    else:
        op = WindowAggOperator(TumblingEventTimeWindows.of(WINDOW_MS),
                               SumAggregator(jnp.float32), **kwargs)
    op.open(RuntimeContext())
    return op


def _batches(n=8, b=512, keys=61, seed=9, integer_values=False):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = rng.integers(0, keys, b)
        if integer_values:
            # integer-valued floats: exact in f32 AND f64, so device-tier
            # and degraded (f64 mirror) runs are bit-comparable — the
            # PR-4 digest convention
            v = rng.integers(1, 8, b).astype(np.float32)
        else:
            v = (rng.random(b) * 10).astype(np.float32)
        ts = i * (WINDOW_MS // 2) + np.sort(
            rng.integers(0, WINDOW_MS // 2, b)).astype(np.int64)
        out.append((k, v, ts))
    return out


def _drain(op, batches):
    out = []
    for k, v, ts in batches:
        out += op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
    out += op.end_input()
    return out


def _fire_values(elements, value_col="result"):
    """key -> (value, window_start) of the NEWEST fired window containing
    the key — what a live read must return, bit-equal."""
    expect = {}
    for el in elements:
        if not hasattr(el, "columns") or value_col not in el.columns:
            continue
        ks = np.asarray(el.column("k"))
        vs = np.asarray(el.column(value_col))
        ws = np.asarray(el.column("window_start"))
        for k, v, w in zip(ks.tolist(), vs.tolist(), ws.tolist()):
            if k not in expect or w >= expect[k][1]:
                expect[k] = (v, w)
    return expect


def _assert_view_bit_equal(view, expect, retained_windows=4):
    starts = sorted({w for _v, w in expect.values()}, reverse=True)
    served = set(starts[:retained_windows])
    keys = [k for k, (_v, w) in expect.items() if w in served]
    assert keys
    found, values, tags = view.lookup_batch(np.asarray(keys, np.int64))
    assert found.all()
    for i, k in enumerate(keys):
        v, w = expect[k]
        assert values[i]["result"] == v, (k, values[i], v)   # bit-equal
        assert values[i]["window_start"] == w
    return tags


# ---------------------------------------------------------------------------
# layer 1: live reads
# ---------------------------------------------------------------------------

def test_live_view_bit_equal_host_tier():
    op = _build_op(emit_tier="host")
    expect = _fire_values(_drain(op, _batches()))
    tags = _assert_view_bit_equal(op.queryable_view(), expect)
    assert tags["watermark"] is not None
    # checkpoint tag reflects notifications
    op.notify_checkpoint_complete(7)
    op2 = _build_op(emit_tier="host")
    op2.notify_checkpoint_complete(7)
    _drain(op2, _batches())
    assert op2.queryable_view().tags()["checkpoint_id"] == 7


def test_live_view_bit_equal_device_tier():
    op = _build_op(emit_tier="device")
    expect = _fire_values(_drain(op, _batches()))
    _assert_view_bit_equal(op.queryable_view(), expect)


def test_live_view_bit_equal_mesh_1_and_2():
    """Acceptance: live reads bit-equal to fire-time values at mesh 1 AND
    mesh 2 (and the two meshes agree with each other bit-for-bit)."""
    batches = _batches(seed=17)
    expects = []
    for d in (1, 2):
        op = _build_op(mesh_devices=d)
        expect = _fire_values(_drain(op, batches))
        _assert_view_bit_equal(op.queryable_view(), expect)
        expects.append(expect)
    assert expects[0] == expects[1]


def test_live_view_missing_key_and_retention():
    op = _build_op()
    _drain(op, _batches())
    view = op.queryable_view()
    found, values, _ = view.lookup_batch(np.asarray([10 ** 12], np.int64))
    assert not found[0] and values[0] is None
    # the ring retains the newest few windows only
    assert len(view._segments) <= view.retain_windows * 2
    assert view.published_windows >= 4


def test_live_view_never_blocks_on_pipelined_operator():
    """The monitoring contract: a lookup takes no pipeline barrier — it
    must answer while a hot stage is mid-flight (no flush)."""
    op = _build_op(pipeline_depth=1)
    batches = _batches()
    for k, v, ts in batches[:-1]:
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        op.process_watermark(Watermark(int(ts.max()) - 1))
    t0 = time.perf_counter()
    op.queryable_view().lookup_batch(np.asarray([1, 2, 3], np.int64))
    assert time.perf_counter() - t0 < 1.0
    op.end_input()
    op.close()


@pytest.mark.chaos
def test_live_read_during_quarantine_degrade_digest_consistent():
    """PR-4 acceptance extended to reads: wedge the device mid-job, let
    the operator degrade to the host tier — live reads must stay
    bit-equal to the (digest-identical) fire-time values."""
    from flink_tpu.runtime import device_health as dh
    from flink_tpu.runtime.device_health import (DeviceHealthMonitor,
                                                 WatchdogConfig)
    prev = dh.get_monitor(create=False)
    try:
        cfg = WatchdogConfig(deadline_floor_s=0.25,
                             first_dispatch_grace_s=30.0,
                             backoff_initial_s=0.001, backoff_max_s=0.01,
                             probe_backoff_initial_s=0.02,
                             probe_backoff_max_s=0.1)
        dh.set_monitor(DeviceHealthMonitor(cfg, heal_async=False))
        batches = _batches(n=10, seed=3, integer_values=True)
        clean_op = _build_op(emit_tier="device", queryable=None)
        clean = _fire_values(_drain(clean_op, batches))

        dh.set_monitor(DeviceHealthMonitor(cfg, heal_async=False))
        op = _build_op(emit_tier="device")
        inj = FaultInjector(seed=1)
        sched = inj.inject("device.dispatch", WedgedDevice(at=6))
        out = []
        with chaos.installed(inj):
            for i, (k, v, ts) in enumerate(batches):
                out += op.process_batch(
                    RecordBatch({"k": k, "v": v}, timestamps=ts))
                out += op.process_watermark(Watermark(int(ts.max()) - 1))
                if i == 7:
                    sched.heal()
            out += op.end_input()
        assert op.device_health_stats()["quarantine_migrations"] == 1
        expect = _fire_values(out)
        assert expect == clean          # digest-consistent with host tier
        _assert_view_bit_equal(op.queryable_view(), expect)
    finally:
        chaos.uninstall()
        dh.set_monitor(prev if prev is not None and prev.healthy else None)


# ---------------------------------------------------------------------------
# layer 2: checkpoint replicas
# ---------------------------------------------------------------------------

def _assembled_from(op, cid, uid="win"):
    op.prepare_snapshot_pre_barrier()
    return {uid: {"subtasks": [{"operator": {"op0": op.snapshot_state()}}]},
            "__job__": {"checkpoint_id": cid}}


def _expected_sums(batches):
    exp = {}
    for k, v, _ts in batches:
        for kk, vv in zip(k.tolist(), v.tolist()):
            exp[kk] = exp.get(kk, 0.0) + vv
    return exp


def test_replica_serves_last_completed_checkpoint():
    batches = _batches(n=4, seed=21)
    op = _build_op(queryable=None, allowed_lateness_ms=60_000)
    for k, v, ts in batches:
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        op.process_watermark(Watermark(int(ts.max()) - 1))
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k", op.agg))
    assert rep.ingest_assembled(1, _assembled_from(op, 1))
    exp = _expected_sums(batches)
    q = np.asarray(sorted(exp), np.int64)
    found, values, tags = rep.lookup_batch(q)
    assert found.all()
    assert tags["checkpoint_id"] == 1
    for i, k in enumerate(q.tolist()):
        assert abs(values[i]["result"] - exp[k]) <= 2e-2 + 1e-4 * abs(exp[k])
    # unknown key: found=False, no insert anywhere
    f2, v2, _ = rep.lookup_batch([987654321])
    assert not f2[0] and v2[0] is None


def test_replica_subtask_sharding_routes_like_a_record():
    """Two hash-partitioned subtask snapshots: the replica routes each
    query to the shard whose key-group range owns the key — a key placed
    (wrongly) in the OTHER shard must not be served from there."""
    from flink_tpu.queryable.view import route_keys
    keys = np.arange(40, dtype=np.int64)
    owner = route_keys(keys, 2, 128)
    ops = []
    for sub in (0, 1):
        op = _build_op(queryable=None, allowed_lateness_ms=60_000)
        mine = keys[owner == sub]
        vals = (mine * 10 + sub).astype(np.float32)
        ts = np.full(mine.size, 10, np.int64)
        op.process_batch(RecordBatch({"k": mine, "v": vals}, timestamps=ts))
        op.process_watermark(Watermark(50))
        ops.append(op)
    assembled = {"win": {"subtasks": [
        {"operator": {"op0": ops[0].snapshot_state()}},
        {"operator": {"op0": ops[1].snapshot_state()}}]}}
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k",
                                               ops[0].agg))
    assert rep.ingest_assembled(1, assembled)
    st = rep.stats()
    assert len(st["shards"]) == 2
    # manifest = the job's own key-group ranges
    assert st["shards"][0]["key_groups"] == [0, 63]
    assert st["shards"][1]["key_groups"] == [64, 127]
    found, values, _ = rep.lookup_batch(keys)
    assert found.all()
    for i, k in enumerate(keys.tolist()):
        assert values[i]["result"] == float(k * 10 + owner[i])


def test_replica_routes_with_full_parallelism_when_a_subtask_is_empty():
    """A subtask that saw no records has no keyed snapshot, but it still
    OWNS its key-group range: routing must use the FULL subtask count, or
    present keys resolve as not-found."""
    from flink_tpu.queryable.view import route_keys
    keys = np.arange(60, dtype=np.int64)
    owner = route_keys(keys, 3, 128)
    ops = {}
    for sub in (0, 2):                   # subtask 1 stays empty
        op = _build_op(queryable=None, allowed_lateness_ms=60_000)
        mine = keys[owner == sub]
        op.process_batch(RecordBatch(
            {"k": mine, "v": (mine * 2).astype(np.float32)},
            timestamps=np.full(mine.size, 10, np.int64)))
        op.process_watermark(Watermark(50))
        ops[sub] = op
    assembled = {"win": {"subtasks": [
        {"operator": {"op0": ops[0].snapshot_state()}},
        {"operator": {}},                # no keyed state yet
        {"operator": {"op0": ops[2].snapshot_state()}}]}}
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k",
                                               ops[0].agg))
    assert rep.ingest_assembled(1, assembled)
    served = keys[(owner == 0) | (owner == 2)]
    found, values, _ = rep.lookup_batch(served)
    assert found.all()
    for i, k in enumerate(served.tolist()):
        assert values[i]["result"] == float(k * 2)
    # subtask 1's keys are genuinely absent, not misrouted
    f_empty, _v, _t = rep.lookup_batch(keys[owner == 1])
    assert not f_empty.any()


def test_non_scalar_keys_rejected_cleanly():
    """List/dict/null keys from an untrusted client must come back as an
    'err' reply — never an unreplied dropped connection."""
    op = _build_op()
    _drain(op, _batches(n=2, seed=55))
    registry = KvStateRegistry()
    registry.register_views("agg", [op.queryable_view()], 1, 128)
    status, msg = registry.lookup_batch("agg", [[1, 2], 3])
    assert status == "err" and "JSON scalars" in msg
    status, msg = registry.lookup("agg", {"k": 1})
    assert status == "err" and "scalar" in msg
    server = QueryableStateServer(registry).start()
    pool = QueryableStateClientPool(server.host, server.port, retries=0)
    try:
        with pytest.raises(RuntimeError, match="JSON scalars"):
            pool.get_batch("agg", [[1, 2]])
        # the connection survived the poison request
        got = pool.get_batch("agg", [1, 2])
        assert len(got["found"]) == 2
    finally:
        pool.close()
        server.stop()


def test_legacy_lookup_on_replica_only_state_names_the_consistency():
    op = _build_op(queryable=None, allowed_lateness_ms=60_000)
    registry = KvStateRegistry()
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k", op.agg))
    registry.register_replica("agg", rep)
    status, msg = registry.lookup("agg", 1)
    assert status == "err" and "checkpoint" in msg
    assert "unknown" not in msg


def test_replica_mesh_slices_and_rescale_catch_up():
    """Mesh-2 slices ingest with their manifests; a later checkpoint from
    a DIFFERENT layout (mesh 1) re-shards the replica wholesale —
    manifest-driven catch-up, counted."""
    batches = _batches(n=3, seed=33)
    exp = _expected_sums(batches)
    q = np.asarray(sorted(exp), np.int64)

    def run_mesh(d):
        op = _build_op(queryable=None, mesh_devices=d,
                       allowed_lateness_ms=60_000)
        for k, v, ts in batches:
            op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
            op.process_watermark(Watermark(int(ts.max()) - 1))
        return op

    op2 = run_mesh(2)
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k", op2.agg))
    assert rep.ingest_assembled(1, _assembled_from(op2, 1))
    st = rep.stats()
    assert len(st["shards"]) == 2
    assert all(s["row_range"] is not None for s in st["shards"])
    found, values, _ = rep.lookup_batch(q)
    assert found.all()
    for i, k in enumerate(q.tolist()):
        assert abs(values[i]["result"] - exp[k]) <= 2e-2 + 1e-4 * abs(exp[k])

    op1 = run_mesh(1)
    assert rep.ingest_assembled(2, _assembled_from(op1, 2))
    st2 = rep.stats()
    assert st2["catch_ups"] == 1 and st2["serving_checkpoint_id"] == 2
    found2, values2, tags2 = rep.lookup_batch(q)
    assert found2.all() and tags2["checkpoint_id"] == 2


@pytest.mark.chaos
def test_partitioned_replica_serves_stale_and_reconverges():
    """Nemesis acceptance: ``Partition(direction="storage->replica")``
    blackholes the replica's bulk fetch.  It must KEEP SERVING its last
    ingested checkpoint at the advertised staleness (lag gauges move),
    and re-converge after heal."""
    storage = InMemoryCheckpointStorage(retain=5)
    op = _build_op(queryable=None, allowed_lateness_ms=60_000)
    b1 = _batches(n=2, seed=40)
    for k, v, ts in b1:
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        op.process_watermark(Watermark(int(ts.max()) - 1))
    storage.store(1, _assembled_from(op, 1))
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k", op.agg),
                            storage=storage)
    assert rep.poll_once()
    exp1 = _expected_sums(b1)
    q = np.asarray(sorted(exp1), np.int64)
    found, values1, tags = rep.lookup_batch(q)
    assert found.all() and tags["replica_lag_checkpoints"] == 0

    inj = FaultInjector(seed=2)
    part = inj.inject(REPLICA_FETCH_POINT,
                      Partition(direction="storage->replica"))
    b2 = _batches(n=2, seed=41)
    for k, v, ts in b2:
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        op.process_watermark(Watermark(int(ts.max()) - 1))
    storage.store(2, _assembled_from(op, 2))
    storage.store(3, _assembled_from(op, 3))
    with chaos.installed(inj):
        assert not rep.poll_once()      # fetch dropped: stays stale
        f2, values_stale, tags2 = rep.lookup_batch(q)
        # advertised staleness: still serving checkpoint 1, 2 behind
        assert tags2["checkpoint_id"] == 1
        assert tags2["replica_lag_checkpoints"] == 2
        assert tags2["replica_lag_ms"] >= 0.0
        assert f2.all()
        assert [v["result"] for v in values_stale] == \
            [v["result"] for v in values1]
        part.heal()
        assert rep.poll_once()          # re-converges
    tags3 = rep.tags()
    assert tags3["checkpoint_id"] == 3
    assert tags3["replica_lag_checkpoints"] == 0
    exp_all = _expected_sums(b1 + b2)
    f3, v3, _ = rep.lookup_batch(q)
    assert f3.all()
    for i, k in enumerate(q.tolist()):
        assert abs(v3[i]["result"] - exp_all[k]) \
            <= 2e-2 + 1e-4 * abs(exp_all[k])


@pytest.mark.chaos
def test_slow_disk_replica_keeps_serving():
    """``SlowDisk`` on the storage load path only DELAYS catch-up; every
    query in between is answered from the frozen arrays (no blocking)."""
    storage = InMemoryCheckpointStorage(retain=5)
    op = _build_op(queryable=None, allowed_lateness_ms=60_000)
    b1 = _batches(n=2, seed=44)
    for k, v, ts in b1:
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        op.process_watermark(Watermark(int(ts.max()) - 1))
    storage.store(1, _assembled_from(op, 1))
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k", op.agg),
                            storage=storage)
    inj = FaultInjector(seed=3)
    inj.inject("checkpoint.load", SlowDisk(max_s=0.15, min_s=0.05, p=1.0))
    with chaos.installed(inj):
        t0 = time.perf_counter()
        assert rep.poll_once()          # slow, but lands
        assert time.perf_counter() - t0 >= 0.05
        q = np.asarray(sorted(_expected_sums(b1)), np.int64)
        t1 = time.perf_counter()
        found, _v, _t = rep.lookup_batch(q)
        assert found.all()
        assert time.perf_counter() - t1 < 0.05   # lookups never touch disk
    assert inj.fired("checkpoint.load") >= 1


# ---------------------------------------------------------------------------
# layer 3: serving front end
# ---------------------------------------------------------------------------

def test_unknown_state_reply_does_not_leak_registry():
    registry = KvStateRegistry()
    op = _build_op()
    registry.register_views("secret-state-name", [op.queryable_view()], 1,
                            128)
    status, msg = registry.lookup("nope", 1)
    assert status == "err"
    assert "secret-state-name" not in str(msg)
    status2, msg2 = registry.lookup_batch("nope", [1, 2])
    assert status2 == "err" and "secret-state-name" not in str(msg2)


def test_batched_tcp_protocol_live_and_checkpoint():
    op = _build_op(allowed_lateness_ms=60_000)
    batches = _batches(n=4, seed=50)
    out = []
    for k, v, ts in batches:
        out += op.process_batch(RecordBatch({"k": k, "v": v},
                                            timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
    svc = QueryableStateService()
    svc.register_views("agg", [op.queryable_view()], 1, 128)
    rep = svc.add_replica("agg", QueryableStateSpec("agg", "win", "k",
                                                    op.agg))
    # snapshot the live panes BEFORE end-of-input expires them: the
    # replica serves the last completed checkpoint's cut
    svc.on_checkpoint_complete(5, _assembled_from(op, 5))
    assert svc.drain_feed()
    out += op.end_input()
    expect = _fire_values(out)
    server = svc.start_server()
    pool = QueryableStateClientPool(server.host, server.port, size=2)
    try:
        some = sorted(expect)[:16]
        got = pool.get_batch("agg", some, consistency="live")
        assert got["found"] == [True] * len(some)
        for i, k in enumerate(some):
            assert got["values"][i]["result"] == expect[k][0]
        assert got["tags"]["consistency"] == "live"

        exp_sums = _expected_sums(batches)
        gc = pool.get_batch("agg", some, consistency="checkpoint")
        assert gc["found"] == [True] * len(some)
        assert gc["tags"]["checkpoint_id"] == 5
        for i, k in enumerate(some):
            assert abs(gc["values"][i]["result"] - exp_sums[k]) \
                <= 2e-2 + 1e-4 * abs(exp_sums[k])

        # consistency errors + single-get compatibility
        with pytest.raises(RuntimeError):
            pool.get_batch("agg", [1], consistency="bogus")
        assert pool.get("agg", some[0])["result"] == expect[some[0]][0]
        with pytest.raises(KeyError):
            pool.get("agg", 987654321)
        # service measured the traffic
        st = svc.stats()
        assert st["lookups_total"] >= len(some) * 2
        assert st["lookup_p99_ms"] is not None
        assert st["per_state"]["agg"]["replica"]["serving_checkpoint_id"] \
            == 5
        assert rep.stats()["ingests"] == 1
    finally:
        pool.close()
        svc.close()


def test_legacy_single_socket_client_still_works():
    op = _build_op()
    expect = _fire_values(_drain(op, _batches(n=3, seed=51)))
    registry = KvStateRegistry()
    registry.register_views("agg", [op.queryable_view()], 1, 128)
    server = QueryableStateServer(registry).start()
    try:
        client = QueryableStateClient(server.host, server.port)
        k = sorted(expect)[0]
        assert client.get("agg", k)["result"] == expect[k][0]
        with pytest.raises(KeyError):
            client.get("agg", 10 ** 12)
        client.close()
    finally:
        server.stop()


class _FlakyOneShotServer:
    """Answers exactly one request per connection, then slams the socket —
    the mid-stream failure mode the pooled client must absorb."""

    def __init__(self):
        registry = KvStateRegistry()
        op = _build_op()
        _drain(op, _batches(n=2, seed=52))
        self._registry = registry
        registry.register_views("agg", [op.queryable_view()], 1, 128)
        reg = registry
        _len = struct.Struct("<I")

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                from flink_tpu.queryable.server import _recv_exact
                hdr = _recv_exact(self.request, _len.size)
                if hdr is None:
                    return
                (n,) = _len.unpack(hdr)
                payload = _recv_exact(self.request, n)
                req = json.loads(payload)
                resp = reg.lookup_batch(req["state"], req["keys"],
                                        req.get("consistency", "live"))
                data = json.dumps(resp).encode()
                self.request.sendall(_len.pack(len(data)) + data)
                # one answer per connection: next request on this socket
                # dies mid-stream
                self.request.close()

        self._srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0),
                                                    Handler)
        self._srv.daemon_threads = True
        self.host, self.port = self._srv.server_address
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_pooled_client_evicts_broken_connections_and_retries():
    srv = _FlakyOneShotServer()
    pool = QueryableStateClientPool(srv.host, srv.port, size=2, retries=1,
                                    backoff_s=0.01)
    try:
        # every request after the first rides a pooled-but-dead socket:
        # the pool must evict it and retry on a fresh connection
        for _ in range(5):
            got = pool.get_batch("agg", [1, 2, 3])
            assert len(got["found"]) == 3
        assert pool.stats["evictions"] >= 1
        assert pool.stats["retries"] >= 1
    finally:
        pool.close()
        srv.stop()
    # the old single-socket client on the same server: second get raises
    # and the socket stays broken (the documented legacy behavior)
    srv2 = _FlakyOneShotServer()
    try:
        c = QueryableStateClient(srv2.host, srv2.port)
        with pytest.raises((RuntimeError, KeyError, ConnectionError)):
            c.get("agg", 1)
            c.get("agg", 2)
            c.get("agg", 3)
        c.close()
    finally:
        srv2.stop()


def test_batch_size_bound():
    registry = KvStateRegistry()
    op = _build_op()
    registry.register_views("agg", [op.queryable_view()], 1, 128)
    status, msg = registry.lookup_batch("agg", list(range(1 << 16 | 1)))
    assert status == "err" and "batch too large" in msg


# ---------------------------------------------------------------------------
# cluster wiring: MiniCluster auto-registration + checkpoint feed + REST
# ---------------------------------------------------------------------------

def _run_cluster_job(n=20_000, checkpoint_interval_ms=30):
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 41, n)
    vals = np.ones(n, np.float64)
    ts = np.sort(rng.integers(0, 4000, n))
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                         batch_size=128)
        .assign_timestamps_and_watermarks(0, timestamp_column="t")
        .key_by("k")
        .window(TumblingEventTimeWindows.of(1000))
        .aggregate(SumAggregator(jnp.float64), value_column="v",
                   queryable="totals")
        .collect())
    inj = chaos.FaultInjector(seed=6)
    inj.inject("channel.recv",
               chaos.SlowConsumer(max_s=0.02, min_s=0.01, p=0.2, burst=20,
                                  channel="[0]->"))
    storage = InMemoryCheckpointStorage(retain=5)
    with chaos.installed(inj):
        res = env.execute_cluster(storage=storage,
                                  checkpoint_interval_ms=
                                  checkpoint_interval_ms,
                                  timeout_s=240)
    return env._last_cluster, res, keys, vals


@pytest.mark.chaos
def test_minicluster_serving_tier_end_to_end():
    cluster, res, keys, vals = _run_cluster_job()
    try:
        assert res.state == "FINISHED"
        assert len(res.completed_checkpoints) >= 1
        svc = cluster.queryable
        assert svc is not None
        assert svc.drain_feed()

        status = cluster.job_status()["queryable"]
        assert "totals" in status["states"]
        rep_stats = status["per_state"]["totals"]["replica"]
        assert rep_stats["serving_checkpoint_id"] == \
            max(res.completed_checkpoints)
        assert rep_stats["replica_lag_checkpoints"] == 0
        assert len(rep_stats["shards"]) == 2   # parallelism-2 key groups

        # gauges registered on the job metric group
        all_metrics = cluster.metrics_registry.all_metrics()
        assert any(n.endswith("queryable.replica_lag_checkpoints")
                   for n in all_metrics)

        # live + checkpoint reads over TCP with subtask routing
        server = cluster.start_queryable_server()
        pool = QueryableStateClientPool(server.host, server.port)
        exp = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            exp[k] = exp.get(k, 0.0) + v
        q = sorted(exp)
        live = pool.get_batch("totals", q, consistency="live")
        assert all(live["found"])
        ck = pool.get_batch("totals", q, consistency="checkpoint")
        assert ck["tags"]["checkpoint_id"] == max(res.completed_checkpoints)
        assert any(ck["found"])        # the last ckpt precedes end-of-input
        pool.close()
    finally:
        if cluster.queryable is not None:
            cluster.queryable.close()


def test_rest_state_endpoints_and_panel():
    from flink_tpu.rest.server import JobRegistry, RestServer
    cluster, res, keys, vals = _run_cluster_job(n=6000,
                                                checkpoint_interval_ms=0)
    registry = JobRegistry()
    jid = registry.register("qjob", cluster)
    rest = RestServer(registry).start()
    try:
        assert cluster.queryable is not None
        base = f"{rest.url}/jobs/{jid}"
        k = int(keys[0])
        got = json.load(urllib.request.urlopen(
            f"{base}/state/totals/{k}?consistency=live"))
        assert got["key"] == k and "result" in got["value"]
        assert got["tags"]["consistency"] == "live"
        # missing key -> 404 with tags
        try:
            urllib.request.urlopen(f"{base}/state/totals/999999999")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # batch endpoint
        req = urllib.request.Request(
            f"{base}/state/totals:batch",
            data=json.dumps({"keys": [k, 999999999],
                             "consistency": "live"}).encode(),
            headers={"Content-Type": "application/json"})
        got2 = json.load(urllib.request.urlopen(req))
        assert got2["found"] == [True, False]
        # stats + panel
        st = json.load(urllib.request.urlopen(f"{base}/queryable"))
        assert "totals" in st["states"]
        html = urllib.request.urlopen(
            f"{base}/queryable.html").read().decode()
        assert 'class="qs-panel"' in html and 'data-state="totals"' in html
    finally:
        rest.stop()
        if cluster.queryable is not None:
            cluster.queryable.close()


# ---------------------------------------------------------------------------
# ProcessCluster wiring: coordinator-side replica off the checkpoint stream
# ---------------------------------------------------------------------------

def test_process_cluster_replica_wiring(tmp_path):
    """The coordinator's serving tier is replica-only (live views live in
    the worker processes): enable_queryable + the checkpoint-stream feed,
    exercised against the storage a coordinator writes — without
    spawning workers (tier-1 friendly)."""
    from flink_tpu.cluster.distributed import ProcessCluster
    from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage

    storage = FileCheckpointStorage(str(tmp_path / "ckpts"))
    op = _build_op(queryable=None, allowed_lateness_ms=60_000)
    batches = _batches(n=3, seed=60)
    for k, v, ts in batches:
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        op.process_watermark(Watermark(int(ts.max()) - 1))
    pc = ProcessCluster("qjob", n_workers=1, checkpoint_storage=storage,
                        spawn=False)
    svc = pc.enable_queryable("totals", "win", op.agg, "k")
    assert pc.queryable is svc

    # the coordinator's _complete feed path
    pc.queryable.on_checkpoint_complete(1, _assembled_from(op, 1))
    assert svc.drain_feed()
    assert pc.queryable_stats()["per_state"]["totals"]["replica"][
        "serving_checkpoint_id"] == 1

    # and the storage-tailing path an external serving process would use
    storage.store(2, _assembled_from(op, 2))
    rep = svc.registry.replicas()["totals"]
    assert rep.poll_once()
    exp = _expected_sums(batches)
    q = np.asarray(sorted(exp), np.int64)
    found, values, tags = rep.lookup_batch(q)
    assert found.all() and tags["checkpoint_id"] == 2
    svc.close()
