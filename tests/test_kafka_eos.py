"""Kafka exactly-once produce: KIP-98 transactions on the broker
(InitProducerId / AddPartitionsToTxn / EndTxn / ListTransactions) and the
checkpoint-bound 2PC sink — ``FlinkKafkaProducer.java:100`` analog.
"""

import json

import numpy as np
import pytest

from flink_tpu.connectors.kafka import (KafkaError, KafkaExactlyOnceSink,
                                        KafkaWireBroker, KafkaWireClient)
from flink_tpu.core.batch import RecordBatch


@pytest.fixture
def broker(tmp_path):
    b = KafkaWireBroker(directory=str(tmp_path / "kafka")).start()
    b.create_topic("t", partitions=1)
    yield b
    b.stop()


def consume_all(b, topic="t", part=0):
    c = KafkaWireClient(b.host, b.port)
    try:
        out = []
        hw = c.latest_offset(topic, part)
        off = 0
        while off < hw:
            msgs, _ = c.fetch(topic, part, off)
            for o, _k, v in msgs:
                if o >= hw:
                    break
                out.append(json.loads(v.decode()) if v else None)
                off = o + 1
        return out
    finally:
        c.close()


def batch(vals):
    return RecordBatch({"v": np.asarray(vals, np.int64)})


class TestBrokerTransactions:
    def test_staged_invisible_until_commit(self, broker):
        c = KafkaWireClient(broker.host, broker.port)
        pid, ep = c.init_producer_id("tx1")
        c.add_partitions_to_txn("tx1", pid, ep, {"t": [0]})
        c.produce_txn("tx1", pid, ep, "t", 0, [(None, b'{"v": 1}')])
        assert consume_all(broker) == []            # invisible pre-commit
        assert [t[0] for t in c.list_transactions()] == ["tx1"]
        c.end_txn("tx1", pid, ep, commit=True)
        assert consume_all(broker) == [{"v": 1}]
        assert c.list_transactions() == []
        # commit replay is idempotent (recover-and-commit path)
        c.end_txn("tx1", pid, ep, commit=True)
        assert consume_all(broker) == [{"v": 1}]
        c.close()

    def test_abort_discards(self, broker):
        c = KafkaWireClient(broker.host, broker.port)
        pid, ep = c.init_producer_id("tx2")
        c.add_partitions_to_txn("tx2", pid, ep, {"t": [0]})
        c.produce_txn("tx2", pid, ep, "t", 0, [(None, b'{"v": 9}')])
        c.end_txn("tx2", pid, ep, commit=False)
        assert consume_all(broker) == []
        c.close()

    def test_zombie_fencing(self, broker):
        c = KafkaWireClient(broker.host, broker.port)
        pid, ep = c.init_producer_id("tx3")
        c.add_partitions_to_txn("tx3", pid, ep, {"t": [0]})
        c.produce_txn("tx3", pid, ep, "t", 0, [(None, b'{"v": 1}')])
        # a new incarnation re-initializes: epoch bumps, old txn aborts
        pid2, ep2 = c.init_producer_id("tx3")
        assert pid2 == pid and ep2 == ep + 1
        with pytest.raises(KafkaError):             # zombie produce fenced
            c.produce_txn("tx3", pid, ep, "t", 0, [(None, b'{"v": 2}')])
        with pytest.raises(KafkaError):             # zombie commit fenced
            c.end_txn("tx3", pid, ep, commit=True)
        assert consume_all(broker) == []            # old staged rows gone
        c.close()

    def test_multi_partition_commit_is_atomic(self, broker):
        broker.create_topic("mp", partitions=3)
        c = KafkaWireClient(broker.host, broker.port)
        pid, ep = c.init_producer_id("tx4")
        c.add_partitions_to_txn("tx4", pid, ep, {"mp": [0, 1, 2]})
        for p in range(3):
            c.produce_txn("tx4", pid, ep, "mp", p,
                          [(None, json.dumps({"p": p}).encode())])
        for p in range(3):
            assert consume_all(broker, "mp", p) == []
        c.end_txn("tx4", pid, ep, commit=True)
        for p in range(3):
            assert consume_all(broker, "mp", p) == [{"p": p}]
        c.close()

    def test_tid_reuse_after_commit(self, broker):
        """Standard Kafka usage: ONE transactional id across many
        transactions.  A new txn under a previously committed id must
        commit its own records — not be swallowed by the idempotent
        commit-replay check."""
        c = KafkaWireClient(broker.host, broker.port)
        for i in range(3):
            pid, ep = c.init_producer_id("reuse")
            c.add_partitions_to_txn("reuse", pid, ep, {"t": [0]})
            c.produce_txn("reuse", pid, ep, "t", 0,
                          [(None, json.dumps({"v": i}).encode())])
            c.end_txn("reuse", pid, ep, commit=True)
        assert [r["v"] for r in consume_all(broker)] == [0, 1, 2]
        assert c.list_transactions() == []      # nothing dangling
        c.close()

    def test_open_txn_survives_broker_restart(self, tmp_path):
        """The 2PC crash window: a PRE-COMMITTED (open) transaction must
        survive a broker restart so the sink's recover-and-commit replay
        finds it — staged records are durable, not memory-only."""
        d = str(tmp_path / "kafka")
        b1 = KafkaWireBroker(directory=d).start()
        b1.create_topic("t", partitions=1)
        c = KafkaWireClient(b1.host, b1.port)
        pid, ep = c.init_producer_id("open1")
        c.add_partitions_to_txn("open1", pid, ep, {"t": [0]})
        c.produce_txn("open1", pid, ep, "t", 0, [(None, b'{"v": 42}')])
        c.close()
        b1.stop()                               # crash with the txn OPEN

        b2 = KafkaWireBroker(directory=d).start()
        try:
            c2 = KafkaWireClient(b2.host, b2.port)
            assert [t[0] for t in c2.list_transactions()] == ["open1"]
            assert consume_all(b2) == []        # still invisible
            c2.end_txn("open1", pid, ep, commit=True)
            assert consume_all(b2) == [{"v": 42}]
            c2.close()
        finally:
            b2.stop()

    def test_committed_tids_survive_broker_restart(self, tmp_path):
        d = str(tmp_path / "kafka")
        b1 = KafkaWireBroker(directory=d).start()
        b1.create_topic("t", partitions=1)
        c = KafkaWireClient(b1.host, b1.port)
        pid, ep = c.init_producer_id("txr")
        c.add_partitions_to_txn("txr", pid, ep, {"t": [0]})
        c.produce_txn("txr", pid, ep, "t", 0, [(None, b'{"v": 5}')])
        c.end_txn("txr", pid, ep, commit=True)
        c.close()
        b1.stop()

        b2 = KafkaWireBroker(directory=d).start()
        try:
            assert consume_all(b2) == [{"v": 5}]
            c2 = KafkaWireClient(b2.host, b2.port)
            # commit replay after restart is STILL idempotent
            c2.end_txn("txr", pid, ep, commit=True)
            assert consume_all(b2) == [{"v": 5}]
            c2.close()
        finally:
            b2.stop()


class TestExactlyOnceSink:
    def test_crash_between_precommit_and_commit(self, broker):
        """The verdict's done-criterion: a crash between pre-commit and
        commit neither loses nor duplicates."""
        from flink_tpu.operators.base import snapshot_scope

        sink = KafkaExactlyOnceSink(broker.host, broker.port, "t",
                                    sink_id="eos")
        sink.open(type("Ctx", (), {"subtask_index": 0})())
        sink.write_batch(batch([1, 2]))
        with snapshot_scope(1):
            snap = sink.snapshot_state()        # epoch 0 staged @ ckpt 1
        # ... checkpoint 1 completes but the notification is LOST ...
        sink.write_batch(batch([3]))
        with snapshot_scope(2):
            sink.snapshot_state()               # epoch 1 staged @ ckpt 2
        del sink                                # crash before notify

        assert consume_all(broker) == []        # nothing visible yet

        restored = KafkaExactlyOnceSink(broker.host, broker.port, "t",
                                        sink_id="eos")
        restored.open(type("Ctx", (), {"subtask_index": 0})())
        restored.restore_state(snap)
        # epoch 0 (in the checkpoint) committed; epoch 1 aborted
        vals = sorted(r["v"] for r in consume_all(broker))
        assert vals == [1, 2]
        # upstream replays the post-checkpoint rows
        restored.write_batch(batch([3]))
        with snapshot_scope(2):
            restored.snapshot_state()
        restored.notify_checkpoint_complete(2)
        vals = sorted(r["v"] for r in consume_all(broker))
        assert vals == [1, 2, 3]                # no loss, no duplicates
        restored.close()

    def test_double_restore_is_idempotent(self, broker):
        from flink_tpu.operators.base import snapshot_scope

        sink = KafkaExactlyOnceSink(broker.host, broker.port, "t",
                                    sink_id="eos2")
        sink.open(type("Ctx", (), {"subtask_index": 0})())
        sink.write_batch(batch([7]))
        with snapshot_scope(1):
            snap = sink.snapshot_state()
        del sink
        for _ in range(2):                      # restore twice (retry)
            r = KafkaExactlyOnceSink(broker.host, broker.port, "t",
                                     sink_id="eos2")
            r.open(type("Ctx", (), {"subtask_index": 0})())
            r.restore_state(snap)
            r.close()
        assert [r["v"] for r in consume_all(broker)] == [7]

    def test_notify_skips_later_checkpoints(self, broker):
        from flink_tpu.operators.base import snapshot_scope

        sink = KafkaExactlyOnceSink(broker.host, broker.port, "t",
                                    sink_id="eos3")
        sink.open(type("Ctx", (), {"subtask_index": 0})())
        sink.write_batch(batch([1]))
        with snapshot_scope(1):
            sink.snapshot_state()
        sink.write_batch(batch([2]))
        with snapshot_scope(2):
            sink.snapshot_state()
        sink.notify_checkpoint_complete(1)
        assert [r["v"] for r in consume_all(broker)] == [1]
        sink.notify_checkpoint_complete(2)
        assert sorted(r["v"] for r in consume_all(broker)) == [1, 2]
        sink.close()
