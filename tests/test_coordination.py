"""Control plane: session cluster, slot lifecycle, dispatcher recovery,
heartbeat-driven executor loss, CLI."""

import subprocess
import sys
import time

import numpy as np
import pytest

from flink_tpu.cluster.coordination import StandaloneSessionCluster
from flink_tpu.cluster.ha import HaServices
from flink_tpu.cluster.rpc import await_future
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

pytestmark = pytest.mark.slow


def _plan(n=50_000, keys=13, name="job"):
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": np.arange(n) % keys,
                                         "v": np.ones(n)}, batch_size=256)
            .key_by("k").sum("v").collect())
    return env.get_stream_graph(name).to_plan(), sink


def test_session_cluster_submit_and_complete():
    cluster = StandaloneSessionCluster(num_task_executors=2,
                                      slots_per_executor=2)
    try:
        client = cluster.client()
        ov = client.overview()
        assert ov == {"task_executors": 2, "slots_total": 4, "slots_free": 4}
        plan, sink = _plan()
        job_id = client.submit(plan, parallelism=2)
        assert job_id in client.list_jobs()
        result = client.wait_for_completion(job_id, timeout_s=120)
        assert result.state == "FINISHED"
        assert client.overview()["slots_free"] == 4   # slots released
        final = {r["k"]: r["v"] for r in sink.rows()}
        assert len(final) == 13
    finally:
        cluster.shutdown()


def test_slots_exhausted_job_waits():
    cluster = StandaloneSessionCluster(num_task_executors=1,
                                      slots_per_executor=1)
    try:
        client = cluster.client()
        plan, _ = _plan(n=500_000)
        j1 = client.submit(plan, parallelism=1)
        time.sleep(0.1)
        plan2, _ = _plan(n=1000)
        j2 = client.submit(plan2, parallelism=1)
        st2 = client.status(j2)
        assert st2["status"] == "WAITING_FOR_RESOURCES"
        client.wait_for_completion(j1, timeout_s=120)
        # freed slots: the waiting job must now be scheduled and finish
        res2 = client.wait_for_completion(j2, timeout_s=120)
        assert res2.state == "FINISHED"
    finally:
        cluster.shutdown()


def test_cancel_via_dispatcher():
    cluster = StandaloneSessionCluster(num_task_executors=1,
                                      slots_per_executor=2)
    try:
        client = cluster.client()
        plan, _ = _plan(n=1_500_000)
        job_id = client.submit(plan, parallelism=2)
        time.sleep(0.2)
        client.cancel(job_id)
        res = client.wait_for_completion(job_id, timeout_s=60)
        assert res.state == "CANCELED"
    finally:
        cluster.shutdown()


def test_savepoint_via_dispatcher():
    storages = {}
    cluster = StandaloneSessionCluster(
        num_task_executors=1, slots_per_executor=2,
        checkpoint_storage_factory=lambda jid: storages.setdefault(
            jid, InMemoryCheckpointStorage()))
    try:
        client = cluster.client()
        plan, _ = _plan(n=1_500_000)
        job_id = client.submit(plan, parallelism=2)
        time.sleep(0.3)
        sp = client.savepoint(job_id)
        assert sp is not None
        assert storages[job_id].load(sp) is not None
        client.cancel(job_id)
        client.wait_for_completion(job_id, timeout_s=60)
    finally:
        cluster.shutdown()


def _recovery_plan_builder(spec):
    plan, _sink = _plan(n=spec["n"], keys=spec["keys"])
    return plan


def test_dispatcher_recovers_persisted_jobs(tmp_path):
    """Leader failover: a NEW dispatcher re-submits unfinished persisted
    jobs (rebuilt from the picklable spec) and restores them from their
    latest checkpoint."""
    ha = HaServices(str(tmp_path / "ha"))
    storages = {}

    def factory(jid):
        return storages.setdefault(jid, InMemoryCheckpointStorage())

    c1 = StandaloneSessionCluster(num_task_executors=1, slots_per_executor=2,
                                  ha_services=ha,
                                  checkpoint_storage_factory=factory,
                                  plan_builder=_recovery_plan_builder)
    client = c1.client()
    spec = {"n": 2_000_000, "keys": 13}
    plan, _ = _plan(n=spec["n"], keys=spec["keys"])
    job_id = client.submit(plan, parallelism=2, checkpoint_interval_ms=10,
                           job_spec=spec)
    time.sleep(0.6)
    # "leader dies" without finishing the job
    c1.shutdown()
    assert ha.job_ids() == [job_id]
    # new leader recovers and finishes it
    c2 = StandaloneSessionCluster(num_task_executors=1, slots_per_executor=2,
                                  ha_services=ha,
                                  checkpoint_storage_factory=factory,
                                  plan_builder=_recovery_plan_builder)
    try:
        client2 = c2.client()
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            jobs = client2.list_jobs()
            if jobs:
                st = client2.status(jobs[0])
                if st["status"] == "FINISHED":
                    break
            time.sleep(0.1)
        assert ha.job_ids() == []   # finished job removed from HA store
    finally:
        c2.shutdown()


def test_executor_loss_drops_slots():
    cluster = StandaloneSessionCluster(num_task_executors=2,
                                      slots_per_executor=1)
    try:
        client = cluster.client()
        assert client.overview()["slots_total"] == 2
        # kill one TE: heartbeats stop answering -> RM unregisters it
        cluster.rpc.stop_endpoint("taskexecutor-1")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if client.overview()["slots_total"] == 1:
                break
            time.sleep(0.1)
        assert client.overview() == {"task_executors": 1, "slots_total": 1,
                                     "slots_free": 1}
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_run_script(tmp_path):
    script = tmp_path / "wordjob.py"
    script.write_text(
        "import numpy as np\n"
        "(env.from_collection(columns={'k': np.arange(100) % 5,\n"
        "                              'v': np.ones(100)})\n"
        "    .key_by('k').sum('v').print())\n")
    out = subprocess.run(
        [sys.executable, "-m", "flink_tpu", "run", str(script)],
        capture_output=True, text=True, timeout=300, cwd="/root/repo")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "job finished" in out.stdout


def test_cli_sql(tmp_path):
    import flink_tpu.formats as formats
    from flink_tpu.core.batch import RecordBatch

    p = tmp_path / "t.csv"
    formats.write_csv([RecordBatch({"k": np.array([1, 1, 2]),
                                    "v": np.array([1., 2., 3.])})], str(p))
    out = subprocess.run(
        [sys.executable, "-m", "flink_tpu", "sql",
         "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k",
         "--table", f"t={p}"],
        capture_output=True, text=True, timeout=300, cwd="/root/repo")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "3.0" in out.stdout


def test_cli_info():
    out = subprocess.run([sys.executable, "-m", "flink_tpu", "info"],
                         capture_output=True, text=True, timeout=300,
                         cwd="/root/repo")
    assert out.returncode == 0
    assert "native layer: ok" in out.stdout
