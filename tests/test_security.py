"""Transport security: mutual TLS on the data plane, token-guarded control
plane, TLS + bearer-token REST (``SecurityOptions`` analog)."""

import json
import ssl
import urllib.request

import numpy as np
import pytest

from flink_tpu.security import SecurityConfig, generate_self_signed

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key, ca = generate_self_signed(str(d))
    return cert, key, ca


def make_config(certs, token=None):
    cert, key, ca = certs
    return SecurityConfig(internal_ssl=True, rest_ssl=True, cert_path=cert,
                          key_path=key, ca_path=ca, auth_token=token)


def test_data_plane_mutual_tls(certs):
    from flink_tpu.cluster.net import ChannelServer, RemoteChannel
    from flink_tpu.core.batch import RecordBatch

    sec = make_config(certs)
    server = ChannelServer(ssl_context=sec.server_context())
    try:
        w = RemoteChannel(server.host, server.port, "tls-ch",
                          ssl_context=sec.client_context())
        q = server.channel("tls-ch")
        assert w.put(RecordBatch({"x": np.arange(10)}))
        got = q.poll(timeout_s=5)
        assert got is not None and len(got) == 10
        w.close()
    finally:
        server.stop()


def test_data_plane_tls_rejects_plaintext_peer(certs):
    from flink_tpu.cluster.net import ChannelServer, RemoteChannel

    sec = make_config(certs)
    server = ChannelServer(ssl_context=sec.server_context())
    try:
        # no client context: the TLS handshake cannot complete and the
        # channel never becomes writable (no credits arrive)
        from flink_tpu.core.batch import RecordBatch
        w = RemoteChannel(server.host, server.port, "plain")
        assert not w.put(RecordBatch({"x": np.arange(1)}), timeout_s=1.0)
        w.close()
    finally:
        server.stop()


def test_rest_tls_and_bearer_token(certs):
    from flink_tpu.rest.server import JobRegistry, RestServer

    sec = make_config(certs, token="s3cret")
    server = RestServer(JobRegistry(), ssl_context=sec.server_context(
        mutual=False), auth_token="s3cret").start()
    try:
        cert, key, ca = certs
        ctx = ssl.create_default_context(cafile=ca)
        ctx.check_hostname = False

        req = urllib.request.Request(
            f"{server.url}/overview",
            headers={"Authorization": "Bearer s3cret"})
        with urllib.request.urlopen(req, context=ctx, timeout=10) as r:
            assert json.loads(r.read())["jobs_total"] == 0

        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                urllib.request.Request(f"{server.url}/overview"),
                context=ctx, timeout=10)
        assert e.value.code == 401
    finally:
        server.stop()


def test_security_config_from_configuration(certs):
    from flink_tpu.config.config_option import Configuration
    from flink_tpu.config.options import SecurityOptions as S
    from flink_tpu.security import load_security_config

    cert, key, ca = certs
    conf = Configuration()
    conf.set(S.SSL_INTERNAL_ENABLED, True)
    conf.set(S.SSL_CERT, cert)
    conf.set(S.SSL_KEY, key)
    conf.set(S.SSL_CA, ca)
    conf.set(S.AUTH_TOKEN, "tok")
    sec = load_security_config(conf)
    assert sec.internal_ssl and not sec.rest_ssl
    assert sec.server_context() is not None
    nonce = b"x" * 32
    assert sec.verify(nonce, sec.sign(nonce))
    assert not sec.verify(nonce, b"bad")


def test_process_cluster_with_tls_and_token(certs, tmp_path):
    """End to end: a 2-process job where control AND data plane run over
    mutual TLS and workers must answer the token challenge."""
    import sys
    import textwrap

    from flink_tpu.cluster.distributed import ProcessCluster

    mod = tmp_path / "sec_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(2)
            n = 5000
            keys = (np.arange(n) % 5).astype(np.int64)
            (env.from_collection(columns={"k": keys, "v": np.ones(n)},
                                 batch_size=256)
                .key_by("k").sum("v").collect())
            return env.get_stream_graph("secure-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        sec = make_config(certs, token="cluster-secret")
        pc = ProcessCluster("sec_job_mod:build", n_workers=2,
                            extra_sys_path=(str(tmp_path),), security=sec)
        res = pc.run(timeout_s=180)
        assert res["state"] == "FINISHED", res["error"]
        last = {}
        for r in res["rows"]:
            last[r["k"]] = r["v"]
        assert last == {i: 1000.0 for i in range(5)}
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("sec_job_mod", None)
