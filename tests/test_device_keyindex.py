"""Device-resident key probe (ISSUE 7 tentpole contract).

``WindowAggOperator(device_probe=...)`` resolves warm keys ON the device,
inside the jitted step, via ``state/device_keyindex.py``: warm-row
contributions accumulate in mirror-precision delta arrays and the host C
pass touches only misses.  The probe is a pure scheduling/placement change:
fire digests, snapshots, and counters must be BIT-identical with the probe
on vs off — on the host tier under both sync cadences, with the numpy
mirror fallback, under paging, across mesh sizes, and through a mid-batch
WedgedDevice quarantine.  Steady state (a second pass over identical keys)
must show ZERO host fold work via the miss counters, and capacity must be
sticky: exactly one XLA compile per (table capacity, K_cap, batch
geometry).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.state.keyindex import KeyIndex
from flink_tpu.state.device_keyindex import (DeviceKeyIndex, lax_probe,
                                             probe_impl)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _mk_op(device_probe="off", emit_tier="host", device_sync="scatter",
           native=True, paging=None, **kw):
    if paging is not None:
        emit_tier = "device"
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(100), SumAggregator(jnp.float32),
        key_column="k", value_column="v", emit_tier=emit_tier,
        snapshot_source="mirror" if emit_tier == "host" else "device",
        device_sync=device_sync if emit_tier == "host" else "scatter",
        native_emit=native, paging=paging, device_probe=device_probe, **kw)
    op.open(RuntimeContext())
    return op


def _digests(out):
    return [(int(np.asarray(b.column("window_start"))[0]), len(b),
             np.asarray(b.column("k")).tobytes(),
             np.asarray(b.column("result")).tobytes())
            for b in out if hasattr(b, "columns") and "result" in b.columns]


def _counters(op):
    return {
        "late_dropped": op.late_dropped,
        "num_keys": op.key_index.num_keys if op.key_index else 0,
        "watermark": op.watermark,
        "last_fired_window": op.last_fired_window,
    }


def _assert_snap_equal(a, b):
    assert set(a) == set(b), set(a) ^ set(b)
    for k in sorted(a):
        va, vb = a[k], b[k]
        if isinstance(va, np.ndarray):
            assert np.array_equal(va, np.asarray(vb)), k
        elif isinstance(va, (list, tuple)):
            for x, y in zip(va, vb):
                assert np.array_equal(np.asarray(x), np.asarray(y)), k
        elif isinstance(va, dict):
            continue  # key_index internals: covered by digest equality
        else:
            assert va == vb, k


def _seeded_run(op, n_batches=10, nk=1500, b=4000, seed=11, snap_at=6):
    rng = np.random.default_rng(seed)
    out, snap = [], None
    for i in range(n_batches):
        keys = rng.integers(0, nk, b).astype(np.int64)
        vals = rng.random(b).astype(np.float32)
        ts = i * 50 + np.sort(rng.integers(0, 50, b)).astype(np.int64)
        out += op.process_batch(RecordBatch({"k": keys, "v": vals},
                                            timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        if i == snap_at:
            op.prepare_snapshot_pre_barrier()
            snap = op.snapshot_state()
    out += op.end_input()
    counters = _counters(op)
    return _digests(out), snap, counters


# ---------------------------------------------------------------------------
# the table itself
# ---------------------------------------------------------------------------

def test_lax_probe_matches_keyindex_lookup(rng):
    keys = rng.integers(-2 ** 62, 2 ** 62, 5000).astype(np.int64)
    keys = np.concatenate([keys, keys[:700]])          # duplicates
    ki = KeyIndex()
    ki.lookup_or_insert(keys)
    dki = DeviceKeyIndex(initial_capacity=1 << 10)     # forces growth
    assert dki.ensure_loaded(ki) == ki.num_keys
    klo, khi, start = dki.prepare_batch(keys)
    got = np.asarray(jax.jit(lax_probe)(
        *dki.table(), jnp.asarray(klo), jnp.asarray(khi),
        jnp.asarray(start)))
    assert np.array_equal(got, ki.lookup(keys))
    # unseen keys miss
    unk = rng.integers(2 ** 62, 2 ** 63 - 1, 200).astype(np.int64)
    klo, khi, start = dki.prepare_batch(unk)
    got = np.asarray(jax.jit(lax_probe)(
        *dki.table(), jnp.asarray(klo), jnp.asarray(khi),
        jnp.asarray(start)))
    assert np.array_equal(got, ki.lookup(unk))


def test_incremental_insert_and_sticky_growth(rng):
    ki = KeyIndex()
    dki = DeviceKeyIndex(initial_capacity=1 << 10)
    cap_seen = []
    for wave in range(4):
        keys = rng.integers(0, 1 << 40, 2000).astype(np.int64)
        ki.lookup_or_insert(keys)
        dki.ensure_loaded(ki)
        cap_seen.append(dki.capacity)
        klo, khi, start = dki.prepare_batch(keys)
        got = np.asarray(jax.jit(lax_probe)(
            *dki.table(), jnp.asarray(klo), jnp.asarray(khi),
            jnp.asarray(start)))
        assert np.array_equal(got, ki.lookup(keys)), f"wave {wave}"
    # sticky pow2 high-water: never shrinks, always a power of two
    assert all(c & (c - 1) == 0 for c in cap_seen)
    assert cap_seen == sorted(cap_seen)
    assert ki.num_keys <= dki.capacity // 2  # load factor <= 0.5 held


def test_probe_impl_is_lax_on_cpu():
    """Tier-1 runs under JAX_PLATFORMS=cpu: the Pallas kernel must stay
    behind its capability check and the pure-lax fallback must serve."""
    name, fn = probe_impl(1 << 16)
    assert name == "lax" and fn is lax_probe


# ---------------------------------------------------------------------------
# digest equality: probe on vs off, every tier
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sync", ["scatter", "deferred"])
def test_host_tier_bit_identical_probe_on_off(sync):
    ref = _seeded_run(_mk_op("off", device_sync=sync))
    got = _seeded_run(_mk_op("on", device_sync=sync))
    assert got[0] == ref[0], f"fire digests diverged under {sync}"
    _assert_snap_equal(got[1], ref[1])
    assert got[2] == ref[2]


def test_numpy_mirror_fallback_bit_identical():
    """native_emit=False pins the numpy value mirror: the delta applies
    through the numpy twin instead of wm_apply_delta — same digests."""
    ref = _seeded_run(_mk_op("off", native=False))
    got = _seeded_run(_mk_op("on", native=False))
    assert got[0] == ref[0]
    _assert_snap_equal(got[1], ref[1])
    assert got[2] == ref[2]


def test_steady_state_zero_host_fold_work(rng):
    """The acceptance assertion: a second pass over IDENTICAL keys must
    resolve entirely on device — the host C fold touches zero rows (the
    miss counters do not move)."""
    op = _mk_op("on")
    keys = rng.integers(0, 4096, 8192).astype(np.int64)
    vals = rng.random(8192).astype(np.float32)
    op.process_batch(RecordBatch(
        {"k": keys, "v": vals},
        timestamps=np.full(8192, 10, np.int64)))
    s1 = op.device_probe_stats()
    assert s1["enabled"] and s1["probe_misses"] == 8192  # empty table
    op.process_batch(RecordBatch(
        {"k": keys, "v": vals},
        timestamps=np.full(8192, 20, np.int64)))
    s2 = op.device_probe_stats()
    assert s2["probe_misses"] == s1["probe_misses"], \
        "second pass over identical keys reached the host fold"
    assert s2["probe_hits"] == s1["probe_hits"] + 8192
    assert s2["miss_inserts"] == op.key_index.num_keys
    out = op.process_watermark(Watermark(10_000))
    total = sum(float(np.asarray(b.column("result"), np.float64).sum())
                for b in out if hasattr(b, "columns"))
    assert total == pytest.approx(2.0 * float(vals.astype(np.float64).sum()))
    op.close()


def test_restore_into_probe_off_operator_and_back():
    """Snapshots are probe-agnostic: a probe-on snapshot restores into a
    probe-off operator (and vice versa) with identical remainder fires."""
    rng = np.random.default_rng(5)
    batches = []
    for i in range(8):
        keys = rng.integers(0, 1000, 3000).astype(np.int64)
        vals = rng.random(3000).astype(np.float32)
        ts = i * 50 + np.sort(rng.integers(0, 50, 3000)).astype(np.int64)
        batches.append((keys, vals, ts))

    def run_from(op, start, out):
        for keys, vals, ts in batches[start:]:
            out += op.process_batch(RecordBatch({"k": keys, "v": vals},
                                                timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
        out += op.end_input()
        return _digests(out)

    for src_probe in ("on", "off"):
        src = _mk_op(src_probe)
        for keys, vals, ts in batches[:4]:
            src.process_batch(RecordBatch({"k": keys, "v": vals},
                                          timestamps=ts))
            src.process_watermark(Watermark(int(ts.max()) - 1))
        src.prepare_snapshot_pre_barrier()
        mid = src.snapshot_state()
        # the SAME snapshot restored under either probe mode must replay
        # the remainder identically (restored state is f32-cast either
        # way, so restored-vs-restored is the apples-to-apples compare)
        runs = {}
        for dst_probe in ("on", "off"):
            dst = _mk_op(dst_probe)
            dst.restore_state(mid)
            runs[dst_probe] = run_from(dst, 4, [])
        assert runs["on"] == runs["off"], \
            f"restore of a probe-{src_probe} snapshot diverged by probe mode"


# ---------------------------------------------------------------------------
# paging: the probe is structurally ineligible there (gid->row translation
# is host work per batch) — requesting it must degrade to OFF, not break
# ---------------------------------------------------------------------------

def test_paging_64k_cap_256k_keys_probe_request_is_noop():
    from flink_tpu.state.paging import PagingConfig

    def run(device_probe, tmp):
        op = _mk_op(device_probe,
                    paging=PagingConfig(capacity=1 << 16, directory=tmp))
        rng = np.random.default_rng(3)
        out = []
        n_keys = 1 << 18
        for i in range(4):
            keys = rng.integers(0, n_keys, 1 << 15).astype(np.int64)
            vals = rng.random(1 << 15).astype(np.float32)
            ts = i * 50 + np.sort(
                rng.integers(0, 50, 1 << 15)).astype(np.int64)
            out += op.process_batch(RecordBatch({"k": keys, "v": vals},
                                                timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
        out += op.end_input()
        stats = op.device_probe_stats()
        op.close()
        return _digests(out), stats

    import tempfile
    with tempfile.TemporaryDirectory() as t1, \
            tempfile.TemporaryDirectory() as t2:
        ref, _ = run("off", t1)
        got, stats = run("on", t2)
    assert got == ref
    assert stats["enabled"] == 0 and stats["probe_hits"] == 0


# ---------------------------------------------------------------------------
# mesh: one logical operator, probe on vs off at mesh 1 v 2
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sync", ["scatter", "deferred"])
def test_mesh_1v2_bit_identical_probe_on_off(sync):
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator

    def mk(device_probe, D):
        op = MeshWindowAggOperator(
            TumblingEventTimeWindows.of(100), SumAggregator(jnp.float32),
            key_column="k", value_column="v", emit_tier="host",
            snapshot_source="mirror", device_sync=sync,
            device_probe=device_probe, mesh=make_mesh(D),
            initial_key_capacity=2048)
        op.open(RuntimeContext(max_parallelism=128))
        return op

    ref = _seeded_run(mk("off", 1), n_batches=6)
    for D in (1, 2):
        got = _seeded_run(mk("on", D), n_batches=6)
        assert got[0] == ref[0], f"mesh x{D} fire digests diverged"
        assert got[2] == ref[2]


# ---------------------------------------------------------------------------
# quarantine: mid-batch WedgedDevice with the probe active
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_mid_batch_wedge_quarantine_digest_identical():
    from flink_tpu.runtime import device_health as dh
    from flink_tpu.testing import chaos

    rng = np.random.default_rng(7)
    batches = []
    for i in range(20):
        k = rng.integers(0, 64, 512).astype(np.int64)
        v = np.ones(512, np.float32)
        ts = i * 50 + np.sort(rng.integers(0, 50, 512)).astype(np.int64)
        batches.append((k, v, ts))

    def one_pass(device_probe, inject):
        prev = dh.get_monitor(create=False)
        dh.set_monitor(dh.DeviceHealthMonitor(
            dh.WatchdogConfig(deadline_floor_s=0.5), heal_async=False))
        inj = chaos.FaultInjector(seed=3)
        sched = (inj.inject("device.dispatch", chaos.WedgedDevice(at=8))
                 if inject else None)
        op = _mk_op(device_probe)
        out = []
        snap_degraded = False
        try:
            with chaos.installed(inj):
                for i, (k, v, ts) in enumerate(batches):
                    out += op.process_batch(
                        RecordBatch({"k": k, "v": v}, timestamps=ts))
                    out += op.process_watermark(Watermark(int(ts.max()) - 1))
                    if inject and i == 12:
                        op.prepare_snapshot_pre_barrier()
                        op.snapshot_state()   # checkpoint DURING quarantine
                        snap_degraded = op._degraded
                        sched.heal()
                        dh.get_monitor().probe_now()
                    if inject and i == 16:
                        out += op.prepare_snapshot_pre_barrier()
                out += op.end_input()
            stats = op.device_health_stats()
            op.close()
        finally:
            dh.set_monitor(prev)
        return _digests(out), stats, snap_degraded

    clean, _s, _d = one_pass("off", False)
    wedged, stats, snap_degraded = one_pass("on", True)
    assert wedged == clean, "wedged probe-on run diverged from clean run"
    assert stats["quarantine_migrations"] == 1
    assert stats["repromotions"] == 1 and stats["degraded"] == 0
    assert snap_degraded, "snapshot did not run during quarantine"


# ---------------------------------------------------------------------------
# compile discipline: sticky capacity, one compile per geometry
# ---------------------------------------------------------------------------

def test_compile_once_per_table_capacity_and_geometry(rng):
    # pre-sized K: key growth is a LEGITIMATE recompile (K_cap is part of
    # the geometry), so pin it to isolate the sticky-table-capacity claim
    op = _mk_op("on", initial_key_capacity=4096)
    base = op.devprobe_step_cache_size()["_probed_update_step"]
    if base < 0:
        pytest.skip("jax without the jit cache probe")
    keys = rng.integers(0, 2000, 4096).astype(np.int64)
    for i in range(6):
        vals = rng.random(4096).astype(np.float32)
        ts = np.full(4096, 10 + i, np.int64)
        op.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))
    sizes = op.devprobe_step_cache_size()
    # same keys, same geometry, capacity sticky: exactly ONE compile
    assert sizes["_probed_update_step"] - base == 1, sizes
    cap0 = op._dki.capacity
    # force a capacity growth: a burst of fresh keys past the load factor.
    # The growth batch itself compiles once at the OLD capacity (its probe
    # ran before the misses inserted) with the new batch geometry, and the
    # first steady batch compiles once at the NEW (capacity, K) — then the
    # cache must go quiet.
    many = rng.integers(1 << 40, 1 << 41, 40_000).astype(np.int64)
    for i in range(4):
        op.process_batch(RecordBatch(
            {"k": many, "v": np.ones(many.size, np.float32)},
            timestamps=np.full(many.size, 20 + i, np.int64)))
    assert op._dki.capacity > cap0
    grown = op.devprobe_step_cache_size()["_probed_update_step"]
    assert grown - sizes["_probed_update_step"] == 2, \
        "sticky capacity failed: steady state kept recompiling"
    op.close()


def test_device_probe_stats_surface():
    op = _mk_op("on")
    s = op.device_probe_stats()
    assert set(s) >= {"enabled", "probe_hits", "probe_misses",
                      "miss_inserts", "delta_syncs", "probe_hit_rate",
                      "delta_d2h_bytes"}
    op.process_batch(RecordBatch(
        {"k": np.arange(100, dtype=np.int64),
         "v": np.ones(100, np.float32)},
        timestamps=np.full(100, 10, np.int64)))
    op.process_watermark(Watermark(1000))
    s = op.device_probe_stats()
    assert s["enabled"] == 1
    assert s["probe_hits"] + s["probe_misses"] == 100
    assert s["delta_d2h_bytes"] >= 0
    op.close()
