"""Adaptive scheduler (reactive rescale), failover strategies, pipelined-
region restart, HA leader election."""

import os
import threading
import time

import numpy as np
import pytest

from flink_tpu import formats
from flink_tpu.cluster.adaptive import (AdaptiveScheduler, SchedulerStates,
                                        rescale_snapshot)
from flink_tpu.cluster.failover import (ExponentialDelayRestartStrategy,
                                        FailureRateRestartStrategy,
                                        FixedDelayRestartStrategy,
                                        pipelined_regions)
from flink_tpu.cluster.ha import FileLeaderElection, HaServices
from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.cluster.task import TaskStates
from flink_tpu.core.batch import RecordBatch
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# restart strategies
# ---------------------------------------------------------------------------

def test_fixed_delay_strategy():
    s = FixedDelayRestartStrategy(attempts=2, delay_ms=7)
    for expected in (True, True, False):
        s.notify_failure()
        assert s.can_restart() == expected
    assert s.delay_ms() == 7


def test_exponential_strategy_backs_off():
    s = ExponentialDelayRestartStrategy(initial_delay_ms=10, max_delay_ms=50,
                                        backoff_multiplier=2.0)
    s.notify_failure()
    d1 = s.delay_ms()
    s.notify_failure()
    d2 = s.delay_ms()
    s.notify_failure()
    s.notify_failure()
    s.notify_failure()
    assert d1 == 10 and d2 == 20 and s.delay_ms() == 50  # capped


def test_failure_rate_strategy():
    s = FailureRateRestartStrategy(max_failures=2, interval_ms=60_000)
    s.notify_failure()
    s.notify_failure()
    assert s.can_restart()
    s.notify_failure()
    assert not s.can_restart()


# ---------------------------------------------------------------------------
# pipelined regions
# ---------------------------------------------------------------------------

def _two_region_env():
    env = StreamExecutionEnvironment()
    a = (env.from_collection(columns={"k": np.arange(1000) % 5,
                                      "v": np.ones(1000)}, batch_size=64)
         .key_by("k").sum("v").collect())
    b = (env.from_collection(columns={"x": np.arange(500, dtype=np.int64)},
                             batch_size=64)
         .map(lambda c: {"x": np.asarray(c["x"]) * 2}).collect())
    return env, a, b


def test_pipelined_regions_found():
    env, _a, _b = _two_region_env()
    plan = env.get_stream_graph().to_plan()
    regions = pipelined_regions(plan)
    assert len(regions) == 2
    assert {len(r) >= 1 for r in regions} == {True}


def test_region_restart_leaves_other_region_running():
    """A poisoned vertex in one region restarts only that region."""
    boom = {"n": 0, "armed": True}

    def poison(cols):
        boom["n"] += 1
        if boom["armed"] and boom["n"] == 3:
            boom["armed"] = False
            raise RuntimeError("region failure")
        return cols

    env = StreamExecutionEnvironment()
    a = (env.from_collection(columns={"k": np.arange(2000) % 5,
                                      "v": np.ones(2000)}, batch_size=64)
         .map(poison).key_by("k").sum("v").collect())
    b = (env.from_collection(columns={"x": np.arange(2000, dtype=np.int64)},
                             batch_size=64)
         .map(lambda c: {"x": np.asarray(c["x"])}).collect())
    plan = env.get_stream_graph().to_plan()
    storage = InMemoryCheckpointStorage()
    mc = MiniCluster(checkpoint_storage=storage, checkpoint_interval_ms=5,
                     restart_attempts=2)
    res = mc.execute(plan, timeout_s=120)
    assert res.state == TaskStates.FINISHED
    assert res.restarts >= 1
    # both sinks produced complete results
    final = {}
    for r in a.rows():
        final[r["k"]] = r["v"]
    assert final and all(v == 400.0 for v in final.values())
    assert len(b.rows()) == 2000


# ---------------------------------------------------------------------------
# adaptive rescale
# ---------------------------------------------------------------------------

def test_adaptive_rescale_mid_job(tmp_path):
    """Start at parallelism 1, declare 3 slots mid-run: the scheduler takes
    a savepoint, re-splits keyed state by key-group, and finishes correctly
    at the new parallelism (reactive mode)."""
    from flink_tpu.connectors.partitioned_log import LogSink, PartitionedLog
    from flink_tpu.connectors.file_source import FileSource
    from flink_tpu.connectors.sinks import CollectSink

    # stable-split source: 2 files regardless of job parallelism
    n = 120_000
    for i in range(2):
        lo = i * (n // 2)
        formats.write_csv(
            [RecordBatch({"k": (np.arange(lo, lo + n // 2) % 31),
                          "v": np.ones(n // 2)})],
            str(tmp_path / f"in{i}.csv"))
    sink = CollectSink()

    def plan_factory(parallelism):
        env = StreamExecutionEnvironment()
        env.set_parallelism(parallelism)
        (env.from_source(FileSource(str(tmp_path), format="csv",
                                    batch_size=256))
         .key_by("k").sum("v").add_sink(sink))
        return env.get_stream_graph("adaptive-job").to_plan()

    storage = InMemoryCheckpointStorage(retain=5)
    sched = AdaptiveScheduler(plan_factory, checkpoint_storage=storage,
                              checkpoint_interval_ms=10)
    sched.start()
    sched.declare_slots(1)
    time.sleep(0.4)
    sched.declare_slots(3)             # reactive scale-up mid-run
    result = sched.join(timeout_s=180)
    assert sched.state == SchedulerStates.FINISHED, sched.state
    assert sched.rescales >= 1
    final = {}
    for r in sink.rows():
        final[int(r["k"])] = r["v"]
    expect = {}
    for k in (np.arange(n) % 31).tolist():
        expect[k] = expect.get(k, 0) + 1.0
    assert final == expect, "exactly-once across rescale violated"


def test_rescale_snapshot_errors_on_unstable_source():
    env = StreamExecutionEnvironment()
    (env.from_collection(columns={"k": np.arange(10) % 2,
                                  "v": np.ones(10)})
     .key_by("k").sum("v").collect())
    plan = env.get_stream_graph().to_plan()
    src_uid = next(v.uid for v in plan.vertices if v.is_source)
    snap = {src_uid: {"subtasks": [{"operator": {}, "source_offset": 1}]}}
    with pytest.raises(ValueError, match="stable-split"):
        rescale_snapshot(snap, plan, {v.uid: 3 for v in plan.vertices})


# ---------------------------------------------------------------------------
# HA leader election
# ---------------------------------------------------------------------------

def test_leader_election_single_winner(tmp_path):
    path = str(tmp_path / "leader")
    a = FileLeaderElection(path, "a", lease_ms=300, renew_ms=30).start()
    b = FileLeaderElection(path, "b", lease_ms=300, renew_ms=30).start()
    try:
        time.sleep(0.3)
        assert a.is_leader != b.is_leader          # exactly one leader
        leader, follower = (a, b) if a.is_leader else (b, a)
        # leader dies -> follower takes over after the lease expires
        leader.stop(abdicate=False)
        deadline = time.monotonic() + 5
        while not follower.is_leader and time.monotonic() < deadline:
            time.sleep(0.05)
        assert follower.is_leader
    finally:
        a.stop()
        b.stop()


def test_leader_abdication_hands_over_fast(tmp_path):
    path = str(tmp_path / "leader")
    a = FileLeaderElection(path, "a", lease_ms=2000, renew_ms=30).start()
    time.sleep(0.2)
    assert a.is_leader
    b = FileLeaderElection(path, "b", lease_ms=2000, renew_ms=30).start()
    a.stop(abdicate=True)                          # clean handover
    deadline = time.monotonic() + 5
    while not b.is_leader and time.monotonic() < deadline:
        time.sleep(0.05)
    assert b.is_leader
    b.stop()


def test_ha_services_persist_and_recover(tmp_path):
    ha = HaServices(str(tmp_path / "ha"))
    ha.persist_job("j1", {"name": "my-job", "plan": [1, 2, 3]})
    ha.set_latest_checkpoint("j1", 7)
    # the NEW leader process reads everything back
    ha2 = HaServices(str(tmp_path / "ha"))
    assert ha2.job_ids() == ["j1"]
    assert ha2.load_job("j1")["name"] == "my-job"
    assert ha2.latest_checkpoint("j1") == 7
    ha2.remove_job("j1")
    assert ha2.job_ids() == []


def test_adaptive_double_declare_race(tmp_path):
    """Regression: slots changing AGAIN while a rescale is in progress must
    re-split the snapshot for the parallelism actually deployed (a split for
    the stale target silently dropped/misrouted key-group ranges)."""
    from flink_tpu.connectors.file_source import FileSource
    from flink_tpu.connectors.sinks import CollectSink

    n = 90_000
    for i in range(3):
        lo = i * (n // 3)
        formats.write_csv(
            [RecordBatch({"k": (np.arange(lo, lo + n // 3) % 41),
                          "v": np.ones(n // 3)})],
            str(tmp_path / f"in{i}.csv"))
    sink = CollectSink()

    def plan_factory(parallelism):
        env = StreamExecutionEnvironment()
        env.set_parallelism(parallelism)
        (env.from_source(FileSource(str(tmp_path), format="csv",
                                    batch_size=256))
         .key_by("k").sum("v").add_sink(sink))
        return env.get_stream_graph("race-job").to_plan()

    storage = InMemoryCheckpointStorage(retain=5)
    sched = AdaptiveScheduler(plan_factory, checkpoint_storage=storage,
                              checkpoint_interval_ms=10)
    sched.start()
    sched.declare_slots(1)
    time.sleep(0.25)
    sched.declare_slots(4)     # rescale target captured...
    time.sleep(0.02)
    sched.declare_slots(2)     # ...then changed before redeploy
    sched.join(timeout_s=180)
    assert sched.state == SchedulerStates.FINISHED, sched.state
    final = {}
    for r in sink.rows():
        final[int(r["k"])] = r["v"]
    expect = {}
    for k in (np.arange(n) % 41).tolist():
        expect[k] = expect.get(k, 0) + 1.0
    assert final == expect


def test_scheduler_surfaces_rescale_errors():
    """Regression: an exception in the scheduler loop (e.g. rescaling an
    unstable-split source) must surface as FAILED, not a silently dead
    thread."""
    def plan_factory(parallelism):
        env = StreamExecutionEnvironment()
        env.set_parallelism(parallelism)
        n = 200_000
        (env.from_collection(columns={"k": np.arange(n) % 7,
                                      "v": np.ones(n)}, batch_size=128)
         .key_by("k").sum("v").collect())
        return env.get_stream_graph().to_plan()

    storage = InMemoryCheckpointStorage()
    sched = AdaptiveScheduler(plan_factory, checkpoint_storage=storage,
                              checkpoint_interval_ms=10)
    sched.start()
    sched.declare_slots(1)
    time.sleep(0.3)
    sched.declare_slots(2)   # collection source: splits change -> rescale fails
    sched.join(timeout_s=60)
    assert sched.state in (SchedulerStates.FAILED, SchedulerStates.FINISHED)
    if sched.state == SchedulerStates.FAILED:
        assert "stable-split" in sched.error
