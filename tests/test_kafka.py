"""Kafka binary wire protocol (closing the 'Kafka's wire protocol is NOT
spoken' gap): frame/message-set encoding with CRC verification, client ↔
broker over real TCP frames, raw hand-built requests (client
independence), persistence across broker restarts, and the source/sink
seams feeding a pipeline.

Environment note: no real Kafka broker exists in this image (no JVM
Kafka, no kafka-python), so ground truth is the published v0 wire format
(fixed framing + CRC32 message sets) exercised by BOTH an independent
raw-socket test and the structured client.
"""

import json
import socket
import struct
import zlib

import numpy as np
import pytest

from flink_tpu.connectors.kafka import (KafkaWireBroker, KafkaWireClient,
                                        KafkaWireSink, KafkaWireSource,
                                        decode_message_set,
                                        encode_message_set,
                                        encode_message_v0)


@pytest.fixture
def broker(tmp_path):
    b = KafkaWireBroker(directory=str(tmp_path / "kafka")).start()
    yield b
    b.stop()


def test_message_v0_layout_and_crc():
    """The v0 message layout is fixed by the protocol: crc:uint32 magic:0
    attributes:0 key:bytes value:bytes, crc over magic..value."""
    m = encode_message_v0(b"k", b"hello")
    crc = struct.unpack(">I", m[:4])[0]
    assert crc == zlib.crc32(m[4:]) & 0xFFFFFFFF
    assert m[4] == 0 and m[5] == 0                 # magic, attributes
    assert struct.unpack(">i", m[6:10])[0] == 1    # key length
    assert m[10:11] == b"k"
    assert struct.unpack(">i", m[11:15])[0] == 5   # value length
    assert m[15:] == b"hello"
    # null key encodes as length -1
    m2 = encode_message_v0(None, b"x")
    assert struct.unpack(">i", m2[6:10])[0] == -1

    # roundtrip + corruption detection
    ms = encode_message_set([(7, b"k", b"v"), (8, None, b"w")])
    assert decode_message_set(ms) == [(7, b"k", b"v"), (8, None, b"w")]
    corrupted = ms[:14] + bytes([ms[14] ^ 0xFF]) + ms[15:]
    with pytest.raises(ValueError, match="CRC"):
        decode_message_set(corrupted)


def test_client_broker_roundtrip(broker):
    broker.create_topic("t", partitions=2)
    c = KafkaWireClient(broker.host, broker.port)
    try:
        versions = dict((k, (lo, hi)) for k, lo, hi in c.api_versions())
        # v0 stays supported; v2-era ranges advertised since round 4
        assert versions[0] == (0, 3) and versions[1] == (0, 7)
        assert versions[11] == (0, 0) and versions[14] == (0, 0)
        meta = c.metadata(["t"])
        assert meta["brokers"][0]["port"] == broker.port
        assert len(meta["topics"][0]["partitions"]) == 2

        base = c.produce("t", 0, [(b"a", b"1"), (b"b", b"2")])
        assert base == 0
        assert c.produce("t", 0, [(None, b"3")]) == 2
        msgs, hw = c.fetch("t", 0, 0)
        assert hw == 3
        assert [(o, k, v) for o, k, v in msgs] == \
            [(0, b"a", b"1"), (1, b"b", b"2"), (2, None, b"3")]
        # offset resume + latest
        msgs2, _ = c.fetch("t", 0, 2)
        assert msgs2 == [(2, None, b"3")]
        assert c.latest_offset("t", 0) == 3
        assert c.latest_offset("t", 1) == 0
        with pytest.raises(IndexError):
            c.fetch("t", 0, 99)
    finally:
        c.close()


def test_raw_socket_client_independence(broker):
    """Hand-built frames over a bare socket — no client class involved —
    must interoperate: the broker speaks the published wire format, not a
    private dialect."""
    broker.create_topic("raw", partitions=1)
    s = socket.create_connection((broker.host, broker.port), timeout=10)
    try:
        # Produce v0, hand-assembled: header + acks/timeout + topic array
        msg = encode_message_v0(None, b"payload")
        mset = struct.pack(">qi", 0, len(msg)) + msg
        body = (struct.pack(">hi", -1, 5000)
                + struct.pack(">i", 1)                       # 1 topic
                + struct.pack(">h", 3) + b"raw"
                + struct.pack(">i", 1)                       # 1 partition
                + struct.pack(">i", 0)
                + struct.pack(">i", len(mset)) + mset)
        header = (struct.pack(">hhi", 0, 0, 42)              # Produce v0
                  + struct.pack(">h", 4) + b"test")
        frame = header + body
        s.sendall(struct.pack(">i", len(frame)) + frame)
        (size,) = struct.unpack(">i", s.recv(4))
        resp = b""
        while len(resp) < size:
            resp += s.recv(size - len(resp))
        corr, n_topics = struct.unpack(">ii", resp[:8])
        assert corr == 42 and n_topics == 1
        tlen = struct.unpack(">h", resp[8:10])[0]
        assert resp[10:10 + tlen] == b"raw"
        _nparts, part, err, base = struct.unpack(
            ">iihq", resp[10 + tlen:10 + tlen + 18])
        assert (part, err, base) == (0, 0, 0)
    finally:
        s.close()
    # the structured client reads what the raw producer wrote
    c = KafkaWireClient(broker.host, broker.port)
    try:
        msgs, hw = c.fetch("raw", 0, 0)
        assert hw == 1 and msgs == [(0, None, b"payload")]
    finally:
        c.close()


def test_broker_persistence_across_restart(tmp_path):
    d = str(tmp_path / "klog")
    b1 = KafkaWireBroker(directory=d).start()
    b1.create_topic("dur", partitions=1)
    c1 = KafkaWireClient(b1.host, b1.port)
    c1.produce("dur", 0, [(b"k", b"v1"), (b"k", b"v2")])
    c1.close()
    b1.stop()

    b2 = KafkaWireBroker(directory=d).start()
    c2 = KafkaWireClient(b2.host, b2.port)
    try:
        msgs, hw = c2.fetch("dur", 0, 0)
        assert hw == 2 and [v for _, _, v in msgs] == [b"v1", b"v2"]
    finally:
        c2.close()
        b2.stop()


def test_kafka_source_sink_pipeline(broker):
    """A pipeline consumes a Kafka topic over the wire protocol and
    produces results back to another topic."""
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    broker.create_topic("in", partitions=2)
    broker.create_topic("out", partitions=1)
    c = KafkaWireClient(broker.host, broker.port)
    try:
        for p in range(2):
            for lo in range(0, 300, 100):
                c.produce("in", p, [
                    (None, json.dumps({"k": int(i % 5), "v": 1.0}).encode())
                    for i in range(lo, lo + 100)])

        env = StreamExecutionEnvironment()
        src = KafkaWireSource(broker.host, broker.port, "in")
        sink = KafkaWireSink(broker.host, broker.port, "out")
        (env.from_source(src).key_by("k")
            .sum("v", output_column="total").add_sink(sink))
        env.execute()

        rows = []
        msgs, _ = c.fetch("out", 0, 0, max_bytes=1 << 22)
        rows = [json.loads(v.decode()) for _, _, v in msgs]
        finals = {}
        for r in rows:
            finals[int(r["k"])] = max(finals.get(int(r["k"]), 0.0),
                                      r["total"])
        assert finals == {k: 120.0 for k in range(5)}
    finally:
        c.close()


def test_topic_metadata_survives_restart_and_bad_ids_rejected(tmp_path):
    """Review regressions: empty topics/partitions survive a broker
    restart (durable manifest); negative partition ids and offsets error
    instead of Python-indexing from the end."""
    d = str(tmp_path / "kmeta")
    b1 = KafkaWireBroker(directory=d).start()
    b1.create_topic("t", partitions=2)
    c1 = KafkaWireClient(b1.host, b1.port)
    c1.produce("t", 0, [(None, b"x")])     # partition 1 stays EMPTY
    with pytest.raises(ValueError):
        c1.produce("t", -1, [(None, b"y")])
    with pytest.raises(IndexError):
        c1.fetch("t", 0, -2)
    c1.close()
    b1.stop()

    b2 = KafkaWireBroker(directory=d).start()
    c2 = KafkaWireClient(b2.host, b2.port)
    try:
        meta = c2.metadata(["t"])
        assert len(meta["topics"][0]["partitions"]) == 2
        assert c2.latest_offset("t", 1) == 0     # empty partition intact
        assert c2.latest_offset("t", 0) == 1
    finally:
        c2.close()
        b2.stop()


# ---------------------------------------------------------------------------
# SASL/PLAIN (SaslHandshake v0/v1 + SaslAuthenticate v0)
# ---------------------------------------------------------------------------

def _rx(s, n):
    """Exact-length socket read (recv may short-read under load)."""
    from flink_tpu.connectors.kafka import KafkaWireBroker

    buf = KafkaWireBroker._recv_exact(s, n)
    assert buf is not None
    return buf


def _sasl_broker(**kw):
    from flink_tpu.connectors.kafka import KafkaWireBroker

    b = KafkaWireBroker(users={"alice": "secret"}, **kw).start()
    b.create_topic("t", partitions=1)
    return b


def test_sasl_plain_client_round_trip():
    b = _sasl_broker()
    try:
        c = KafkaWireClient(b.host, b.port, username="alice",
                            password="secret")
        assert c.produce("t", 0, [(b"k", b"v")]) == 0
        msgs, hw = c.fetch("t", 0, 0)
        assert hw == 1 and msgs == [(0, b"k", b"v")]
        c.close()
    finally:
        b.stop()


def test_sasl_wrong_password_and_unauthenticated_drop():
    from flink_tpu.connectors.kafka import KafkaError

    b = _sasl_broker()
    try:
        bad = KafkaWireClient(b.host, b.port, username="alice",
                              password="nope")
        with pytest.raises(KafkaError, match="authentication failed"):
            bad.metadata(["t"])
        # no credentials at all: the broker drops the connection on the
        # first data API (real-broker behavior), surfacing as OSError
        anon = KafkaWireClient(b.host, b.port)
        with pytest.raises(OSError):
            anon.metadata(["t"])
        anon.close()
    finally:
        b.stop()


def test_sasl_raw_frames():
    """Hand-built SaslHandshake + SaslAuthenticate frames over a bare
    socket: mechanism list, RFC 4616 NUL-joined token, then a metadata
    call proving the CONNECTION is what got authenticated."""
    b = _sasl_broker()
    s = socket.create_connection((b.host, b.port), timeout=10)
    try:
        # SaslHandshake v1: api 17, mechanism string "PLAIN"
        hs = (struct.pack(">hhi", 17, 1, 7) + struct.pack(">h", 4) + b"test"
              + struct.pack(">h", 5) + b"PLAIN")
        s.sendall(struct.pack(">i", len(hs)) + hs)
        (size,) = struct.unpack(">i", _rx(s, 4))
        resp = _rx(s, size)
        corr, err, nmech = struct.unpack(">ihi", resp[:10])
        assert (corr, err, nmech) == (7, 0, 2)  # PLAIN + SCRAM-SHA-256
        mlen = struct.unpack(">h", resp[10:12])[0]
        assert resp[12:12 + mlen] == b"PLAIN"
        # SaslAuthenticate v0: api 36, bytes = \0 user \0 password
        token = b"\0alice\0secret"
        au = (struct.pack(">hhi", 36, 0, 8) + struct.pack(">h", 4) + b"test"
              + struct.pack(">i", len(token)) + token)
        s.sendall(struct.pack(">i", len(au)) + au)
        (size,) = struct.unpack(">i", _rx(s, 4))
        resp = _rx(s, size)
        corr, err = struct.unpack(">ih", resp[:6])
        assert (corr, err) == (8, 0)
        # the authenticated connection can now call Metadata v0
        md = (struct.pack(">hhi", 3, 0, 9) + struct.pack(">h", 4) + b"test"
              + struct.pack(">i", 1) + struct.pack(">h", 1) + b"t")
        s.sendall(struct.pack(">i", len(md)) + md)
        (size,) = struct.unpack(">i", _rx(s, 4))
        assert size > 0 and struct.unpack(">i", _rx(s, 4))[0] == 9
    finally:
        s.close()
        b.stop()


def test_sasl_wrong_mechanism_and_missing_handshake():
    b = _sasl_broker()
    s = socket.create_connection((b.host, b.port), timeout=10)
    try:
        # unsupported mechanism
        hs = (struct.pack(">hhi", 17, 1, 1) + struct.pack(">h", 4) + b"test"
              + struct.pack(">h", 8) + b"SCRAM256")
        s.sendall(struct.pack(">i", len(hs)) + hs)
        (size,) = struct.unpack(">i", _rx(s, 4))
        resp = _rx(s, size)
        assert struct.unpack(">ih", resp[:6])[1] == 33  # UNSUPPORTED_SASL
        # authenticate without a successful handshake: ILLEGAL_SASL_STATE
        token = b"\0alice\0secret"
        au = (struct.pack(">hhi", 36, 0, 2) + struct.pack(">h", 4) + b"test"
              + struct.pack(">i", len(token)) + token)
        s.sendall(struct.pack(">i", len(au)) + au)
        (size,) = struct.unpack(">i", _rx(s, 4))
        resp = _rx(s, size)
        assert struct.unpack(">ih", resp[:6])[1] == 34  # ILLEGAL_SASL_STATE
    finally:
        s.close()
        b.stop()


def test_sasl_with_v2_consumer_group():
    """The v2 stack (record batches, groups) rides the same authenticated
    client connection."""
    from flink_tpu.connectors.kafka_v2 import produce_v2, fetch_v2

    b = _sasl_broker()
    try:
        c = KafkaWireClient(b.host, b.port, username="alice",
                            password="secret")
        produce_v2(c, "t", 0, [(1000, b"k1", b"v1", []),
                               (1001, None, b"v2", [])])
        got, hw = fetch_v2(c, "t", 0, 0)
        assert hw == 2 and [r[3] for r in got] == [b"v1", b"v2"]
        c.close()
    finally:
        b.stop()


def test_sasl_scram_sha256(tmp_path):
    """SCRAM-SHA-256 over SaslAuthenticate: two token rounds, client
    proof verified server-side, SERVER signature verified client-side
    (mutual auth) — shared RFC 5802 math with the Postgres handshake."""
    from flink_tpu.connectors.kafka import KafkaError

    b = KafkaWireBroker(directory=str(tmp_path / "k"),
                        users={"alice": "s3cret"}).start()
    try:
        b.create_topic("t", partitions=1)
        c = KafkaWireClient(b.host, b.port, username="alice",
                            password="s3cret",
                            sasl_mechanism="SCRAM-SHA-256")
        c.produce("t", 0, [(None, b"hello")])
        msgs, hw = c.fetch("t", 0, 0)
        assert hw == 1 and msgs[0][2] == b"hello"
        c.close()
        # wrong password fails the proof
        with pytest.raises(KafkaError, match="SCRAM|authentication"):
            KafkaWireClient(b.host, b.port, username="alice",
                            password="wrong",
                            sasl_mechanism="SCRAM-SHA-256").metadata()
        # unknown user: the handshake COMPLETES round 1 (decoy salt — no
        # username enumeration) and fails at the round-2 proof with the
        # same error a wrong password gets
        with pytest.raises(KafkaError, match="SCRAM|authentication"):
            KafkaWireClient(b.host, b.port, username="mallory",
                            password="s3cret",
                            sasl_mechanism="SCRAM-SHA-256").metadata()
    finally:
        b.stop()


def test_sasl_scram_no_username_enumeration_and_cached_pbkdf2(tmp_path):
    """SCRAM hardening: (a) unknown users get a DETERMINISTIC decoy salt
    (same server-first shape as a real user, stable across attempts, user
    -dependent) and fail only at the proof; (b) the salted password is
    cached per (user, salt, iterations), so repeated handshakes — the
    unauthenticated brute-force shape — cost one 4096-iteration PBKDF2
    total, not one per attempt."""
    import base64

    from flink_tpu.connectors.kafka import KafkaWireBroker
    from flink_tpu.security import scram as scram_mod
    from flink_tpu.security.scram import ScramClient, ScramServer

    b = KafkaWireBroker(directory=str(tmp_path / "k"),
                        users={"alice": "s3cret"})

    def server_first(user):
        c = ScramClient(user, "x")
        srv = ScramServer(iterations=4096)
        salt, salted = b._scram_credentials(user)
        return srv.first_response(c.first(), salt=salt, salted=salted)

    def salt_of(msg):
        return base64.b64decode(dict(p.split("=", 1)
                                     for p in msg.split(","))["s"])

    # decoy salts: stable per unknown user, distinct across users, same
    # message shape as a real user's
    s1, s2 = salt_of(server_first("mallory")), salt_of(server_first("mallory"))
    assert s1 == s2, "a changing salt would itself leak nonexistence"
    assert salt_of(server_first("eve")) != s1
    assert {a.split("=", 1)[0] for a in server_first("mallory").split(",")} \
        == {a.split("=", 1)[0] for a in server_first("alice").split(",")}

    # PBKDF2 cost: N handshakes for a known user derive the salted
    # password ONCE (cached per (user, salt, iterations)); the decoy path
    # derives it ZERO times — unauthenticated attempts are cheap
    import hashlib as _hl
    calls = []
    real = _hl.pbkdf2_hmac
    try:
        _hl.pbkdf2_hmac = lambda *a, **kw: (calls.append(1),
                                            real(*a, **kw))[1]
        b._scram_cache.clear()
        b._scram_salts.clear()
        for _ in range(5):
            b._scram_credentials("alice")    # one derivation, then cache
        for _ in range(5):
            b._scram_credentials("mallory")  # decoy: zero derivations
    finally:
        _hl.pbkdf2_hmac = real
    assert len(calls) == 1
    assert scram_mod is not None  # shared RFC 5802 math module in use


def test_tls_listener_sasl_ssl(tmp_path):
    """security.protocol=SASL_SSL analog: a TLS listener handshakes before
    the first frame, then SCRAM authenticates inside the tunnel; a
    PLAINTEXT client never reaches the frame loop."""
    from flink_tpu.connectors.kafka import KafkaError
    from flink_tpu.security import SecurityConfig, generate_self_signed

    cert, key, ca = generate_self_signed(str(tmp_path / "pki"))
    sec = SecurityConfig(internal_ssl=True, cert_path=cert, key_path=key,
                         ca_path=ca)
    b = KafkaWireBroker(directory=str(tmp_path / "k"),
                        users={"alice": "pw"},
                        ssl_context=sec.server_context(mutual=False)).start()
    try:
        b.create_topic("t", partitions=1)
        c = KafkaWireClient(b.host, b.port, username="alice",
                            password="pw",
                            sasl_mechanism="SCRAM-SHA-256",
                            ssl_context=sec.client_context(mutual=False))
        c.produce("t", 0, [(None, b"over-tls")])
        msgs, hw = c.fetch("t", 0, 0)
        assert hw == 1 and msgs[0][2] == b"over-tls"
        c.close()
        # a plaintext client cannot speak to the TLS listener
        plain = KafkaWireClient(b.host, b.port, timeout_s=3)
        with pytest.raises((KafkaError, OSError, ValueError)):
            plain.metadata()
    finally:
        b.stop()


def test_incremental_fetch_sessions_v7(tmp_path):
    """KIP-227: a full fetch establishes a session; incremental polls
    send only changed partitions and receive only partitions with news;
    stale epochs re-establish."""
    from flink_tpu.connectors.kafka_v2 import IncrementalFetcher, produce_v2

    b = KafkaWireBroker(directory=str(tmp_path / "k")).start()
    try:
        b.create_topic("t", partitions=2)
        c = KafkaWireClient(b.host, b.port)
        produce_v2(c, "t", 0, [(0, None, b"a0", [])])
        produce_v2(c, "t", 1, [(0, None, b"b0", [])])
        f = IncrementalFetcher(c, "t", [0, 1])
        got = f.poll()                          # full fetch
        assert f.session_id > 0 and f.epoch == 1
        assert {p: [r[3] for r in rs] for p, rs in got.items()} == \
            {0: [b"a0"], 1: [b"b0"]}
        # idle incremental poll: nothing changed, nothing returned
        assert f.poll() == {}
        assert f.epoch == 2
        # news on ONE partition only
        produce_v2(c, "t", 1, [(0, None, b"b1", [])])
        got = f.poll()
        assert list(got) == [1]
        assert got[1][0][3] == b"b1"
        assert f.offsets == {0: 1, 1: 2}
        # a second fetcher killing the session state: simulate epoch skew
        f.epoch = 99                            # stale epoch
        produce_v2(c, "t", 0, [(0, None, b"a1", [])])
        got = f.poll()                          # auto re-establishes
        assert got[0][0][3] == b"a1"
        c.close()
    finally:
        b.stop()


def test_incremental_fetch_partition_error_isolated(tmp_path):
    """A bad partition (out-of-range offset) must not lose the healthy
    partitions' records: it lands in partition_errors, leaves the
    session, and can be re-added."""
    from flink_tpu.connectors.kafka_v2 import IncrementalFetcher, produce_v2

    b = KafkaWireBroker(directory=str(tmp_path / "k")).start()
    try:
        b.create_topic("t", partitions=2)
        c = KafkaWireClient(b.host, b.port)
        produce_v2(c, "t", 0, [(0, None, b"ok", [])])
        f = IncrementalFetcher(c, "t", [0, 1], start_offsets={1: 999})
        got = f.poll()
        assert got[0][0][3] == b"ok"            # healthy data delivered
        assert 1 in f.partition_errors          # OFFSET_OUT_OF_RANGE
        assert 1 not in f.offsets
        assert f.poll() == {}                   # errored part forgotten
        assert f.partition_errors == {}
        f.add_partition(1, 0)                   # caller corrects offset
        produce_v2(c, "t", 1, [(0, None, b"back", [])])
        got = f.poll()
        assert got[1][0][3] == b"back"
        c.close()
    finally:
        b.stop()
