"""State Processor API (read/bootstrap/modify savepoints) and queryable
state (live point lookups)."""

import numpy as np
import pytest

from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.queryable import (KvStateRegistry, QueryableStateClient,
                                 QueryableStateServer)
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
from flink_tpu.state.heap import HeapKeyedStateBackend
from flink_tpu.state_processor import Savepoint, SavepointWriter
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _run_job_with_savepoint(storage):
    env = StreamExecutionEnvironment()
    n = 500
    keys = np.arange(n) % 7
    vals = np.ones(n)
    sink = (env.from_collection(columns={"k": keys, "v": vals})
            .key_by("k").sum("v").collect())
    env.execute(drain=False)
    snap = env._last_executor.trigger_checkpoint(1)
    storage.store(1, snap)
    return snap


def test_read_operator_uids_and_raw(tmp_path):
    storage = InMemoryCheckpointStorage()
    _run_job_with_savepoint(storage)
    reader = Savepoint.load(storage)
    uids = reader.operator_uids()
    assert uids
    assert isinstance(reader.raw(uids[0]), dict)


def test_read_window_state():
    env = StreamExecutionEnvironment()
    n = 300
    keys = np.arange(n) % 5
    vals = np.ones(n, np.float32)
    ts = np.linspace(0, 900, n).astype(np.int64)
    (env.from_collection(columns={"k": keys, "v": vals, "t": ts})
     .assign_timestamps_and_watermarks(0, timestamp_column="t")
     .key_by("k")
     .window(TumblingEventTimeWindows.of(10_000))  # never fires in-run
     .sum("v").collect())
    env.execute(drain=False)
    snap = env._last_executor.trigger_checkpoint(1)
    reader = Savepoint.from_snapshot(snap)

    def has_window_state(uid):
        try:
            reader.read_window_state(uid)
            return True
        except (ValueError, KeyError):
            return False

    window_uid = next(u for u in reader.operator_uids() if has_window_state(u))
    rows = reader.read_window_state(window_uid).collect()
    # 5 keys x 1 pane, each holding its in-flight sum
    assert len(rows) == 5
    assert sum(r["acc0"] for r in rows) == pytest.approx(n)
    assert all(r["count"] == 60 for r in rows)


def test_bootstrap_and_restore_into_job():
    """SavepointWriter bootstraps state a NEW job restores from —
    the bootstrap-then-run workflow of the reference API."""
    from flink_tpu.dataset import ExecutionEnvironment as BatchEnv
    from flink_tpu.operators.process import KeyedProcessFunction
    from flink_tpu.state.api import ValueStateDescriptor

    benv = BatchEnv()
    seed = benv.from_columns({"k": np.array([1, 2, 3]),
                              "total": np.array([100., 200., 300.])})

    writer = SavepointWriter.new_savepoint()
    writer.with_keyed_state("my-op", seed, key_column="k",
                            value_column="total", state_name="total")
    storage = InMemoryCheckpointStorage()
    writer.write(storage, checkpoint_id=1)

    class AddToTotal(KeyedProcessFunction):
        def process_batch(self, ctx, batch):
            st = ctx.state(ValueStateDescriptor("total", default=0.0))
            cur, _alive = st.get_rows(batch.key_ids)
            vals = np.asarray([0.0 if c is None else float(c) for c in cur])
            new = vals + np.asarray(batch.column("v"))
            st.put_rows(batch.key_ids, new)
            return [batch.with_columns({"k": batch.column("k"), "total": new})]

    env = StreamExecutionEnvironment()
    sink = (env.from_collection(columns={"k": np.array([1, 2, 3]),
                                         "v": np.array([1., 1., 1.])})
            .key_by("k").process(AddToTotal(), name="proc").collect())
    # map the bootstrap uid onto the vertex uid the plan assigns
    plan = env.get_stream_graph().to_plan()
    proc_uid = next(v.uid for v in plan.vertices if "proc" in v.name)
    snap = storage.load_latest()
    snap[proc_uid] = snap.pop("my-op")
    env.execute(restore=snap)
    got = {r["k"]: r["total"] for r in sink.rows()}
    assert got == {1: 101.0, 2: 201.0, 3: 301.0}


def test_transform_keyed_state():
    from flink_tpu.dataset import ExecutionEnvironment as BatchEnv

    benv = BatchEnv()
    seed = benv.from_columns({"k": np.array([1, 2]), "x": np.array([10., 20.])})
    writer = SavepointWriter.new_savepoint()
    writer.with_keyed_state("op", seed, "k", "x", "s")
    writer.transform_keyed_state("op", "s", lambda k, v: v * 2)
    reader = Savepoint.from_snapshot(writer.snapshot)
    rows = reader.read_keyed_state("op", "s").collect()
    assert {r["key"]: r["value"] for r in rows} == {1: 20.0, 2: 40.0}


def test_read_source_positions_both_layouts():
    r1 = Savepoint.from_snapshot({"__sources__": {"u": {"s": 42}}})
    assert r1.read_source_positions() == {"u": {"s": 42}}
    r2 = Savepoint.from_snapshot(
        {"src": {"subtasks": [{"operator": {}, "source_offset": 7}]}})
    assert r2.read_source_positions() == {"src": {"0": 7}}


def test_minicluster_layout_merges_subtasks():
    storage = InMemoryCheckpointStorage()
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    n = 40_000
    (env.from_collection(columns={"k": np.arange(n) % 13,
                                  "v": np.ones(n)}, batch_size=256)
     .key_by("k").sum("v").collect())
    res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5)
    if not res.completed_checkpoints:
        pytest.skip("no checkpoint completed in time")
    reader = Savepoint.load(storage)
    uids = reader.operator_uids()

    def keyed_ok(uid):
        try:
            reader._keyed_member(uid)
            return True
        except ValueError:
            return False

    # the keyed vertex snapshot merges across both subtasks: the merged
    # key universe must cover every key of the job
    keyed_uid = next(u for u in uids if keyed_ok(u))
    be = reader._backend_for(keyed_uid)
    assert be.num_keys == 13


# ---------------------------------------------------------------------------
# queryable state
# ---------------------------------------------------------------------------

def test_queryable_state_live_lookup():
    import jax.numpy as jnp

    from flink_tpu.core.functions import SumAggregator

    registry = KvStateRegistry()
    be = HeapKeyedStateBackend()
    st = be.reducing_state("total", reduce_fn=SumAggregator(jnp.float64))
    slots = be.key_slots(np.asarray([10, 20, 30]))
    st.add_rows(slots, np.asarray([1.0, 2.0, 3.0]))
    registry.register("total", be, st)

    server = QueryableStateServer(registry).start()
    try:
        client = QueryableStateClient(server.host, server.port)
        assert client.get("total", 20) == 2.0
        # live mutation is visible (dirty reads by contract)
        st.add_rows(be.key_slots(np.asarray([20])), np.asarray([5.0]))
        assert client.get("total", 20) == 7.0
        with pytest.raises(KeyError):
            client.get("total", 999)
        with pytest.raises(RuntimeError):
            client.get("nope", 1)
        client.close()
    finally:
        server.stop()


def test_queryable_lookup_never_inserts():
    registry = KvStateRegistry()
    be = HeapKeyedStateBackend()
    st = be.value_state("v", default=None)
    be.set_current_key(1)
    st.update("x")
    registry.register("v", be, st)
    n_before = be.num_keys
    assert registry.lookup("v", 999)[0] == "missing"
    assert be.num_keys == n_before   # query did NOT insert the key


def test_transform_preserves_timers_field():
    from flink_tpu.dataset import ExecutionEnvironment as BatchEnv

    benv = BatchEnv()
    seed = benv.from_columns({"k": np.array([1]), "x": np.array([5.0])})
    writer = SavepointWriter.new_savepoint()
    writer.with_keyed_state("op", seed, "k", "x", "s")
    writer.snapshot["op"]["timers"] = {"event": "sentinel"}
    writer.transform_keyed_state("op", "s", lambda k, v: v + 1)
    assert writer.snapshot["op"]["timers"] == {"event": "sentinel"}


def test_transform_does_not_mutate_source_snapshot():
    from flink_tpu.dataset import ExecutionEnvironment as BatchEnv

    benv = BatchEnv()
    seed = benv.from_columns({"k": np.array([1]), "x": np.array([5.0])})
    base_writer = SavepointWriter.new_savepoint()
    base_writer.with_keyed_state("op", seed, "k", "x", "s")
    reader = Savepoint.from_snapshot(base_writer.snapshot)

    w2 = SavepointWriter.from_existing(reader)
    w2.transform_keyed_state("op", "s", lambda k, v: v * 10)
    # the ORIGINAL reader still sees the untransformed value
    orig = reader.read_keyed_state("op", "s").collect()
    assert orig[0]["value"] == 5.0
    new = Savepoint.from_snapshot(w2.snapshot).read_keyed_state("op", "s").collect()
    assert new[0]["value"] == 50.0


def test_read_window_state_from_mesh_snapshot():
    """Mesh snapshots carry per-shard slices with key-group-range
    manifests (ISSUE-6): the offline reader must densify them before
    reading pane state."""
    env = StreamExecutionEnvironment().set_mesh(n_devices=4)
    n = 300
    keys = np.arange(n) % 5
    vals = np.ones(n, np.float32)
    ts = np.linspace(0, 900, n).astype(np.int64)
    (env.from_collection(columns={"k": keys, "v": vals, "t": ts})
     .assign_timestamps_and_watermarks(0, timestamp_column="t")
     .key_by("k")
     .window(TumblingEventTimeWindows.of(10_000))  # never fires in-run
     .sum("v").collect())
    env.execute(drain=False)
    snap = env._last_executor.trigger_checkpoint(1)
    reader = Savepoint.from_snapshot(snap)

    def window_rows(uid):
        try:
            return reader.read_window_state(uid).collect()
        except (ValueError, KeyError):
            return None

    rows = next(r for u in reader.operator_uids()
                if (r := window_rows(u)) is not None)
    assert len(rows) == 5
    assert sorted(int(r["count"]) for r in rows) == [60, 60, 60, 60, 60]
