import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.core.functions import (AvgAggregator, CountAggregator,
                                      LambdaReduce, MaxAggregator,
                                      MinAggregator, SumAggregator,
                                      TupleAggregator)


def _fold(agg, values):
    """Sequentially fold values through lift/combine — the reference's
    add-per-record contract expressed via the monoid."""
    acc = agg.identity()
    lifted = agg.lift(values)
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(lifted)
    n = leaves[0].shape[0]
    for i in range(n):
        one = jax.tree_util.tree_unflatten(treedef, [l[i] for l in leaves])
        acc = agg.combine(acc, one)
    return agg.get_result(acc)


def test_sum_aggregator():
    v = jnp.array([1.0, 2.5, 3.5])
    assert float(_fold(SumAggregator(), v)) == 7.0


def test_min_max():
    v = jnp.array([5, -2, 9], dtype=jnp.int32)
    assert int(_fold(MinAggregator(jnp.int32), v)) == -2
    assert int(_fold(MaxAggregator(jnp.int32), v)) == 9


def test_count():
    v = jnp.array([10.0, 20.0, 30.0])
    assert int(_fold(CountAggregator(), v)) == 3


def test_avg():
    v = jnp.array([2.0, 4.0, 9.0])
    assert float(_fold(AvgAggregator(), v)) == 5.0


def test_avg_acc_spec():
    spec = AvgAggregator().acc_spec()
    assert spec.num_leaves == 2
    rebuilt = spec.unflatten(spec.leaf_inits)
    assert set(rebuilt.keys()) == {"sum", "count"}


def test_tuple_aggregator_multifield():
    agg = TupleAggregator({
        "total": ("price", SumAggregator()),
        "n": ("price", CountAggregator()),
        "biggest": ("qty", MaxAggregator()),
    })
    cols = {"price": jnp.array([1.0, 2.0, 3.0]), "qty": jnp.array([7.0, 1.0, 5.0])}
    out = _fold(agg, cols)
    assert float(out["total"]) == 6.0
    assert int(out["n"]) == 3
    assert float(out["biggest"]) == 7.0


def test_lambda_reduce():
    r = LambdaReduce(lambda a, b: a * b, jnp.ones(()))
    v = jnp.array([2.0, 3.0, 4.0])
    assert float(_fold(r, v)) == 24.0


def test_combine_associative_commutative():
    agg = AvgAggregator()
    a = {"sum": jnp.array(3.0), "count": jnp.array(2, jnp.int32)}
    b = {"sum": jnp.array(5.0), "count": jnp.array(1, jnp.int32)}
    ab = agg.combine(a, b)
    ba = agg.combine(b, a)
    assert float(ab["sum"]) == float(ba["sum"]) == 8.0
    assert int(ab["count"]) == int(ba["count"]) == 3


def test_accumulators_merge_into_job_result():
    """User counters (IntCounter analog) merge across operators into the
    JobExecutionResult."""
    import numpy as np

    from flink_tpu.datastream.api import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()

    from flink_tpu.operators.process import KeyedProcessFunction

    class P(KeyedProcessFunction):
        def open(self, ctx):
            self.acc = ctx.add_accumulator("rows-seen")

        def process_batch(self, ctx, batch):
            self.acc.add(len(batch))
            return [batch]

    (env.from_collection(columns={"k": np.arange(100) % 3,
                                  "v": np.ones(100)})
     .key_by("k").process(P()).collect())
    res = env.execute()
    assert res.get_accumulator_result("rows-seen") == 100


def test_float64_requests_canonicalize_without_warning():
    """ISSUE-6 satellite: aggregators asked for float64 under an x64-off
    backend must request the CANONICAL dtype (f32) instead of letting jax
    truncate-and-warn on every identity() — the UserWarning that spammed
    every MULTICHIP tail (functions.py:290)."""
    import warnings

    import jax

    from flink_tpu.core.functions import (MaxAggregator, MinAggregator,
                                          SumAggregator, default_float_dtype)

    x64 = bool(jax.config.jax_enable_x64)
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        for agg in (SumAggregator(jnp.float64), MinAggregator(np.float64),
                    MaxAggregator("float64"), AvgAggregator(jnp.float64)):
            ident = agg.identity()
            leaves = jax.tree_util.tree_leaves(ident)
            want = jnp.float64 if x64 else jnp.float32
            float_leaves = [l for l in leaves
                            if jnp.issubdtype(l.dtype, jnp.floating)]
            assert float_leaves
            assert all(l.dtype == want for l in float_leaves)
    # the datastream default rides the same rule
    assert default_float_dtype() == (jnp.float64 if x64 else jnp.float32)


def test_explicit_float32_request_unchanged():
    from flink_tpu.core.functions import SumAggregator

    assert SumAggregator(jnp.float32).identity().dtype == jnp.float32
