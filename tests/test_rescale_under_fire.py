"""Rescale under fire (ISSUE-14): channel-state redistribution on rescale
restores of unaligned checkpoints, the reactive autoscaler, and the
chaos-proof rescale lifecycle (deadline, rollback, idempotent re-trigger).

Reference semantics: the FLIP-76 follow-on (channel-state redistribution
on restore at a new parallelism — ``StateAssignmentOperation.
reDistributeKeyedStates`` for in-flight data) + FLIP-160's reactive
scheduler, closed over the job's own backpressure gauges.
"""

import threading
import time

import numpy as np
import pytest

from flink_tpu import formats
from flink_tpu.cluster.adaptive import (AutoscalerPolicy, ReactiveAutoscaler,
                                        SchedulerStates, counts_for_plan,
                                        maybe_rescale_restore,
                                        rescale_snapshot)
from flink_tpu.cluster.channels import LocalChannel
from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.cluster.task import Subtask, TaskStates
from flink_tpu.core.batch import (CheckpointBarrier, EndOfInput, RecordBatch,
                                  Watermark)
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.core.keygroups import route_raw_keys
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
from flink_tpu.state.redistribute import (ChannelStateRescaleError,
                                          redistribute_channel_state)
from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import (ClockSkew, FailTimes, FaultInjector,
                                     KillDuringRescale, SlowConsumer)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

pytestmark = pytest.mark.chaos

MAXP = 128


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    chaos.uninstall()


def _batch(keys, vals=None):
    keys = np.asarray(keys, np.int64)
    vals = (np.ones(len(keys), np.float64) if vals is None
            else np.asarray(vals, np.float64))
    return RecordBatch({"k": keys, "v": vals})


def _hash_input(logical=0, key_column="k", maxp=MAXP):
    return {"partitioning": "hash", "key_column": key_column,
            "max_parallelism": maxp, "logical": logical}


def _v2_section(elements, inputs):
    return {"version": 2, "elements": elements, "inputs": inputs,
            "persisted_bytes": 1, "overtaken_bytes": 1,
            "alignment_ms": 2.0, "unaligned": True}


# ---------------------------------------------------------------------------
# redistribute_channel_state: route-by-key correctness
# ---------------------------------------------------------------------------

def test_route_by_key_correctness_p1_to_7():
    """Every persisted keyed row lands on exactly the subtask
    ``route_raw_keys`` assigns its key to, at every parallelism 1..7,
    with per-subtask relative order preserved."""
    rng = np.random.default_rng(7)
    all_keys = [rng.integers(0, 1000, 37), rng.integers(0, 1000, 11),
                rng.integers(0, 1000, 23)]
    sections = [
        _v2_section([(0, _batch(all_keys[0])), (0, _batch(all_keys[1]))],
                    [_hash_input()]),
        _v2_section([(0, _batch(all_keys[2]))], [_hash_input()]),
    ]
    flat_keys = np.concatenate(all_keys)
    for p in range(1, 8):
        secs = redistribute_channel_state(sections, p)
        assert len(secs) == p
        seen = []
        for t, sec in enumerate(secs):
            assert sec["version"] == 2 and sec["by_logical_port"]
            expect_order = [k for k in flat_keys
                            if route_raw_keys(np.asarray([k]), p, MAXP)[0]
                            == t]
            got = [int(k) for _port, el in sec["elements"]
                   for k in np.asarray(el.column("k"))]
            for k in got:
                assert route_raw_keys(np.asarray([k]), p, MAXP)[0] == t, \
                    f"key {k} misrouted to subtask {t} at P={p}"
            assert got == expect_order, \
                f"P={p} subtask {t}: relative order not preserved"
            seen.extend(got)
        assert sorted(seen) == sorted(int(k) for k in flat_keys), \
            f"P={p}: rows lost or duplicated by redistribution"


def test_route_prefers_batch_key_groups_over_key_column():
    """A batch already carrying key_groups (keyed upstream) routes by
    them — the exact groups the live dispatcher would use."""
    from flink_tpu.core import keygroups
    keys = np.arange(50, dtype=np.int64)
    kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys), MAXP)
    b = RecordBatch({"k": keys, "v": np.ones(50)}, key_groups=kg)
    secs = redistribute_channel_state(
        [_v2_section([(0, b)], [{"partitioning": "forward",
                                 "max_parallelism": MAXP, "logical": 0,
                                 "key_column": None}])], 4)
    total = 0
    for t, sec in enumerate(secs):
        for _p, el in sec["elements"]:
            total += len(el)
            tgt = (np.asarray(el.key_groups, np.int64) * 4) // MAXP
            assert (tgt == t).all()
    assert total == 50


def test_non_keyed_and_control_elements_replay_on_subtask_zero():
    rebalance_in = {"partitioning": "rebalance", "key_column": None,
                    "max_parallelism": MAXP, "logical": 0}
    sec = _v2_section([(0, _batch([1, 2, 3])), (0, Watermark(77))],
                      [rebalance_in])
    secs = redistribute_channel_state([sec], 3)
    assert [len(s["elements"]) for s in secs] == [2, 0, 0]
    kinds = [type(el).__name__ for _p, el in secs[0]["elements"]]
    assert kinds == ["RecordBatch", "Watermark"]


def test_redistributed_sections_are_re_redistributable():
    """A redistributed section carries port-indexed routing metadata, so
    a SECOND pass (e.g. restoring a rewritten savepoint at yet another
    parallelism) routes by the same key/max-parallelism as the first —
    never the defaults."""
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 500, 64)
    maxp = 64   # NON-default: a second pass falling back to 128 would
    #             route differently and the coverage check would fail
    sec = _v2_section([(0, _batch(keys))],
                      [_hash_input(maxp=maxp, logical=1)])
    first = redistribute_channel_state([sec], 1)   # collapse to one
    assert first[0]["inputs"][1]["max_parallelism"] == maxp
    second = redistribute_channel_state(first, 5)
    seen = []
    for t, s in enumerate(second):
        for port, el in s["elements"]:
            assert port == 1, "logical port lost across passes"
            for k in np.asarray(el.column("k")):
                assert route_raw_keys(np.asarray([k]), 5, maxp)[0] == t, \
                    f"second pass misrouted key {k} (wrong max_parallelism)"
                seen.append(int(k))
    assert sorted(seen) == sorted(int(k) for k in keys)


def test_v1_section_with_elements_fails_loudly():
    v1 = {"version": 1, "elements": [(0, _batch([1]))],
          "persisted_bytes": 1, "overtaken_bytes": 1,
          "alignment_ms": 1.0, "unaligned": True}
    with pytest.raises(ChannelStateRescaleError, match="v1"):
        redistribute_channel_state([v1], 2)
    # empty v1 sections pass (aligned checkpoints written by old runtimes)
    empty = dict(v1, elements=[])
    out = redistribute_channel_state([empty], 2)
    assert all(not s["elements"] for s in out)


def test_unknown_version_fails_loudly():
    with pytest.raises(ValueError, match="99"):
        redistribute_channel_state(
            [{"version": 99, "elements": [(0, _batch([1]))]}], 2)


# ---------------------------------------------------------------------------
# v2 write format + replay-before-input ordering
# ---------------------------------------------------------------------------

class _SeenOp:
    """Stateful test operator recording per-row arrival order."""

    name = "seen"
    forwards_watermarks = True
    is_stateless = False
    is_two_input = False

    def open(self, ctx):
        self.seen = []
        self.total = 0.0

    def process_batch(self, batch):
        vals = np.asarray(batch.column("v"))
        self.total += float(vals.sum())
        self.seen.extend(int(k) for k in np.asarray(batch.column("k")))
        return []

    def process_watermark(self, wm):
        return []

    def on_processing_time(self, ts):
        return []

    def end_input(self):
        return []

    def snapshot_state(self):
        return {"total": self.total}

    def restore_state(self, snap):
        self.total = snap["total"]

    def notify_checkpoint_complete(self, cid):
        pass

    def close(self):
        pass


class _Recorder:
    def __init__(self):
        self.acks = {}
        self.declines = []
        self.states = []

    def task_state_changed(self, uid, idx, state, error):
        self.states.append((state, error))

    def acknowledge_checkpoint(self, cid, uid, idx, snap):
        self.acks[cid] = snap

    def decline_checkpoint(self, cid, uid, idx, error):
        self.declines.append((cid, error))


class _Out:
    def __init__(self):
        self.elements = []
        self.channels = []

    def emit(self, el):
        self.elements.append(el)


def test_subtask_writes_v2_section_with_input_routing():
    """The unaligned snapshot carries the v2 section: elements plus the
    per-input routing metadata the deploying cluster captured."""
    ch0, ch1 = LocalChannel(16, "c0"), LocalChannel(16, "c1")
    rec = _Recorder()
    t = Subtask("v1", 0, _SeenOp(), [_Out()], RuntimeContext(), rec,
                [ch0, ch1], unaligned=True,
                input_routing=[_hash_input(), _hash_input(logical=1)])
    t.start()
    ch0.put(_batch([1]))
    time.sleep(0.05)
    ch0.put(CheckpointBarrier(1, 0))
    time.sleep(0.05)
    ch1.put(_batch([5]))
    time.sleep(0.05)
    ch1.put(CheckpointBarrier(1, 0))
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()
    cs = rec.acks[1]["channel_state"]
    assert cs["version"] == 2 and cs["unaligned"]
    assert len(cs["elements"]) == 1
    assert cs["inputs"][0]["key_column"] == "k"
    assert cs["inputs"][0]["max_parallelism"] == MAXP
    assert cs["inputs"][1]["logical"] == 1
    # the recorded section round-trips through redistribution
    secs = redistribute_channel_state([cs], 3)
    routed = sum(len(el) for s in secs for _p, el in s["elements"])
    assert routed == 1


def test_redistributed_section_replays_before_new_input():
    """A by-logical-port (rescale-redistributed) section replays its
    elements into the operator strictly BEFORE any new channel input —
    the PR-5 ordering contract, preserved across the parallelism change."""
    ch = LocalChannel(16, "c0")
    rec = _Recorder()
    op = _SeenOp()
    section = {"version": 2, "by_logical_port": True,
               "elements": [(0, _batch([101])), (0, _batch([102]))],
               "inputs": [], "persisted_bytes": 8, "overtaken_bytes": 8,
               "alignment_ms": 1.0, "unaligned": True}
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec, [ch],
                input_routing=[_hash_input()])
    t.start({"operator": {"total": 0.0}, "channel_state": section})
    ch.put(_batch([7]))
    ch.put(EndOfInput())
    t.join()
    assert t.state == TaskStates.FINISHED
    assert op.seen == [101, 102, 7]


# ---------------------------------------------------------------------------
# rescale_snapshot / maybe_rescale_restore plumbing
# ---------------------------------------------------------------------------

class _PacedFileSource:
    """Load-curve source: a FileSource whose reader paces batch emission
    (the millions-of-users arrival-rate model — without pacing an
    in-process source always saturates the pipeline and queue depth stops
    meaning 'overloaded').  Built lazily to dodge import-order issues."""

    def __new__(cls, path, pace_s: float, **kw):
        from flink_tpu.connectors.file_source import FileSource

        class Paced(FileSource):
            def _read_file(self, p, start_row):
                for el in super()._read_file(p, start_row):
                    if isinstance(el, RecordBatch):
                        time.sleep(pace_s)
                    yield el

        return Paced(path, **kw)


def _window_plan_factory(tmp_path, n=24_000, n_files=2, keys_mod=31,
                         batch_size=128, sink=None, pace_s=0.0):
    """Stable-split (file) keyed window job: parallelism-independent
    source splits, key_by -> tumbling window sum -> shared collect sink.
    ``pace_s`` > 0 paces each split's batch emission (load-curve mode)."""
    from flink_tpu.connectors.sinks import CollectSink

    tmp_path.mkdir(parents=True, exist_ok=True)
    written = tmp_path / "_written"
    if not written.exists():
        per = n // n_files
        for i in range(n_files):
            lo = i * per
            ks = (np.arange(lo, lo + per) % keys_mod).astype(np.int64)
            ts = np.sort(np.arange(per) * (4000 // per)).astype(np.int64)
            formats.write_csv(
                [RecordBatch({"k": ks, "v": np.ones(per), "t": ts})],
                str(tmp_path / f"in{i}.csv"))
        written.mkdir()
    sink = sink if sink is not None else CollectSink()

    def plan_factory(parallelism):
        from flink_tpu.connectors.file_source import FileSource
        env = StreamExecutionEnvironment()
        env.set_parallelism(parallelism)
        src = (_PacedFileSource(str(tmp_path), pace_s, format="csv",
                                batch_size=batch_size) if pace_s > 0
               else FileSource(str(tmp_path), format="csv",
                               batch_size=batch_size))
        (env.from_source(src)
         .assign_timestamps_and_watermarks(0, timestamp_column="t")
         .key_by("k")
         .window(TumblingEventTimeWindows.of(1000))
         .sum("v").add_sink(sink))
        return env.get_stream_graph("rescale-job").to_plan()

    return plan_factory, sink


def _digest(sink):
    return sorted(tuple(sorted((k, float(v)) for k, v in r.items()
                               if k != "__ts__"))
                  for r in sink.rows())


def _expected_per_key(n, keys_mod):
    expect = {}
    for k in (np.arange(n) % keys_mod).tolist():
        expect[k] = expect.get(k, 0) + 1.0
    return expect


def _per_key_counters(sink):
    final = {}
    for r in sink.rows():
        final[int(r["k"])] = final.get(int(r["k"]), 0) + float(r["v"])
    return final


def test_shared_sink_merge_is_owner_filtered_union():
    """Shared collect-sink members merge by per-key OWNER filtering: each
    subtask's copy of the shared row list contributes exactly the rows of
    keys it owns, so a fire present only in its owner's (later) copy is
    kept, and rows present in every copy appear exactly once."""
    from flink_tpu.cluster.adaptive import _union_shared_sink_members

    P, maxp = 2, MAXP
    keys = np.arange(40, dtype=np.int64)
    owner = route_raw_keys(keys, P, maxp)
    k0 = keys[owner == 0]
    k1 = keys[owner == 1]

    def copy_of(ks):
        return {"batches": [({"k": np.asarray(ks, np.int64),
                              "v": np.ones(len(ks))}, None)]}

    # subtask 0 snapshotted EARLY: it has its own fires but is missing
    # subtask 1's last fire (k1[-1]); subtask 1's later copy has all
    ops = [{"op0": {}, "op2": copy_of(np.concatenate([k0, k1[:-1]]))},
           {"op0": {}, "op2": copy_of(np.concatenate([k0, k1]))}]
    _union_shared_sink_members(ops, "k", maxp)
    merged = np.sort(np.concatenate(
        [np.asarray(c["k"]) for c, _t in ops[0]["op2"]["batches"]]))
    assert merged.tolist() == sorted(keys.tolist()), \
        "owner union lost or duplicated fire rows"
    assert ops[1]["op2"] == {}


def test_maybe_rescale_restore_identity_and_mismatch(tmp_path):
    plan_factory, _sink = _window_plan_factory(tmp_path, n=2000)
    plan2 = plan_factory(2)
    counts2 = counts_for_plan(plan2)
    win_uid = next(v.uid for v in plan2.vertices if not v.is_source)
    snap = {"__job__": {"parallelism": dict(counts2)},
            win_uid: {"subtasks": [{"operator": {}}, {"operator": {}}]}}
    assert maybe_rescale_restore(snap, plan2) is snap   # counts match
    plan4 = plan_factory(4)
    out = maybe_rescale_restore(snap, plan4)
    assert out is not snap
    assert len(out[win_uid]["subtasks"]) == 4


def test_rescale_snapshot_fires_redistribute_chaos_point(tmp_path):
    plan_factory, _sink = _window_plan_factory(tmp_path, n=2000)
    plan2, plan4 = plan_factory(2), plan_factory(4)
    win_uid = next(v.uid for v in plan2.vertices if not v.is_source)
    snap = {win_uid: {"subtasks": [{"operator": {}}, {"operator": {}}]}}
    inj = FaultInjector(seed=3)
    inj.inject("rescale.redistribute", KillDuringRescale(at=1))
    with chaos.installed(inj):
        with pytest.raises(chaos.InjectedFault, match="rescale"):
            rescale_snapshot(snap, plan4, counts_for_plan(plan4))
        # second attempt (re-trigger) proceeds — the kill fires once
        out = rescale_snapshot(snap, plan4, counts_for_plan(plan4))
        assert len(out[win_uid]["subtasks"]) == 4
        # same-parallelism calls (rollback shape) never fire the point
        rescale_snapshot(snap, plan2, counts_for_plan(plan2))
    assert inj.fired("rescale.redistribute") == 2


# ---------------------------------------------------------------------------
# end-to-end: rescale a BACKPRESSURED job from an unaligned checkpoint
# ---------------------------------------------------------------------------

def _run_to_cut(plan_factory, storage, seed=23, stall_times=3000):
    """Run the job at parallelism 2 under SlowConsumer backpressure, take
    a mid-stream unaligned cut, cancel.  Returns (cut_id, raw_snapshot)."""
    inj = FaultInjector(seed=seed)
    inj.inject("channel.recv",
               SlowConsumer(max_s=0.05, min_s=0.02, p=0.5, burst=60,
                            times=stall_times, channel="[0]->"))
    plan = plan_factory(2)
    cluster = MiniCluster(checkpoint_storage=storage,
                          checkpoint_interval_ms=30,
                          alignment_timeout_ms=100,
                          tolerable_failed_checkpoints=-1)
    done = {}

    def run():
        done["res"] = cluster.execute(plan, timeout_s=300)

    th = threading.Thread(target=run, daemon=True)
    with chaos.installed(inj):
        th.start()
        # wait for the stream to be genuinely mid-flight
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            tasks = getattr(cluster, "_tasks", [])
            if sum(t.records_in for t in tasks
                   if not hasattr(t, "split")) > 2000:
                break
            time.sleep(0.02)
        cut = None
        for _attempt in range(12):
            cid = cluster.checkpoint(timeout_s=30)
            if cid is None:
                break
            raw = storage.load(cid)
            persisted = sum(
                len((sub or {}).get("channel_state", {}).get("elements", []))
                for uid, entry in raw.items() if not uid.startswith("__")
                for sub in entry.get("subtasks", []))
            if persisted > 0:
                cut = (cid, raw)
                break
        cluster.cancel()
        th.join(timeout=60)
    assert cut is not None, \
        "no unaligned cut with persisted in-flight elements could be taken"
    return cut


def test_rescale_backpressured_job_from_unaligned_checkpoint(tmp_path):
    """The tentpole mechanism end-to-end, deterministically staged: a
    SlowConsumer-backpressured job's UNALIGNED checkpoint (persisted
    in-flight elements present) restores at parallelism 4 through
    channel-state redistribution, and the continued job's fire digests +
    per-key counters equal the unfaulted fixed-parallelism control —
    ``reject_channel_state`` never fires on this path."""
    n, keys_mod = 24_000, 31
    # control: unfaulted, fixed parallelism 2
    ctl_factory, ctl_sink = _window_plan_factory(tmp_path / "ctl", n=n,
                                                 keys_mod=keys_mod)
    ctl = MiniCluster()
    res = ctl.execute(ctl_factory(2), timeout_s=300)
    assert res.state == TaskStates.FINISHED
    control_digest = _digest(ctl_sink)
    assert _per_key_counters(ctl_sink) == _expected_per_key(n, keys_mod)

    # faulted run: cut mid-stream under backpressure, rescale 2 -> 4
    plan_factory, sink = _window_plan_factory(tmp_path / "run", n=n,
                                              keys_mod=keys_mod)
    storage = InMemoryCheckpointStorage(retain=10)
    _cid, raw = _run_to_cut(plan_factory, storage)
    plan4 = plan_factory(4)
    restore = rescale_snapshot(raw, plan4, counts_for_plan(plan4))
    # the redistributed restore carries the in-flight elements
    carried = sum(
        len((sub or {}).get("channel_state", {}).get("elements", []))
        for uid, entry in restore.items() if not uid.startswith("__")
        for sub in entry.get("subtasks", []))
    assert carried > 0
    cont = MiniCluster()
    res2 = cont.execute(plan4, restore=restore, timeout_s=300)
    assert res2.state == TaskStates.FINISHED
    assert _digest(sink) == control_digest
    assert _per_key_counters(sink) == _expected_per_key(n, keys_mod)


# ---------------------------------------------------------------------------
# reactive autoscaler: hysteresis / cooldown units
# ---------------------------------------------------------------------------

def _signals(depth=0, align=0, bp=0.0, p99=None):
    return {"max_queue_depth": depth, "alignment_queued_elements": align,
            "backpressured_ms_delta": bp, "latency_p99_ms": p99}


def test_policy_scale_out_needs_sustained_overload():
    p = AutoscalerPolicy(min_parallelism=2, max_parallelism=8,
                         sustain_polls=3, cooldown_ms=0.0,
                         scale_out_queue_depth=16)
    assert p.observe(_signals(depth=20), 2) is None
    assert p.observe(_signals(depth=20), 2) is None
    assert p.observe(_signals(depth=20), 2) == 4
    # one calm poll resets the streak
    assert p.observe(_signals(depth=20), 2) is None
    assert p.observe(_signals(depth=5), 2) is None    # dead band resets
    assert p.observe(_signals(depth=20), 2) is None
    assert p.observe(_signals(depth=20), 2) is None
    assert p.observe(_signals(depth=20), 2) == 4


def test_policy_scale_in_and_bounds():
    p = AutoscalerPolicy(min_parallelism=2, max_parallelism=4,
                         sustain_polls=2, cooldown_ms=0.0,
                         scale_in_queue_depth=2)
    assert p.observe(_signals(depth=0), 4) is None
    assert p.observe(_signals(depth=0), 4) == 2
    # at min parallelism: never below
    assert p.observe(_signals(depth=0), 2) is None
    assert p.observe(_signals(depth=0), 2) is None
    # at max parallelism: never above
    assert p.observe(_signals(depth=99), 4) is None
    assert p.observe(_signals(depth=99), 4) is None
    assert p.observe(_signals(depth=99), 4) is None


def test_policy_alignment_queue_and_p99_trigger_scale_out():
    p = AutoscalerPolicy(sustain_polls=1, cooldown_ms=0.0,
                         scale_out_alignment_queued=100,
                         scale_out_p99_ms=500.0, max_parallelism=8)
    assert p.observe(_signals(align=200), 2) == 4
    p2 = AutoscalerPolicy(sustain_polls=1, cooldown_ms=0.0,
                          scale_out_p99_ms=500.0, max_parallelism=8)
    assert p2.observe(_signals(p99=900.0), 2) == 4


def test_policy_cooldown_blocks_consecutive_decisions():
    p = AutoscalerPolicy(sustain_polls=1, cooldown_ms=60_000.0,
                         max_parallelism=16)
    assert p.observe(_signals(depth=99), 2) == 4
    for _ in range(20):
        assert p.observe(_signals(depth=99), 4) is None
    assert p.in_cooldown() and p.cooldown_remaining_ms() > 0


def test_policy_cooldown_is_skew_proof():
    """Satellite: ClockSkew on the monotonic seam (backward steps +
    jitter + forward jumps) must not turn the cooldown into a rescale
    storm — MonotoneElapsed clamps at its high-water, so the one allowed
    decision happens and the cooldown then HOLDS."""
    inj = FaultInjector(seed=11)
    inj.inject("clock.monotonic",
               ClockSkew(jumps=[(3, -5000.0), (8, 4000.0), (15, -4000.0)],
                         jitter_ms=200.0))
    decisions = 0
    with chaos.installed(inj):
        p = AutoscalerPolicy(sustain_polls=1, cooldown_ms=60_000.0,
                             max_parallelism=64)
        cur = 2
        for _ in range(60):
            t = p.observe(_signals(depth=99), cur)
            if t is not None:
                decisions += 1
                cur = t
    assert decisions == 1, \
        f"clock skew produced a rescale storm ({decisions} decisions)"


# ---------------------------------------------------------------------------
# reactive autoscaler: end-to-end acceptance (2 -> 4 -> 2 under fire)
# ---------------------------------------------------------------------------

N_ACC = 60_000
ACC_PACE_S = 0.012
ACC_BATCH = 100
KEYS_MOD = 31


@pytest.fixture(scope="module")
def control_digest(tmp_path_factory):
    """Unfaulted fixed-parallelism control for the acceptance runs."""
    tmp = tmp_path_factory.mktemp("control")
    factory, sink = _window_plan_factory(tmp, n=N_ACC, keys_mod=KEYS_MOD,
                                         batch_size=ACC_BATCH,
                                         pace_s=ACC_PACE_S)
    res = MiniCluster().execute(factory(2), timeout_s=300)
    assert res.state == TaskStates.FINISHED
    return _digest(sink)


def _acceptance_policy():
    return AutoscalerPolicy(min_parallelism=2, max_parallelism=4,
                            scale_out_queue_depth=12,
                            scale_in_queue_depth=2,
                            sustain_polls=2, cooldown_ms=300.0)


def _run_autoscaled(tmp_path, extra_faults=None, seed=23,
                    stall_times=80):
    factory, sink = _window_plan_factory(tmp_path, n=N_ACC,
                                         keys_mod=KEYS_MOD,
                                         batch_size=ACC_BATCH,
                                         pace_s=ACC_PACE_S)
    inj = FaultInjector(seed=seed)
    inj.inject("channel.recv",
               SlowConsumer(max_s=0.04, min_s=0.015, p=0.4, burst=50,
                            times=stall_times, channel="[0]->"))
    for point, schedule in (extra_faults or {}).items():
        inj.inject(point, schedule)
    storage = InMemoryCheckpointStorage(retain=10)
    scaler = ReactiveAutoscaler(
        factory, checkpoint_storage=storage,
        policy=_acceptance_policy(), initial_parallelism=2,
        poll_interval_ms=15.0, checkpoint_interval_ms=30,
        alignment_timeout_ms=100.0, restart_attempts=4,
        job_timeout_s=300.0)
    with chaos.installed(inj):
        scaler.start()
        scaler.join(timeout_s=300)
    return scaler, sink, storage, inj


def test_acceptance_autoscaled_2_4_2_exactly_once(tmp_path,
                                                  control_digest):
    """THE acceptance: a SlowConsumer-backpressured job autoscales out at
    the (injected) peak and back in after it, through unaligned cuts with
    redistributed channel state, and the fire digests + per-key counters
    are bit-identical to the unfaulted fixed-parallelism control."""
    scaler, sink, storage, _inj = _run_autoscaled(tmp_path)
    assert scaler.state == SchedulerStates.FINISHED, \
        (scaler.state, scaler.error)
    st = scaler.status()
    assert st["rescales"] >= 1, f"autoscaler never rescaled: {st}"
    assert max(st["parallelism_path"]) >= 4, st["parallelism_path"]
    # scale-in after the stall period ended (the diurnal trough)
    assert st["parallelism_path"][-1] < max(st["parallelism_path"]), \
        f"never scaled back in: {st['parallelism_path']}"
    assert st["rollbacks"] == 0
    assert _per_key_counters(sink) == _expected_per_key(N_ACC, KEYS_MOD), \
        "exactly-once across autoscale violated"
    assert _digest(sink) == control_digest


def test_acceptance_kill_during_rescale_is_idempotent(tmp_path,
                                                      control_digest):
    """A kill INSIDE the rescale window (chaos at rescale.redistribute):
    the lifecycle re-triggers from the same immutable cut and the run
    stays exactly-once — digests equal the unfaulted control."""
    scaler, sink, _storage, inj = _run_autoscaled(
        tmp_path, extra_faults={
            "rescale.redistribute": KillDuringRescale(at=1)})
    assert scaler.state == SchedulerStates.FINISHED, \
        (scaler.state, scaler.error)
    st = scaler.status()
    assert st["rescales"] >= 1
    assert st["retriggers"] >= 1, \
        "the injected kill never exercised the re-trigger path"
    assert inj.fired("rescale.redistribute") >= 2
    assert _per_key_counters(sink) == _expected_per_key(N_ACC, KEYS_MOD)
    assert _digest(sink) == control_digest


def test_acceptance_rollback_on_redeploy_failure(tmp_path,
                                                 control_digest):
    """Redeploy failing past the retry budget ROLLS BACK to the old
    parallelism from the pre-rescale checkpoint — the job completes
    exactly-once at the old parallelism."""
    scaler, sink, _storage, _inj = _run_autoscaled(
        tmp_path, extra_faults={"rescale.redeploy": FailTimes(2)})
    assert scaler.state == SchedulerStates.FINISHED, \
        (scaler.state, scaler.error)
    st = scaler.status()
    assert st["rollbacks"] >= 1, f"no rollback recorded: {st}"
    assert st["retriggers"] >= 1
    assert _per_key_counters(sink) == _expected_per_key(N_ACC, KEYS_MOD)
    assert _digest(sink) == control_digest


def test_acceptance_worker_killed_mid_redeploy(tmp_path, control_digest):
    """A subtask crashing right after the rescale redeploy: the cluster's
    own restart strategy restores — through maybe_rescale_restore — from
    the pre-rescale (old parallelism) checkpoint, idempotently.  Still
    exactly-once."""
    # the crash fires on the ~40th batch processed AFTER the redeploy's
    # fresh injector counters — i.e., inside the post-rescale window
    from flink_tpu.testing.chaos import CrashOnceAt
    scaler, sink, _storage, inj = _run_autoscaled(
        tmp_path, extra_faults={"subtask.run": CrashOnceAt(260)})
    assert scaler.state == SchedulerStates.FINISHED, \
        (scaler.state, scaler.error)
    assert inj.fired("subtask.run") >= 260
    assert _per_key_counters(sink) == _expected_per_key(N_ACC, KEYS_MOD)
    assert _digest(sink) == control_digest


# ---------------------------------------------------------------------------
# savepoints: still aligned, still rescalable the old way
# ---------------------------------------------------------------------------

def test_savepoints_stay_aligned_and_split_without_channel_state(tmp_path):
    """Savepoints never escalate (PR-5 contract, unchanged): their v2
    sections have empty elements, and rescale_snapshot splits them
    without attaching channel state to the new subtasks."""
    factory, _sink = _window_plan_factory(tmp_path, n=8000)
    storage = InMemoryCheckpointStorage(retain=5)
    cluster = MiniCluster(checkpoint_storage=storage,
                          alignment_timeout_ms=0)   # pure unaligned mode
    done = {}

    def run():
        done["res"] = cluster.execute(factory(2), timeout_s=120)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    time.sleep(0.15)
    sp = cluster.savepoint()
    th.join(timeout=120)
    if sp is None:
        pytest.skip("job finished before the savepoint could complete")
    raw = storage.load(sp)
    for uid, entry in raw.items():
        if uid.startswith("__"):
            continue
        for sub in entry.get("subtasks", []):
            cs = (sub or {}).get("channel_state")
            if isinstance(cs, dict):
                assert not cs["unaligned"] and cs["elements"] == []
    plan4 = factory(4)
    out = rescale_snapshot(raw, plan4, counts_for_plan(plan4))
    for uid, entry in out.items():
        if uid.startswith("__"):
            continue
        for sub in entry.get("subtasks", []):
            cs = (sub or {}).get("channel_state")
            assert cs is None or not cs.get("elements")


# ---------------------------------------------------------------------------
# observability: status / gauges / REST panel
# ---------------------------------------------------------------------------

def test_autoscaler_status_gauges_and_panel(tmp_path):
    from flink_tpu.metrics.groups import (MetricRegistry, autoscaler_metrics)
    from flink_tpu.rest.views import autoscaler_html

    factory, _sink = _window_plan_factory(tmp_path, n=2000)
    scaler = ReactiveAutoscaler(factory, policy=_acceptance_policy(),
                                initial_parallelism=2)
    st = scaler.status()
    for key in ("state", "current_parallelism", "target_parallelism",
                "rescales", "rollbacks", "retriggers",
                "last_rescale_duration_ms", "cooldown_remaining_ms",
                "parallelism_path", "signals"):
        assert key in st
    reg = MetricRegistry()
    g = autoscaler_metrics(reg.job_manager_group(), scaler.status)
    names = set(reg.all_metrics())
    assert {"jobmanager.autoscaler.current_parallelism",
            "jobmanager.autoscaler.target_parallelism",
            "jobmanager.autoscaler.rescales_total",
            "jobmanager.autoscaler.rollbacks_total",
            "jobmanager.autoscaler.last_rescale_duration_ms"} <= names
    assert g is not None
    html = autoscaler_html(st)
    assert 'data-metric="rescales"' in html
    assert 'data-metric="rollbacks"' in html
    assert "as-panel" in html and "as-path" in html
    assert autoscaler_html({}).count("off") >= 1

    # the cluster an autoscaler deploys surfaces the status in job_status
    cluster = scaler._make_cluster()
    status = cluster.job_status()
    assert status["autoscaler"]["current_parallelism"] == 2
