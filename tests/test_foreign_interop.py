"""Foreign golden-bytes interop (VERDICT r4 weak #4): the parquet/ORC
readers decode files written by a FOREIGN implementation (pyarrow — the
Apache Arrow C++ writers), and pyarrow reads files written by this repo's
from-spec writers.  The checked-in fixtures under
``tests/fixtures/foreign/`` pin the foreign bytes so the read side never
regresses even without pyarrow in the environment; the live round-trip
tests exercise both directions against the installed library.
"""

import json
import os

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.formats.orc import read_orc, write_orc
from flink_tpu.formats.parquet import read_parquet, write_parquet

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "foreign")

try:
    import pyarrow  # noqa: F401
    HAVE_PYARROW = True
except ImportError:                            # pragma: no cover
    HAVE_PYARROW = False


def _expected():
    with open(os.path.join(FIXTURES, "expected.json")) as f:
        return json.load(f)


def _concat(batches, col):
    return np.concatenate([np.asarray(b.column(col)) for b in batches])


def _check_table(batches):
    exp = _expected()
    ids = _concat(batches, "id")
    assert len(ids) == exp["n"]
    assert int(ids.sum()) == exp["id_sum"]
    assert int(_concat(batches, "qty").sum()) == exp["qty_sum"]
    assert float(_concat(batches, "price").sum()) == \
        pytest.approx(exp["price_sum"])
    names = [x for b in batches
             for x in np.asarray(b.column("name")).tolist()]
    assert names[17] == exp["name_17"]
    flags = _concat(batches, "flag")
    assert int(np.asarray(flags, bool).sum()) == exp["flag_true"]


# -- checked-in foreign bytes (no pyarrow needed) ---------------------------


def test_read_pyarrow_parquet_plain():
    _check_table(list(read_parquet(
        os.path.join(FIXTURES, "pyarrow_plain.parquet"))))


def test_read_pyarrow_parquet_gzip():
    _check_table(list(read_parquet(
        os.path.join(FIXTURES, "pyarrow_gzip.parquet"))))


def test_read_pyarrow_orc():
    _check_table(list(read_orc(os.path.join(FIXTURES, "pyarrow.orc"))))


# -- live round trips against the installed foreign library ----------------


def _sample_batch(n=300, seed=9):
    rng = np.random.default_rng(seed)
    return RecordBatch({
        "id": np.arange(n, dtype=np.int64),
        "v32": rng.integers(-1000, 1000, n).astype(np.int32),
        "price": rng.random(n),
        "f32": rng.random(n).astype(np.float32),
        "tag": np.asarray([f"t{i % 23}" for i in range(n)], object),
        "ok": (np.arange(n) % 2 == 0),
    })


@pytest.mark.skipif(not HAVE_PYARROW, reason="pyarrow not installed")
def test_our_parquet_read_by_pyarrow(tmp_path):
    import pyarrow.parquet as pq
    b = _sample_batch()
    path = str(tmp_path / "ours.parquet")
    write_parquet([b], path)
    t = pq.read_table(path)
    assert t["id"].to_pylist() == np.asarray(b.column("id")).tolist()
    assert t["v32"].to_pylist() == np.asarray(b.column("v32")).tolist()
    assert t["tag"].to_pylist() == np.asarray(b.column("tag")).tolist()
    assert t["ok"].to_pylist() == np.asarray(b.column("ok")).tolist()
    assert np.allclose(t["price"].to_numpy(), np.asarray(b.column("price")))


@pytest.mark.skipif(not HAVE_PYARROW, reason="pyarrow not installed")
def test_our_orc_read_by_pyarrow(tmp_path):
    import pyarrow.orc as po
    b = _sample_batch()
    path = str(tmp_path / "ours.orc")
    write_orc([b], path)
    t = po.read_table(path)
    assert t["id"].to_pylist() == np.asarray(b.column("id")).tolist()
    assert t["tag"].to_pylist() == np.asarray(b.column("tag")).tolist()
    assert np.allclose(t["price"].to_numpy(), np.asarray(b.column("price")))


@pytest.mark.skipif(not HAVE_PYARROW, reason="pyarrow not installed")
def test_pyarrow_parquet_read_by_us(tmp_path):
    """Fresh pyarrow bytes (not the pinned fixture): catch drift between
    pyarrow versions and our reader."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    n = 777
    rng = np.random.default_rng(21)
    schema = pa.schema([pa.field("a", pa.int64(), nullable=False),
                        pa.field("b", pa.float64(), nullable=False),
                        pa.field("s", pa.string(), nullable=False)])
    tbl = pa.table({"a": np.arange(n, dtype=np.int64),
                    "b": rng.random(n),
                    "s": [f"x{i % 5}" for i in range(n)]}, schema=schema)
    path = str(tmp_path / "pa.parquet")
    pq.write_table(tbl, path, compression="GZIP", use_dictionary=False,
                   data_page_version="1.0")
    batches = list(read_parquet(path))
    assert _concat(batches, "a").tolist() == list(range(n))
    assert np.allclose(_concat(batches, "b"), tbl["b"].to_numpy())


@pytest.mark.skipif(not HAVE_PYARROW, reason="pyarrow not installed")
def test_pyarrow_orc_read_by_us(tmp_path):
    import pyarrow as pa
    import pyarrow.orc as po
    n = 555
    rng = np.random.default_rng(22)
    tbl = pa.table({"a": np.arange(n, dtype=np.int64),
                    "b": rng.random(n),
                    "s": [f"y{i % 7}" for i in range(n)]})
    path = str(tmp_path / "pa.orc")
    po.write_table(tbl, path, compression="uncompressed")
    batches = list(read_orc(path))
    assert _concat(batches, "a").tolist() == list(range(n))
    names = [x for b in batches for x in np.asarray(b.column("s")).tolist()]
    assert names[8] == "y1"


@pytest.mark.skipif(not HAVE_PYARROW, reason="pyarrow not installed")
def test_orc_timestamp_and_decimal_cross_validation(tmp_path):
    """ORC TIMESTAMP (2015-epoch seconds + scaled nanos) and DECIMAL
    (unbounded zigzag mantissas + scale stream) interop with pyarrow in
    both directions."""
    import decimal

    import pyarrow as pa
    import pyarrow.orc as po

    ts = np.asarray(["2024-01-15T12:30:45.123456789",
                     "2015-01-01T00:00:00",
                     "1969-12-31T23:59:59.5",
                     "2030-06-01T08:00:00.5"], "datetime64[ns]")
    dec = [decimal.Decimal("123.45"), decimal.Decimal("-0.001"),
           decimal.Decimal("-7.25"),
           decimal.Decimal("99999999999999999999.99")]

    # ours -> pyarrow
    ours = str(tmp_path / "ours.orc")
    write_orc([RecordBatch({"t": ts, "d": np.asarray(dec, object)})], ours)
    t = po.read_table(ours)
    assert [x.isoformat() for x in t["t"].to_pylist()] == [
        "2024-01-15T12:30:45.123456789",    # full nanosecond precision
        "2015-01-01T00:00:00",
        "1969-12-31T23:59:59.500000",       # pre-1970 fractional
        "2030-06-01T08:00:00.500000"]
    assert t["d"].to_pylist() == dec        # values equal (scale-normalized)

    # pyarrow -> ours
    theirs = str(tmp_path / "pa.orc")
    po.write_table(pa.table({"t": ts, "d": dec}), theirs,
                   compression="uncompressed")
    (got,) = list(read_orc(theirs))
    assert np.array_equal(np.asarray(got.column("t"), "datetime64[ns]"), ts)
    assert list(got.column("d")) == dec


def test_orc_timestamp_decimal_round_trip(tmp_path):
    """No-pyarrow-needed round trip of the new ORC types, including
    nanosecond precision and negative/large mantissas."""
    import decimal

    ts = np.asarray(["1999-12-31T23:59:59.999999999",
                     "2015-01-01T00:00:00.000000001",
                     "1969-12-31T23:59:59.5",      # pre-1970 fractional:
                     "1969-06-01T00:00:00.25",     # trunc-toward-zero secs
                     "2024-07-04T00:00:00"], "datetime64[ns]")
    dec = [decimal.Decimal("0"), decimal.Decimal("-12345.678901"),
           decimal.Decimal("7"), decimal.Decimal("-0.5"),
           decimal.Decimal("1E+5")]
    path = str(tmp_path / "t.orc")
    write_orc([RecordBatch({"t": ts, "d": np.asarray(dec, object)})], path)
    (got,) = list(read_orc(path))
    assert np.array_equal(np.asarray(got.column("t"), "datetime64[ns]"), ts)
    assert list(got.column("d")) == dec


def test_jsonl_float_columns_not_truncated(tmp_path):
    """Regression: the rows->columns coercion must never pick int64 for a
    float column (np.asarray([1.5], int64) silently truncates)."""
    from flink_tpu.formats import read_jsonl, write_jsonl

    path = str(tmp_path / "f.jsonl")
    with open(path, "w") as f:
        f.write('{"a": 1.5, "b": 2, "c": true}\n')
        f.write('{"a": 2.5, "b": 3, "c": false}\n')
    (b,) = list(read_jsonl(path))
    assert np.asarray(b.column("a")).tolist() == [1.5, 2.5]
    assert np.asarray(b.column("b")).dtype == np.int64
    assert np.asarray(b.column("c")).dtype == np.bool_


def test_sequencefile_round_trip_and_layout(tmp_path):
    """Hadoop SequenceFile v6 (Text/Text, record format): round trip plus
    hand-decoded header bytes the Hadoop reader expects."""
    from flink_tpu.formats import reader_for, writer_for

    path = str(tmp_path / "t.seq")
    b = RecordBatch({"k": np.asarray(["a", "b"], object),
                     "v": np.asarray([1.5, 2.5])})
    assert writer_for("seq")([b], path, key_column="k") == 2
    raw = open(path, "rb").read()
    assert raw[:4] == b"SEQ\x06"
    assert b"org.apache.hadoop.io.Text" in raw[:64]
    (got,) = list(reader_for("seq")(path))
    rows = got.to_rows()
    # the record KEY survives as its own column (foreign files may keep
    # meaning only there)
    assert rows == [{"k": "a", "v": 1.5, "key": "a"},
                    {"k": "b", "v": 2.5, "key": "b"}]


def test_sequencefile_sync_markers_and_skip(tmp_path):
    from flink_tpu.formats.sequencefile import (read_sequencefile,
                                                write_sequencefile)

    path = str(tmp_path / "big.seq")
    n = 500                                   # enough to cross sync points
    b = RecordBatch({"i": np.arange(n, dtype=np.int64),
                     "pad": np.asarray(["x" * 40] * n, object)})
    write_sequencefile([b], path, key_column="i")
    got = [r["i"] for bt in read_sequencefile(path, batch_size=64)
           for r in bt.to_rows()]
    assert got == list(range(n))
    # positioned resume (the source-reader skip contract)
    rest = [r["i"] for bt in read_sequencefile(path, skip_rows=490)
            for r in bt.to_rows()]
    assert rest == list(range(490, 500))


def test_sequencefile_plain_text_values(tmp_path):
    """Foreign files whose Text values are NOT JSON stay readable as
    key/value rows."""
    from flink_tpu.formats.sequencefile import (_text, read_sequencefile,
                                                MAGIC, TEXT, VERSION)
    import os as _os
    import struct as _struct

    path = str(tmp_path / "foreign.seq")
    sync = _os.urandom(16)
    with open(path, "wb") as f:
        f.write(MAGIC + bytes([VERSION]))
        f.write(_text(TEXT) + _text(TEXT) + b"\x00\x00")
        f.write(_struct.pack(">i", 0) + sync)
        krec, vrec = _text(b"k1"), _text(b"hello world")
        f.write(_struct.pack(">ii", len(krec) + len(vrec), len(krec))
                + krec + vrec)
    (got,) = list(read_sequencefile(path))
    assert got.to_rows() == [{"key": "k1", "value": "hello world"}]
