"""State schema evolution: versioned snapshots, widening migration,
incompatible-change rejection (serializer-snapshot analog)."""

import numpy as np
import pytest

from flink_tpu.state.api import ValueStateDescriptor
from flink_tpu.state.evolution import (AFTER_MIGRATION, AS_IS, INCOMPATIBLE,
                                       SchemaEvolutionError,
                                       resolve_compatibility)
from flink_tpu.state.heap import HeapKeyedStateBackend


def test_resolve_verdicts():
    v = resolve_compatibility({"kind": "value", "dtype": "int32", "shape": ()},
                              {"kind": "value", "dtype": "int32", "shape": ()})
    assert v == AS_IS
    v = resolve_compatibility({"kind": "value", "dtype": "int32", "shape": ()},
                              {"kind": "value", "dtype": "int64", "shape": ()})
    assert v == AFTER_MIGRATION
    v = resolve_compatibility({"kind": "value", "dtype": "int64", "shape": ()},
                              {"kind": "value", "dtype": "int32", "shape": ()})
    assert v == INCOMPATIBLE   # narrowing
    v = resolve_compatibility({"kind": "value", "dtype": "int32", "shape": ()},
                              {"kind": "list", "dtype": "int32", "shape": ()})
    assert v == INCOMPATIBLE   # kind change


def test_snapshot_carries_schema_and_widens_on_restore():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int32, default=0))
    slots = b.key_slots(np.array([1, 2, 3]))
    st.put_rows(slots, np.array([10, 20, 30], np.int32))
    snap = b.snapshot()
    assert snap["__schema__"]["v"]["dtype"] == "int32"

    # the evolved job registers the SAME state as int64: widening migration
    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    st2 = b2.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    got, alive = st2.get_rows(b2.key_slots(np.array([1, 2, 3])))
    assert got.dtype == np.int64
    assert got.tolist() == [10, 20, 30]


def test_incompatible_restore_fails_loudly():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    b.set_current_key(1)
    st.update(7)
    snap = b.snapshot()

    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    with pytest.raises(SchemaEvolutionError, match="widening"):
        b2.get_state(ValueStateDescriptor("v", dtype=np.int32, default=0))


def test_added_state_starts_empty():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("old", dtype=np.int32, default=0))
    b.set_current_key(1)
    st.update(5)
    snap = b.snapshot()

    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    new = b2.get_state(ValueStateDescriptor("brand_new", dtype=np.float64,
                                            default=-1.0))
    b2.set_current_key(1)
    assert new.value() == -1.0
    assert b2.get_state(ValueStateDescriptor("old", dtype=np.int32,
                                             default=0)).value() == 5


def test_schema_survives_restore_snapshot_cycle():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int32, default=0))
    b.set_current_key(1)
    st.update(3)
    snap = b.snapshot()
    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    snap2 = b2.snapshot()   # no re-registration before re-snapshot
    assert snap2["__schema__"]["v"]["dtype"] == "int32"


# ---------------------------------------------------------------------------
# composite accumulator evolution (ACC pytree field add/remove/widen)
# ---------------------------------------------------------------------------

def _window_op(agg, tuple_acc=True):
    import jax.numpy as jnp  # noqa: F401
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    # TupleAggregator lifts a column DICT; scalar aggregators lift the column
    kw = (dict(value_selector=lambda c: {"v": c["v"]}) if tuple_acc
          else dict(value_column="v"))
    op = WindowAggOperator(TumblingEventTimeWindows.of(1000), agg,
                           key_column="k", **kw)
    op.open(RuntimeContext())
    return op


def _feed(op, keys, vals, ts):
    from flink_tpu.core.batch import RecordBatch

    return op.process_batch(RecordBatch(
        {"k": np.asarray(keys, np.int64), "v": np.asarray(vals, np.float64)},
        timestamps=np.asarray(ts, np.int64)))


def test_acc_field_added_window_state():
    """SUM ACC evolves to a (sum, count)-style composite: the stored leaf
    restores by NAME, the added field starts at its identity."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import Watermark
    from flink_tpu.core.functions import (AvgAggregator, SumAggregator,
                                          TupleAggregator)

    op = _window_op(TupleAggregator({"s": ("v", SumAggregator(jnp.float32))}))
    _feed(op, [1, 1], [2., 3.], [10, 20])
    snap = op.snapshot_state()
    assert any("'s'" in e["name"] for e in snap["leaf_schema"])

    # v2 of the job adds an average over the same column
    op2 = _window_op(TupleAggregator({
        "s": ("v", SumAggregator(jnp.float32)),
        "a": ("v", AvgAggregator(jnp.float32))}))
    op2.restore_state(snap)
    _feed(op2, [1], [5.], [30])
    out = op2.process_watermark(Watermark(1000))
    rows = [r for b in out for r in b.to_rows()]
    assert len(rows) == 1
    assert rows[0]["s"] == 10.0          # 2+3 restored + 5
    assert rows[0]["a"] == 5.0           # avg counts only post-evolution rows


def test_acc_field_removed_window_state():
    import jax.numpy as jnp

    from flink_tpu.core.batch import Watermark
    from flink_tpu.core.functions import (CountAggregator, SumAggregator,
                                          TupleAggregator)

    op = _window_op(TupleAggregator({
        "s": ("v", SumAggregator(jnp.float32)),
        "n": ("v", CountAggregator())}))
    _feed(op, [7], [4.], [100])
    snap = op.snapshot_state()

    op2 = _window_op(TupleAggregator({"s": ("v", SumAggregator(jnp.float32))}))
    op2.restore_state(snap)
    _feed(op2, [7], [6.], [200])
    out = op2.process_watermark(Watermark(1000))
    rows = [r for b in out for r in b.to_rows()]
    assert rows[0]["s"] == 10.0


def test_acc_leaf_narrowing_rejected():
    import jax.numpy as jnp

    from flink_tpu.core.functions import SumAggregator
    from flink_tpu.state.evolution import SchemaEvolutionError

    # float32 -> int32 is not on the widening lattice (jax-without-x64
    # cannot even materialize a float64 ACC to narrow from)
    op = _window_op(SumAggregator(jnp.float32), tuple_acc=False)
    _feed(op, [1], [1.], [10])
    snap = op.snapshot_state()
    op2 = _window_op(SumAggregator(jnp.int32), tuple_acc=False)
    with pytest.raises(SchemaEvolutionError, match="widening"):
        op2.restore_state(snap)


def test_acc_evolution_heap_backend():
    from flink_tpu.core.functions import (AvgAggregator, SumAggregator,
                                          TupleAggregator)
    from flink_tpu.state.api import AggregatingStateDescriptor
    from flink_tpu.state.heap import HeapKeyedStateBackend

    b = HeapKeyedStateBackend()
    st = b.get_state(AggregatingStateDescriptor(
        "agg", TupleAggregator({"s": ("v", SumAggregator(np.float32))})))
    b.set_current_key(5)
    # TupleAggregator lifts a column dict -> use the batched rows API
    st.add_rows(np.array([st._slot(), st._slot()]),
                {"v": np.array([2.0, 3.0])})
    snap = b.snapshot()

    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    st2 = b2.get_state(AggregatingStateDescriptor(
        "agg", TupleAggregator({"s": ("v", SumAggregator(np.float32)),
                                "a": ("v", AvgAggregator(np.float32))})))
    b2.set_current_key(5)
    st2.add_rows(np.array([st2._slot()]), {"v": np.array([5.0])})
    got = st2.get()
    assert float(got["s"]) == 10.0 and float(got["a"]) == 5.0


def test_aggregating_state_rescale_with_leaf_schema():
    """Regression: leaf_schema is per-state metadata — keyed rescale must
    not try to split it by key group."""
    from flink_tpu.core.functions import SumAggregator
    from flink_tpu.state.api import AggregatingStateDescriptor
    from flink_tpu.state.heap import HeapKeyedStateBackend
    from flink_tpu.state.redistribute import split_keyed_snapshot

    b = HeapKeyedStateBackend()
    st = b.get_state(AggregatingStateDescriptor(
        "agg", SumAggregator(np.float32)))
    for k, v in [(1, 2.0), (2, 3.0), (3, 4.0)]:
        b.set_current_key(k)
        st.add(v)
    snap = b.snapshot()
    parts = split_keyed_snapshot(snap, HeapKeyedStateBackend.row_fields(snap),
                                 128, 2)
    assert len(parts) == 2
    total = 0.0
    for p in parts:
        b2 = HeapKeyedStateBackend()
        b2.restore(p)
        st2 = b2.get_state(AggregatingStateDescriptor(
            "agg", SumAggregator(np.float32)))
        for k in (1, 2, 3):
            try:
                b2.set_current_key(k)
            except Exception:
                continue
            got = st2.get()
            if got is not None:
                total += float(got)
    assert total == 9.0
