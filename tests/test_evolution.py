"""State schema evolution: versioned snapshots, widening migration,
incompatible-change rejection (serializer-snapshot analog)."""

import numpy as np
import pytest

from flink_tpu.state.api import ValueStateDescriptor
from flink_tpu.state.evolution import (AFTER_MIGRATION, AS_IS, INCOMPATIBLE,
                                       SchemaEvolutionError,
                                       resolve_compatibility)
from flink_tpu.state.heap import HeapKeyedStateBackend


def test_resolve_verdicts():
    v = resolve_compatibility({"kind": "value", "dtype": "int32", "shape": ()},
                              {"kind": "value", "dtype": "int32", "shape": ()})
    assert v == AS_IS
    v = resolve_compatibility({"kind": "value", "dtype": "int32", "shape": ()},
                              {"kind": "value", "dtype": "int64", "shape": ()})
    assert v == AFTER_MIGRATION
    v = resolve_compatibility({"kind": "value", "dtype": "int64", "shape": ()},
                              {"kind": "value", "dtype": "int32", "shape": ()})
    assert v == INCOMPATIBLE   # narrowing
    v = resolve_compatibility({"kind": "value", "dtype": "int32", "shape": ()},
                              {"kind": "list", "dtype": "int32", "shape": ()})
    assert v == INCOMPATIBLE   # kind change


def test_snapshot_carries_schema_and_widens_on_restore():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int32, default=0))
    slots = b.key_slots(np.array([1, 2, 3]))
    st.put_rows(slots, np.array([10, 20, 30], np.int32))
    snap = b.snapshot()
    assert snap["__schema__"]["v"]["dtype"] == "int32"

    # the evolved job registers the SAME state as int64: widening migration
    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    st2 = b2.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    got, alive = st2.get_rows(b2.key_slots(np.array([1, 2, 3])))
    assert got.dtype == np.int64
    assert got.tolist() == [10, 20, 30]


def test_incompatible_restore_fails_loudly():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    b.set_current_key(1)
    st.update(7)
    snap = b.snapshot()

    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    with pytest.raises(SchemaEvolutionError, match="widening"):
        b2.get_state(ValueStateDescriptor("v", dtype=np.int32, default=0))


def test_added_state_starts_empty():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("old", dtype=np.int32, default=0))
    b.set_current_key(1)
    st.update(5)
    snap = b.snapshot()

    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    new = b2.get_state(ValueStateDescriptor("brand_new", dtype=np.float64,
                                            default=-1.0))
    b2.set_current_key(1)
    assert new.value() == -1.0
    assert b2.get_state(ValueStateDescriptor("old", dtype=np.int32,
                                             default=0)).value() == 5


def test_schema_survives_restore_snapshot_cycle():
    b = HeapKeyedStateBackend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int32, default=0))
    b.set_current_key(1)
    st.update(3)
    snap = b.snapshot()
    b2 = HeapKeyedStateBackend()
    b2.restore(snap)
    snap2 = b2.snapshot()   # no re-registration before re-snapshot
    assert snap2["__schema__"]["v"]["dtype"] == "int32"
