"""SQL layer tests: parser, expressions, planner, end-to-end queries.

Modeled on the reference's planner/runtime ITCases
(``flink-table-planner-blink`` ``GroupWindowITCase`` et al.): run SQL over
bounded in-memory tables and assert result rows, including the group-window
path of baseline config #5 (SQL TUMBLE over a TPC-H-lineitem-shaped stream).
"""

import numpy as np
import pytest

from flink_tpu.sql import TableEnvironment, parse
from flink_tpu.sql.parser import (Binary, Call, Column, Interval, Literal,
                                  SqlParseError)


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

def test_parse_simple_select():
    s = parse("SELECT a, b + 1 AS c FROM t WHERE a > 3")
    assert s.table == "t"
    assert len(s.items) == 2
    assert s.items[0].expr == Column("a")
    assert s.items[1].alias == "c"
    assert s.where == Binary(">", Column("a"), Literal(3))


def test_parse_group_window():
    s = parse("SELECT k, SUM(v) FROM t "
              "GROUP BY k, TUMBLE(ts, INTERVAL '5' SECOND)")
    assert s.group_by[0] == Column("k")
    w = s.group_by[1]
    assert isinstance(w, Call) and w.name == "TUMBLE"
    assert w.args[1] == Interval(5000)


def test_parse_interval_units():
    assert parse("SELECT a FROM t WHERE ts > INTERVAL '2' MINUTE").where.right \
        == Interval(120_000)


def test_parse_order_limit():
    s = parse("SELECT a FROM t ORDER BY a DESC, b LIMIT 7")
    assert s.order_by[0] == (Column("a"), False)
    assert s.order_by[1] == (Column("b"), True)
    assert s.limit == 7


def test_parse_errors():
    with pytest.raises(SqlParseError):
        parse("SELECT FROM t")
    with pytest.raises(SqlParseError):
        parse("SELECT a FROM t WHERE")


# ---------------------------------------------------------------------------
# projection / filter queries
# ---------------------------------------------------------------------------

def _tenv():
    return TableEnvironment()


def test_select_projection_and_where():
    t = _tenv()
    t.register_collection("r", columns={
        "a": np.arange(10, dtype=np.int64),
        "b": np.arange(10, dtype=np.float64) * 2.0,
    })
    rows = t.execute_sql(
        "SELECT a, b * 10 AS b10 FROM r WHERE a >= 6").collect()
    assert [r["a"] for r in rows] == [6, 7, 8, 9]
    assert [r["b10"] for r in rows] == [120.0, 140.0, 160.0, 180.0]


def test_select_star_and_functions():
    t = _tenv()
    t.register_collection("r", rows=[
        {"name": "ab", "x": -3}, {"name": "CdE", "x": 4}])
    rows = t.execute_sql(
        "SELECT UPPER(name) AS u, ABS(x) AS ax, CHAR_LENGTH(name) ln "
        "FROM r").collect()
    assert rows[0] == {"u": "AB", "ax": 3, "ln": 2}
    assert rows[1]["u"] == "CDE"


def test_case_between_in_like():
    t = _tenv()
    t.register_collection("r", rows=[
        {"s": "apple", "v": 1}, {"s": "banana", "v": 5}, {"s": "avocado", "v": 9}])
    rows = t.execute_sql(
        "SELECT s, CASE WHEN v BETWEEN 0 AND 4 THEN 'low' "
        "WHEN v IN (5, 6) THEN 'mid' ELSE 'high' END AS bucket "
        "FROM r WHERE s LIKE 'a%' OR s = 'banana'").collect()
    assert [r["bucket"] for r in rows] == ["low", "mid", "high"]


def test_cast_and_division_semantics():
    t = _tenv()
    t.register_collection("r", columns={"a": np.array([7, -7], np.int64),
                                        "b": np.array([2, 2], np.int64)})
    rows = t.execute_sql(
        "SELECT a / b AS q, CAST(a AS DOUBLE) / b AS f FROM r").collect()
    # integer division truncates toward zero (Calcite/Java semantics)
    assert [r["q"] for r in rows] == [3, -3]
    assert rows[0]["f"] == pytest.approx(3.5)


# ---------------------------------------------------------------------------
# aggregates
# ---------------------------------------------------------------------------

def test_global_aggregate():
    t = _tenv()
    t.register_collection("r", columns={"v": np.arange(1, 101, dtype=np.float64)})
    rows = t.execute_sql(
        "SELECT SUM(v) AS s, COUNT(*) AS c, AVG(v) AS a, MIN(v) AS lo, "
        "MAX(v) AS hi FROM r").collect()
    assert len(rows) == 1
    r = rows[0]
    assert r["s"] == pytest.approx(5050.0)
    assert r["c"] == 100
    assert r["a"] == pytest.approx(50.5)
    assert (r["lo"], r["hi"]) == (1.0, 100.0)


def test_group_by_single_key():
    t = _tenv()
    t.register_collection("r", rows=[
        {"k": "x", "v": 1.0}, {"k": "y", "v": 2.0}, {"k": "x", "v": 3.0},
        {"k": "y", "v": 4.0}, {"k": "x", "v": 5.0}])
    rows = t.execute_sql(
        "SELECT k, SUM(v) AS s, COUNT(*) AS c FROM r GROUP BY k "
        "ORDER BY k").collect()
    assert rows == [{"k": "x", "s": 9.0, "c": 3}, {"k": "y", "s": 6.0, "c": 2}]


def test_group_by_multi_key_and_having():
    t = _tenv()
    t.register_collection("r", rows=[
        {"a": "p", "b": 1, "v": 10.0}, {"a": "p", "b": 2, "v": 20.0},
        {"a": "q", "b": 1, "v": 30.0}, {"a": "p", "b": 1, "v": 40.0}])
    rows = t.execute_sql(
        "SELECT a, b, SUM(v) AS s FROM r GROUP BY a, b "
        "HAVING SUM(v) > 25 ORDER BY s").collect()
    assert rows == [{"a": "q", "b": 1, "s": 30.0},
                    {"a": "p", "b": 1, "s": 50.0}]


def test_order_by_aggregate_and_limit():
    t = _tenv()
    t.register_collection("r", rows=[
        {"k": "a", "v": 1.0}, {"k": "b", "v": 9.0}, {"k": "c", "v": 5.0}])
    rows = t.execute_sql(
        "SELECT k, SUM(v) AS s FROM r GROUP BY k ORDER BY SUM(v) DESC "
        "LIMIT 2").collect()
    assert [r["k"] for r in rows] == ["b", "c"]


# ---------------------------------------------------------------------------
# group windows (baseline config #5 shape)
# ---------------------------------------------------------------------------

def test_tumble_window_sql():
    t = _tenv()
    t.register_collection(
        "events",
        columns={
            "k": np.array(["a", "a", "b", "a", "b"], object),
            "v": np.array([1.0, 2.0, 10.0, 4.0, 20.0]),
            "ts": np.array([1000, 2000, 3000, 7000, 8000], np.int64),
        })
    rows = t.execute_sql(
        "SELECT k, TUMBLE_START(ts, INTERVAL '5' SECOND) AS ws, "
        "TUMBLE_END(ts, INTERVAL '5' SECOND) AS we, SUM(v) AS s "
        "FROM events GROUP BY k, TUMBLE(ts, INTERVAL '5' SECOND) "
        "ORDER BY ws, k").collect()
    assert rows == [
        {"k": "a", "ws": 0, "we": 5000, "s": 3.0},
        {"k": "b", "ws": 0, "we": 5000, "s": 10.0},
        {"k": "a", "ws": 5000, "we": 10000, "s": 4.0},
        {"k": "b", "ws": 5000, "we": 10000, "s": 20.0},
    ]


def test_hop_window_sql():
    t = _tenv()
    t.register_collection(
        "events",
        columns={"k": np.array(["a"] * 4, object),
                 "v": np.array([1.0, 2.0, 4.0, 8.0]),
                 "ts": np.array([0, 4000, 8000, 12000], np.int64)})
    rows = t.execute_sql(
        "SELECT k, HOP_START(ts, INTERVAL '5' SECOND, INTERVAL '10' SECOND) ws,"
        " SUM(v) AS s FROM events "
        "GROUP BY k, HOP(ts, INTERVAL '5' SECOND, INTERVAL '10' SECOND) "
        "ORDER BY ws").collect()
    # sliding 10s windows every 5s: [-5,5): 1+2, [0,10): 1+2+4, [5,15): 4+8, [10,20): 8
    assert [(r["ws"], r["s"]) for r in rows] == [
        (-5000, 3.0), (0, 7.0), (5000, 12.0), (10000, 8.0)]


def test_session_window_sql():
    t = _tenv()
    t.register_collection(
        "events",
        columns={"k": np.array(["a"] * 4, object),
                 "v": np.array([1.0, 2.0, 4.0, 8.0]),
                 "ts": np.array([0, 1000, 2000, 60_000], np.int64)})
    rows = t.execute_sql(
        "SELECT k, SESSION_START(ts, INTERVAL '10' SECOND) ws, SUM(v) s "
        "FROM events GROUP BY k, SESSION(ts, INTERVAL '10' SECOND) "
        "ORDER BY ws").collect()
    assert [(r["ws"], r["s"]) for r in rows] == [(0, 7.0), (60_000, 8.0)]


def test_tpch_q1_shape():
    """Baseline config #5: GroupWindowAggregate over a TPC-H lineitem stream."""
    rng = np.random.default_rng(42)
    n = 5000
    flags = np.array(["A", "N", "R"], object)[rng.integers(0, 3, n)]
    status = np.array(["F", "O"], object)[rng.integers(0, 2, n)]
    qty = rng.uniform(1, 50, n)
    price = rng.uniform(900, 100_000, n)
    disc = rng.uniform(0, 0.1, n)
    tax = rng.uniform(0, 0.08, n)
    ts = np.sort(rng.integers(0, 60_000, n)).astype(np.int64)

    t = _tenv()
    t.register_collection("lineitem", columns={
        "l_returnflag": flags, "l_linestatus": status,
        "l_quantity": qty, "l_extendedprice": price,
        "l_discount": disc, "l_tax": tax, "l_shipdate": ts})
    rows = t.execute_sql("""
        SELECT l_returnflag, l_linestatus,
               TUMBLE_START(l_shipdate, INTERVAL '10' SECOND) AS ws,
               SUM(l_quantity) AS sum_qty,
               SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               AVG(l_quantity) AS avg_qty,
               COUNT(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= 60000 - INTERVAL '5' SECOND
        GROUP BY l_returnflag, l_linestatus,
                 TUMBLE(l_shipdate, INTERVAL '10' SECOND)
        ORDER BY l_returnflag, l_linestatus, ws
    """).collect()
    assert rows, "TPC-H Q1-shaped query returned no rows"

    # cross-check one group against numpy
    m = ((flags == "A") & (status == "F") & (ts < 10_000)
         & (ts <= 60_000 - 5000))
    expect = float(qty[m].sum())
    got = [r for r in rows if r["l_returnflag"] == "A"
           and r["l_linestatus"] == "F" and r["ws"] == 0]
    assert len(got) == 1
    assert got[0]["sum_qty"] == pytest.approx(expect, rel=1e-4)
    assert got[0]["count_order"] == int(m.sum())
    expect_disc = float((price[m] * (1 - disc[m])).sum())
    assert got[0]["sum_disc_price"] == pytest.approx(expect_disc, rel=1e-4)


# ---------------------------------------------------------------------------
# Table API + views
# ---------------------------------------------------------------------------

def test_table_api_fluent():
    t = _tenv()
    t.register_collection("r", columns={"a": np.arange(6, dtype=np.int64)})
    rows = (t.sql_query("SELECT a FROM r")
            .where("a % 2 = 0")
            .execute().collect())
    assert [r["a"] for r in rows] == [0, 2, 4]

    g = t.sql_query("SELECT * FROM r").group_by("a % 3").select(
        "COUNT(*) AS c")
    assert sorted(r["c"] for r in g.execute().collect()) == [2, 2, 2]


def test_temporary_view():
    t = _tenv()
    t.register_collection("r", rows=[
        {"k": "x", "v": 1.0}, {"k": "x", "v": 3.0}, {"k": "y", "v": 5.0}])
    v = t.sql_query("SELECT k, SUM(v) AS s FROM r GROUP BY k")
    t.create_temporary_view("sums", v)
    rows = t.execute_sql(
        "SELECT k, s * 2 AS d FROM sums ORDER BY k").collect()
    assert rows == [{"k": "x", "d": 8.0}, {"k": "y", "d": 10.0}]


def test_table_api_where_select_composition():
    """where() must survive a subsequent select()/group_by() (review fix)."""
    t = _tenv()
    t.register_collection("r", columns={"a": np.arange(6, dtype=np.int64)})
    rows = (t.sql_query("SELECT * FROM r").where("a > 3").select("a")
            .execute().collect())
    assert [r["a"] for r in rows] == [4, 5]
    rows = (t.sql_query("SELECT * FROM r").where("a > 1").where("a < 4")
            .execute().collect())
    assert [r["a"] for r in rows] == [2, 3]
    rows = (t.sql_query("SELECT * FROM r").where("a >= 2")
            .group_by("a % 2").select("COUNT(*) AS c").execute().collect())
    assert sorted(r["c"] for r in rows) == [2, 2]


def test_unaliased_aggregate_names():
    t = _tenv()
    t.register_collection("g", rows=[{"k": "x", "v": 1.0}, {"k": "x", "v": 2.0}])
    res = t.execute_sql("SELECT k, SUM(v) FROM g GROUP BY k")
    assert res.output_columns == ["k", "sum_v"]
    assert res.collect() == [{"k": "x", "sum_v": 3.0}]


def test_cast_string_boolean():
    t = _tenv()
    t.register_collection("r", rows=[
        {"f": "true", "x": 1}, {"f": "false", "x": 2}, {"f": "1", "x": 3}])
    rows = t.execute_sql(
        "SELECT x FROM r WHERE CAST(f AS BOOLEAN)").collect()
    assert [r["x"] for r in rows] == [1, 3]


def test_windowed_query_over_view():
    t = _tenv()
    t.register_collection("e", columns={
        "k": np.array(["a", "a"], object), "v": np.array([1.0, 2.0]),
        "ts": np.array([0, 1000], np.int64)})
    t.create_temporary_view("ve", t.sql_query("SELECT k, v, ts FROM e"))
    rows = t.execute_sql(
        "SELECT k, SUM(v) s FROM ve "
        "GROUP BY k, TUMBLE(ts, INTERVAL '5' SECOND)").collect()
    assert rows == [{"k": "a", "s": 3.0}]


def test_mod_sign_and_having_in():
    t = _tenv()
    t.register_collection("m", columns={"a": np.array([-7, 7], np.int64),
                                        "k": np.array([0, 1], np.int64)})
    rows = t.execute_sql("SELECT a % 2 AS r, MOD(a, 2) AS m2 FROM m").collect()
    assert [r["r"] for r in rows] == [-1, 1]
    assert [r["m2"] for r in rows] == [-1, 1]
    rows = t.execute_sql(
        "SELECT k, SUM(a) AS s FROM m GROUP BY k HAVING SUM(a) IN (7)").collect()
    assert rows == [{"k": 1, "s": 7.0}]


def test_non_grouped_column_rejected():
    from flink_tpu.sql import PlanError
    t = _tenv()
    t.register_collection("e", rows=[{"k": "a", "v": 1.0}])
    with pytest.raises(PlanError):
        t.execute_sql("SELECT v, SUM(v) AS s FROM e GROUP BY k")
