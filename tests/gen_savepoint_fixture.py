"""Regenerate the checked-in savepoint compatibility fixture.

Run from the repo root:  JAX_PLATFORMS=cpu python tests/gen_savepoint_fixture.py

The fixture freezes the CURRENT checkpoint format; the accompanying test
(``test_savepoint_compat.py``) asserts every later round still restores it —
the analog of the reference's cross-version snapshot files
(``OperatorSnapshotUtil.java``, ``flink-stream-stateful-job-upgrade-test``).
Never regenerate in the same change that alters the snapshot format, unless
a deliberate (documented) format break with a version bump is intended.
"""

import os
import shutil
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

FIXTURE = os.path.join(HERE, "fixtures", "savepoint_v1")


def main():
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import AvgAggregator, RuntimeContext, SumAggregator
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.runtime.checkpoint.storage import write_savepoint
    from flink_tpu.windowing.assigners import SessionGap, TumblingEventTimeWindows

    rng = np.random.default_rng(42)
    keys = rng.integers(0, 50, 400).astype(np.int64)
    vals = (np.arange(400) % 7).astype(np.float32)
    ts = np.sort(rng.integers(0, 5000, 400)).astype(np.int64)

    win = WindowAggOperator(
        TumblingEventTimeWindows.of(10_000), SumAggregator(jnp.float32),
        key_column="k", value_column="v")
    win.open(RuntimeContext())
    win.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))

    avg = WindowAggOperator(
        TumblingEventTimeWindows.of(10_000), AvgAggregator(jnp.float32),
        key_column="k", value_column="v", output_column="avg")
    avg.open(RuntimeContext())
    avg.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))

    sess = SessionWindowOperator(
        SessionGap(500), SumAggregator(jnp.float32),
        key_column="k", value_column="v")
    sess.open(RuntimeContext())
    sess.process_batch(RecordBatch({"k": keys[:100], "v": vals[:100]},
                                   timestamps=ts[:100]))

    snapshot = {
        "tumbling-sum": win.snapshot_state(),
        "tumbling-avg": avg.snapshot_state(),
        "session-sum": sess.snapshot_state(),
        "__fixture__": {
            "keys": keys, "vals": vals, "ts": ts,
            "expected_sum_total": float(vals.sum()),
        },
    }
    if os.path.isdir(FIXTURE):
        shutil.rmtree(FIXTURE)
    path = write_savepoint(FIXTURE, snapshot)
    print("wrote", path)


if __name__ == "__main__":
    main()
