"""Checkpoint/restore/rescale tests.

Modeled on the reference's checkpointing ITCases: snapshot mid-stream,
restore into a fresh job, and assert the continued run equals an
uninterrupted one (exactly-once state semantics); key-group redistribution
mirrors ``StateAssignmentOperation`` rescale tests.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.runtime.checkpoint import (FileCheckpointStorage,
                                          InMemoryCheckpointStorage,
                                          read_savepoint, write_savepoint)
from flink_tpu.state.redistribute import (merge_keyed_snapshots,
                                          split_keyed_snapshot)
from flink_tpu.windowing import TumblingEventTimeWindows


def make_op(**kw):
    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(jnp.float32),
                           key_column="k", value_column="v", **kw)
    op.open(RuntimeContext())
    return op


def feed(op, keys, vals, ts, wm=None):
    out = op.process_batch(RecordBatch(
        {"k": np.asarray(keys), "v": np.asarray(vals, np.float32)},
        timestamps=np.asarray(ts, np.int64)))
    if wm is not None:
        out += op.process_watermark(Watermark(wm))
    return out


def collect(elements):
    rows = {}
    for b in elements:
        for i in range(len(b)):
            rows[(int(np.asarray(b.column("k"))[i]),
                  int(np.asarray(b.column("window_start"))[i]))] = float(
                np.asarray(b.column("result"))[i])
    return rows


def test_file_storage_roundtrip(tmp_path):
    st = FileCheckpointStorage(str(tmp_path), retain=2)
    snap = {"op-a": {"x": np.arange(5), "nested": {"y": np.ones((2, 3))},
                     "scalar": 7, "none": None},
            "op-b": {"keys": {"raw": np.asarray(["a", "b"], object)}}}
    st.store(1, snap)
    st.store(2, snap)
    st.store(3, snap)
    assert st.checkpoint_ids() == [2, 3]  # retention
    back = st.load(3)
    assert np.array_equal(back["op-a"]["x"], np.arange(5))
    assert back["op-a"]["scalar"] == 7
    assert list(back["op-b"]["keys"]["raw"]) == ["a", "b"]
    assert st.metadata(3)["checkpoint_id"] == 3


def test_exactly_once_resume_equals_uninterrupted():
    rng = np.random.default_rng(5)
    n = 4000
    keys = rng.integers(0, 97, n)
    vals = rng.random(n).astype(np.float32)
    ts = np.sort(rng.integers(0, 4000, n))
    half = n // 2

    # uninterrupted
    op_ref = make_op()
    out = feed(op_ref, keys[:half], vals[:half], ts[:half], wm=int(ts[half - 1]))
    out += feed(op_ref, keys[half:], vals[half:], ts[half:], wm=5000)
    expected = collect([e for e in out if isinstance(e, RecordBatch)])

    # snapshot after first half, restore into a NEW operator, continue
    op_a = make_op()
    out_a = feed(op_a, keys[:half], vals[:half], ts[:half], wm=int(ts[half - 1]))
    snap = op_a.snapshot_state()
    op_b = make_op()
    op_b.restore_state(snap)
    out_b = feed(op_b, keys[half:], vals[half:], ts[half:], wm=5000)
    got = collect([e for e in out_a + out_b if isinstance(e, RecordBatch)])
    assert got.keys() == expected.keys()
    for k in expected:
        assert abs(got[k] - expected[k]) < 1e-2


def test_env_level_checkpoint_restore():
    from flink_tpu.datastream import StreamExecutionEnvironment

    def build(env, cols):
        return (env.from_collection(columns=cols)
                .assign_timestamps_and_watermarks(0, timestamp_column="t")
                .key_by("k")
                .window(TumblingEventTimeWindows.of(1000))
                .sum("v"))

    rng = np.random.default_rng(11)
    n = 3000
    keys = rng.integers(0, 53, n)
    vals = rng.random(n).astype(np.float32)
    ts = np.sort(rng.integers(0, 3000, n))
    half = n // 2
    part1 = {"k": keys[:half], "v": vals[:half], "t": ts[:half]}
    part2 = {"k": keys[half:], "v": vals[half:], "t": ts[half:]}
    whole = {"k": keys, "v": vals, "t": ts}

    env_ref = StreamExecutionEnvironment()
    sink_ref = build(env_ref, whole).collect()
    env_ref.execute()
    expected = {(r["k"], r["window_start"]): r["v"] for r in sink_ref.rows()}

    st = InMemoryCheckpointStorage()
    env1 = StreamExecutionEnvironment()
    sink1 = build(env1, part1).collect()
    # stop WITHOUT drain: in-progress windows stay open for the restored job
    env1.execute(drain=False)
    st.store(1, env1._last_executor.trigger_checkpoint(1))

    env2 = StreamExecutionEnvironment()
    sink2 = build(env2, part2).collect()
    env2.execute(restore=st.load_latest())
    got = {}
    for r in sink1.rows() + sink2.rows():
        got[(r["k"], r["window_start"])] = r["v"]
    assert got.keys() == expected.keys()
    for k in expected:
        assert abs(got[k] - expected[k]) < 1e-2


def test_rescale_split_and_merge():
    rng = np.random.default_rng(9)
    n = 2000
    keys = rng.integers(0, 211, n)
    vals = rng.random(n).astype(np.float32)
    ts = np.sort(rng.integers(0, 2000, n))
    op = make_op()
    feed(op, keys, vals, ts, wm=int(ts[-1]) - 500)
    snap = op.snapshot_state()

    parts = WindowAggOperator.split_snapshot(snap, max_parallelism=128,
                                             new_parallelism=4)
    assert len(parts) == 4
    # each part holds disjoint keys; union == all keys
    from flink_tpu.state.keyindex import KeyIndex
    part_keys = [set(KeyIndex.restore(p["key_index"]).reverse_keys().tolist())
                 for p in parts]
    allk = set()
    for pk in part_keys:
        assert not (allk & pk)
        allk |= pk
    assert allk == set(KeyIndex.restore(snap["key_index"]).reverse_keys().tolist())

    # restored split operators: continued processing yields same fires as whole
    tail_keys = rng.integers(0, 211, 500)
    tail_vals = rng.random(500).astype(np.float32)
    tail_ts = np.sort(rng.integers(1500, 2000, 500))

    op_whole = make_op()
    op_whole.restore_state(snap)
    ref = collect(feed(op_whole, tail_keys, tail_vals, tail_ts, wm=5000))

    got = {}
    from flink_tpu.core import keygroups
    kg = keygroups.assign_to_key_group(keygroups.hash_keys(tail_keys), 128)
    ranges = keygroups.key_group_ranges(128, 4)
    for p, r in zip(parts, ranges):
        sub = make_op()
        sub.restore_state(p)
        sel = (kg >= r.start) & (kg <= r.end)
        if sel.any():
            got.update(collect(feed(sub, tail_keys[sel], tail_vals[sel],
                                    tail_ts[sel], wm=5000)))
        else:
            sub.process_watermark(Watermark(5000))
    assert got.keys() == ref.keys()
    for k in ref:
        assert abs(got[k] - ref[k]) < 1e-2

    # merge back (scale-down) must reproduce the whole
    merged = WindowAggOperator.merge_snapshots(parts)
    op_merged = make_op()
    op_merged.restore_state(merged)
    got_m = collect(feed(op_merged, tail_keys, tail_vals, tail_ts, wm=5000))
    assert got_m.keys() == ref.keys()
    for k in ref:
        assert abs(got_m[k] - ref[k]) < 1e-2


def test_savepoint_write_read(tmp_path):
    op = make_op()
    feed(op, [1, 2, 3], [1.0, 2.0, 3.0], [10, 20, 30])
    snap = {"win": op.snapshot_state()}
    p = write_savepoint(str(tmp_path / "sp"), snap)
    back = read_savepoint(p)
    assert "win" in back
    op2 = make_op()
    op2.restore_state(back["win"])
    out = op2.process_watermark(Watermark(5000))
    assert collect(out) == {(1, 0): 1.0, (2, 0): 2.0, (3, 0): 3.0}
