"""End-to-end tracing + latency tracking (ISSUE-10).

Tier-1 coverage of the observability subsystem: the span journal's
ordering/overflow semantics, Chrome trace-event export, the
``metrics.latency.interval`` marker→histogram plumbing (job_status,
Prometheus exposition and the REST latency panel in the SAME run), and
the ProcessCluster cross-worker merged timeline.
"""

import json
import sys
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest

from flink_tpu.config.config_option import Configuration
from flink_tpu.config.options import MetricOptions
from flink_tpu.core.batch import LatencyMarker
from flink_tpu.metrics.core import Histogram, Meter
from flink_tpu.metrics.groups import MetricRegistry
from flink_tpu.metrics.reporters import PrometheusReporter
from flink_tpu.observability import LatencyTracker, SpanJournal, tracing
from flink_tpu.observability.assembly import (estimate_offset_ms,
                                              merge_timelines)


@pytest.fixture(autouse=True)
def _clean_journal():
    """Tracing is a process-global singleton: every test starts and ends
    without one installed, no matter what it does in between."""
    tracing.uninstall()
    yield
    tracing.uninstall()


# ---------------------------------------------------------------------------
# span journal
# ---------------------------------------------------------------------------

def test_span_ordering_and_kinds():
    j = tracing.install(SpanJournal(64))
    with tracing.span("outer", cat="test", k=1):
        tracing.instant("mark", cat="test")
        with tracing.span("inner", cat="test"):
            pass
    spans = j.spans()
    names = [s[3] for s in spans]
    # completion order: instants record immediately, spans on exit
    assert names == ["mark", "inner", "outer"]
    by_name = {s[3]: s for s in spans}
    assert by_name["mark"][0] == "i" and by_name["outer"][0] == "X"
    # the outer span STARTED before the instant and lasted past inner
    assert by_name["outer"][1] <= by_name["mark"][1]
    assert by_name["outer"][2] >= by_name["inner"][2]
    assert by_name["outer"][6] == {"k": 1}


def test_ring_overflow_drop_counter():
    j = tracing.install(SpanJournal(4))
    for i in range(10):
        tracing.instant(f"e{i}", cat="test")
    assert j.recorded == 4
    assert j.dropped == 6
    # the ring keeps the EARLIEST spans (drop-newest): trace start intact
    assert [s[3] for s in j.spans()] == ["e0", "e1", "e2", "e3"]
    assert j.summary()["categories"] == {"test": 4}


def test_ring_concurrent_reservation_exact():
    """The lock-free reservation (one atomic ``itertools.count`` next())
    stays exact under concurrent recorders: recorded + dropped equals the
    total emitted, the ring fills completely, and every reserved slot got
    its writer's span."""
    j = tracing.install(SpanJournal(10_000))
    n_threads, per = 8, 5_000

    def work():
        for _ in range(per):
            tracing.instant("e", cat="test")

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert j.recorded + j.dropped == n_threads * per
    assert j.recorded == 10_000 and j.dropped == 30_000
    assert all(s is not None for s in j._buf)


def test_adopted_journal_survives_cluster_runs():
    """A journal installed by an outer harness (bench --trace, a user's
    big ring) is ADOPTED by a tracing-enabled cluster, not owned: the
    cluster records into it but must never reset() it — the owner's
    accumulated spans and capacity choice survive the job."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    j = tracing.install(SpanJournal(8192))
    tracing.instant("harness-span", cat="test")
    env = StreamExecutionEnvironment()
    n = 30_000
    (env.from_collection(columns={"k": np.arange(n) % 3,
                                  "v": np.ones(n)}, batch_size=128)
        .key_by("k").sum("v").collect())
    plan = env.get_stream_graph("adopt-job").to_plan()
    mc = MiniCluster(checkpoint_interval_ms=10, tracing_enabled=True)
    assert mc._trace_journal is j and not mc._owns_trace_journal
    res = mc.execute(plan, timeout_s=60)
    assert res.state == "FINISHED"
    assert "harness-span" in {s[3] for s in j.spans()}, \
        "cluster reset an adopted journal"
    # with no journal pre-installed the cluster installs its OWN ring
    # (config capacity applies) and THAT one is reset per execution
    tracing.uninstall()
    mc2 = MiniCluster(checkpoint_interval_ms=10, tracing_enabled=True)
    assert mc2._owns_trace_journal and tracing.active() is mc2._trace_journal
    res2 = mc2.execute(plan, timeout_s=60)
    assert res2.state == "FINISHED"
    # an OWNED ring is released at execution end: the singleton is free,
    # the handle still serves job_status()/trace_events(), and the next
    # tracing-enabled cluster installs fresh instead of adopting (and
    # reporting) job B's spans as its own
    assert tracing.active() is None
    assert mc2._trace_journal.recorded > 0
    assert mc2.job_status()["trace"]["spans"] > 0
    mc3 = MiniCluster(tracing_enabled=True)
    assert mc3._owns_trace_journal
    assert mc3._trace_journal is not mc2._trace_journal


def test_adopting_cluster_recovers_after_owner_release():
    """Two tracing-enabled clusters constructed back to back: B adopts
    A's ring.  After A's execute releases the singleton, B must stand up
    its OWN fresh ring at execute time — never run trace-dead while
    reporting A's stale spans as its own."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    def make_plan(name):
        env = StreamExecutionEnvironment()
        (env.from_collection(columns={"k": np.arange(30_000) % 3,
                                      "v": np.ones(30_000)},
                             batch_size=128)
            .key_by("k").sum("v").collect())
        return env.get_stream_graph(name).to_plan()

    a = MiniCluster(checkpoint_interval_ms=10, tracing_enabled=True)
    b = MiniCluster(checkpoint_interval_ms=10, tracing_enabled=True)
    assert a._owns_trace_journal and not b._owns_trace_journal
    assert b._trace_journal is a._trace_journal
    assert a.execute(make_plan("job-a"), timeout_s=60).state == "FINISHED"
    assert tracing.active() is None          # A released its ring
    assert b.execute(make_plan("job-b"), timeout_s=60).state == "FINISHED"
    assert b._owns_trace_journal
    assert b._trace_journal is not a._trace_journal
    assert b.job_status()["trace"]["spans"] > 0
    assert tracing.active() is None          # B released its ring too


def test_owner_readopts_foreign_ring_at_execute():
    """An OWNING cluster whose singleton was taken over by a DIFFERENT
    owner between executions re-adopts the live ring at execute() — its
    own ring is no longer where instrumentation records, so reporting
    from it would serve the previous execution's spans as the new job's."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    def make_plan(name):
        env = StreamExecutionEnvironment()
        (env.from_collection(columns={"k": np.arange(30_000) % 3,
                                      "v": np.ones(30_000)},
                             batch_size=128)
            .key_by("k").sum("v").collect())
        return env.get_stream_graph(name).to_plan()

    mc = MiniCluster(checkpoint_interval_ms=10, tracing_enabled=True)
    assert mc._owns_trace_journal
    own = mc._trace_journal
    assert mc.execute(make_plan("job-a"), timeout_s=60).state == "FINISHED"
    assert tracing.active() is None and own.recorded > 0
    # an outer harness installs ITS journal between the two executions
    harness = tracing.install(SpanJournal(1 << 15))
    assert mc.execute(make_plan("job-b"), timeout_s=60).state == "FINISHED"
    # job B's spans landed in the harness ring and the cluster reports it
    assert mc._trace_journal is harness and not mc._owns_trace_journal
    assert harness.recorded > 0
    assert mc.job_status()["trace"]["spans"] == harness.recorded
    # adopted, so NOT released: the harness keeps the singleton
    assert tracing.active() is harness


def test_disabled_tracing_is_a_noop():
    assert not tracing.enabled()
    with tracing.span("nope", cat="test"):
        tracing.instant("nor-this")
    tracing.complete("neither", 0, 10)
    assert tracing.active() is None


def test_chrome_export_schema():
    j = tracing.install(SpanJournal(64))
    with tracing.span("work", cat="hot_stage", batch=3):
        pass
    tracing.instant("tick", cat="checkpoint")
    events = tracing.to_chrome(j.snapshot(), pid=7, process_name="p7")
    json.dumps(events)                       # wire-serializable
    meta = [e for e in events if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in meta}
    x = next(e for e in events if e["ph"] == "X")
    assert x["name"] == "work" and x["cat"] == "hot_stage"
    assert x["pid"] == 7 and "dur" in x and x["ts"] > 0
    i = next(e for e in events if e["ph"] == "i")
    assert i["name"] == "tick" and i["s"] == "t"
    # wall anchoring: ts is microseconds since the epoch, roughly now
    assert abs(x["ts"] / 1e6 - time.time()) < 3600


def test_clock_offset_estimation_and_merge():
    # worker clock 250ms ahead; symmetric RTT -> exact recovery
    assert estimate_offset_ms(1000.0, 1010.0, 1255.0) == 250.0
    j = tracing.install(SpanJournal(16))
    tracing.instant("local", cat="test")
    local = j.snapshot()
    worker_j = SpanJournal(16)
    worker_j.record("i", worker_j.anchor_perf_ns, 0, "remote", "test", None)
    dump = {"journal": worker_j.snapshot(),
            "wall_now_ms": worker_j.anchor_wall_us / 1000.0 + 250.0,
            "latency": [{"source": "s", "hop": "h", "count": 1}]}
    t0 = worker_j.anchor_wall_us / 1000.0
    merged = merge_timelines(local, [(0, dump, t0)], t0_ms=t0)
    assert merged["displayTimeUnit"] == "ms"
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {0, 1}
    assert merged["otherData"]["workers"] == 1
    assert merged["otherData"]["clock_offsets_ms"][0] != 0.0
    assert merged["otherData"]["latency"][0]["worker"] == 0
    ts = [e["ts"] for e in merged["traceEvents"] if "ts" in e and e["ts"]]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# marker → histogram plumbing
# ---------------------------------------------------------------------------

def test_latency_tracker_records_per_source_hop():
    class FakeClock:
        now = 1_000_000

        def now_ms(self):
            return self.now

        def now_ms_f(self):
            return float(self.now)

    c = FakeClock()
    lt = LatencyTracker(clock_=c)
    marked = (c.now_ms() - 40) / 1000.0          # marked 40ms ago
    m = LatencyMarker(marked, subtask_index=1, source="src")
    lat = lt.record(m, "sink")
    assert lat == pytest.approx(40.0)
    # a skew-negative reading clamps to zero, never a negative sample
    future = LatencyMarker((c.now_ms() + 5000) / 1000.0, source="src")
    assert lt.record(future, "sink") == 0.0
    panel = lt.panel()
    assert len(panel) == 2                       # (src,1,sink) + (src,0,sink)
    row = next(r for r in panel if r["source_subtask"] == 1)
    assert row["source"] == "src" and row["hop"] == "sink"
    assert row["count"] == 1 and row["p99_ms"] == pytest.approx(40.0)
    assert lt.summary() == {"hops": 2, "samples": 2}


def test_latency_tracker_metrics_exported_via_prometheus():
    reg = MetricRegistry()
    group = reg.job_manager_group()
    lt = LatencyTracker().bind_group(group)
    m = LatencyMarker(time.time() - 0.05, source="src")
    for _ in range(4):
        lt.record(m, "agg")
    reporter = PrometheusReporter(registry=reg)
    text = reporter.scrape()
    # summary family with proper quantile labels + _sum/_count and gauges
    assert 'flink_tpu_jobmanager_latency_source_src_0_op_agg' in text
    assert 'quantile="0.99"' in text and 'quantile="0.5"' in text
    assert "_sum " in text and "_count 4" in text
    assert "p99_ms" in text and "p50_ms" in text


def test_latency_tracker_reset_per_execution():
    """reset() drops every hop row (job B must not report job A's hops
    or samples) while a reappearing hop reuses its already-registered
    Histogram, so the panel and the Prometheus exposition keep reading
    ONE reservoir."""
    reg = MetricRegistry()
    lt = LatencyTracker().bind_group(reg.job_manager_group())
    lt.record(LatencyMarker(time.time() - 0.05, source="src"), "agg")
    lt.record(LatencyMarker(time.time() - 0.05, source="src"), "only-a")
    assert {r["hop"] for r in lt.panel()} == {"agg", "only-a"}
    lt.reset()
    assert lt.panel() == []
    assert lt.summary() == {"hops": 0, "samples": 0}
    lt.record(LatencyMarker(time.time() - 0.02, source="src"), "agg")
    panel = lt.panel()
    assert [r["hop"] for r in panel] == ["agg"]
    assert panel[0]["count"] == 1
    # the registered series IS the live reservoir: count restarted at 1,
    # and the job-A-only hop's registered series was cleared, not frozen
    text = PrometheusReporter(registry=reg).scrape()
    assert "latency_source_src_0_op_agg_count 1" in text
    assert "latency_source_src_0_op_only_a_count 0" in text


def test_prometheus_histogram_summary_wire_format():
    """render()-style wire assertion (like the push reporters): a
    Histogram ships as a Prometheus SUMMARY — quantile series, _sum,
    _count — under the sanitized metric name."""
    reg = MetricRegistry()
    h = reg.job_manager_group().histogram("latency.e2e_ms")
    h.update_all(np.arange(1, 101, dtype=np.float64))
    lines = PrometheusReporter(registry=reg).render(reg.all_metrics())
    name = "flink_tpu_jobmanager_latency_e2e_ms"
    assert f"# TYPE {name} summary" in lines
    assert f'{name}{{quantile="0.5"}} 50.5' in lines
    assert f'{name}{{quantile="0.99"}} 99.01' in lines
    assert f"{name}_sum 5050.0" in lines
    assert f"{name}_count 100" in lines


def test_meter_deque_rate_semantics():
    """The O(1)-trim deque keeps get_rate() bit-identical: rate is
    (last - first) count over the retained window."""
    now = [0.0]
    m = Meter(window_s=10.0, clock=lambda: now[0])
    for i in range(5):
        now[0] = float(i)
        m.mark_event(2)
    assert m.get_count() == 10
    assert m.get_rate() == pytest.approx((10 - 2) / 4.0)
    # events beyond the window trim from the LEFT in O(1)
    now[0] = 100.0
    m.mark_event()
    assert m.get_rate() == pytest.approx((11 - 10) / (100.0 - 4.0))


# ---------------------------------------------------------------------------
# MiniCluster end-to-end: config key → markers → histograms → REST
# ---------------------------------------------------------------------------

def test_latency_interval_config_key_wired():
    from flink_tpu.cluster.minicluster import MiniCluster

    config = Configuration().set(MetricOptions.LATENCY_INTERVAL, "5 ms")
    mc = MiniCluster(config=config)
    assert mc.latency_interval_ms == 5
    # explicit arg wins over config
    mc2 = MiniCluster(config=config, latency_interval_ms=11)
    assert mc2.latency_interval_ms == 11
    # tracing config key installs the journal
    config2 = Configuration().set(MetricOptions.TRACING_ENABLED, True) \
        .set(MetricOptions.TRACING_BUFFER, 128)
    mc3 = MiniCluster(config=config2)
    assert mc3.tracing_enabled and tracing.active().capacity == 128


def test_minicluster_latency_and_trace_end_to_end():
    """ONE run: p99 per (source, sink-hop) visible in job_status(), the
    Prometheus exposition, and the REST panel; the span journal holds
    checkpoint lifecycle spans exported as Chrome trace JSON."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.rest.server import JobRegistry, RestServer
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    n = 120_000
    (env.from_collection(columns={"k": np.arange(n) % 13,
                                  "v": np.ones(n)}, batch_size=128)
        .key_by("k").sum("v").collect())
    plan = env.get_stream_graph("lat-job").to_plan()
    mc = MiniCluster(checkpoint_storage=InMemoryCheckpointStorage(retain=3),
                     checkpoint_interval_ms=20,
                     latency_interval_ms=2, tracing_enabled=True)
    registry = JobRegistry()
    job_id = registry.register("lat-job", mc)
    server = RestServer(registry).start()
    try:
        res = mc.execute(plan, timeout_s=120)
        assert res.state == "FINISHED"
        assert res.completed_checkpoints, "no checkpoint completed"

        # 1. job_status(): per-(source, hop) latency incl. the sink hop
        status = mc.job_status()
        hops = status["latency"]
        assert hops, "no latency hops recorded"
        sink_uids = [v["id"] for v in status["vertices"]
                     if "sink" in v["name"] or "collect" in v["name"]]
        hop_ids = {h["hop"] for h in hops}
        assert any(u in hop_ids for u in sink_uids) or len(hop_ids) >= 2
        assert all(h["p99_ms"] >= 0 and h["count"] > 0 for h in hops)
        # trace summary rides job_status too
        assert status["trace"]["enabled"]
        assert status["trace"]["spans"] > 0
        assert status["trace"]["categories"].get("checkpoint", 0) > 0

        # 2. Prometheus exposition, same run
        text = PrometheusReporter(registry=mc.metrics_registry).scrape()
        assert "latency_source_" in text and 'quantile="0.99"' in text

        # 3. REST: latency JSON + panel + Chrome trace, same run
        with urllib.request.urlopen(
                f"{server.url}/jobs/{job_id}/latency", timeout=10) as r:
            lat = json.loads(r.read())
        assert lat["hops"] and lat["hops"][0]["count"] > 0
        with urllib.request.urlopen(
                f"{server.url}/jobs/{job_id}/latency.html", timeout=10) as r:
            html = r.read().decode()
        assert 'class="lat-row"' in html and "p99 ms" in html
        with urllib.request.urlopen(
                f"{server.url}/jobs/{job_id}/trace", timeout=10) as r:
            trace = json.loads(r.read())
        evs = trace["traceEvents"]
        assert evs and trace["displayTimeUnit"] == "ms"
        cats = {e.get("cat") for e in evs}
        assert "checkpoint" in cats
        names = {e["name"] for e in evs}
        # full lifecycle: trigger → barrier/snapshot → ack → complete
        assert {"checkpoint.trigger", "checkpoint.snapshot",
                "checkpoint.ack", "checkpoint"} <= names
        assert trace["otherData"]["latency"]
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# ProcessCluster: ONE merged timeline across workers
# ---------------------------------------------------------------------------

TRACE_JOB = textwrap.dedent('''
    """Deterministic keyed-sum job, sized so checkpoints land mid-run."""
    import numpy as np
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    N = 60_000
    K = 13

    def build():
        env = StreamExecutionEnvironment()
        env.set_parallelism(2)
        keys = (np.arange(N) % K).astype(np.int64)
        (env.from_collection(columns={"k": keys, "v": np.ones(N)},
                             batch_size=64)
            .key_by("k").sum("v").collect())
        return env.get_stream_graph("trace-job")
''')


def test_process_cluster_latency_without_tracing(tmp_path):
    """``metrics.latency.interval`` alone (no tracing) must still surface
    the per-hop histograms: the workers answer trace_request with an
    empty journal but a full latency panel, and run()'s result carries
    ``latency`` without a ``trace``."""
    from flink_tpu.cluster.distributed import ProcessCluster

    mod = tmp_path / "latonly_job_mod.py"
    mod.write_text(TRACE_JOB)
    sys.path.insert(0, str(tmp_path))
    try:
        pc = ProcessCluster("latonly_job_mod:build", n_workers=1,
                            extra_sys_path=(str(tmp_path),),
                            tracing=False, latency_interval_ms=5)
        res = pc.run(timeout_s=300)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("latonly_job_mod", None)
    assert res["state"] == "FINISHED", res["error"]
    assert "trace" not in res
    assert res.get("latency"), "latency panel lost without tracing"
    row = res["latency"][0]
    assert {"hop", "p99_ms", "worker"} <= set(row)


def test_collect_trace_does_not_stall_on_dead_workers():
    """A worker whose control connection EOF'd (SIGKILL, crash) can never
    answer a trace_request — collect_trace must exclude already-dead
    conns up front and shrink its wait when one dies MID-collect, instead
    of sitting out the full timeout."""
    from flink_tpu.cluster.distributed import ProcessCluster

    pc = ProcessCluster("fake_mod:build", n_workers=2)
    sent = []
    pc._to_worker = lambda idx, msg: sent.append(idx)

    # both conns already dead: returns immediately, requests nothing
    pc._conns = {0: object(), 1: object()}
    pc._dead_conn_idx = {0, 1}
    t0 = time.monotonic()
    merged = pc.collect_trace(timeout_s=10.0)
    assert time.monotonic() - t0 < 2.0
    assert sent == [] and merged["otherData"]["requested_workers"] == 0

    # one live conn dying mid-collect unblocks the wait early
    pc._dead_conn_idx = {1}

    def _die_later():
        time.sleep(0.3)
        pc._dead_conn_idx.add(0)
        with pc._trace_cv:
            pc._trace_cv.notify_all()

    threading.Thread(target=_die_later, daemon=True).start()
    t0 = time.monotonic()
    merged = pc.collect_trace(timeout_s=10.0)
    assert time.monotonic() - t0 < 5.0, "stalled on a dead worker"
    assert sent == [0] and merged["otherData"]["workers"] == 0


def test_process_cluster_merged_timeline(tmp_path):
    """A ProcessCluster job with tracing on yields ONE merged Chrome
    timeline: coordinator checkpoint spans (pid 0) + both workers' task
    spans, clock-offset aligned, plus the workers' latency panels."""
    from flink_tpu.cluster.distributed import ProcessCluster

    mod = tmp_path / "trace_job_mod.py"
    mod.write_text(TRACE_JOB)
    sys.path.insert(0, str(tmp_path))
    try:
        pc = ProcessCluster("trace_job_mod:build", n_workers=2,
                            checkpoint_interval_ms=50,
                            extra_sys_path=(str(tmp_path),),
                            tracing=True, latency_interval_ms=5)
        res = pc.run(timeout_s=300)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("trace_job_mod", None)
    assert res["state"] == "FINISHED", res["error"]
    trace = res["trace"]
    assert trace is pc.last_trace
    other = trace["otherData"]
    assert other["requested_workers"] == 2
    assert other["workers"] == 2, "a worker's ring never arrived"
    assert set(other["clock_offsets_ms"]) == {0, 1}
    evs = trace["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert {0, 1, 2} <= pids, f"merged timeline missing processes: {pids}"
    # coordinator lifecycle + worker snapshot spans on the SAME timeline
    names_by_pid = {}
    for e in evs:
        names_by_pid.setdefault(e["pid"], set()).add(e["name"])
    assert "checkpoint.trigger" in names_by_pid[0]
    worker_names = names_by_pid.get(1, set()) | names_by_pid.get(2, set())
    assert "checkpoint.snapshot" in worker_names
    # workers recorded marker latency at their hops
    assert other["latency"], "no worker latency panels in the merge"
    assert {"worker", "hop", "p99_ms"} <= set(other["latency"][0])
    # one ordered timeline (metadata events carry no ts)
    ts = [e["ts"] for e in evs if "ts" in e]
    assert ts == sorted(ts)
    json.dumps(trace)                    # Perfetto-loadable = valid JSON
