import numpy as np
import pytest

from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex, make_key_index


def test_basic_insert_lookup():
    ki = KeyIndex(initial_capacity=16)
    ids = ki.lookup_or_insert(np.array([10, 20, 10, 30], np.int64))
    assert ids[0] == ids[2]
    assert len(set(ids.tolist())) == 3
    assert ki.num_keys == 3
    again = ki.lookup(np.array([10, 20, 30, 99], np.int64))
    assert (again[:3] == ids[[0, 1, 3]]).all()
    assert again[3] == -1


def test_slot_ids_dense_and_stable():
    ki = KeyIndex(initial_capacity=16)
    a = ki.lookup_or_insert(np.arange(100, dtype=np.int64))
    assert sorted(a.tolist()) == list(range(100))
    b = ki.lookup_or_insert(np.arange(100, dtype=np.int64))
    assert (a == b).all()


def test_growth_preserves_ids(rng):
    ki = KeyIndex(initial_capacity=16)
    keys1 = rng.choice(10**9, size=5000, replace=False).astype(np.int64)
    ids1 = ki.lookup_or_insert(keys1)
    keys2 = rng.choice(10**9, size=50000, replace=False).astype(np.int64)
    ki.lookup_or_insert(keys2)
    assert (ki.lookup(keys1) == ids1).all()
    assert (ki.reverse_keys()[ids1] == keys1).all()


def test_adversarial_collisions():
    # many keys hashing near each other + duplicates in batch
    ki = KeyIndex(initial_capacity=8)
    keys = np.repeat(np.arange(1000, dtype=np.int64) * 2**32, 3)
    ids = ki.lookup_or_insert(keys)
    assert ki.num_keys == 1000
    assert (ids.reshape(1000, 3) == ids.reshape(1000, 3)[:, :1]).all()
    assert (ki.reverse_keys()[ids] == keys).all()


def test_negative_and_extreme_keys():
    ki = KeyIndex(initial_capacity=8)
    keys = np.array([0, -1, 2**63 - 1, -(2**63), 5], np.int64)
    ids = ki.lookup_or_insert(keys)
    assert len(set(ids.tolist())) == 5
    assert (ki.lookup(keys) == ids).all()


def test_snapshot_restore(rng):
    ki = KeyIndex(initial_capacity=16)
    keys = rng.choice(10**12, size=2000, replace=False).astype(np.int64)
    ids = ki.lookup_or_insert(keys)
    snap = ki.snapshot()
    ki2 = KeyIndex.restore(snap)
    assert ki2.num_keys == 2000
    assert (ki2.lookup(keys) == ids).all()


def test_object_key_index():
    ki = ObjectKeyIndex()
    words = np.array(["the", "quick", "the", "fox"], dtype=object)
    ids = ki.lookup_or_insert(words)
    assert ids[0] == ids[2]
    assert ki.num_keys == 3
    assert ki.lookup(np.array(["fox", "missing"], dtype=object))[1] == -1
    snap = ki.snapshot()
    ki2 = ObjectKeyIndex.restore(snap)
    assert (ki2.lookup(words) == ids).all()


def test_make_key_index_dispatch():
    assert isinstance(make_key_index(np.int64(3)), KeyIndex)
    assert isinstance(make_key_index("word"), ObjectKeyIndex)


def test_empty_batch():
    ki = KeyIndex()
    assert ki.lookup_or_insert(np.array([], np.int64)).size == 0
    assert ki.lookup(np.array([], np.int64)).size == 0


def test_large_random_fuzz(rng):
    ki = KeyIndex(initial_capacity=8)
    oracle = {}
    for _ in range(20):
        batch = rng.integers(-10**6, 10**6, size=3000).astype(np.int64)
        ids = ki.lookup_or_insert(batch)
        for k, i in zip(batch.tolist(), ids.tolist()):
            if k in oracle:
                assert oracle[k] == i, k
            else:
                oracle[k] = i
    assert ki.num_keys == len(oracle)


def test_object_index_rejects_null_keys():
    ki = ObjectKeyIndex()
    with pytest.raises(ValueError):
        ki.lookup_or_insert(np.array(["a", None, "b"], dtype=object))
    ki.lookup_or_insert(np.array(["a"], dtype=object))
    assert (ki.lookup(np.array([None, "a"], dtype=object)) == [-1, 0]).all()
    assert (ki.lookup(np.array([None], dtype=object)) == [-1]).all()
