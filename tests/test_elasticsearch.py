"""Elasticsearch connector (ElasticsearchSink.java:63 analog): REST wire
server + client + bulk-flushing sink."""

import json
import urllib.request

import numpy as np
import pytest

from flink_tpu.connectors.elasticsearch import (ElasticsearchClient,
                                                ElasticsearchError,
                                                ElasticsearchServer,
                                                ElasticsearchSink)
from flink_tpu.core.batch import RecordBatch


@pytest.fixture
def es():
    srv = ElasticsearchServer()
    yield srv
    srv.close()


def client(srv):
    return ElasticsearchClient(srv.host, srv.port)


class TestWire:
    def test_index_and_get(self, es):
        c = client(es)
        c.create_index("people")
        c.bulk([{"op": "index", "index": "people", "id": 1,
                 "doc": {"name": "ada", "age": 36}}])
        assert c.get("people", "1") == {"name": "ada", "age": 36}
        assert c.get("people", "2") is None
        assert c.count("people") == 1

    def test_bulk_ndjson_over_raw_http(self, es):
        """A FOREIGN http client speaking the documented _bulk NDJSON."""
        body = (json.dumps({"index": {"_index": "t", "_id": "a"}}) + "\n"
                + json.dumps({"x": 1}) + "\n"
                + json.dumps({"delete": {"_index": "t", "_id": "a"}})
                + "\n").encode()
        req = urllib.request.Request(
            f"http://{es.host}:{es.port}/_bulk", data=body, method="POST")
        req.add_header("Content-Type", "application/x-ndjson")
        res = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert [list(i)[0] for i in res["items"]] == ["index", "delete"]
        assert client(es).count("t") == 0

    def test_create_conflicts_and_update_merges(self, es):
        c = client(es)
        c.bulk([{"op": "create", "index": "i", "id": "x",
                 "doc": {"a": 1}}])
        with pytest.raises(ElasticsearchError, match="bulk failures"):
            c.bulk([{"op": "create", "index": "i", "id": "x",
                     "doc": {"a": 2}}])
        c.bulk([{"op": "update", "index": "i", "id": "x",
                 "doc": {"b": 2}}])
        assert c.get("i", "x") == {"a": 1, "b": 2}

    def test_search_term_and_match_all(self, es):
        c = client(es)
        c.bulk([{"op": "index", "index": "s", "id": i,
                 "doc": {"grp": "a" if i % 2 == 0 else "b", "n": i}}
                for i in range(6)])
        assert len(c.search("s", size=100)) == 6
        evens = c.search("s", {"term": {"grp": "a"}}, size=100)
        assert sorted(d["n"] for d in evens) == [0, 2, 4]


class TestSink:
    def test_flush_on_checkpoint_at_least_once(self, es):
        sink = ElasticsearchSink(es.host, es.port, "out", bulk_actions=100)
        sink.open(None)
        sink.write_batch(RecordBatch(
            {"id": np.asarray([1, 2], np.int64),
             "v": np.asarray([1.5, 2.5])}))
        assert client(es).count("out") == 0    # still buffered
        sink.snapshot_state()                  # checkpoint flushes
        assert client(es).count("out") == 2

    def test_deterministic_ids_make_replay_idempotent(self, es):
        def run():
            sink = ElasticsearchSink(es.host, es.port, "idem",
                                     id_column="id")
            sink.open(None)
            sink.write_batch(RecordBatch(
                {"id": np.asarray([1, 2, 3], np.int64),
                 "v": np.asarray([10.0, 20.0, 30.0])}))
            sink.end_input()
            sink.close()
        run()
        run()                                  # replay after a crash
        c = client(es)
        assert c.count("idem") == 3            # no duplicates
        assert c.get("idem", "2")["v"] == 20.0

    def test_bulk_size_triggers_flush(self, es):
        sink = ElasticsearchSink(es.host, es.port, "big", bulk_actions=8)
        sink.open(None)
        sink.write_batch(RecordBatch(
            {"id": np.arange(20, dtype=np.int64)}))
        assert client(es).count("big") >= 16   # two bulks auto-flushed
        sink.end_input()
        assert client(es).count("big") == 20
