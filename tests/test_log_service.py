"""External log service + object store (VERDICT r1 #8): a broker process
any client can dial over HTTP (the Kafka-connector analog,
``flink-connectors/flink-connector-kafka``), and an S3-shaped checkpoint
backend behind the storage seam (``flink-filesystems/flink-s3-fs-base``).
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest

from flink_tpu.connectors.log_service import (LogServiceBroker,
                                              LogServiceClient,
                                              LogServiceSink,
                                              LogServiceSource)
from flink_tpu.core.batch import RecordBatch
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.objectstore import (
    ObjectStoreCheckpointStorage, ObjectStoreServer)


@pytest.fixture
def broker(tmp_path):
    b = LogServiceBroker(str(tmp_path / "broker")).start()
    yield b
    b.stop()


def test_broker_append_fetch_roundtrip(broker):
    c = LogServiceClient(broker.url)
    c.create_topic("t", partitions=2)
    c.append("t", 0, RecordBatch({"x": np.arange(5)}))
    c.append("t", 1, RecordBatch({"x": np.arange(5, 9)}))
    batches, nxt = c.fetch("t", 0, 0)
    assert [int(v) for b in batches for v in np.asarray(b.column("x"))] == \
        [0, 1, 2, 3, 4]
    batches2, _ = c.fetch("t", 1, 0)
    assert len(batches2) == 1
    # offset resume: fetching from nxt returns nothing new
    more, nxt2 = c.fetch("t", 0, nxt)
    assert more == [] and nxt2 == nxt


def test_idempotent_producer_dedup(broker):
    c = LogServiceClient(broker.url)
    c.create_topic("t")
    b = RecordBatch({"x": np.arange(3)})
    c.append("t", 0, b, producer="p1", seq=7)
    c.append("t", 0, b, producer="p1", seq=7)   # retry: dropped
    c.append("t", 0, b, producer="p1", seq=6)   # stale: dropped
    c.append("t", 0, b, producer="p2", seq=1)   # other producer: kept
    batches, _ = c.fetch("t", 0, 0)
    assert len(batches) == 2


def test_source_sink_job_roundtrip(broker, tmp_path):
    """Pipeline consumes from the broker and produces exactly-once back."""
    c = LogServiceClient(broker.url)
    c.create_topic("in", partitions=2)
    for p in range(2):
        for lo in range(0, 300, 100):
            c.append("in", p, RecordBatch({
                "k": (np.arange(lo, lo + 100) % 5).astype(np.int64),
                "v": np.ones(100)}))

    env = StreamExecutionEnvironment()
    src = LogServiceSource(broker.url, "in")
    sink = LogServiceSink(broker.url, "out", num_partitions=2,
                          key_column="k")
    (env.from_source(src).key_by("k")
        .sum("v", output_column="total").add_sink(sink))
    env.execute()
    out_rows = []
    for p in range(2):
        batches, _ = c.fetch("out", p, 0, max_bytes=1 << 24)
        for b in batches:
            out_rows.extend(b.to_rows())
    finals = {}
    for r in out_rows:
        finals[int(r["k"])] = max(finals.get(int(r["k"]), 0), r["total"])
    assert finals == {k: 120.0 for k in range(5)}


def test_external_process_feeds_broker(broker, tmp_path):
    """A SEPARATE OS process produces into the broker over the wire — the
    external-world integration the in-repo partitioned log cannot do."""
    script = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, "/root/repo")
        import numpy as np
        from flink_tpu.connectors.log_service import LogServiceClient
        from flink_tpu.core.batch import RecordBatch
        c = LogServiceClient("{broker.url}")
        c.create_topic("ext", partitions=1)
        for i in range(4):
            c.append("ext", 0, RecordBatch({{"n": np.arange(i*10, i*10+10)}}))
        print("fed")
    """)
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=120)
    assert "fed" in out.stdout, out.stderr
    src = LogServiceSource(broker.url, "ext")
    env = StreamExecutionEnvironment()
    got = env.from_source(src).collect()
    env.execute()
    assert sorted(int(r["n"]) for r in got.rows()) == list(range(40))


def test_sink_commit_replay_dedups(broker):
    """2PC replay: restoring a snapshot re-commits staged txns with the
    same producer sequences; the broker drops the duplicates."""
    sink = LogServiceSink(broker.url, "txn", num_partitions=1)
    sink.open(None)
    sink.write_batch(RecordBatch({"x": np.arange(4)}))
    snap = sink.snapshot_state()          # pre-commit (staged txn 1)
    sink.notify_checkpoint_complete(1)    # commit

    sink2 = LogServiceSink(broker.url, "txn", num_partitions=1)
    sink2.restore_state(snap)             # replays the same txn
    c = LogServiceClient(broker.url)
    batches, _ = c.fetch("txn", 0, 0)
    total = sum(len(b) for b in batches)
    assert total == 4                     # committed exactly once


def test_sink_pipelined_checkpoints_commit_by_id(broker):
    """A txn staged for checkpoint 2 must NOT commit when only checkpoint 1
    completes (TwoPhaseCommitSinkFunction: commit txns with id <= notified)."""
    from flink_tpu.operators.base import snapshot_scope

    sink = LogServiceSink(broker.url, "pipelined", num_partitions=1)
    sink.open(None)
    sink.write_batch(RecordBatch({"x": np.arange(3)}))
    with snapshot_scope(1):
        sink.snapshot_state()             # txn for checkpoint 1
    sink.write_batch(RecordBatch({"x": np.arange(3, 8)}))
    with snapshot_scope(2):
        sink.snapshot_state()             # txn for checkpoint 2 (pipelined)

    c = LogServiceClient(broker.url)
    sink.notify_checkpoint_complete(1)
    batches, _ = c.fetch("pipelined", 0, 0)
    assert sum(len(b) for b in batches) == 3      # only checkpoint 1's rows
    sink.notify_checkpoint_complete(2)
    batches, _ = c.fetch("pipelined", 0, 0)
    assert sum(len(b) for b in batches) == 8


def test_broker_persists_seq_after_data(broker, tmp_path):
    """Durability ordering: the idempotent-producer sequence is recorded
    only after the partition data is written+fsynced, so a crash between
    the two re-admits the retry (duplicate = at-least-once floor) instead
    of dropping acknowledged-but-unwritten data."""
    import flink_tpu.connectors.log_service as ls

    c = LogServiceClient(broker.url)
    c.create_topic("dur")
    orig_persist = ls.LogServiceBroker._persist_seqs
    seen = {}

    def spy(self):
        # at seq-persist time the data must already be on disk
        log = self._logs["dur"]
        seen["end_at_persist"] = log.end_offset(0)
        return orig_persist(self)

    ls.LogServiceBroker._persist_seqs = spy
    try:
        c.append("dur", 0, RecordBatch({"x": np.arange(3)}),
                 producer="p", seq=1)
    finally:
        ls.LogServiceBroker._persist_seqs = orig_persist
    assert seen["end_at_persist"] > 0


def test_object_store_checkpoint_storage(tmp_path):
    server = ObjectStoreServer(str(tmp_path / "os")).start()
    try:
        st = ObjectStoreCheckpointStorage(server.url, prefix="jobA/",
                                          retain=2)
        for cid in (1, 2, 3):
            st.store(cid, {"op": {"value": np.arange(cid)}})
        assert st.checkpoint_ids() == [2, 3]   # retention pruned chk-1
        snap = st.load_latest()
        np.testing.assert_array_equal(snap["op"]["value"], np.arange(3))
        meta = st.metadata(3)
        assert meta["checkpoint_id"] == 3
    finally:
        server.stop()


def test_object_store_backs_a_cluster_job(tmp_path):
    """The object store plugs into the SAME seam as FileCheckpointStorage:
    a MiniCluster job checkpoints to it and restores from it."""
    from flink_tpu.cluster.task import TaskStates

    server = ObjectStoreServer(str(tmp_path / "os")).start()
    try:
        st = ObjectStoreCheckpointStorage(server.url)
        env = StreamExecutionEnvironment()
        n = 50_000
        keys = (np.arange(n) % 7).astype(np.int64)
        sink = (env.from_collection(columns={"k": keys, "v": np.ones(n)},
                                    batch_size=256)
                .key_by("k").sum("v").collect())
        res = env.execute_cluster(storage=st, checkpoint_interval_ms=20,
                                  timeout_s=120)
        assert res.state == TaskStates.FINISHED
        assert st.checkpoint_ids(), "no checkpoints reached the store"
        snap = st.load_latest()
        assert any(isinstance(v, dict) for v in snap.values())
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# cross-host leader election over the object-store lease service
# (VERDICT r1 weak #7: the flock lease is single-host)
# ---------------------------------------------------------------------------

def test_lease_leader_election_single_leader_and_failover(tmp_path):
    import time

    from flink_tpu.cluster.ha import LeaseLeaderElection

    server = ObjectStoreServer(str(tmp_path / "os")).start()
    try:
        a = LeaseLeaderElection(server.url, contender_id="A",
                                lease_ms=400, renew_ms=100).start()
        time.sleep(0.3)
        b = LeaseLeaderElection(server.url, contender_id="B",
                                lease_ms=400, renew_ms=100).start()
        time.sleep(0.4)
        assert a.is_leader and not b.is_leader
        token_a = a.fencing_token
        assert token_a is not None
        # leader dies WITHOUT releasing (crash): the lease expires and the
        # contender takes over with a HIGHER fencing token
        a.stop(abdicate=False)
        deadline = time.time() + 5
        while not b.is_leader and time.time() < deadline:
            time.sleep(0.05)
        assert b.is_leader
        assert b.fencing_token is not None and b.fencing_token > token_a
    finally:
        for e in ("a", "b"):
            try:
                locals()[e].stop()
            except Exception:  # noqa: BLE001
                pass
        server.stop()


def test_lease_fencing_rejects_deposed_leader(tmp_path):
    import time

    from flink_tpu.runtime.checkpoint.objectstore import ObjectStoreServer as S

    server = S(str(tmp_path / "os")).start()
    try:
        r1 = server.lease_acquire("job", "old", ttl_ms=50)
        assert r1["acquired"]
        time.sleep(0.1)                       # lease expires
        r2 = server.lease_acquire("job", "new", ttl_ms=5000)
        assert r2["acquired"] and r2["token"] > r1["token"]
        # the DEPOSED leader's renew (stale token) is rejected
        assert not server.lease_renew("job", "old", r1["token"],
                                      5000)["renewed"]
        st = server.lease_state("job")
        assert st["held"] and st["holder"] == "new"
    finally:
        server.stop()


def test_lease_tokens_survive_server_restart(tmp_path):
    from flink_tpu.runtime.checkpoint.objectstore import ObjectStoreServer as S

    d = str(tmp_path / "os")
    s1 = S(d)
    t1 = s1.lease_acquire("e", "h1", 50)["token"]
    s1._httpd.server_close()
    s2 = S(d)
    t2 = s2.lease_acquire("e", "h2", 50)["token"]
    s2._httpd.server_close()
    assert t2 > t1  # fencing monotonicity across restarts
