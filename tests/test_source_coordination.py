"""FLIP-27 runtime source coordination (VERDICT r1 #6): the enumerator
lives on the coordinator, readers request splits at runtime, enumerator
state rides checkpoints.  Reference: ``SourceCoordinator.java:75,155-170,229``.
"""

import os
import threading
import time

import numpy as np
import pytest

from flink_tpu.cluster.task import TaskStates
from flink_tpu.connectors.enumerator import (DirectoryEnumerator,
                                             DynamicFileSource)
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage


def _write_csv(path: str, lo: int, hi: int) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("k,v\n")
        for i in range(lo, hi):
            f.write(f"{i % 7},{i}\n")
    os.replace(tmp, path)  # atomic: the enumerator never sees partials


def test_split_list_grows_while_job_runs(tmp_path):
    """Files added AFTER the job started are discovered and read — the
    dynamic case deploy-time split creation cannot express."""
    d = str(tmp_path)
    _write_csv(os.path.join(d, "a.csv"), 0, 50)

    def feeder():
        time.sleep(0.3)
        _write_csv(os.path.join(d, "b.csv"), 50, 120)
        time.sleep(0.2)
        _write_csv(os.path.join(d, "c.csv"), 120, 200)
        open(os.path.join(d, "_DONE"), "w").close()

    t = threading.Thread(target=feeder)
    t.start()
    env = StreamExecutionEnvironment()
    src = DynamicFileSource(d, format="csv")
    sink = env.from_source(src).collect()
    res = env.execute_cluster(timeout_s=60)
    t.join()
    assert res.state == TaskStates.FINISHED
    got = sorted(int(r["v"]) for r in sink.rows())
    assert got == list(range(200))


def test_restore_mid_enumeration_exactly_once(tmp_path):
    """Injected failure mid-read; restart restores the enumerator's
    assigned-set + the reader's in-flight split/offset from the checkpoint,
    final keyed sums stay exact (no loss, no double-read)."""
    d = str(tmp_path)
    for i, name in enumerate(["a.csv", "b.csv", "c.csv", "d.csv"]):
        _write_csv(os.path.join(d, name), i * 500, (i + 1) * 500)
    open(os.path.join(d, "_DONE"), "w").close()

    fail_once = {"armed": True, "count": 0}

    def poison(row_cols):
        if fail_once["armed"] and fail_once["count"] >= 2:
            fail_once["armed"] = False
            raise RuntimeError("injected failure")
        fail_once["count"] += 1
        return row_cols

    storage = InMemoryCheckpointStorage(retain=10)
    env = StreamExecutionEnvironment()
    src = DynamicFileSource(d, format="csv")
    sink = (env.from_source(src).map(poison)
            .key_by("k").sum("v").collect())
    res = env.execute_cluster(storage=storage, checkpoint_interval_ms=2,
                              restart_attempts=2, timeout_s=60)
    assert res.state == TaskStates.FINISHED
    assert res.restarts >= 1
    vals = np.arange(2000)
    expect = {k: int(vals[vals % 7 == k].sum()) for k in range(7)}
    final = {int(r["k"]): int(r["v"]) for r in sink.rows()}
    assert final == expect


def test_enumerator_snapshot_reclaim_protocol(tmp_path):
    """Protocol unit test: a split assigned AFTER the enumerator snapshot
    but owned by a reader at the barrier is reclaimed on restore and never
    handed out twice (``SourceCoordinator`` ownership model)."""
    d = str(tmp_path)
    for name in ("a.csv", "b.csv", "c.csv"):
        _write_csv(os.path.join(d, name), 0, 5)
    src = DynamicFileSource(d)
    enum = DirectoryEnumerator(src)
    s1 = enum.next_split(0)
    snap = enum.snapshot_state()          # trigger-time snapshot: only a.csv
    s2 = enum.next_split(0)               # assigned post-snapshot
    assert s1.path.endswith("a.csv") and s2.path.endswith("b.csv")

    restored = DirectoryEnumerator(src)
    restored.restore_state(snap)
    restored.reclaim(s2)                  # reader's restored current_split
    s3 = restored.next_split(1)
    assert s3.path.endswith("c.csv")
    assert restored.next_split(1) is None
    assert not restored.done()            # no _DONE marker yet
    open(os.path.join(d, "_DONE"), "w").close()
    assert restored.done()


def test_static_enumerator_reclaim_protocol():
    """_StaticEnumerator honors the same base contract: a split handed out
    after its trigger-time snapshot, reclaimed from a reader's restored
    snapshot (by id), is never assigned a second time."""
    from flink_tpu.connectors.enumerator import _StaticEnumerator
    from flink_tpu.connectors.sources import CollectionSource

    src = CollectionSource([{"v": i} for i in range(9)])
    splits = src.create_splits(3)
    enum = _StaticEnumerator(splits)
    s1 = enum.next_split(0)
    snap = enum.snapshot_state()          # only s1 assigned at trigger time
    s2 = enum.next_split(0)               # assigned post-snapshot

    restored = _StaticEnumerator(splits)
    restored.restore_state(snap)
    # readers snapshot split IDS — reclaim must accept the plain id
    restored.reclaim(f"{s2.index}/{s2.of}")
    s3 = restored.next_split(1)
    assert {(_s.index, _s.of) for _s in (s1, s2, s3)} == \
        {(s.index, s.of) for s in splits}
    assert restored.next_split(1) is None and restored.done()


def test_dynamic_source_static_fallback(tmp_path):
    """Executors without runtime coordination still read the directory as a
    static split list (deploy-time enumeration)."""
    d = str(tmp_path)
    _write_csv(os.path.join(d, "a.csv"), 0, 30)
    _write_csv(os.path.join(d, "b.csv"), 30, 80)
    src = DynamicFileSource(d)
    splits = src.create_splits(4)
    assert len(splits) == 2
    rows = []
    for s in splits:
        for el in s.read():
            if hasattr(el, "columns"):
                rows.extend(el.to_rows())
    assert sorted(int(r["v"]) for r in rows) == list(range(80))


@pytest.mark.slow
def test_cross_process_split_requests(tmp_path):
    """ProcessCluster: readers in WORKER PROCESSES request splits from the
    coordinator over the control plane (the actual RPC case of
    ``SourceCoordinator.java:155-170``)."""
    import sys
    import textwrap

    from flink_tpu.cluster.distributed import ProcessCluster

    d = tmp_path / "data"
    d.mkdir()
    for i, name in enumerate(["a.csv", "b.csv", "c.csv"]):
        _write_csv(str(d / name), i * 100, (i + 1) * 100)
    (d / "_DONE").touch()

    mod = tmp_path / "dyn_src_job.py"
    mod.write_text(textwrap.dedent(f'''
        from flink_tpu.connectors.enumerator import DynamicFileSource
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(2)
            (env.from_source(DynamicFileSource({str(d)!r}, format="csv"))
                .key_by("k").sum("v").collect())
            return env.get_stream_graph("dyn-src-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        pc = ProcessCluster("dyn_src_job:build", n_workers=2,
                            extra_sys_path=(str(tmp_path),))
        res = pc.run(timeout_s=120)
        assert res["state"] == "FINISHED", res
        vals = np.arange(300)
        expect = {k: int(vals[vals % 7 == k].sum()) for k in range(7)}
        final = {}
        for r in res["rows"]:
            final[int(r["k"])] = int(r["v"])
        assert final == expect
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("dyn_src_job", None)
