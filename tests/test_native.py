"""Native layer: FLZ compression, varint codec, CRC, spill store, ring,
batch codec. The C++ library must build in this environment (g++ is baked
in); fallback paths are exercised explicitly where meaningful."""

import os

import numpy as np
import pytest

from flink_tpu import native
from flink_tpu.native import codec, fallback


def test_native_builds():
    assert native.native_available(), native.build_error()


def test_lz_roundtrip_compressible():
    data = (b"hello world, hello world, hello world! " * 200
            + bytes(range(256)) * 4)
    c = native.lz_compress(data)
    assert len(c) < len(data) // 2
    assert native.lz_decompress(c, len(data)) == data


def test_lz_roundtrip_random():
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
    c = native.lz_compress(data)
    assert native.lz_decompress(c, len(data)) == data


def test_lz_roundtrip_edge_cases():
    for data in [b"", b"a", b"ab" * 3, b"\x00" * 100_000,
                 b"abcabcabcabcabc", os.urandom(17)]:
        c = native.lz_compress(data)
        assert native.lz_decompress(c, len(data)) == data


def test_lz_malformed_rejected():
    with pytest.raises(ValueError):
        native.lz_decompress(b"\xff\xff\xff\xff", 100)


def test_delta_varint_roundtrip():
    rng = np.random.default_rng(1)
    vals = np.cumsum(rng.integers(0, 1000, 5000)).astype(np.int64)
    enc = native.delta_varint_encode(vals)
    assert len(enc) < vals.nbytes / 3  # sorted data compresses well
    out = native.delta_varint_decode(enc, len(vals))
    np.testing.assert_array_equal(out, vals)


def test_delta_varint_negative_and_extremes():
    vals = np.array([0, -1, 2**62, -(2**62), 7, 7, -100], np.int64)
    out = native.delta_varint_decode(native.delta_varint_encode(vals), len(vals))
    np.testing.assert_array_equal(out, vals)


def test_delta_varint_fallback_parity():
    vals = np.array([5, -3, 1000, -2**40, 2**40, 0], np.int64)
    enc_native = native.delta_varint_encode(vals)
    enc_py = fallback.delta_varint_encode(vals)
    assert enc_native == enc_py
    np.testing.assert_array_equal(fallback.delta_varint_decode(enc_native, len(vals)), vals)


def test_crc32_matches_zlib():
    import zlib
    data = b"the quick brown fox" * 10
    assert native.crc32(data) == zlib.crc32(data)


def test_spill_store_basic(tmp_path):
    with native.SpillStore(str(tmp_path / "s"), mem_budget=1 << 20) as s:
        s.put(b"a", b"1")
        s.put(b"b", b"2" * 1000)
        assert s.get(b"a") == b"1"
        assert s.get(b"b") == b"2" * 1000
        assert s.get(b"missing") is None
        assert len(s) == 2
        assert s.delete(b"a")
        assert not s.delete(b"a")
        assert s.get(b"a") is None
        assert sorted(s.keys()) == [b"b"]


def test_spill_store_eviction_beyond_budget(tmp_path):
    # 100 x 10KB values with a 50KB budget: most values must spill to disk
    # and still read back correctly.
    with native.SpillStore(str(tmp_path / "s"), mem_budget=50_000) as s:
        vals = {f"k{i}".encode(): os.urandom(10_000) for i in range(100)}
        for k, v in vals.items():
            s.put(k, v)
        assert s.mem_used() <= 50_000
        assert s.log_bytes() > 0
        for k, v in vals.items():
            assert s.get(k) == v


def test_spill_store_overwrite_and_large_value(tmp_path):
    with native.SpillStore(str(tmp_path / "s"), mem_budget=1000) as s:
        big = os.urandom(50_000)
        s.put(b"k", big)
        s.put(b"k", b"small")       # overwrite a spilled value
        assert s.get(b"k") == b"small"
        assert len(s) == 1


def test_spill_store_flush_reopen(tmp_path):
    d = str(tmp_path / "s")
    s = native.SpillStore(d, mem_budget=5_000)
    vals = {f"key-{i}".encode(): (f"val-{i}" * 50).encode() for i in range(50)}
    for k, v in vals.items():
        s.put(k, v)
    s.flush()
    s.close()
    s2 = native.SpillStore(d, mem_budget=5_000)
    assert len(s2) == 50
    for k, v in vals.items():
        assert s2.get(k) == v
    s2.close()


def test_spill_store_compact(tmp_path):
    with native.SpillStore(str(tmp_path / "s"), mem_budget=100) as s:
        for i in range(50):
            s.put(b"churn", os.urandom(5_000))  # repeatedly overwrite
        for i in range(10):
            s.put(f"live-{i}".encode(), os.urandom(2_000))
        live = {f"live-{i}".encode(): s.get(f"live-{i}".encode()) for i in range(10)}
        s.compact()
        for k, v in live.items():
            assert s.get(k) == v
        assert s.get(b"churn") is not None


def test_ring_buffer():
    r = native.RingBuffer(1 << 14)
    assert r.pop() is None
    msgs = [os.urandom(i * 37 % 500 + 1) for i in range(20)]
    for m in msgs:
        assert r.push(m)
    for m in msgs:
        assert r.pop() == m
    assert r.pop() is None
    r.close()


def test_ring_buffer_backpressure():
    r = native.RingBuffer(100)
    big = b"x" * 90
    assert r.push(big)
    assert not r.push(b"y" * 20)   # no credit left -> refused, not dropped
    assert r.pop() == big
    assert r.push(b"y" * 20)
    r.close()


def test_ring_buffer_threaded():
    import threading
    r = native.RingBuffer(1 << 14)
    n = 2000
    out = []

    def consumer():
        while len(out) < n:
            m = r.pop()
            if m is not None:
                out.append(m)

    t = threading.Thread(target=consumer)
    t.start()
    for i in range(n):
        m = str(i).encode()
        while not r.push(m):
            pass
    t.join(timeout=30)
    assert [int(m) for m in out] == list(range(n))
    r.close()


# ---------------------------------------------------------------------------
# batch codec
# ---------------------------------------------------------------------------

def _assert_batches_equal(a, b):
    assert set(a.columns) == set(b.columns)
    for n in a.columns:
        ca, cb = np.asarray(a.columns[n]), np.asarray(b.columns[n])
        if ca.dtype == object:
            assert list(ca) == list(cb)
        else:
            np.testing.assert_array_equal(ca, cb)
            assert ca.dtype == cb.dtype
    for attr in ("timestamps", "key_ids", "key_groups"):
        va, vb = getattr(a, attr), getattr(b, attr)
        assert (va is None) == (vb is None)
        if va is not None:
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_codec_roundtrip_numeric():
    from flink_tpu.core.batch import RecordBatch
    rng = np.random.default_rng(2)
    b = RecordBatch(
        {"f32": rng.random(500).astype(np.float32),
         "f64": rng.random(500),
         "i32": rng.integers(-1000, 1000, 500).astype(np.int32),
         "i64": rng.integers(-10**12, 10**12, 500),
         "vec": rng.random((500, 4)).astype(np.float32)},
        timestamps=np.sort(rng.integers(0, 10**9, 500)),
        key_ids=rng.integers(0, 100, 500).astype(np.int32),
        key_groups=rng.integers(0, 16, 500).astype(np.int32))
    _assert_batches_equal(b, codec.decode_batch(codec.encode_batch(b)))


def test_codec_roundtrip_object_columns():
    from flink_tpu.core.batch import RecordBatch
    b = RecordBatch({"word": np.asarray(["alpha", "beta", "gamma"], object),
                     "n": np.asarray([1, 2, 3], np.int64)})
    _assert_batches_equal(b, codec.decode_batch(codec.encode_batch(b)))


def test_codec_empty_batch():
    from flink_tpu.core.batch import RecordBatch
    b = RecordBatch({})
    _assert_batches_equal(b, codec.decode_batch(codec.encode_batch(b)))


def test_codec_compresses_repetitive_data():
    from flink_tpu.core.batch import RecordBatch
    b = RecordBatch({"v": np.zeros(100_000, np.float32)},
                    timestamps=np.arange(100_000, dtype=np.int64))
    enc = codec.encode_batch(b)
    assert len(enc) < b.column("v").nbytes / 10


def test_codec_bad_magic():
    with pytest.raises(ValueError):
        codec.decode_batch(b"XXXX123")


def test_spill_eviction_on_updates(tmp_path):
    """Regression: repeated updates of existing keys must keep evicting —
    the budget holds under an update-heavy state access pattern."""
    with native.SpillStore(str(tmp_path / "s"), mem_budget=1000) as s:
        for i in range(50):
            s.put(f"k{i}".encode(), bytes(100))
        for rnd in range(3):
            for i in range(50):
                s.put(f"k{i}".encode(), bytes(100) + bytes([rnd]))
        assert s.mem_used() <= 1000
        for i in range(50):
            assert s.get(f"k{i}".encode()) == bytes(100) + bytes([2])


def test_spill_compact_then_reopen(tmp_path):
    """Regression: compact() must leave a consistent on-disk manifest so a
    reopen (crash recovery) sees post-compaction offsets."""
    d = str(tmp_path / "s")
    s = native.SpillStore(d, mem_budget=500)
    for i in range(20):
        s.put(f"key{i:02d}".encode(), bytes([65 + i]) * 3000)
    s.flush()
    s.put(b"key05", b"F" * 3000)  # garbage in log
    s.compact()
    s.close()
    s2 = native.SpillStore(d, mem_budget=500)
    assert s2.get(b"key05") == b"F" * 3000
    for i in range(20):
        if i != 5:
            assert s2.get(f"key{i:02d}".encode()) == bytes([65 + i]) * 3000
    s2.close()


def test_delta_varint_fallback_extreme_delta():
    """Regression: deltas beyond the int64 range must wrap identically in
    the Python fallback and the native path."""
    vals = np.array([-(2**63), 2**63 - 1, 0, 2**62, -(2**62)], np.int64)
    enc_py = fallback.delta_varint_encode(vals)
    enc_nat = native.delta_varint_encode(vals)
    assert enc_py == enc_nat
    np.testing.assert_array_equal(fallback.delta_varint_decode(enc_py, len(vals)), vals)
    np.testing.assert_array_equal(native.delta_varint_decode(enc_py, len(vals)), vals)


def test_codec_compress_false_skips_compression():
    from flink_tpu.core.batch import RecordBatch
    b = RecordBatch({"v": np.zeros(10_000, np.float32)},
                    timestamps=np.arange(10_000, dtype=np.int64))
    enc = codec.encode_batch(b, compress=False)
    # raw float block must dominate: no LZ pass ran over it
    assert len(enc) > 39_000
    _assert_batches_equal(b, codec.decode_batch(enc))
