"""Cross-host data plane: TCP channels, codec on the wire, credit-based
backpressure, subtask pipeline over real sockets, multi-process exchange."""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from flink_tpu.cluster.net import ChannelServer, RemoteChannel
from flink_tpu.core.batch import (CheckpointBarrier, EndOfInput, RecordBatch,
                                  Watermark)


def test_roundtrip_batches_and_controls():
    server = ChannelServer()
    try:
        w = RemoteChannel(server.host, server.port, "ch-0")
        q = server.channel("ch-0")
        b = RecordBatch({"k": np.arange(100) % 7,
                         "v": np.random.rand(100)},
                        timestamps=np.arange(100, dtype=np.int64))
        assert w.put(b)
        assert w.put(Watermark(123))
        assert w.put(CheckpointBarrier(5, 10, True))
        assert w.put(EndOfInput())
        got = [q.poll(timeout_s=5) for _ in range(4)]
        assert isinstance(got[0], RecordBatch)
        np.testing.assert_array_equal(np.asarray(got[0].column("k")),
                                      np.arange(100) % 7)
        np.testing.assert_array_equal(np.asarray(got[0].timestamps),
                                      np.arange(100))
        assert got[1] == Watermark(123)
        assert got[2] == CheckpointBarrier(5, 10, True)
        assert isinstance(got[3], EndOfInput)
        w.close()
    finally:
        server.stop()


def test_credit_backpressure_blocks_sender():
    server = ChannelServer(channel_capacity=4)
    try:
        w = RemoteChannel(server.host, server.port, "bp")
        q = server.channel("bp")
        time.sleep(0.1)
        # 4 credits granted; the 5th put must block until the consumer polls
        for i in range(4):
            assert w.put(RecordBatch({"x": np.array([i])}))
        assert not w.put(RecordBatch({"x": np.array([99])}), timeout_s=0.3)
        assert q.poll(timeout_s=5) is not None       # drain 1 -> credit back
        assert w.put(RecordBatch({"x": np.array([5])}), timeout_s=5)
        w.close()
    finally:
        server.stop()


def test_pipeline_subtask_over_tcp():
    """A real Subtask consumes its input from a TCP channel: the network
    tier slots in where LocalChannel does."""
    from flink_tpu.cluster.task import Subtask, TaskListener
    from flink_tpu.core.functions import RuntimeContext

    class _SumOp:
        name = "sum"
        forwards_watermarks = True
        is_stateless = False
        is_two_input = False

        def open(self, ctx):
            self.total = 0.0

        def process_batch(self, batch):
            self.total += float(np.asarray(batch.column("v")).sum())
            return []

        def process_watermark(self, wm):
            return []

        def on_processing_time(self, ts):
            return []

        def end_input(self):
            return [RecordBatch({"total": np.asarray([self.total])})]

        def snapshot_state(self):
            return {}

        def restore_state(self, s):
            pass

        def notify_checkpoint_complete(self, c):
            pass

        def close(self):
            pass

    server = ChannelServer(channel_capacity=8)
    result = {}

    class _Out:
        channels = []

        def emit(self, el):
            if isinstance(el, RecordBatch) and "total" in el.columns:
                result["total"] = float(np.asarray(el.column("total"))[0])

    try:
        q = server.channel("in-0")
        t = Subtask("v1", 0, _SumOp(), [_Out()], RuntimeContext(),
                    TaskListener(), [q])
        t.start()
        w = RemoteChannel(server.host, server.port, "in-0")
        n = 0.0
        for i in range(50):
            vals = np.random.rand(64)
            n += float(vals.sum())
            assert w.put(RecordBatch({"v": vals}), timeout_s=10)
        w.put(Watermark(10_000), timeout_s=10)
        w.put(EndOfInput(), timeout_s=10)
        t.join(timeout_s=30)
        assert abs(result["total"] - n) < 1e-6
        w.close()
    finally:
        server.stop()


def test_multi_process_exchange(tmp_path):
    """TRUE cross-process data plane: a separate Python process produces
    batches into this process's channel server over TCP."""
    server = ChannelServer(channel_capacity=16)
    producer = f"""
import sys
sys.path.insert(0, "/root/repo")
import numpy as np
from flink_tpu.cluster.net import RemoteChannel
from flink_tpu.core.batch import EndOfInput, RecordBatch

w = RemoteChannel("{server.host}", {server.port}, "xproc")
total = 0.0
for i in range(20):
    vals = np.full(128, float(i))
    total += float(vals.sum())
    assert w.put(RecordBatch({{"v": vals}}), timeout_s=30)
assert w.put(EndOfInput(), timeout_s=30)
print(total)
"""
    try:
        proc = subprocess.Popen([sys.executable, "-c", producer],
                                stdout=subprocess.PIPE, text=True)
        q = server.channel("xproc")
        got = 0.0
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            el = q.poll(timeout_s=1)
            if el is None:
                continue
            if isinstance(el, EndOfInput):
                break
            got += float(np.asarray(el.column("v")).sum())
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0
        assert abs(got - float(out.strip())) < 1e-6
        assert got == sum(i * 128.0 for i in range(20))
    finally:
        server.stop()
