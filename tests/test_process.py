"""KeyedProcessFunction + timer service tests (KeyedProcessOperatorTest /
InternalTimerServiceImplTest analogs)."""

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.operators.process import (KeyedProcessFunction,
                                         KeyedProcessOperator)
from flink_tpu.runtime.timers import InternalTimerService
from flink_tpu.state.api import ValueStateDescriptor
from flink_tpu.testing.harness import KeyedOneInputOperatorHarness

pytestmark = pytest.mark.slow


# ---------------------------------------------------------------- timer table

def test_timer_fire_order_and_dedup():
    t = InternalTimerService()
    t.register_event_time([3, 1, 2], [30, 10, 20])
    t.register_event_time([1], [10])  # duplicate — idempotent
    slots, _, ts = t.advance_watermark(25)
    np.testing.assert_array_equal(ts, [10, 20])
    np.testing.assert_array_equal(slots, [1, 2])
    slots, _, ts = t.advance_watermark(25)
    assert slots.size == 0  # already fired
    slots, _, ts = t.advance_watermark(100)
    np.testing.assert_array_equal(slots, [3])


def test_timer_delete():
    t = InternalTimerService()
    t.register_event_time([1, 2], [10, 10])
    t.delete_event_time([1], [10])
    slots, _, _ = t.advance_watermark(100)
    np.testing.assert_array_equal(slots, [2])


def test_timer_snapshot_restore():
    t = InternalTimerService()
    t.register_event_time([1, 2], [10, 20])
    t.register_processing_time([5], [50])
    snap = t.snapshot()
    t2 = InternalTimerService()
    t2.restore(snap)
    slots, _, _ = t2.advance_watermark(15)
    np.testing.assert_array_equal(slots, [1])
    slots, _, _ = t2.advance_processing_time(60)
    np.testing.assert_array_equal(slots, [5])


def test_namespaced_timers_distinct():
    t = InternalTimerService()
    t.register_event_time([1, 1], [10, 10], namespaces=[100, 200])
    slots, ns, _ = t.advance_watermark(10)
    assert slots.size == 2
    np.testing.assert_array_equal(np.sort(ns), [100, 200])


# ------------------------------------------------------------ process operator

class DedupeWithTimeout(KeyedProcessFunction):
    """Emit first occurrence per key; per-key timer clears the seen flag after
    ``timeout`` ms of event time (the classic state+timer pattern)."""

    def __init__(self, timeout_ms: int = 100):
        self.timeout_ms = timeout_ms
        self.seen_desc = ValueStateDescriptor("seen", dtype=np.int64, default=0)

    def process_batch(self, ctx, batch):
        seen = ctx.state(self.seen_desc)
        vals, alive = seen.get_rows(ctx.slots)
        # first occurrence of each slot within the batch
        _, first_idx = np.unique(ctx.slots, return_index=True)
        first_mask = np.zeros(len(batch), bool)
        first_mask[first_idx] = True
        fresh = first_mask & ~(alive & (vals > 0))
        seen.put_rows(ctx.slots, np.ones(len(batch), np.int64))
        ctx.timer_service.register_event_time_timers(
            ctx.slots[fresh], np.asarray(batch.timestamps)[fresh] + self.timeout_ms)
        return [batch.select(fresh)]

    def on_timer_batch(self, ctx, slots, timestamps):
        ctx.state(self.seen_desc).clear_rows(slots)
        return None


def _batch(keys, ts):
    return RecordBatch({"k": np.asarray(keys, np.int64)},
                       timestamps=np.asarray(ts, np.int64))


def test_process_function_dedupe_with_timer_reset():
    h = KeyedOneInputOperatorHarness(
        KeyedProcessOperator(DedupeWithTimeout(100), "k"))
    h.process_batch(_batch([1, 2, 1], [10, 11, 12]))
    assert [r["k"] for r in h.extract_output_rows()] == [1, 2]
    h.clear_output()
    # before the timeout: still deduped
    h.process_batch(_batch([1], [50]))
    assert h.extract_output_rows() == []
    # watermark past the timer resets key 1
    h.process_watermark(200)
    h.process_batch(_batch([1], [210]))
    assert [r["k"] for r in h.extract_output_rows()] == [1]


def test_process_operator_snapshot_restore_keeps_timers_and_state():
    op = KeyedProcessOperator(DedupeWithTimeout(100), "k")
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(_batch([1, 2], [10, 20]))
    snap = h.snapshot()

    op2 = KeyedProcessOperator(DedupeWithTimeout(100), "k")
    h2 = KeyedOneInputOperatorHarness.restored(op2, snap)
    # state survived: keys 1,2 still deduped
    h2.process_batch(_batch([1, 2], [30]*2))
    assert h2.extract_output_rows() == []
    h2.clear_output()
    # timers survived: firing past 110/120 resets both keys
    h2.process_watermark(300)
    h2.process_batch(_batch([1, 2], [310, 311]))
    assert sorted(r["k"] for r in h2.extract_output_rows()) == [1, 2]


class CountAndEmitOnTimer(KeyedProcessFunction):
    """Accumulate per-key count; emit (key, count) when the timer fires —
    exercises keys_of + emitting from on_timer_batch."""

    def __init__(self):
        self.cnt_desc = ValueStateDescriptor("cnt", dtype=np.int64, default=0)

    def process_batch(self, ctx, batch):
        cnt = ctx.state(self.cnt_desc)
        vals, _ = cnt.get_rows(ctx.slots)
        np.add.at(vals, np.arange(len(vals)), 0)  # copy semantics guard
        # accumulate counts per slot within the batch
        uniq, inverse, counts = np.unique(ctx.slots, return_inverse=True,
                                          return_counts=True)
        base, _ = cnt.get_rows(uniq)
        cnt.put_rows(uniq, base + counts)
        ctx.timer_service.register_event_time_timers(
            uniq, np.full(uniq.size, 100, np.int64))
        return None

    def on_timer_batch(self, ctx, slots, timestamps):
        vals, _ = ctx.state(self.cnt_desc).get_rows(slots)
        return [RecordBatch({"k": ctx.keys_of(slots),
                             "count": vals},
                            timestamps=np.asarray(timestamps))]


def test_emit_from_timer():
    h = KeyedOneInputOperatorHarness(KeyedProcessOperator(CountAndEmitOnTimer(), "k"))
    h.process_batch(_batch([7, 7, 8], [1, 2, 3]))
    h.process_batch(_batch([7], [4]))
    assert h.extract_output_rows() == []
    h.process_watermark(150)
    rows = sorted(({"k": r["k"], "count": r["count"]}
                   for r in h.extract_output_rows()), key=lambda r: r["k"])
    assert rows == [{"k": 7, "count": 3}, {"k": 8, "count": 1}]


def test_process_in_datastream_pipeline():
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    rows = [{"k": i % 3, "v": i} for i in range(9)]
    out = (env.from_collection(rows, timestamp_column=None)
           .assign_timestamps_and_watermarks(0, timestamp_fn=lambda c: np.asarray(c["v"]) * 10)
           .key_by("k")
           .process(DedupeWithTimeout(1_000_000))
           .execute_and_collect())
    assert sorted(r["k"] for r in out) == [0, 1, 2]


def test_scale_down_merges_timers_from_all_subtasks():
    """merge_snapshots must union timers, not keep only subtask 0's."""
    snaps = []
    for sub in range(2):
        op = KeyedProcessOperator(DedupeWithTimeout(100), "k")
        h = KeyedOneInputOperatorHarness(op)
        h.process_batch(_batch([sub * 10 + 1], [10]))  # distinct keys
        snaps.append(h.snapshot())
    merged = KeyedProcessOperator.merge_snapshots(snaps)
    op2 = KeyedProcessOperator(DedupeWithTimeout(100), "k")
    h2 = KeyedOneInputOperatorHarness.restored(op2, merged)
    # both keys' timers must fire and reset the dedupe state
    h2.process_watermark(1000)
    h2.process_batch(_batch([1, 11], [1100, 1101]))
    assert sorted(r["k"] for r in h2.extract_output_rows()) == [1, 11]


class EmitOnProcTimer(KeyedProcessFunction):
    def process_batch(self, ctx, batch):
        # timer at t=0: due as soon as the executor's wall clock ticks
        ctx.timer_service.register_processing_time_timers(
            np.unique(ctx.slots), np.zeros(len(np.unique(ctx.slots)), np.int64))
        return None

    def on_timer_batch(self, ctx, slots, timestamps):
        return [RecordBatch({"fired_k": ctx.keys_of(slots)})]


def test_executor_fires_processing_time_timers():
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    rows = [{"k": i % 2} for i in range(8)]
    out = (env.from_collection(rows, batch_size=2)  # several source rounds
           .key_by("k").process(EmitOnProcTimer())
           .execute_and_collect())
    assert sorted(set(r["fired_k"] for r in out)) == [0, 1]
