"""Incremental checkpoint storage (blob dedup + shared-state refcounts) and
the changelog keyed-state backend (log mutations, materialize, replay)."""

import numpy as np
import pytest

from flink_tpu.runtime.checkpoint.incremental import IncrementalCheckpointStorage
from flink_tpu.state.changelog import ChangelogKeyedStateBackend
from flink_tpu.state.heap import HeapKeyedStateBackend


def _snap(arr_a, arr_b):
    return {"op1": {"state.x.rows": arr_a, "small": 7},
            "op2": {"leaves": [arr_b], "name": "w"}}


def test_incremental_dedup_unchanged_blobs(tmp_path):
    st = IncrementalCheckpointStorage(str(tmp_path), retain=5,
                                      min_blob_bytes=1024)
    a = np.arange(10_000, dtype=np.float64)      # 80KB, stays identical
    b = np.zeros(5_000, np.float32)
    st.store(1, _snap(a, b))
    blobs_after_1 = st.shared_blob_count()
    st.store(2, _snap(a, b + 1))                 # only b changed
    assert st.shared_blob_count() == blobs_after_1 + 1  # ONE new blob
    assert st.metadata(2)["new_blobs"] == 1
    # loads resolve to full arrays
    got = st.load(2)
    np.testing.assert_array_equal(got["op1"]["state.x.rows"], a)
    np.testing.assert_array_equal(got["op2"]["leaves"][0], b + 1)
    assert got["op1"]["small"] == 7


def test_incremental_retention_releases_blobs(tmp_path):
    st = IncrementalCheckpointStorage(str(tmp_path), retain=2,
                                      min_blob_bytes=64)
    shared = np.arange(1000, dtype=np.float64)   # referenced by every chk
    for cid in range(1, 6):
        unique = np.full(1000, cid, np.float64)  # referenced by one chk
        st.store(cid, {"shared": shared, "unique": unique})
    assert st.checkpoint_ids() == [4, 5]
    # shared blob survives; evicted checkpoints' unique blobs are gone
    assert st.shared_blob_count() == 3   # shared + unique4 + unique5
    got = st.load(4)
    np.testing.assert_array_equal(got["shared"], shared)
    np.testing.assert_array_equal(got["unique"], np.full(1000, 4, np.float64))


def test_incremental_registry_survives_reopen(tmp_path):
    st = IncrementalCheckpointStorage(str(tmp_path), retain=3,
                                      min_blob_bytes=64)
    a = np.arange(500, dtype=np.int64)
    st.store(1, {"a": a})
    st2 = IncrementalCheckpointStorage(str(tmp_path), retain=3,
                                       min_blob_bytes=64)
    st2.store(2, {"a": a})                       # same content: deduped
    assert st2.metadata(2)["new_blobs"] == 0
    np.testing.assert_array_equal(st2.load(2)["a"], a)


# ---------------------------------------------------------------------------
# changelog backend
# ---------------------------------------------------------------------------

def test_changelog_records_and_replays():
    be = ChangelogKeyedStateBackend(HeapKeyedStateBackend(max_parallelism=16))
    st = be.value_state("v", default=0)
    be.set_current_key("a")
    st.update(1)
    be.set_current_key("b")
    st.update(2)
    be.materialize()                       # base: {a:1, b:2}
    be.set_current_key("a")
    st.update(10)                          # post-materialization delta
    ls = be.list_state("l")
    ls.add("x")
    snap = be.snapshot()
    assert snap["changelog_backend"]
    # log is short: registers + 3 entries, not the whole history
    assert len(snap["changelog"]) <= 6

    be2 = ChangelogKeyedStateBackend(HeapKeyedStateBackend(max_parallelism=16))
    be2.restore(snap)
    st2 = be2.value_state("v", default=0)
    be2.set_current_key("a")
    assert st2.value() == 10
    be2.set_current_key("b")
    assert st2.value() == 2
    be2.set_current_key("a")               # "x" was added under key "a"
    assert be2.list_state("l").get() == ["x"]


def test_changelog_snapshot_is_cheap_after_materialize():
    be = ChangelogKeyedStateBackend(HeapKeyedStateBackend())
    st = be.value_state("v", default=0.0)
    keys = np.arange(1000)
    slots = be.key_slots(keys)
    st.put_rows(slots, np.arange(1000.0))
    be.materialize()
    assert be.changelog_size() <= 1        # register entries only
    be.set_current_key(5)
    st.update(99.0)
    snap = be.snapshot()
    assert len(snap["changelog"]) <= 3     # register + key + mutation


def test_changelog_vectorized_rows_replay():
    be = ChangelogKeyedStateBackend(HeapKeyedStateBackend())
    import jax.numpy as jnp

    from flink_tpu.core.functions import SumAggregator
    rs = be.reducing_state("sum", reduce_fn=SumAggregator(jnp.float64))
    slots = be.key_slots(np.array([3, 1, 4, 1, 5]))
    rs.add_rows(slots, np.asarray([1.0, 2.0, 3.0, 4.0, 5.0]))
    snap = be.snapshot()                   # no materialization: pure log

    be2 = ChangelogKeyedStateBackend(HeapKeyedStateBackend())
    be2.restore(snap)
    rs2 = be2.reducing_state("sum", reduce_fn=SumAggregator(jnp.float64))
    be2.set_current_key(1)
    assert float(rs2.get()) == 6.0
    be2.set_current_key(5)
    assert float(rs2.get()) == 5.0


def test_changelog_restore_then_snapshot_keeps_deltas():
    """A restore -> immediate snapshot cycle must not lose the replayed
    suffix (the restored log carries over)."""
    be = ChangelogKeyedStateBackend(HeapKeyedStateBackend())
    st = be.value_state("v", default=0)
    be.set_current_key("k")
    st.update(42)
    snap1 = be.snapshot()

    be2 = ChangelogKeyedStateBackend(HeapKeyedStateBackend())
    be2.restore(snap1)
    snap2 = be2.snapshot()                 # no new mutations in between

    be3 = ChangelogKeyedStateBackend(HeapKeyedStateBackend())
    be3.restore(snap2)
    be3.set_current_key("k")
    assert be3.value_state("v", default=0).value() == 42
