"""SQL DDL: CREATE TABLE ... WITH (connector), CREATE VIEW, DROP,
SHOW TABLES, DESCRIBE, durable catalog — ``SqlCreateTable`` +
``TableEnvironmentImpl.executeSql`` DDL dispatch analogs.
"""

import os

import numpy as np
import pytest

from flink_tpu.sql.parser import parse_any, CreateTableStmt, SqlParseError
from flink_tpu.sql.planner import PlanError
from flink_tpu.sql.table_env import TableEnvironment


def test_parse_create_table():
    stmt = parse_any("""
        CREATE TABLE IF NOT EXISTS orders (
          id BIGINT,
          amount DOUBLE,
          ts BIGINT,
          note VARCHAR(255),
          WATERMARK FOR ts AS ts - INTERVAL '5' SECOND,
          PRIMARY KEY (id) NOT ENFORCED
        ) WITH ('connector' = 'filesystem', 'path' = '/tmp/x.csv')
    """)
    assert isinstance(stmt, CreateTableStmt)
    assert stmt.if_not_exists
    assert [c.name for c in stmt.columns] == ["id", "amount", "ts", "note"]
    assert stmt.columns[3].type_name == "VARCHAR(255)"
    assert stmt.watermark_column == "ts" and stmt.watermark_delay_ms == 5000
    assert stmt.primary_key == "id"
    assert stmt.properties == {"connector": "filesystem",
                               "path": "/tmp/x.csv"}


def test_filesystem_ddl_end_to_end(tmp_path):
    """The verdict's done-criterion: a job defined purely in SQL — DDL
    source → windowed aggregate → INSERT INTO DDL sink."""
    src = str(tmp_path / "events.csv")
    dst = str(tmp_path / "out.csv")
    with open(src, "w") as f:
        f.write("k,v,ts\n")
        for t in range(0, 1000, 10):
            f.write(f"a,1,{t}\nb,2,{t}\n")
    tenv = TableEnvironment()
    tenv.execute_sql(f"""
        CREATE TABLE events (k STRING, v DOUBLE, ts BIGINT,
          WATERMARK FOR ts AS ts - INTERVAL '0' SECOND)
        WITH ('connector' = 'filesystem', 'path' = '{src}',
              'format' = 'csv')
    """)
    tenv.execute_sql(f"""
        CREATE TABLE win_out (k STRING, total DOUBLE, wstart BIGINT)
        WITH ('connector' = 'filesystem', 'path' = '{dst}',
              'format' = 'csv')
    """)
    res = tenv.execute_sql(
        "INSERT INTO win_out "
        "SELECT k, SUM(v) AS total, TUMBLE_START(ts, INTERVAL '100' "
        "MILLISECOND) AS wstart FROM events "
        "GROUP BY k, TUMBLE(ts, INTERVAL '100' MILLISECOND)")
    assert res.collect()[0]["rows_written"] == 20      # 2 keys x 10 windows
    from flink_tpu.formats import read_csv
    rows = [r for b in read_csv(dst) for r in b.to_rows()]
    assert len(rows) == 20
    a_rows = [r for r in rows if r["k"] == "a"]
    assert all(float(r["total"]) == 10.0 for r in a_rows)


def test_create_view_and_select(tmp_path):
    src = str(tmp_path / "d.jsonl")
    with open(src, "w") as f:
        for i in range(6):
            f.write('{"x": %d}\n' % i)
    tenv = TableEnvironment()
    tenv.execute_sql(f"CREATE TABLE d (x BIGINT) WITH "
                     f"('connector'='filesystem', 'path'='{src}', "
                     f"'format'='jsonl')")
    tenv.execute_sql("CREATE VIEW big AS SELECT x FROM d WHERE x > 2")
    rows = tenv.execute_sql("SELECT SUM(x) AS s FROM big").collect()
    assert rows[0]["s"] == 3 + 4 + 5


def test_show_describe_drop(tmp_path):
    tenv = TableEnvironment()
    tenv.execute_sql(f"CREATE TABLE t1 (a INT, b STRING) WITH "
                     f"('connector'='filesystem', "
                     f"'path'='{tmp_path}/t1.csv')")
    names = [r["table name"] for r in
             tenv.execute_sql("SHOW TABLES").collect()]
    assert names == ["t1"]
    desc = tenv.execute_sql("DESCRIBE t1").collect()
    assert desc == [{"name": "a", "type": "INT"},
                    {"name": "b", "type": "STRING"}]
    tenv.execute_sql("DROP TABLE t1")
    assert tenv.execute_sql("SHOW TABLES").collect() == []
    with pytest.raises(PlanError, match="does not exist"):
        tenv.execute_sql("DROP TABLE t1")
    tenv.execute_sql("DROP TABLE IF EXISTS t1")     # no error


def test_create_errors(tmp_path):
    tenv = TableEnvironment()
    with pytest.raises(PlanError, match="requires a 'connector'"):
        tenv.execute_sql("CREATE TABLE x (a INT) WITH ('path'='/tmp/x')")
    tenv.execute_sql(f"CREATE TABLE x (a INT) WITH ("
                     f"'connector'='filesystem', 'path'='{tmp_path}/x.csv')")
    with pytest.raises(PlanError, match="already exists"):
        tenv.execute_sql(f"CREATE TABLE x (a INT) WITH ("
                         f"'connector'='filesystem', "
                         f"'path'='{tmp_path}/x.csv')")
    tenv.execute_sql(f"CREATE TABLE IF NOT EXISTS x (a INT) WITH ("
                     f"'connector'='filesystem', 'path'='{tmp_path}/x.csv')")
    with pytest.raises(SqlParseError):
        tenv.execute_sql("CREATE TABLE bad (a INT)")   # no WITH


def test_drop_kind_must_match(tmp_path):
    tenv = TableEnvironment()
    tenv.execute_sql(f"CREATE TABLE t (a INT) WITH "
                     f"('connector'='filesystem', "
                     f"'path'='{tmp_path}/t.csv')")
    tenv.execute_sql("CREATE VIEW v AS SELECT a FROM t")
    with pytest.raises(PlanError, match="is a table, not a view"):
        tenv.execute_sql("DROP VIEW t")
    with pytest.raises(PlanError, match="is a view, not a table"):
        tenv.execute_sql("DROP TABLE v")
    tenv.execute_sql("DROP VIEW v")
    tenv.execute_sql("DROP TABLE t")
    assert tenv.execute_sql("SHOW TABLES").collect() == []


def test_kafka_cdc_ddl_is_changelog(tmp_path):
    """'format'='debezium-json' on a Kafka DDL table decodes envelopes to
    changelog rows and marks the table a changelog."""
    import json
    from flink_tpu.connectors.kafka import KafkaWireBroker, KafkaWireClient

    broker = KafkaWireBroker(directory=str(tmp_path / "kafka")).start()
    try:
        broker.create_topic("cdc", partitions=1)
        envs = [
            {"before": None, "after": {"k": "a", "v": 10}, "op": "c"},
            {"before": {"k": "a", "v": 10}, "after": {"k": "a", "v": 20},
             "op": "u"},
        ]
        c = KafkaWireClient(broker.host, broker.port)
        c.produce("cdc", 0, [(None, json.dumps(e).encode()) for e in envs])
        c.close()
        tenv = TableEnvironment()
        tenv.execute_sql(f"""
            CREATE TABLE cdc (k STRING, v BIGINT) WITH (
              'connector' = 'kafka', 'topic' = 'cdc',
              'properties.bootstrap.servers' =
                '{broker.host}:{broker.port}',
              'format' = 'debezium-json')
        """)
        assert tenv._catalog["cdc"].changelog
        rows = tenv.execute_sql("SELECT op, k, v FROM cdc").collect()
        assert [r["op"] for r in rows] == ["+I", "-U", "+U"]
        assert rows[-1]["v"] == 20
        # aggregates over the raw changelog are rejected, not garbage
        with pytest.raises(PlanError):
            tenv.execute_sql("SELECT SUM(v) FROM cdc").collect()
    finally:
        broker.stop()


def test_durable_catalog_survives_restart(tmp_path):
    src = str(tmp_path / "in.csv")
    with open(src, "w") as f:
        f.write("a\n1\n2\n3\n")
    cat = str(tmp_path / "catalog")
    t1 = TableEnvironment(catalog_dir=cat)
    t1.execute_sql(f"CREATE TABLE src (a BIGINT) WITH "
                   f"('connector'='filesystem', 'path'='{src}', "
                   f"'format'='csv')")
    t1.execute_sql("CREATE VIEW doubled AS SELECT a * 2 AS d FROM src")
    t1.execute_sql(f"CREATE TABLE dropme (z INT) WITH "
                   f"('connector'='filesystem', "
                   f"'path'='{tmp_path}/z.csv')")
    t1.execute_sql("DROP TABLE dropme")

    # a NEW environment replays the persisted DDL
    t2 = TableEnvironment(catalog_dir=cat)
    names = [r["table name"] for r in
             t2.execute_sql("SHOW TABLES").collect()]
    assert names == ["doubled", "src"]
    rows = t2.execute_sql("SELECT SUM(d) AS s FROM doubled").collect()
    assert rows[0]["s"] == 12


def test_kafka_ddl_source_and_sink(tmp_path):
    from flink_tpu.connectors.kafka import KafkaWireBroker, KafkaWireClient

    broker = KafkaWireBroker(directory=str(tmp_path / "kafka")).start()
    try:
        broker.create_topic("numbers", partitions=1)
        tenv = TableEnvironment()
        tenv.execute_sql(f"""
            CREATE TABLE numbers (n BIGINT) WITH (
              'connector' = 'kafka', 'topic' = 'numbers',
              'properties.bootstrap.servers' =
                '{broker.host}:{broker.port}')
        """)
        import json
        c = KafkaWireClient(broker.host, broker.port)
        c.produce("numbers", 0,
                  [(None, json.dumps({"n": i}).encode()) for i in range(5)])
        c.close()
        rows = tenv.execute_sql(
            "SELECT SUM(n) AS s FROM numbers").collect()
        assert rows[0]["s"] == 10
        # sink direction
        broker.create_topic("out", partitions=1)
        tenv.execute_sql(f"""
            CREATE TABLE out (n BIGINT) WITH (
              'connector' = 'kafka', 'topic' = 'out',
              'properties.bootstrap.servers' =
                '{broker.host}:{broker.port}')
        """)
        res = tenv.execute_sql(
            "INSERT INTO out SELECT n FROM numbers WHERE n > 2")
        assert res.collect()[0]["rows_written"] == 2
    finally:
        broker.stop()


def test_postgres_ddl_source_and_sink():
    from flink_tpu.connectors.postgres import (PostgresWireClient,
                                               PostgresWireServer)

    srv = PostgresWireServer()
    try:
        with PostgresWireClient(srv.host, srv.port) as c:
            c.execute("CREATE TABLE people (id int8, age int8)")
            c.execute("INSERT INTO people (id, age) VALUES "
                      "(1, 30), (2, 40), (3, 50)")
            c.execute("CREATE TABLE adults (id int8, age int8)")
        tenv = TableEnvironment()
        tenv.execute_sql(f"""
            CREATE TABLE people (id BIGINT, age BIGINT) WITH (
              'connector' = 'postgres', 'hostname' = '{srv.host}',
              'port' = '{srv.port}', 'table-name' = 'people',
              'scan.partition.column' = 'id')
        """)
        tenv.execute_sql(f"""
            CREATE TABLE adults (id BIGINT, age BIGINT) WITH (
              'connector' = 'postgres', 'hostname' = '{srv.host}',
              'port' = '{srv.port}', 'table-name' = 'adults')
        """)
        res = tenv.execute_sql(
            "INSERT INTO adults SELECT id, age FROM people WHERE age > 35")
        assert res.collect()[0]["rows_written"] == 2
        with PostgresWireClient(srv.host, srv.port) as c:
            cols = c.query_columns("SELECT id FROM adults ORDER BY id")
        assert cols["id"].tolist() == [2, 3]
    finally:
        srv.close()
