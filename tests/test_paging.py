"""Cold-key paging subsystem: state larger than HBM for the pane ring.

The acceptance contract (ISSUE 2): with K_cap forced far below the key
cardinality, a paged run is FIRE-DIGEST-IDENTICAL to a fully-resident run —
spilled keys participate in fires, snapshots and restore (at a different
K_cap, and across the paged/resident boundary in both directions), and the
occupancy counters are live in operator stats / job-scope metrics.

Tier-1 carries the 64k-cap / 256k-key variant; the 1M-key eviction stress
is marked ``slow``.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.state.paging import DevicePager, PagingConfig
from flink_tpu.state.spill import PaneSpillStore
from flink_tpu.windowing.assigners import (SlidingEventTimeWindows,
                                           TumblingEventTimeWindows)


def _digests(elements):
    """Sorted (window_start, key, result) — order-independent, and exact
    because the tests use integer-valued float32 (sums < 2**24)."""
    out = []
    for b in elements:
        if hasattr(b, "columns") and "result" in b.columns:
            out.extend(zip(np.asarray(b.column("window_start")).tolist(),
                           np.asarray(b.column("k")).tolist(),
                           np.asarray(b.column("result")).tolist()))
    return sorted(out)


def _mk_op(paging, window_ms=1000, assigner=None, capacity_hint=1 << 13,
           **kw):
    kw.setdefault("emit_tier", "device")
    op = WindowAggOperator(
        assigner or TumblingEventTimeWindows.of(window_ms),
        SumAggregator(jnp.float32), key_column="k", value_column="v",
        initial_key_capacity=capacity_hint, paging=paging, **kw)
    op.open(RuntimeContext())
    return op


def _feed(op, keys, ts_value, out, batch=512):
    for lo in range(0, keys.size, batch):
        k = keys[lo: lo + batch]
        v = (k % 17 + 1).astype(np.float32)
        ts = np.full(k.size, ts_value, np.int64)
        out += op.process_batch(RecordBatch({"k": k, "v": v},
                                            timestamps=ts))


def _run(paging, n_keys=4096, windows=2, reps=2, seed=7, batch=512):
    op = _mk_op(paging)
    rng = np.random.default_rng(seed)
    out = []
    for w in range(windows):
        for _ in range(reps):
            _feed(op, rng.permutation(n_keys).astype(np.int64),
                  w * 1000 + 10, out, batch)
        out += op.process_watermark(Watermark(w * 1000 + 999))
    out += op.end_input()
    return _digests(out), op


# ---------------------------------------------------------------------------
# PaneSpillStore codec
# ---------------------------------------------------------------------------

def test_pane_spill_store_roundtrip(tmp_path):
    st = PaneSpillStore(str(tmp_path / "pages"), 1 << 20,
                        leaf_dtypes=(np.float32, np.int64),
                        leaf_shapes=((), (2,)))
    st.put(7, -3, 1, 42, [np.float32(1.5), np.array([4, 5], np.int64)])
    flags, count, vals = st.get(7, -3)
    assert (flags, count) == (1, 42)
    assert vals[0] == np.float32(1.5)
    np.testing.assert_array_equal(vals[1], [4, 5])
    assert st.get(7, -2) is None and st.get(8, -3) is None
    assert len(st) == 1
    st.delete(7, -3)
    assert st.get(7, -3) is None and len(st) == 0
    # bit-exactness: float32 payloads survive exactly (paging round trips
    # must not perturb accumulation history)
    v = np.float32(0.1) + np.float32(1e-7)
    st.put(1, 0, 0, 1, [v, np.zeros(2, np.int64)])
    assert st.get(1, 0)[2][0].tobytes() == v.tobytes()
    st.close()


def test_pane_spill_store_clear(tmp_path):
    st = PaneSpillStore(str(tmp_path / "pages"), 1 << 20,
                        leaf_dtypes=(np.float32,), leaf_shapes=((),))
    for g in range(10):
        st.put(g, 0, 1, 1, [np.float32(g)])
    assert len(st) == 10
    st.clear()
    assert len(st) == 0
    st.close()


# ---------------------------------------------------------------------------
# DevicePager unit behavior
# ---------------------------------------------------------------------------

def test_pager_lru_evicts_coldest(tmp_path):
    spec = SumAggregator(jnp.float32).acc_spec()
    pager = DevicePager(PagingConfig(4, policy="lru",
                                     directory=str(tmp_path / "p")), spec, 4)
    pager.ensure_gids(8)
    rows, _ = pager.assign_rows(np.arange(4, dtype=np.int64))
    pager.touch(rows[2:])                     # rows 0,1 stay coldest
    victims = pager.pick_victims(2, np.empty(0, np.int64))
    assert sorted(victims.tolist()) == [0, 1]


def test_pager_clock_second_chance(tmp_path):
    spec = SumAggregator(jnp.float32).acc_spec()
    pager = DevicePager(PagingConfig(4, policy="clock",
                                     directory=str(tmp_path / "p")), spec, 4)
    pager.ensure_gids(8)
    pager.assign_rows(np.arange(4, dtype=np.int64))   # all ref bits set
    # first sweep clears every ref bit, second sweep yields victims —
    # deterministic hand order
    victims = pager.pick_victims(2, np.empty(0, np.int64))
    assert victims.size == 2
    assert set(victims.tolist()) <= {0, 1, 2, 3}


def test_pager_protected_rows_never_evicted(tmp_path):
    spec = SumAggregator(jnp.float32).acc_spec()
    pager = DevicePager(PagingConfig(4, policy="lru",
                                     directory=str(tmp_path / "p")), spec, 4)
    pager.ensure_gids(8)
    pager.assign_rows(np.arange(4, dtype=np.int64))
    victims = pager.pick_victims(2, np.array([0, 1], np.int64))
    assert set(victims.tolist()) == {2, 3}
    with pytest.raises(RuntimeError):
        pager.pick_victims(3, np.array([0, 1], np.int64))


def test_paging_config_validation():
    with pytest.raises(ValueError):
        _mk_op(PagingConfig(16, policy="fifo"))     # unknown policy
    with pytest.raises(ValueError):
        _mk_op(PagingConfig(16), emit_tier="host")  # host tier unsupported
    from flink_tpu.windowing.triggers import CountTrigger
    with pytest.raises(ValueError):
        _mk_op(PagingConfig(16), trigger=CountTrigger.of(3))


# ---------------------------------------------------------------------------
# fire-digest equality: paged == fully resident
# ---------------------------------------------------------------------------

def test_fire_digests_identical_under_paging_both_policies():
    ref, _ = _run(None)
    clock, op_c = _run(PagingConfig(1024, policy="clock"))
    lru, op_l = _run(PagingConfig(1024, policy="lru"))
    assert clock == ref and lru == ref
    for op in (op_c, op_l):
        st = op.paging_stats()
        assert st["evictions"] > 0 and st["promotions"] > 0
        assert st["resident_keys"] == 1024
        assert st["resident_keys"] + st["spilled_keys"] == 4096


def test_paging_sliding_windows_digest_identical():
    """Sliding windows: spilled cells span multiple panes per window and
    every pane feeds two windows — the pane combine must agree across
    tiers."""
    assigner = SlidingEventTimeWindows.of(2000, 1000)
    def run(paging):
        op = _mk_op(paging, assigner=assigner)
        rng = np.random.default_rng(11)
        out = []
        for w in range(4):
            _feed(op, rng.permutation(2048).astype(np.int64),
                  w * 1000 + 10, out)
            out += op.process_watermark(Watermark(w * 1000 + 999))
        out += op.end_input()
        return _digests(out)
    assert run(PagingConfig(512)) == run(None)


def test_paging_late_records_within_lateness_refire():
    """A late record for a key whose pane cells are SPILLED folds in after
    promotion and re-fires identically to the resident run."""
    def run(paging):
        op = _mk_op(paging, allowed_lateness_ms=1000)
        out = []
        keys = np.arange(1024, dtype=np.int64)
        _feed(op, keys, 10, out)
        out += op.process_watermark(Watermark(999))       # window 0 fires
        _feed(op, np.arange(1024, 2048, dtype=np.int64), 1010, out)  # evicts
        late = np.arange(0, 512, dtype=np.int64)          # late for window 0
        # batch=128 (= K_cap/2): identical batch boundaries in both runs —
        # each late batch refires window 0, so granularity must match
        _feed(op, late, 20, out, batch=128)               # refires window 0
        out += op.process_watermark(Watermark(1999))
        out += op.end_input()
        return _digests(out)
    assert run(PagingConfig(256)) == run(None)


def test_async_fire_eviction_between_fire_and_drain_keeps_attribution():
    """async_fire + paging: a queued fire's HBM rows may be evicted and
    REASSIGNED before the download drains — emissions must stay attributed
    to the keys that fired (rows translate to global ids at fire time)."""
    def run(async_fire):
        op = _mk_op(PagingConfig(256), async_fire=async_fire)
        out = []
        _feed(op, np.arange(1024, dtype=np.int64), 10, out, batch=128)
        out += op.process_watermark(Watermark(999))   # fire (queued if async)
        # evict + reassign the fired rows before any drain completes
        _feed(op, np.arange(1024, 2048, dtype=np.int64), 1010, out, batch=128)
        out += op.process_watermark(Watermark(1999))
        out += op.end_input()                          # force-drains
        return _digests(out)
    assert run(True) == run(False)


def test_k_cap_one_extreme_still_correct():
    """K_cap=1: every batch splits to single records and every access
    evicts — degenerate but correct (and must not recurse forever)."""
    def run(paging):
        op = _mk_op(paging)
        out = []
        _feed(op, np.arange(16, dtype=np.int64), 10, out, batch=8)
        out += op.end_input()
        return _digests(out)
    assert run(PagingConfig(1)) == run(None)


def test_oversized_batch_splits_instead_of_overflowing():
    """A single batch with more distinct keys than K_cap/2 splits
    host-side and still produces the resident run's digests."""
    def run(paging):
        op = _mk_op(paging)
        out = []
        keys = np.arange(2048, dtype=np.int64)
        _feed(op, keys, 10, out, batch=2048)   # one batch >> K_cap=256
        out += op.end_input()
        return _digests(out)
    assert run(PagingConfig(256)) == run(None)


# ---------------------------------------------------------------------------
# snapshots: restore at a different K_cap, across tiers, and rescale
# ---------------------------------------------------------------------------

def _run_with_cut(p_before, p_after, n_keys=4096, cut_at=10, seed=3):
    """Feed 2 windows x 2 passes; snapshot mid-window-0 at batch ``cut_at``
    and continue in a fresh operator configured with ``p_after``."""
    rng = np.random.default_rng(seed)
    plan = []
    for w in range(2):
        for _ in range(2):
            keys = rng.permutation(n_keys).astype(np.int64)
            for lo in range(0, n_keys, 512):
                plan.append((keys[lo: lo + 512], w))
    op = _mk_op(p_before)
    out = []
    lastw = 0
    for i, (k, w) in enumerate(plan):
        if i == cut_at:
            snap = op.snapshot_state()
            op = _mk_op(p_after)
            op.restore_state(snap)
        if w != lastw:
            out += op.process_watermark(Watermark(lastw * 1000 + 999))
            lastw = w
        v = (k % 17 + 1).astype(np.float32)
        out += op.process_batch(RecordBatch(
            {"k": k, "v": v}, timestamps=np.full(k.size, w * 1000 + 10,
                                                 np.int64)))
    out += op.process_watermark(Watermark(lastw * 1000 + 999))
    out += op.end_input()
    return _digests(out)


def test_restore_at_smaller_and_larger_k_cap():
    ref = _run_with_cut(None, None)
    assert _run_with_cut(PagingConfig(1024), PagingConfig(256)) == ref
    assert _run_with_cut(PagingConfig(256), PagingConfig(2048)) == ref


def test_savepoint_compat_resident_to_paged_and_back():
    """ISSUE satellite: a savepoint written by a fully-resident run
    restores into a paging run with a smaller K_cap, and vice versa, with
    identical fire digests."""
    ref = _run_with_cut(None, None)
    assert _run_with_cut(None, PagingConfig(512)) == ref
    assert _run_with_cut(PagingConfig(512), None) == ref


def test_paged_snapshot_rescales_through_redistribute():
    """The paged snapshot is the repo-standard dense keyed format:
    split_keyed_snapshot + merge round-trips it (rescale compatibility)."""
    op = _mk_op(PagingConfig(256))
    out = []
    _feed(op, np.arange(2000, dtype=np.int64), 10, out)
    snap = op.snapshot_state()
    parts = WindowAggOperator.split_snapshot(snap, 128, 4)
    assert len(parts) == 4
    sizes = [len(p["key_index"]["reverse"]) for p in parts]
    assert sum(sizes) == 2000 and all(s > 0 for s in sizes)
    merged = WindowAggOperator.merge_snapshots(parts)
    op2 = _mk_op(PagingConfig(512))
    op2.restore_state(merged)
    out2 = op2.process_watermark(Watermark(999)) + op2.end_input()
    d = _digests(out2)
    assert len(d) == 2000
    assert d == _digests(op_reference_fire())


def op_reference_fire():
    op = _mk_op(None)
    out = []
    _feed(op, np.arange(2000, dtype=np.int64), 10, out)
    out += op.process_watermark(Watermark(999))
    out += op.end_input()
    return out


def test_snapshot_reports_paging_stats():
    op = _mk_op(PagingConfig(256))
    out = []
    _feed(op, np.arange(1000, dtype=np.int64), 10, out)
    snap = op.snapshot_state()
    st = snap["paging_stats"]
    assert st["resident_keys"] == 256 and st["spilled_keys"] == 744


# ---------------------------------------------------------------------------
# occupancy metrics: job scope + stats surface
# ---------------------------------------------------------------------------

def test_paging_metrics_register_on_job_scope():
    from flink_tpu.metrics.groups import (MetricRegistry, PAGING_EVICTIONS,
                                          PAGING_PROMOTIONS,
                                          PAGING_RESIDENT_KEYS,
                                          PAGING_SPILLED_KEYS,
                                          paging_metrics)
    op = _mk_op(PagingConfig(256))
    out = []
    _feed(op, np.arange(1000, dtype=np.int64), 10, out)
    reg = MetricRegistry()
    group = reg.job_manager_group()
    paging_metrics(group, op.paging_stats)
    metrics = {k.split(".", 1)[-1]: m for k, m in reg.all_metrics().items()}
    assert metrics[PAGING_RESIDENT_KEYS].get_value() == 256
    assert metrics[PAGING_SPILLED_KEYS].get_value() == 744
    assert metrics[PAGING_EVICTIONS].get_value() > 0
    assert metrics[PAGING_PROMOTIONS].get_value() >= 0


def test_minicluster_job_status_aggregates_paging():
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    n = 6000
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 3000, n)
    vals = np.ones(n, np.float32)
    ts = np.sort(rng.integers(0, 2000, n))
    env = StreamExecutionEnvironment()
    env.set_parallelism(1)
    sink = (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                batch_size=256)
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .aggregate(SumAggregator(jnp.float32), value_column="v",
                       emit_tier="device", paging=PagingConfig(512))
            .collect())
    env.execute_cluster()
    cluster = env._last_cluster
    status = cluster.job_status()
    assert "paging" in status
    assert status["paging"]["evictions"] > 0
    assert status["paging"]["capacity"] == 512
    names = set(cluster.metrics_registry.all_metrics())
    assert any(k.endswith("paging.resident_keys") for k in names)
    total = sum(r["result"] for r in sink.rows())
    assert total == float(n)


# ---------------------------------------------------------------------------
# scale: the acceptance variant (tier-1) + the 1M stress (slow)
# ---------------------------------------------------------------------------

def _scale_run(paging, n_keys, extra_refeed=0, seed=13, batch=1 << 15):
    op = _mk_op(paging, window_ms=1000,
                capacity_hint=1 << 10 if paging else n_keys)
    rng = np.random.default_rng(seed)
    out = []
    for w in range(2):
        _feed(op, rng.permutation(n_keys).astype(np.int64),
              w * 1000 + 10, out, batch)
        if extra_refeed and w == 0:
            # re-touch a spilled slice while its pane is live -> promotions
            _feed(op, np.arange(extra_refeed, dtype=np.int64),
                  w * 1000 + 10, out, batch)
        out += op.process_watermark(Watermark(w * 1000 + 999))
    out += op.end_input()
    return _digests(out), op


def test_acceptance_64k_cap_256k_keys_digest_identical():
    """THE acceptance run: K_cap = 64k forced far below 256k live keys.
    Every key fires in every window (spilled keys fold into fires), the
    digests match the fully-resident run exactly, and the occupancy
    counters prove the ring ran as a cache."""
    n_keys = 256 * 1024
    cap = 64 * 1024
    ref, _ = _scale_run(None, n_keys, extra_refeed=cap)
    paged, op = _scale_run(PagingConfig(cap), n_keys, extra_refeed=cap)
    assert len(ref) == 2 * n_keys
    assert paged == ref
    st = op.paging_stats()
    assert st["resident_keys"] == cap
    assert st["spilled_keys"] == n_keys - cap
    assert st["evictions"] >= n_keys - cap
    assert st["promotions"] > 0


@pytest.mark.slow
def test_eviction_stress_1m_keys():
    """1M keys through a 64k-row ring: the eviction path at scale.  The
    digest check is against per-key expectations (a 1M-key resident
    reference run would double the runtime for no extra coverage)."""
    n_keys = 1 << 20
    cap = 64 * 1024
    d, op = _scale_run(PagingConfig(cap), n_keys, batch=1 << 15)
    assert len(d) == 2 * n_keys
    # every (window, key) present exactly once with the exact sum
    expect = sorted((w * 1000, k, float(np.float32(k % 17 + 1)))
                    for w in range(2) for k in range(n_keys))
    assert d == expect
    st = op.paging_stats()
    assert st["resident_keys"] == cap
    assert st["evictions"] >= n_keys - cap
