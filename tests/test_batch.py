"""RecordBatch / stream-element tests.

The columnar batch is the TPU-native unit of flow (reference moves one
``StreamElement`` at a time, ``flink-streaming-java/.../streamrecord/``).
"""

import numpy as np
import pytest

from flink_tpu.core.batch import (
    MAX_WATERMARK,
    CheckpointBarrier,
    RecordBatch,
    Watermark,
)


def _batch(n=4, keyed=False):
    b = RecordBatch(
        {"v": np.arange(n, dtype=np.float32)},
        timestamps=np.arange(n, dtype=np.int64) * 10,
    )
    if keyed:
        b = b.with_keys(np.arange(n, dtype=np.int32) % 2,
                        np.arange(n, dtype=np.int32) % 8)
    return b


def test_basic_shape_and_len():
    b = _batch(5)
    assert len(b) == 5 and b.size == 5
    assert b.column("v").dtype == np.float32


def test_empty_batch():
    b = RecordBatch({})
    assert len(b) == 0


def test_misaligned_timestamps_rejected():
    with pytest.raises(ValueError):
        RecordBatch({"v": np.zeros(3)}, timestamps=np.zeros(2, np.int64))


def test_misaligned_columns_rejected():
    with pytest.raises(ValueError):
        RecordBatch({"a": np.zeros(3), "b": np.zeros(4)})


def test_with_columns_size_change_rejected():
    # A size-changing map must not silently pair new rows with stale keys.
    b = _batch(4, keyed=True)
    with pytest.raises(ValueError):
        b.with_columns({"v": np.zeros(2, np.float32)})


def test_select_preserves_keyedness():
    b = _batch(4, keyed=True)
    out = b.select(np.array([True, False, True, False]))
    assert len(out) == 2
    assert out.key_ids is not None and out.key_groups is not None
    assert out.timestamps.tolist() == [0, 20]


def test_select_all_false_keeps_schema():
    b = _batch(4, keyed=True)
    out = b.select(np.zeros(4, bool))
    assert len(out) == 0
    assert set(out.columns) == {"v"}
    assert out.timestamps is not None and out.key_ids is not None


def test_take_reorders():
    b = _batch(4)
    out = b.take(np.array([3, 0]))
    assert out.column("v").tolist() == [3.0, 0.0]
    assert out.timestamps.tolist() == [30, 0]


def test_concat():
    b = RecordBatch.concat([_batch(2), _batch(3)])
    assert len(b) == 5
    assert b.timestamps.tolist() == [0, 10, 0, 10, 20]


def test_concat_skips_empty():
    b = RecordBatch.concat([_batch(2), _batch(0), _batch(3)])
    assert len(b) == 5


def test_concat_all_empty_preserves_schema():
    # An all-empty flush must keep schema/keyed-ness: downstream presence
    # checks (timestamps is not None) branch on it.
    e = _batch(0, keyed=True)
    out = RecordBatch.concat([e, e])
    assert len(out) == 0
    assert set(out.columns) == {"v"}
    assert out.timestamps is not None and out.key_ids is not None


def test_concat_of_nothing():
    assert len(RecordBatch.concat([])) == 0


def test_concat_heterogeneous_rejected():
    a = RecordBatch({"x": np.zeros(2)})
    b = RecordBatch({"y": np.zeros(2)})
    with pytest.raises(ValueError):
        RecordBatch.concat([a, b])


def test_concat_inconsistent_timestamps_rejected():
    a = RecordBatch({"x": np.zeros(2)}, timestamps=np.zeros(2, np.int64))
    b = RecordBatch({"x": np.zeros(2)})
    with pytest.raises(ValueError):
        RecordBatch.concat([a, b])


def test_from_rows_round_trip():
    rows = [{"w": 1, "c": 2}, {"w": 3, "c": 4}]
    b = RecordBatch.from_rows(rows, timestamps=[5, 6])
    assert b.to_rows() == rows
    assert b.timestamps.tolist() == [5, 6]


def test_control_elements():
    assert Watermark(MAX_WATERMARK).timestamp == MAX_WATERMARK
    cb = CheckpointBarrier(7, 123)
    assert cb.checkpoint_id == 7 and cb.timestamp == 123
    assert not cb.is_batch()
    assert _batch(1).is_batch()
