"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on a virtual 8-device CPU platform (the reference's analog is
MiniCluster: multi-node semantics in one process, ``MiniCluster.java``).
Must run before jax initializes its backends, hence top of conftest.
"""

import os

# Force, don't setdefault: the driver environment pre-sets JAX_PLATFORMS to the
# real TPU platform, and unit tests must never contend for the one real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# The TPU-tunnel site hook (sitecustomize → axon.register) runs at interpreter
# startup and overrides platform selection via jax.config.update("jax_platforms",
# "axon,cpu") — the env var alone is not enough.  Re-force the config to CPU
# before any backend initializes, otherwise the first jax.devices() call in a
# test dials the (single, possibly busy) real chip and blocks indefinitely.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
