"""Scatter-combine kernels: fold a record batch into dense keyed device state.

This replaces the reference's per-record state-map probe+update
(``CopyOnWriteStateMap.transform`` called from ``HeapAggregatingState.java:42``
for every element, SURVEY §3.3 hot loop (c)) with ONE fused device op per
micro-batch over ``[num_slots, ...]`` dense state:

- **fast path** — when every accumulator leaf's ``combine`` is an elementwise
  add/min/max (covers sum/count/avg/min/max and products thereof, i.e. every
  built-in reference aggregation, ``SumAggregator.java``/``ComparableAggregator.java``),
  the whole batch folds with ``state.at[idx].add|min|max(lifted)`` — a single
  XLA scatter per leaf that TPU executes without host round-trips.

- **generic path** — any associative+commutative ``combine`` (the reference's
  ``AggregateFunction.merge`` contract, ``AggregateFunction.java:114``): sort
  the batch by slot id, run a *segmented* ``lax.associative_scan`` (flag/value
  pairs), and scatter each segment's total with ``.at[].set`` — indices are
  unique after segmentation, so arbitrary monoids stay race-free.

Out-of-range slot ids (== num_slots) are dropped by XLA scatter semantics —
padding rows use that to make batch shapes static (no recompiles per batch).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

#: scatter kinds an accumulator leaf may declare
SCATTER_KINDS = ("add", "min", "max")


def _bcast_flags(flags, like):
    """Reshape [B] flags to broadcast against a [B, ...] leaf."""
    extra = like.ndim - 1
    return flags.reshape(flags.shape + (1,) * extra)


def scatter_fast(state_leaves, slot_ids, lifted_leaves, kinds: Sequence[str]):
    """Fold lifted [B, ...] leaves into [N, ...] state via add/min/max scatters.

    slot_ids: int32[B]; ids == N (out of range) are dropped (padding).
    """
    out = []
    for leaf, lifted, kind in zip(state_leaves, lifted_leaves, kinds):
        ref = leaf.at[slot_ids]
        if kind == "add":
            out.append(ref.add(lifted.astype(leaf.dtype), mode="drop"))
        elif kind == "min":
            out.append(ref.min(lifted.astype(leaf.dtype), mode="drop"))
        elif kind == "max":
            out.append(ref.max(lifted.astype(leaf.dtype), mode="drop"))
        else:
            raise ValueError(f"unknown scatter kind {kind!r}")
    return tuple(out)


def scatter_fold_counts(flat_leaves, flat_counts, slot_ids, lifted_leaves,
                        kinds: Sequence[str]):
    """One batch's fold into FLAT ``[K*P]`` keyed state: the value leaves
    scatter-combine by kind and the element counts scatter-add ones — the
    shared body of the per-batch update step, the device-probe delta fold,
    and the fused scan megastep's per-step fold (window_agg), so the three
    lanes cannot drift arithmetically.  Out-of-range ids (padding, probe
    misses) drop."""
    new_leaves = scatter_fast(flat_leaves, slot_ids, lifted_leaves, kinds)
    ones = jnp.ones(slot_ids.shape, jnp.int32)
    return new_leaves, flat_counts.at[slot_ids].add(ones, mode="drop")


def segment_fold(slot_ids, lifted_leaves, combine_leaves: Callable,
                 num_slots: int = 0):
    """Generic per-batch segment reduction: returns (unique_slot_ids[B],
    is_segment_end[B], folded_leaves[B, ...]) where rows flagged as segment
    ends hold the full fold of their slot's records in this batch.

    combine_leaves(a_leaves, b_leaves) -> leaves; must be associative +
    commutative per the ``AggregateFunction.merge`` contract.
    """
    _, sids, is_end, folded = segment_running_fold(slot_ids, lifted_leaves,
                                                   combine_leaves)
    return sids, is_end, folded


def segment_running_fold(slot_ids, lifted_leaves, combine_leaves: Callable):
    """Per-record *running* segment fold (keyed ``reduce()`` semantics:
    every input record emits its key's fold-so-far within the batch).

    Returns (order[B], sids[B], is_end[B], prefix_leaves[B, ...]) where
    ``prefix_leaves[i]`` is the inclusive fold of sorted rows of the same slot
    up to i; ``order`` maps sorted position -> original row.
    """
    order = jnp.argsort(slot_ids)
    sids = slot_ids[order]
    svals = tuple(l[order] for l in lifted_leaves)
    first = jnp.concatenate([jnp.ones((1,), bool), sids[1:] != sids[:-1]])

    def seg_op(a, b):
        fa, va = a[0], a[1:]
        fb, vb = b[0], b[1:]
        merged = combine_leaves(va, vb)
        vals = tuple(
            jnp.where(_bcast_flags(fb, m), y, m)
            for m, y in zip(merged, vb)
        )
        return (fa | fb,) + vals

    scanned = jax.lax.associative_scan(seg_op, (first,) + svals)
    is_end = jnp.concatenate([sids[1:] != sids[:-1], jnp.ones((1,), bool)])
    return order, sids, is_end, scanned[1:]


def scatter_generic(state_leaves, slot_ids, lifted_leaves,
                    combine_leaves: Callable, num_slots: int):
    """Fold a batch into state with an arbitrary monoid combine.

    1. segment-fold the batch per slot (associative scan),
    2. gather current state at each segment-end slot,
    3. combine and ``.at[].set`` — segment-end slots are unique, so the
       read-modify-write races the reference solves with single-threaded
       mailboxing (``MailboxProcessor.java:66``) cannot occur.
    """
    sids, is_end, folded = segment_fold(slot_ids, lifted_leaves, combine_leaves, num_slots)
    write_ids = jnp.where(is_end, sids, num_slots)  # non-ends dropped
    safe_gather = jnp.minimum(sids, num_slots - 1)
    current = tuple(l[safe_gather] for l in state_leaves)
    merged = combine_leaves(current, folded)
    return tuple(
        l.at[write_ids].set(m.astype(l.dtype), mode="drop")
        for l, m in zip(state_leaves, merged)
    )


def gather_row_pane_columns(state_leaves, counts, rows, pane_slots):
    """Page-out gather: the ``rows x pane_slots`` sub-grid of ``[K, P, ...]``
    keyed state — ``(counts[V, m], leaves[V, m, *leaf])``.  Row/pane pads
    may use any in-range id (callers slice the pads off host-side);
    ``jnp.take`` clips out-of-range pads."""
    sel_counts = jnp.take(jnp.take(counts, rows, axis=0), pane_slots, axis=1)
    sel_leaves = tuple(
        jnp.take(jnp.take(l, rows, axis=0), pane_slots, axis=1)
        for l in state_leaves)
    return sel_counts, sel_leaves


def reset_rows(state_leaves, counts, rows, leaf_inits):
    """Reset whole key rows (every pane slot) to the accumulator identity.
    Row pads use id K (out of range, dropped)."""
    new_leaves = tuple(
        l.at[rows].set(
            jnp.broadcast_to(jnp.asarray(init, l.dtype),
                             (rows.shape[0],) + l.shape[1:]),
            mode="drop")
        for l, init in zip(state_leaves, leaf_inits))
    return new_leaves, counts.at[rows].set(0, mode="drop")


def set_row_pane_columns(state_leaves, counts, rows, pane_slots,
                         leaf_cols, counts_cols, leaf_inits):
    """Page-in: reset the target rows across the whole ring, then set their
    ``pane_slots`` columns from the promoted cells (identity where nothing
    was spilled).  Row pads = K, pane pads = P (both dropped)."""
    new_leaves, new_counts = reset_rows(state_leaves, counts, rows,
                                        leaf_inits)
    new_leaves = tuple(
        l.at[rows[:, None], pane_slots[None, :]].set(col, mode="drop")
        for l, col in zip(new_leaves, leaf_cols))
    new_counts = new_counts.at[rows[:, None], pane_slots[None, :]].set(
        counts_cols, mode="drop")
    return new_leaves, new_counts


def combine_along_axis(leaves, combine_leaves: Callable, axis: int, keepdims: bool = False):
    """Tree-reduce leaves along ``axis`` with an arbitrary monoid — the fire-time
    pane combine (blockwise partials → window total, SURVEY §5.7). Log-depth."""
    n = leaves[0].shape[axis]

    def take(ls, sl):
        return tuple(jax.lax.slice_in_dim(l, sl.start, sl.stop, axis=axis) for l in ls)

    cur = leaves
    size = n
    while size > 1:
        half = size // 2
        a = take(cur, slice(0, half))
        b = take(cur, slice(half, 2 * half))
        merged = combine_leaves(a, b)
        if size % 2:
            tail = take(cur, slice(2 * half, size))
            merged = tuple(jnp.concatenate([m, t], axis=axis) for m, t in zip(merged, tail))
            size = half + 1
        else:
            size = half
        cur = merged
    if keepdims:
        return cur
    return tuple(jnp.squeeze(l, axis=axis) for l in cur)
