"""Static-shape sizing helpers shared by the device operators.

XLA compiles one program per shape, so batch/capacity paddings are rounded
to a small set of sizes: pow2 for growth-style capacities, pow2/4 or pow2/8
sub-steps where padding waste is the scarcer resource (e.g. device->host
transfers) — each distinct size is one compile, so the step count bounds the
jit cache."""

from __future__ import annotations


def next_pow2(n: int, floor: int = 1) -> int:
    c = floor
    while c < n:
        c <<= 1
    return c


def quantize_pow2(n: int, floor: int = 64, steps: int = 4) -> int:
    """Round ``n`` up to a multiple of ``next_pow2(n)/steps`` (>= floor):
    at most ``steps`` distinct sizes per pow2 decade, <= 1/steps padding."""
    p = next_pow2(max(n, floor), floor)
    q = max(p // steps, floor)
    return ((n + q - 1) // q) * q
