"""Device equi-join kernels: sorted-merge pair enumeration on the MXU host.

The device analog of the blink join runtime's sort/hash machinery
(``flink-table-runtime-blink/.../operators/join/stream/StreamingJoinOperator.java``,
``hashtable/BytesHashMap.java``): both key columns are sorted on device,
matching key spans are intersected, and every cross pair is enumerated by a
vectorized prefix-sum expansion — no Python loop over keys.

Two-phase static-shape protocol (XLA needs static output shapes):
phase 1 returns the exact pair count (one scalar sync); phase 2 compiles at
a pow2/4-quantized capacity and fills ``(left_idx, right_idx)`` padded with
``-1``.  The jit caches are keyed on (L, R, cap) so steady workloads compile
O(log) times.

When to use: pipelines whose batches already live on device (the mesh
runtime, device-resident table programs) or whose join sides are large
enough that sort cost dominates transfer.  Host pipelines over numpy batches
default to the numpy span-intersection join (``operators/joins._join_pairs``)
— on the axon tunnel transport a device→host index download costs ~350ms/MB,
dwarfing any sort speedup (see the tunnel-asymmetry note in
``operators/window_agg.py``).  Enable globally with
``FLINK_TPU_DEVICE_JOIN=1`` or per-call via ``device_join_pairs``.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _pair_count(lk, rk):
    """Exact number of equi-join pairs: for each left row, the size of the
    matching right span (searchsorted bounds on the sorted right keys)."""
    rks = jnp.sort(rk)
    lo = jnp.searchsorted(rks, lk, side="left")
    hi = jnp.searchsorted(rks, lk, side="right")
    return (hi - lo).sum()


@partial(jax.jit, static_argnums=(2,))
def _pair_emit(lk, rk, cap: int):
    """(left_idx[cap], right_idx[cap], n) — pairs in left-major order,
    right matches in right-sort order; padding rows are -1."""
    L = lk.shape[0]
    ro = jnp.argsort(rk, stable=True)
    rks = rk[ro]
    lo = jnp.searchsorted(rks, lk, side="left")
    hi = jnp.searchsorted(rks, lk, side="right")
    counts = hi - lo
    off = jnp.cumsum(counts) - counts          # start offset per left row
    n = counts.sum()
    pos = jnp.arange(cap)
    # which left row does output position p belong to?
    li = jnp.searchsorted(off + counts, pos, side="right")
    li = jnp.minimum(li, L - 1)
    within = pos - off[li]
    ri = ro[jnp.minimum(lo[li] + within, rk.shape[0] - 1)]
    valid = pos < n
    return (jnp.where(valid, li, -1).astype(jnp.int32),
            jnp.where(valid, ri, -1).astype(jnp.int32), n)


from flink_tpu.ops.shapes import quantize_pow2 as _quantize


def device_join_pairs(lk: np.ndarray, rk: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Device sorted-merge equi-join; same contract as
    ``operators.joins._join_pairs`` (all cross pairs with equal keys).
    Integer keys only — factorize object keys first (``state/keyindex``)."""
    lk = np.ascontiguousarray(lk)
    rk = np.ascontiguousarray(rk)
    if lk.size == 0 or rk.size == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    # ALWAYS factorize to dense codes first: jnp defaults to int32, so raw
    # int64 keys would silently truncate; dense codes also make the device
    # sort radix-friendly.  Absent right keys get distinct negative codes
    # (they join with nothing; left codes are all >= 0).
    if lk.dtype.kind in "iu" and rk.dtype.kind in "iu":
        from flink_tpu.state.keyindex import KeyIndex
        ki = KeyIndex()
        lcodes = ki.lookup_or_insert(lk).astype(np.int64)
        rcodes = ki.lookup(rk).astype(np.int64)
    else:
        from flink_tpu.state.keyindex import ObjectKeyIndex
        ki = ObjectKeyIndex()
        lcodes = ki.lookup_or_insert(lk).astype(np.int64)
        rcodes = ki.lookup(rk).astype(np.int64)
    lk = lcodes
    rk = np.where(rcodes < 0, -(np.arange(rcodes.size) + 2), rcodes)
    n = int(_pair_count(jnp.asarray(lk), jnp.asarray(rk)))
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    cap = _quantize(n)
    li, ri, _ = _pair_emit(jnp.asarray(lk), jnp.asarray(rk), cap)
    li = np.asarray(li)[:n].astype(np.int64)
    ri = np.asarray(ri)[:n].astype(np.int64)
    return li, ri
