"""Window evictors (``api/windowing/evictors/`` analog).

An evictor trims a window's buffered rows before the window function runs
(evicting windows buffer raw elements rather than folding into an ACC —
``EvictingWindowOperator`` semantics).  Vectorized: an evictor receives the
window's row index order + timestamps and returns a keep-mask.
"""

from __future__ import annotations

import numpy as np


class Evictor:
    def keep_mask(self, timestamps: np.ndarray, window_max_ts: int,
                  rows=None) -> np.ndarray:
        """bool[n] over rows sorted by arrival order: True = keep.
        ``rows`` is the window's buffered row dicts (same order) so
        value-inspecting evictors need no side channel."""
        raise NotImplementedError


class CountEvictor(Evictor):
    """Keep only the LAST ``n`` rows (``CountEvictor.of``)."""

    def __init__(self, n: int):
        self.n = n

    @staticmethod
    def of(n: int) -> "CountEvictor":
        return CountEvictor(n)

    def keep_mask(self, timestamps: np.ndarray, window_max_ts: int,
                  rows=None) -> np.ndarray:
        m = np.zeros(len(timestamps), bool)
        m[max(0, len(timestamps) - self.n):] = True
        return m


class TimeEvictor(Evictor):
    """Keep rows within ``window_ms`` of the newest row (``TimeEvictor.of``)."""

    def __init__(self, window_ms: int):
        self.window_ms = window_ms

    @staticmethod
    def of(window_ms: int) -> "TimeEvictor":
        return TimeEvictor(window_ms)

    def keep_mask(self, timestamps: np.ndarray, window_max_ts: int,
                  rows=None) -> np.ndarray:
        ts = np.asarray(timestamps, np.int64)
        if ts.size == 0:
            return np.zeros(0, bool)
        return ts >= ts.max() - self.window_ms

class DeltaEvictor(Evictor):
    """Keep rows whose value is within ``threshold`` of the newest row's
    value (``DeltaEvictor`` analog)."""

    def __init__(self, threshold: float, value_column: str):
        self.threshold = threshold
        self.value_column = value_column

    @staticmethod
    def of(threshold: float, value_column: str) -> "DeltaEvictor":
        return DeltaEvictor(threshold, value_column)

    def keep_mask(self, timestamps: np.ndarray, window_max_ts: int,
                  rows=None) -> np.ndarray:
        if not rows:
            return np.ones(len(timestamps), bool)
        values = np.asarray([r[self.value_column] for r in rows], np.float64)
        return np.abs(values - values[-1]) <= self.threshold
