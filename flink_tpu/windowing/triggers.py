"""Triggers: when a window's contents are emitted.

Analog of ``flink-streaming-java/.../api/windowing/triggers/Trigger.java``
(onElement/onEventTime/onProcessingTime → CONTINUE/FIRE/PURGE/FIRE_AND_PURGE).
In the batched runtime the trigger is consulted *per micro-batch*, not per
record: after each batch the operator asks the trigger which windows fire now
(count triggers check per-key device counters), and on each watermark advance
which windows fire by time.  Semantics match the reference for the shipped
triggers; the per-record granularity difference is only observable for
CountTrigger mid-batch (fires at batch boundaries — same behavior as the
reference's mini-batch/bundle SQL operators, ``operators/bundle/``).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class TriggerResult:
    fire: bool
    purge: bool

    CONTINUE = None  # filled below
    FIRE = None
    PURGE = None
    FIRE_AND_PURGE = None


TriggerResult.CONTINUE = TriggerResult(False, False)
TriggerResult.FIRE = TriggerResult(True, False)
TriggerResult.PURGE = TriggerResult(False, True)
TriggerResult.FIRE_AND_PURGE = TriggerResult(True, True)


class Trigger:
    """Batched trigger contract.

    ``on_event_time`` / ``on_processing_time`` decide whether windows whose
    end has been passed fire; ``fires_on_batch`` lets count-like triggers fire
    eagerly after a micro-batch.
    """

    #: True if this trigger fires windows when event/processing time passes
    #: the window end (the EventTime/ProcessingTime trigger family).
    fires_on_time: bool = True
    #: True if the operator must evaluate per-key counts after each batch.
    fires_on_count: bool = False
    #: fire count threshold (for count triggers)
    count_threshold: int = 0
    #: purge window state on fire (PurgingTrigger / FIRE_AND_PURGE)
    purges_on_fire: bool = True

    def with_purging(self) -> "Trigger":
        return self


class EventTimeTrigger(Trigger):
    """Default for event-time windows (``EventTimeTrigger.java``): FIRE when
    the watermark passes the window end; late elements within allowed lateness
    re-FIRE immediately."""

    fires_on_time = True
    purges_on_fire = True  # window state purged at cleanup time; per-fire the
    # operator keeps panes until retention expires (lateness), matching the
    # reference where PURGE happens at cleanup, not on each FIRE.

    @staticmethod
    def create() -> "EventTimeTrigger":
        return EventTimeTrigger()


class ProcessingTimeTrigger(Trigger):
    """FIRE when processing time passes window end (``ProcessingTimeTrigger.java``)."""

    fires_on_time = True

    @staticmethod
    def create() -> "ProcessingTimeTrigger":
        return ProcessingTimeTrigger()


class CountTrigger(Trigger):
    """FIRE when a key's window holds >= n elements (``CountTrigger.java``);
    evaluated after each micro-batch against the device count state.

    ``purge=False`` (default — matching the reference's raw ``CountTrigger``,
    FIRE only): the window keeps accumulating and fires again every n
    elements with the full running contents.  ``purge=True`` is the
    ``countWindow`` behavior (``PurgingTrigger(CountTrigger)``): fired state
    clears, the next fire needs n fresh elements — ``count_window()`` passes
    it explicitly.  Sliding (multi-pane) assigners support only
    ``purge=False``, because overlapping windows share pane state."""

    fires_on_time = False
    fires_on_count = True

    def __init__(self, n: int, purge: bool = False):
        self.count_threshold = int(n)
        self.purges_on_fire = bool(purge)

    @staticmethod
    def of(n: int, purge: bool = False) -> "CountTrigger":
        return CountTrigger(n, purge)


class PurgingTrigger(Trigger):
    """Wraps a trigger so every FIRE becomes FIRE_AND_PURGE (``PurgingTrigger.java``)."""

    def __init__(self, inner: Trigger):
        self.inner = inner
        self.fires_on_time = inner.fires_on_time
        self.fires_on_count = inner.fires_on_count
        self.count_threshold = inner.count_threshold
        self.purges_on_fire = True

    @staticmethod
    def of(inner: Trigger) -> "PurgingTrigger":
        return PurgingTrigger(inner)


class NeverTrigger(Trigger):
    """GlobalWindows default (``GlobalWindows.NeverTrigger``)."""

    fires_on_time = False
    fires_on_count = False
