"""Window assigners, pane-decomposed for batched TPU execution.

The reference assigns each element to its window set per record
(``flink-streaming-java/.../api/windowing/assigners/``: Tumbling/Sliding/
Session/Global × event/processing time) and, on the SQL fast path, decomposes
overlapping windows into **panes** — maximal non-overlapping spans shared by
all windows covering them (``flink-table-runtime-blink/.../window/assigners/
PanedWindowAssigner.java``, ``grouping/HeapWindowsGrouping.java``).

The TPU-native design makes the pane the *only* unit the per-record hot path
sees: ``pane_of(timestamps)`` is one vectorized int op over the batch, device
state is a ``[keys, panes]`` ring buffer, and full windows are assembled at
fire time by combining each window's (static, precomputed) pane set — the
blockwise-partial/combine structure that maps onto ``segment_sum`` +
tree-combine on the MXU-friendly dense layout.

Session windows are data-dependent (gap merging) and handled by a dedicated
operator (see ``flink_tpu/operators/session.py``), mirroring how the reference
splits the merging path (``MergingWindowSet.java``) from the paned path.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import gcd
from typing import List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import LONG_MAX, LONG_MIN


@dataclass(frozen=True, order=True)
class TimeWindow:
    """[start, end) time window (``TimeWindow.java``); max_timestamp = end - 1."""

    start: int
    end: int

    @property
    def max_timestamp(self) -> int:
        return self.end - 1

    def intersects(self, other: "TimeWindow") -> bool:
        return self.start <= other.end and other.start <= self.end

    def cover(self, other: "TimeWindow") -> "TimeWindow":
        return TimeWindow(min(self.start, other.start), max(self.end, other.end))


class WindowAssigner:
    """Pane-decomposed window assigner.

    Contract (all windows are unions of contiguous panes):
      pane_ms                       pane width in ms
      panes_per_window              number of consecutive panes per window
      pane_stride                   panes between consecutive window starts
      pane_of(ts[B]) -> int64[B]    pane id per record (one vector op)
      window_of_last_pane(pane)     window id of the *latest* window containing
                                    this pane (used for retention math)
    Window id ``w`` covers panes ``[w * pane_stride, w * pane_stride +
    panes_per_window)``; its time span is ``window_bounds(w)``.
    """

    is_event_time: bool = True
    pane_ms: int = 0
    panes_per_window: int = 1
    pane_stride: int = 1

    def pane_of(self, timestamps: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def window_panes(self, window_id: int) -> Tuple[int, int]:
        """[first_pane, last_pane] inclusive for a window id."""
        first = window_id * self.pane_stride
        return first, first + self.panes_per_window - 1

    def window_bounds(self, window_id: int) -> TimeWindow:
        start = window_id * self.pane_stride * self.pane_ms + self._offset
        return TimeWindow(start, start + self.panes_per_window * self.pane_ms)

    def windows_of_pane(self, pane_id: int) -> Tuple[int, int]:
        """[first_window, last_window] inclusive containing pane_id."""
        last = pane_id // self.pane_stride
        first = (pane_id - self.panes_per_window + self.pane_stride) // self.pane_stride
        return first, last

    def last_window_end_of_pane(self, pane_id: int) -> int:
        """End timestamp of the latest window containing this pane — the pane
        can be cleared once the watermark passes this + allowed lateness."""
        _, last_w = self.windows_of_pane(pane_id)
        return self.window_bounds(last_w).end

    _offset: int = 0


@dataclass(frozen=True)
class _FixedPaneAssigner(WindowAssigner):
    size_ms: int = 0
    slide_ms: int = 0
    offset_ms: int = 0
    is_event_time: bool = True

    def __post_init__(self):
        if self.size_ms <= 0 or self.slide_ms <= 0:
            raise ValueError(
                f"window size/slide must be > 0, got size={self.size_ms} slide={self.slide_ms}")
        if self.slide_ms > self.size_ms:
            # Tumbling-with-gaps (slide > size) is rejected by the reference too
            # (SlidingEventTimeWindows checks size >= slide indirectly via panes).
            raise ValueError("slide must be <= size")
        pane = gcd(self.size_ms, self.slide_ms)
        object.__setattr__(self, "pane_ms", pane)
        object.__setattr__(self, "panes_per_window", self.size_ms // pane)
        object.__setattr__(self, "pane_stride", self.slide_ms // pane)
        object.__setattr__(self, "_offset", self.offset_ms % self.slide_ms)

    def pane_of(self, timestamps: np.ndarray) -> np.ndarray:
        ts = np.asarray(timestamps, np.int64)
        return (ts - self._offset) // np.int64(self.pane_ms)


class TumblingEventTimeWindows(_FixedPaneAssigner):
    """``TumblingEventTimeWindows.of(size[, offset])`` — pane == window."""

    def __init__(self, size_ms: int, offset_ms: int = 0):
        super().__init__(size_ms=size_ms, slide_ms=size_ms, offset_ms=offset_ms,
                         is_event_time=True)

    @staticmethod
    def of(size_ms: int, offset_ms: int = 0) -> "TumblingEventTimeWindows":
        return TumblingEventTimeWindows(size_ms, offset_ms)


class TumblingProcessingTimeWindows(_FixedPaneAssigner):
    def __init__(self, size_ms: int, offset_ms: int = 0):
        super().__init__(size_ms=size_ms, slide_ms=size_ms, offset_ms=offset_ms,
                         is_event_time=False)

    @staticmethod
    def of(size_ms: int, offset_ms: int = 0) -> "TumblingProcessingTimeWindows":
        return TumblingProcessingTimeWindows(size_ms, offset_ms)


class SlidingEventTimeWindows(_FixedPaneAssigner):
    """``SlidingEventTimeWindows.of(size, slide)``: windows overlap; each record
    lands in exactly one pane, each window combines size/gcd panes at fire."""

    def __init__(self, size_ms: int, slide_ms: int, offset_ms: int = 0):
        super().__init__(size_ms=size_ms, slide_ms=slide_ms, offset_ms=offset_ms,
                         is_event_time=True)

    @staticmethod
    def of(size_ms: int, slide_ms: int, offset_ms: int = 0) -> "SlidingEventTimeWindows":
        return SlidingEventTimeWindows(size_ms, slide_ms, offset_ms)


class SlidingProcessingTimeWindows(_FixedPaneAssigner):
    def __init__(self, size_ms: int, slide_ms: int, offset_ms: int = 0):
        super().__init__(size_ms=size_ms, slide_ms=slide_ms, offset_ms=offset_ms,
                         is_event_time=False)

    @staticmethod
    def of(size_ms: int, slide_ms: int, offset_ms: int = 0) -> "SlidingProcessingTimeWindows":
        return SlidingProcessingTimeWindows(size_ms, slide_ms, offset_ms)


class GlobalWindows(WindowAssigner):
    """One window covering everything (``GlobalWindows.java``); fires only via
    a count/custom trigger.  Modeled as a single pane with an effectively
    infinite width."""

    is_event_time = True
    pane_ms = LONG_MAX // 4
    panes_per_window = 1
    pane_stride = 1

    def pane_of(self, timestamps: np.ndarray) -> np.ndarray:
        return np.zeros(np.shape(timestamps)[0], np.int64)

    def window_bounds(self, window_id: int) -> TimeWindow:
        return TimeWindow(LONG_MIN, LONG_MAX)

    def last_window_end_of_pane(self, pane_id: int) -> int:
        return LONG_MAX

    @staticmethod
    def create() -> "GlobalWindows":
        return GlobalWindows()


@dataclass(frozen=True)
class SessionGap:
    """Session spec: windows merge while gaps < gap_ms (``EventTimeSessionWindows``).
    Consumed by the dedicated session operator, not the paned one."""

    gap_ms: int
    is_event_time: bool = True


def EventTimeSessionWindows(gap_ms: int) -> SessionGap:
    return SessionGap(gap_ms, True)


def ProcessingTimeSessionWindows(gap_ms: int) -> SessionGap:
    return SessionGap(gap_ms, False)
