"""CEP NFA operator over keyed streams.

Analog of ``flink-libraries/flink-cep``'s ``CepOperator`` + ``nfa/NFA.java:86``
+ ``sharedbuffer/SharedBuffer.java:62``, re-shaped for the batched runtime:

- **Vectorized condition evaluation** (the device-friendly half): every
  stage's predicate runs ONCE per batch over the whole column set, producing
  a ``[B, num_stages]`` bool matrix — the per-event work the reference does
  in ``ConditionContext`` collapses into a handful of vector ops.
- **Host NFA transitions** (the data-dependent half): per key, events are
  buffered until the watermark passes them (the reference buffers in
  ``elementQueueState`` and processes on watermark,
  ``CepOperator.onEventTime``), then sorted by timestamp and fed through the
  NFA with branching partial matches (take/proceed — the reference's
  ``SharedBuffer`` version tree, here explicit partial-match branches).

Supported semantics: strict (``next``) / relaxed (``followedBy``) /
non-deterministic relaxed (``followedByAny``) contiguity, NOT-patterns
(``notNext``/``notFollowedBy``, incl. trailing ``notFollowedBy`` completing
on ``within``-window close), ``times``/``oneOrMore``/``optional``
quantifiers with ``greedy()`` and ``until()``, ``within``, NO_SKIP and
SKIP_PAST_LAST_EVENT after-match strategies (``NFA.java:86``,
``Quantifier.java``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern, Stage
from flink_tpu.operators.base import StreamOperator


@dataclass(frozen=True)
class _Partial:
    """One partial match: position in the pattern + taken events.

    events: tuple of (stage_index, event_id); count = matches of the
    CURRENT stage taken so far (for quantifiers); greedy_from: index of the
    greedy looping stage this partial advanced out of (-1 = none) — while
    events still match that loop, the loop sibling consumes them and this
    partial must ignore them (``Quantifier.greedy`` semantics)."""

    stage_i: int
    count: int
    events: Tuple[Tuple[int, int], ...]
    first_ts: int
    greedy_from: int = -1


class NFA:
    """Pattern matcher for one key (``NFA.java:86`` analog)."""

    def __init__(self, pattern: Pattern):
        self.pattern = pattern
        self.stages = pattern.stages
        last = pattern.stages[-1]
        #: fast-path flag: only trailing notFollowedBy patterns need the
        #: per-event window-close harvest
        self._trailing_negation = last.negated and last.contiguity != "strict"
        self.partials: List[_Partial] = [_Partial(0, 0, (), LONG_MIN)]
        #: SKIP_PAST_LAST_EVENT barrier: events at/before this ts cannot
        #: extend or start matches
        self.skip_until_ts: int = LONG_MIN
        #: event id -> row, for match assembly (``SharedBuffer`` analog);
        #: pruned to events referenced by live partials after every drain
        self._rows: Dict[int, dict] = {}

    def _expired(self, pm: _Partial, ts: int) -> bool:
        w = self.pattern.within_ms
        return (w is not None and pm.first_ts != LONG_MIN
                and ts - pm.first_ts > w)

    def advance(self, event_id: int, ts: int, stage_bits: np.ndarray,
                until_bits: Optional[np.ndarray] = None,
                ) -> List[Tuple[Tuple[int, int], ...]]:
        """Feed one event; returns completed matches (event lists).

        Per partial the NFA edges are: **take** (event matches current
        stage — branch into 'stay in looping stage' and, once the
        quantifier's minimum is met, 'pointer moves to next stage'),
        **ignore** (relaxed stages skip non-matching events; ``relaxed_any``
        = ``followedByAny`` may skip matching ones too), and **die** (strict
        stage miss — the pointer-move sibling was already branched at take
        time, so nothing is lost).  Optional stages forward the event to the
        following stage when they have taken nothing yet.  NEGATED stages
        (``notNext``/``notFollowedBy``) invert: a condition match KILLS the
        partial; strict negation is satisfied by one clean event (which then
        feeds the following stage), relaxed negation watches until the
        following stage matches.  Greedy loops consume events the advanced
        sibling would otherwise take; ``until`` closes a loop without taking
        the closing event."""
        if ts <= self.skip_until_ts:
            return []
        n_stages = len(self.stages)
        matches: List[Tuple[Tuple[int, int], ...]] = []
        new_partials: List[_Partial] = []
        seen = set()

        def add(pm: _Partial):
            if pm.stage_i >= n_stages:
                matches.append(pm.events)
                return
            key = (pm.stage_i, pm.count, pm.events, pm.greedy_from)
            if key not in seen:
                seen.add(key)
                new_partials.append(pm)

        def take(pm: _Partial, i: int):
            st = self.stages[i]
            first = pm.first_ts if pm.first_ts != LONG_MIN else ts
            taken = pm.events + ((i, event_id),)
            c = pm.count + 1
            if st.times_max is None or c < st.times_max:
                add(_Partial(i, c, taken, first))       # stay in looping stage
            if c >= st.times_min:
                add(_Partial(i + 1, 0, taken, first,    # stage satisfied
                             i if st.greedy else -1))

        def feed(pm: _Partial, i: int) -> bool:
            """Match the event against stage i (skipping through optionals)."""
            st = self.stages[i]
            if st.negated:
                return False  # negated stages are driven by the main loop
            if stage_bits[i]:
                if i == pm.stage_i and until_bits is not None \
                        and until_bits[i]:
                    return False  # until: the loop is closed to this event
                cnt = pm.count if i == pm.stage_i else 0
                take(_Partial(i, cnt, pm.events, pm.first_ts,
                              pm.greedy_from), i)
                return True
            took_nothing = pm.count == 0 or i != pm.stage_i
            if st.optional and took_nothing and i + 1 < n_stages:
                return feed(pm, i + 1)
            return False

        for pm in self.partials:
            if self._expired(pm, ts):
                continue  # within window exceeded: prune
            if pm.greedy_from >= 0 and stage_bits[pm.greedy_from] \
                    and not (until_bits is not None
                             and until_bits[pm.greedy_from]):
                # the event extends the greedy loop this partial advanced
                # out of: the loop sibling consumes it and THIS branch is
                # non-maximal — it dies (greedy suppresses the ignore edge).
                # EXCEPT when until() closes the loop on this very event:
                # the loop cannot consume it, so this branch lives on.
                continue
            i = pm.stage_i
            st = self.stages[i]
            if st.negated:
                if stage_bits[i]:
                    continue        # forbidden event arrived: partial dies
                if st.contiguity == "strict":
                    # notNext satisfied by this one clean event; the SAME
                    # event then feeds the following stage
                    adv = _Partial(i + 1, 0, pm.events,
                                   pm.first_ts if pm.first_ts != LONG_MIN
                                   else ts)
                    if i + 1 >= n_stages:
                        add(adv)    # notNext at the end: match completes
                        continue
                    matched = feed(adv, i + 1)
                    nxt = self.stages[i + 1]
                    if matched:
                        if nxt.contiguity == "relaxed_any":
                            add(adv)
                    elif nxt.contiguity in ("relaxed", "relaxed_any"):
                        add(adv)
                    # strict next-stage miss: partial dies
                else:
                    # notFollowedBy: watch for the forbidden event while
                    # offering each event to the FOLLOWING stage; once that
                    # stage matches, the watcher retires (first-match
                    # semantics — staying would turn a plain followedBy
                    # into followedByAny)
                    matched = (feed(pm, i + 1) if i + 1 < n_stages
                               else False)
                    nxt = (self.stages[i + 1] if i + 1 < n_stages else None)
                    if matched:
                        if nxt is not None and nxt.contiguity == "relaxed_any":
                            add(pm)
                    elif nxt is None or nxt.contiguity != "strict":
                        add(pm)     # keep watching (relaxed)
                continue
            # until on a looping stage: the closing event ends the loop
            # permanently — the advanced sibling (created at the last take)
            # carries on; this stay-partial dies without taking
            if until_bits is not None and until_bits[i] and pm.count > 0:
                continue
            matched = feed(pm, i)
            if i == 0 and pm.count == 0:
                add(pm)                 # the start state always persists
            elif matched:
                if st.contiguity == "relaxed_any":
                    add(pm)             # followedByAny: may ignore a match
            elif st.contiguity in ("relaxed", "relaxed_any"):
                add(pm)                 # skip the non-matching event
            # else: strict miss -> partial dies

        if not any(p.stage_i == 0 and p.count == 0 for p in new_partials):
            new_partials.append(_Partial(0, 0, (), LONG_MIN))
        self.partials = new_partials

        if matches and self.pattern.skip_strategy == \
                AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT:
            self.skip_until_ts = ts
            self.partials = [_Partial(0, 0, (), LONG_MIN)]
        return matches

    def harvest_expired_negations(self, now: int
                                  ) -> List[Tuple[Tuple[Tuple[int, int], ...],
                                                  int]]:
        """A pattern ENDING in ``notFollowedBy`` completes when its
        ``within`` window closes without the forbidden event (the reference
        only allows a trailing notFollowedBy under ``within``).  Returns
        ``(events, completion_ts)`` pairs — the match's event time is the
        WINDOW CLOSE (first_ts + within), not the draining watermark."""
        w = self.pattern.within_ms
        if w is None or not self._trailing_negation:
            return []
        n = len(self.stages)
        out: List[Tuple[Tuple[Tuple[int, int], ...], int]] = []
        keep: List[_Partial] = []
        for pm in self.partials:
            if (pm.stage_i == n - 1 and pm.first_ts != LONG_MIN
                    and now - pm.first_ts > w):
                out.append((pm.events, pm.first_ts + w))
                continue
            keep.append(pm)
        self.partials = keep
        return out


class CepOperator(StreamOperator):
    """Keyed CEP: buffer events to watermark, run per-key NFAs, emit matches.

    ``select_fn(match: Dict[stage_name, List[row_dict]]) -> row_dict``
    (``PatternSelectFunction`` analog).
    """

    def __init__(self, pattern: Pattern, key_column: str,
                 select_fn: Callable[[Dict[str, List[dict]]], dict],
                 name: str = "cep",
                 defer_conditions: bool = False,
                 prev_columns: Optional[List[str]] = None,
                 leftmost_order_column: Optional[str] = None):
        last = pattern.stages[-1]
        if last.negated and last.contiguity != "strict" \
                and pattern.within_ms is None:
            # the reference's rule: NotFollowedBy can't end a pattern
            # without a within window (the match could never complete)
            raise ValueError("notFollowedBy cannot be the last pattern "
                             "stage without within()")
        self.pattern = pattern
        self.key_column = key_column
        self.select_fn = select_fn
        self.name = name
        #: evaluate conditions at DRAIN time, per key over event-time-sorted
        #: rows, instead of at arrival — required when conditions reference
        #: order-dependent derived columns (MATCH_RECOGNIZE ``PREV(col)``:
        #: ``__prev_<col>`` = the previous row of the same key in rowtime
        #: order, which arrival order cannot provide)
        self.defer_conditions = defer_conditions or bool(prev_columns)
        self.prev_columns = list(prev_columns or [])
        #: MATCH_RECOGNIZE determinism: when several branches complete on
        #: the same event under SKIP PAST LAST ROW, SQL row-pattern
        #: matching emits only the match attempt with the EARLIEST start
        #: row (``SqlMatchRecognize`` leftmost semantics); CEP emits all.
        #: Names the rowtime column used to order starts.
        self.leftmost_order_column = leftmost_order_column
        self._nfas: Dict[Any, NFA] = {}
        #: per key: list of (ts, event_id, stage_bits, until_bits|None, row)
        self._buffers: Dict[Any, List] = {}
        #: per key: last drained row (PREV continuity across drains)
        self._last_row: Dict[Any, dict] = {}
        self._next_event_id = 0
        self.watermark = LONG_MIN

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        cols = batch.columns
        if self.defer_conditions:
            bits = ubits = None
        else:
            # vectorized: all stage (and until) conditions over the batch
            bits = np.stack([s.matches(cols) for s in self.pattern.stages],
                            axis=1)
            ubits = (np.stack([s.until_matches(cols)
                               for s in self.pattern.stages], axis=1)
                     if any(s.until is not None for s in self.pattern.stages)
                     else None)
        keys = np.asarray(cols[self.key_column])
        ts = (np.asarray(batch.timestamps, np.int64)
              if batch.timestamps is not None
              else np.arange(len(batch), dtype=np.int64) + self._next_event_id)
        rows = batch.to_rows()
        for i in range(len(batch)):
            k = keys[i].item() if isinstance(keys[i], np.generic) else keys[i]
            eid = self._next_event_id
            self._next_event_id += 1
            self._buffers.setdefault(k, []).append(
                (int(ts[i]), eid, None if bits is None else bits[i],
                 None if ubits is None else ubits[i], rows[i]))
        if batch.timestamps is None:
            # processing-time style: no watermarks will come, match eagerly
            return self._drain(2 ** 62)
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        self.watermark = max(self.watermark, watermark.timestamp)
        return self._drain(self.watermark)

    def end_input(self) -> List[StreamElement]:
        return self._drain(2 ** 62)

    def _drain(self, up_to_ts: int) -> List[StreamElement]:
        out_rows: List[dict] = []
        out_ts: List[int] = []

        def emit(nfa, match, ts):
            named: Dict[str, List[dict]] = {}
            for stage_i, ev_id in match:
                named.setdefault(self.pattern.stages[stage_i].name,
                                 []).append(nfa._rows[ev_id])
            res = self.select_fn(named)
            if res is not None:
                out_rows.append(res)
                out_ts.append(ts)

        for k, buf in self._buffers.items():
            ready = [e for e in buf if e[0] <= up_to_ts]
            if not ready:
                continue
            self._buffers[k] = [e for e in buf if e[0] > up_to_ts]
            ready.sort(key=lambda e: (e[0], e[1]))
            if self.defer_conditions:
                ready = self._evaluate_deferred(k, ready)
            nfa = self._nfas.get(k)
            if nfa is None:
                nfa = self._nfas[k] = NFA(self.pattern)
            for ts, eid, bits, ubits, row in ready:
                nfa._rows[eid] = row
            for ts, eid, bits, ubits, row in ready:
                # a trailing notFollowedBy completes by TIME, which may
                # happen between events (the within window closing)
                for match, cts in nfa.harvest_expired_negations(ts):
                    emit(nfa, match, cts)
                ms = nfa.advance(eid, ts, bits, ubits)
                if len(ms) > 1 and self.leftmost_order_column is not None \
                        and self.pattern.skip_strategy == \
                        AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT:
                    oc = self.leftmost_order_column
                    ms = [min(ms, key=lambda m: (
                        nfa._rows[m[0][1]].get(oc), m[0][1]))]
                for match in ms:
                    emit(nfa, match, ts)
        # time-driven completions for EVERY key — including quiet ones whose
        # within window the watermark just closed
        for k, nfa in self._nfas.items():
            for match, cts in nfa.harvest_expired_negations(up_to_ts):
                emit(nfa, match, cts)
            # SharedBuffer-style pruning: rows only live as long as a partial
            # match references them — otherwise host memory (and every
            # checkpoint) grows with total events processed
            referenced = {ev_id for pm in nfa.partials
                          for _stage, ev_id in pm.events}
            if len(nfa._rows) > len(referenced):
                nfa._rows = {e: r for e, r in nfa._rows.items()
                             if e in referenced}
        if not out_rows:
            return []
        cols = {c: np.asarray([r[c] for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols, timestamps=np.asarray(out_ts, np.int64))]

    def _evaluate_deferred(self, k, ready):
        """Drain-time condition evaluation over the key's event-time-sorted
        rows: inject ``__prev_<col>`` columns (the previous row's values in
        ROWTIME order, seeded from the last drained row of this key), then
        run every stage condition vectorized over the chunk."""
        rows_ = [e[4] for e in ready]
        cols = {c: np.asarray([r.get(c) for r in rows_])
                for c in rows_[0]}
        prev = self._last_row.get(k)
        for c in self.prev_columns:
            vals = []
            p = prev
            for r in rows_:
                vals.append(p.get(c) if p is not None else None)
                p = r
            arr = np.asarray(vals, object)
            try:
                # numeric prevs: None -> NaN so ordering comparisons are
                # well-defined (and False) on the partition's first row
                arr = arr.astype(np.float64)
            except (TypeError, ValueError):
                pass
            cols["__prev_" + c] = arr
        self._last_row[k] = rows_[-1]
        bits = np.stack([s.matches(cols) for s in self.pattern.stages],
                        axis=1)
        ubits = (np.stack([s.until_matches(cols)
                           for s in self.pattern.stages], axis=1)
                 if any(s.until is not None for s in self.pattern.stages)
                 else None)
        return [(ts, eid, bits[i], None if ubits is None else ubits[i], row)
                for i, (ts, eid, _b, _u, row) in enumerate(ready)]

    # -- checkpointing -------------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "buffers": {k: list(v) for k, v in self._buffers.items()},
            "nfas": {k: (n.partials, n.skip_until_ts,
                         getattr(n, "_rows", {}))
                     for k, n in self._nfas.items()},
            "last_rows": dict(self._last_row),
            "next_event_id": self._next_event_id,
            "watermark": self.watermark,
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._buffers = {k: list(v) for k, v in snap["buffers"].items()}
        self._nfas = {}
        for k, (partials, skip_ts, rows) in snap["nfas"].items():
            nfa = NFA(self.pattern)
            nfa.partials = list(partials)
            nfa.skip_until_ts = skip_ts
            nfa._rows = dict(rows)
            self._nfas[k] = nfa
        self._last_row = dict(snap.get("last_rows", {}))
        self._next_event_id = snap["next_event_id"]
        self.watermark = snap["watermark"]


class CEP:
    """Entry point (``CEP.java``): ``CEP.pattern(keyed_stream, pattern)``."""

    @staticmethod
    def pattern(keyed_stream, pattern: Pattern) -> "PatternStream":
        return PatternStream(keyed_stream, pattern)


class PatternStream:
    def __init__(self, keyed_stream, pattern: Pattern):
        self.keyed = keyed_stream
        self.pattern = pattern

    def select(self, fn: Callable[[Dict[str, List[dict]]], dict],
               name: str = "cep-select"):
        from flink_tpu.datastream.api import DataStream
        key_col = self.keyed.key_column
        pat = self.pattern
        t = self.keyed._then(
            name, lambda: CepOperator(pat, key_col, fn, name))
        return DataStream(self.keyed.env, t)
