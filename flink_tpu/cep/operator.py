"""CEP NFA operator over keyed streams.

Analog of ``flink-libraries/flink-cep``'s ``CepOperator`` + ``nfa/NFA.java:86``
+ ``sharedbuffer/SharedBuffer.java:62``, re-shaped for the batched runtime:

- **Vectorized condition evaluation** (the device-friendly half): every
  stage's predicate runs ONCE per batch over the whole column set, producing
  a ``[B, num_stages]`` bool matrix — the per-event work the reference does
  in ``ConditionContext`` collapses into a handful of vector ops.
- **Batched NFA transitions** (the formerly data-dependent half): for
  eligible patterns (``cep/vectorized.py`` classifier) the per-key partial
  matches of ALL keys advance together as fixed-shape arrays — one batched
  state-transition dispatch per event step per drain — bit-identical to the
  interpreted matcher below.  Ineligible shapes (``followedByAny``,
  ``greedy()``, drain-time/``PREV`` conditions) run the interpreted
  per-key NFA: per key, events are buffered until the watermark passes
  them (the reference buffers in ``elementQueueState`` and processes on
  watermark, ``CepOperator.onEventTime``), then sorted by timestamp and
  fed through the NFA with branching partial matches.

Event rows are buffered **columnar** (``_RowStore``): ``process_batch``
never materializes per-row dicts up front — rows materialize lazily, only
for events referenced by live partials or completed matches at emit time.

Supported semantics: strict (``next``) / relaxed (``followedBy``) /
non-deterministic relaxed (``followedByAny``) contiguity, NOT-patterns
(``notNext``/``notFollowedBy``, incl. trailing ``notFollowedBy`` completing
on ``within``-window close), ``times``/``oneOrMore``/``optional``
quantifiers with ``greedy()`` and ``until()``, ``within``, NO_SKIP and
SKIP_PAST_LAST_EVENT after-match strategies (``NFA.java:86``,
``Quantifier.java``).
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern
from flink_tpu.observability import tracing
from flink_tpu.operators.base import StreamOperator


@dataclass(frozen=True)
class _Partial:
    """One partial match: position in the pattern + taken events.

    events: tuple of (stage_index, event_id); count = matches of the
    CURRENT stage taken so far (for quantifiers); greedy_from: index of the
    greedy looping stage this partial advanced out of (-1 = none) — while
    events still match that loop, the loop sibling consumes them and this
    partial must ignore them (``Quantifier.greedy`` semantics)."""

    stage_i: int
    count: int
    events: Tuple[Tuple[int, int], ...]
    first_ts: int
    greedy_from: int = -1


class NFA:
    """Pattern matcher for one key (``NFA.java:86`` analog)."""

    def __init__(self, pattern: Pattern):
        self.pattern = pattern
        self.stages = pattern.stages
        last = pattern.stages[-1]
        #: fast-path flag: only trailing notFollowedBy patterns need the
        #: per-event window-close harvest
        self._trailing_negation = last.negated and last.contiguity != "strict"
        self.partials: List[_Partial] = [_Partial(0, 0, (), LONG_MIN)]
        #: SKIP_PAST_LAST_EVENT barrier: events at/before this ts cannot
        #: extend or start matches
        self.skip_until_ts: int = LONG_MIN
        #: legacy event-id -> row map: rows now resolve through the
        #: operator's columnar ``_RowStore``; stays (empty) for readers of
        #: the old layout
        self._rows: Dict[int, dict] = {}

    def _expired(self, pm: _Partial, ts: int) -> bool:
        w = self.pattern.within_ms
        return (w is not None and pm.first_ts != LONG_MIN
                and ts - pm.first_ts > w)

    def advance(self, event_id: int, ts: int, stage_bits: np.ndarray,
                until_bits: Optional[np.ndarray] = None,
                ) -> List[Tuple[Tuple[int, int], ...]]:
        """Feed one event; returns completed matches (event lists).

        Per partial the NFA edges are: **take** (event matches current
        stage — branch into 'stay in looping stage' and, once the
        quantifier's minimum is met, 'pointer moves to next stage'),
        **ignore** (relaxed stages skip non-matching events; ``relaxed_any``
        = ``followedByAny`` may skip matching ones too), and **die** (strict
        stage miss — the pointer-move sibling was already branched at take
        time, so nothing is lost).  Optional stages forward the event to the
        following stage when they have taken nothing yet.  NEGATED stages
        (``notNext``/``notFollowedBy``) invert: a condition match KILLS the
        partial; strict negation is satisfied by one clean event (which then
        feeds the following stage), relaxed negation watches until the
        following stage matches.  Greedy loops consume events the advanced
        sibling would otherwise take; ``until`` closes a loop without taking
        the closing event."""
        if ts <= self.skip_until_ts:
            return []
        n_stages = len(self.stages)
        matches: List[Tuple[Tuple[int, int], ...]] = []
        new_partials: List[_Partial] = []
        seen = set()

        def add(pm: _Partial):
            if pm.stage_i >= n_stages:
                matches.append(pm.events)
                return
            key = (pm.stage_i, pm.count, pm.events, pm.greedy_from)
            if key not in seen:
                seen.add(key)
                new_partials.append(pm)

        def take(pm: _Partial, i: int):
            st = self.stages[i]
            first = pm.first_ts if pm.first_ts != LONG_MIN else ts
            taken = pm.events + ((i, event_id),)
            c = pm.count + 1
            if st.times_max is None or c < st.times_max:
                add(_Partial(i, c, taken, first))       # stay in looping stage
            if c >= st.times_min:
                add(_Partial(i + 1, 0, taken, first,    # stage satisfied
                             i if st.greedy else -1))

        def feed(pm: _Partial, i: int) -> bool:
            """Match the event against stage i (skipping through optionals)."""
            st = self.stages[i]
            if st.negated:
                return False  # negated stages are driven by the main loop
            if stage_bits[i]:
                if i == pm.stage_i and until_bits is not None \
                        and until_bits[i]:
                    return False  # until: the loop is closed to this event
                cnt = pm.count if i == pm.stage_i else 0
                take(_Partial(i, cnt, pm.events, pm.first_ts,
                              pm.greedy_from), i)
                return True
            took_nothing = pm.count == 0 or i != pm.stage_i
            if st.optional and took_nothing and i + 1 < n_stages:
                return feed(pm, i + 1)
            return False

        for pm in self.partials:
            if self._expired(pm, ts):
                continue  # within window exceeded: prune
            if pm.greedy_from >= 0 and stage_bits[pm.greedy_from] \
                    and not (until_bits is not None
                             and until_bits[pm.greedy_from]):
                # the event extends the greedy loop this partial advanced
                # out of: the loop sibling consumes it and THIS branch is
                # non-maximal — it dies (greedy suppresses the ignore edge).
                # EXCEPT when until() closes the loop on this very event:
                # the loop cannot consume it, so this branch lives on.
                continue
            i = pm.stage_i
            st = self.stages[i]
            if st.negated:
                if stage_bits[i]:
                    continue        # forbidden event arrived: partial dies
                if st.contiguity == "strict":
                    # notNext satisfied by this one clean event; the SAME
                    # event then feeds the following stage
                    adv = _Partial(i + 1, 0, pm.events,
                                   pm.first_ts if pm.first_ts != LONG_MIN
                                   else ts)
                    if i + 1 >= n_stages:
                        add(adv)    # notNext at the end: match completes
                        continue
                    matched = feed(adv, i + 1)
                    nxt = self.stages[i + 1]
                    if matched:
                        if nxt.contiguity == "relaxed_any":
                            add(adv)
                    elif nxt.contiguity in ("relaxed", "relaxed_any"):
                        add(adv)
                    # strict next-stage miss: partial dies
                else:
                    # notFollowedBy: watch for the forbidden event while
                    # offering each event to the FOLLOWING stage; once that
                    # stage matches, the watcher retires (first-match
                    # semantics — staying would turn a plain followedBy
                    # into followedByAny)
                    matched = (feed(pm, i + 1) if i + 1 < n_stages
                               else False)
                    nxt = (self.stages[i + 1] if i + 1 < n_stages else None)
                    if matched:
                        if nxt is not None and nxt.contiguity == "relaxed_any":
                            add(pm)
                    elif nxt is None or nxt.contiguity != "strict":
                        add(pm)     # keep watching (relaxed)
                continue
            # until on a looping stage: the closing event ends the loop
            # permanently — the advanced sibling (created at the last take)
            # carries on; this stay-partial dies without taking
            if until_bits is not None and until_bits[i] and pm.count > 0:
                continue
            matched = feed(pm, i)
            if i == 0 and pm.count == 0:
                add(pm)                 # the start state always persists
            elif matched:
                if st.contiguity == "relaxed_any":
                    add(pm)             # followedByAny: may ignore a match
            elif st.contiguity in ("relaxed", "relaxed_any"):
                add(pm)                 # skip the non-matching event
            # else: strict miss -> partial dies

        if not any(p.stage_i == 0 and p.count == 0 for p in new_partials):
            new_partials.append(_Partial(0, 0, (), LONG_MIN))
        self.partials = new_partials

        if matches and self.pattern.skip_strategy == \
                AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT:
            self.skip_until_ts = ts
            self.partials = [_Partial(0, 0, (), LONG_MIN)]
        return matches

    def harvest_expired_negations(self, now: int
                                  ) -> List[Tuple[Tuple[Tuple[int, int], ...],
                                                  int]]:
        """A pattern ENDING in ``notFollowedBy`` completes when its
        ``within`` window closes without the forbidden event (the reference
        only allows a trailing notFollowedBy under ``within``).  Returns
        ``(events, completion_ts)`` pairs — the match's event time is the
        WINDOW CLOSE (first_ts + within), not the draining watermark."""
        w = self.pattern.within_ms
        if w is None or not self._trailing_negation:
            return []
        n = len(self.stages)
        out: List[Tuple[Tuple[Tuple[int, int], ...], int]] = []
        keep: List[_Partial] = []
        for pm in self.partials:
            if (pm.stage_i == n - 1 and pm.first_ts != LONG_MIN
                    and now - pm.first_ts > w):
                out.append((pm.events, pm.first_ts + w))
                continue
            keep.append(pm)
        self.partials = keep
        return out


class _RowStore:
    """Columnar event-row store (the lazy half of the ``SharedBuffer``
    analog): ``process_batch`` registers each batch's column arrays once;
    row dicts materialize on demand — only for events referenced by live
    partials or completed matches at emit time.  ``prune`` drops whole
    batches once no referenced event id falls in their range."""

    def __init__(self):
        #: parallel sorted lists: event-id base per batch + (n, columns)
        self._bases: List[int] = []
        self._batches: List[Tuple[int, Dict[str, np.ndarray]]] = []
        #: restored-snapshot rows (already materialized dicts)
        self._extra: Dict[int, dict] = {}

    def add_batch(self, cols: Dict[str, Any], base: int, n: int) -> None:
        if n == 0:
            return
        self._bases.append(base)          # bases are monotone (event ids)
        self._batches.append((n, {k: np.asarray(v)
                                  for k, v in cols.items()}))

    def put_row(self, eid: int, row: dict) -> None:
        self._extra[eid] = row

    def row(self, eid: int) -> dict:
        r = self._extra.get(eid)
        if r is not None:
            return r
        i = bisect.bisect_right(self._bases, eid) - 1
        if i < 0:
            raise KeyError(f"event {eid} not in row store")
        base = self._bases[i]
        n, arrs = self._batches[i]
        if eid >= base + n:
            raise KeyError(f"event {eid} not in row store")
        j = eid - base

        def cell(a):
            x = a[j]
            return x.item() if isinstance(x, np.generic) else x

        return {k: cell(a) for k, a in arrs.items()}

    def prune(self, referenced) -> None:
        """Drop batches with no referenced event and stale restored rows.
        ``referenced``: iterable/array of still-live event ids."""
        ref = np.unique(np.asarray(list(referenced)
                                   if not isinstance(referenced, np.ndarray)
                                   else referenced, np.int64))
        keep_b, keep_bt = [], []
        for base, (n, arrs) in zip(self._bases, self._batches):
            lo = np.searchsorted(ref, base)
            if lo < ref.size and ref[lo] < base + n:
                keep_b.append(base)
                keep_bt.append((n, arrs))
        self._bases, self._batches = keep_b, keep_bt
        if self._extra:
            refset = set(ref.tolist())
            self._extra = {e: r for e, r in self._extra.items()
                           if e in refset}

    def stats(self) -> Dict[str, int]:
        return {"batches": len(self._batches),
                "restored_rows": len(self._extra)}


class _VecState:
    """Array-resident NFA state for ALL keys (the vectorized engine's
    half of ``CepOperator``): ``[K, M]`` planes of (stage, count,
    first_ts, event-ring length, rolling event hash), a ``[K, M, E]``
    bounded event-pointer ring, per-key live count + skip barrier, and the
    key <-> slot mapping.  M/E are sticky pow2 high-waters."""

    def __init__(self, tab, kernel: str, m_cap: int = 4, e_cap: int = 4):
        self.tab = tab
        self.kernel = kernel
        self.m_cap = m_cap
        self.e_cap = e_cap
        self.index = None                  # key index, built on first batch
        self.n_slots = 0
        k0 = 0
        self.st = np.zeros((k0, m_cap), np.int32)
        self.cnt = np.zeros((k0, m_cap), np.int32)
        self.fst = np.full((k0, m_cap), LONG_MIN, np.int64)
        self.eln = np.zeros((k0, m_cap), np.int32)
        self.ev = np.zeros((k0, m_cap, e_cap), np.int64)
        self.evh = np.zeros((k0, m_cap), np.int32)
        self.nlv = np.zeros(k0, np.int32)
        self.skip = np.full(k0, LONG_MIN, np.int64)
        #: slots in first-DRAIN order (the interpreted ``_nfas`` creation
        #: order — final negation harvests emit in this order)
        self.drained_order: List[int] = []
        self.drained = np.zeros(k0, bool)
        self.rank = np.full(k0, -1, np.int32)
        #: pending (buffered, not-yet-drained) events as columnar pieces:
        #: dicts of slot/ts/eid int64 + bits/ubits [n, S] bool
        self.pending: List[Dict[str, np.ndarray]] = []

    # -- key slots -----------------------------------------------------------
    def map_keys(self, keys: np.ndarray) -> np.ndarray:
        from flink_tpu.state.keyindex import make_key_index

        if self.index is None:
            self.index = make_key_index(keys[0])
        slots = np.asarray(self.index.lookup_or_insert(keys), np.int64)
        self.ensure_slots(int(self.index.num_keys))
        return slots

    def key_of(self, slot: int):
        k = self.index.reverse_keys()[slot]
        return k.item() if isinstance(k, np.generic) else k

    def ensure_slots(self, n: int) -> None:
        if n <= self.n_slots:
            return
        cap = max(64, self.st.shape[0])
        while cap < n:
            cap *= 2
        if cap > self.st.shape[0]:
            grow = cap - self.st.shape[0]

            def w(a, fill, dtype):
                return np.concatenate(
                    [a, np.full((grow,) + a.shape[1:], fill, dtype)], axis=0)

            self.st = w(self.st, 0, np.int32)
            self.cnt = w(self.cnt, 0, np.int32)
            self.fst = w(self.fst, LONG_MIN, np.int64)
            self.eln = w(self.eln, 0, np.int32)
            self.ev = w(self.ev, 0, np.int64)
            self.evh = w(self.evh, 0, np.int32)
            self.nlv = np.concatenate(
                [self.nlv, np.zeros(grow, np.int32)])
            self.skip = np.concatenate(
                [self.skip, np.full(grow, LONG_MIN, np.int64)])
            self.drained = np.concatenate(
                [self.drained, np.zeros(grow, bool)])
            self.rank = np.concatenate(
                [self.rank, np.full(grow, -1, np.int32)])
        # fresh slots carry one pristine start partial
        self.nlv[self.n_slots:n] = 1
        self.n_slots = n

    def grow_caps(self, m_cap: int, e_cap: int) -> None:
        if m_cap > self.m_cap:
            pad = m_cap - self.m_cap
            K = self.st.shape[0]

            def w(a, fill):
                return np.concatenate(
                    [a, np.full((K, pad) + a.shape[2:], fill, a.dtype)],
                    axis=1)

            self.st, self.cnt = w(self.st, 0), w(self.cnt, 0)
            self.fst = w(self.fst, LONG_MIN)
            self.eln, self.evh = w(self.eln, 0), w(self.evh, 0)
            self.ev = w(self.ev, 0)
            self.m_cap = m_cap
        if e_cap > self.e_cap:
            K, M = self.ev.shape[:2]
            wide = np.zeros((K, M, e_cap), np.int64)
            wide[:, :, :self.e_cap] = self.ev
            self.ev = wide
            self.e_cap = e_cap

    # -- drain helpers -------------------------------------------------------
    def consolidate(self) -> Optional[Dict[str, np.ndarray]]:
        if not self.pending:
            return None
        if len(self.pending) == 1:
            out = self.pending[0]
        else:
            out = {k: np.concatenate([p[k] for p in self.pending])
                   for k in self.pending[0]}
        self.pending = []
        return out

    def gather(self, slots: np.ndarray, m_cap: int, e_cap: int):
        """Copy the rows for ``slots`` into a compact block at the
        requested caps (the transactional unit the kernel advances).
        Narrower-than-storage caps are fine when the rows fit — callers
        size them from the rows' own nlv/eln high-water, so only dead
        (pristine) columns are dropped."""
        kc = slots.size
        wm = min(m_cap, self.st.shape[1])

        def g2(a, fill, dtype):
            out = np.full((kc, m_cap), fill, dtype)
            out[:, :wm] = a[slots][:, :wm]
            return out

        we = min(e_cap, self.ev.shape[2])
        ev = np.zeros((kc, m_cap, e_cap), np.int64)
        ev[:, :wm, :we] = self.ev[slots][:, :wm, :we]
        return (g2(self.st, 0, np.int32), g2(self.cnt, 0, np.int32),
                g2(self.fst, LONG_MIN, np.int64), g2(self.eln, 0, np.int32),
                ev, g2(self.evh, 0, np.int32),
                self.nlv[slots].copy(), self.skip[slots].copy())

    def adopt(self, chunks, m_cap: int, e_cap: int) -> None:
        """Commit the advanced blocks (after the whole drain's compute
        succeeded — a quarantined dispatch leaves the state untouched)."""
        self.grow_caps(m_cap, e_cap)
        for slots, block in chunks:
            if (block[4].shape[1] < self.m_cap
                    or block[4].shape[2] < self.e_cap):
                block = _grow_block(block, self.m_cap, self.e_cap)
            st, cnt, fst, eln, ev, evh, nlv, skip = block
            self.st[slots] = st
            self.cnt[slots] = cnt
            self.fst[slots] = fst
            self.eln[slots] = eln
            self.ev[slots] = ev
            self.evh[slots] = evh
            self.nlv[slots] = nlv
            self.skip[slots] = skip

    def mark_drained(self, slots: np.ndarray) -> None:
        newly = slots[~self.drained[slots]]
        if newly.size:
            self.drained[newly] = True
            base = len(self.drained_order)
            self.rank[newly] = base + np.arange(newly.size, dtype=np.int32)
            self.drained_order.extend(int(s) for s in newly)

    def referenced_event_ids(self) -> np.ndarray:
        """Event ids referenced by any live partial (for row pruning)."""
        from flink_tpu.cep.vectorized import _PACK_MASK

        rows = np.flatnonzero((self.nlv > 0)
                              & (self.eln.max(axis=1, initial=0) > 0))
        if rows.size == 0:
            return np.empty(0, np.int64)
        ev = self.ev[rows]
        eln = self.eln[rows]
        mask = (np.arange(ev.shape[2])[None, None, :]
                < eln[:, :, None])
        return np.unique(ev[mask] & np.int64(_PACK_MASK))

    def total_partials(self) -> int:
        if self.n_slots == 0:
            return 0
        return int(self.nlv[:self.n_slots][
            self.drained[:self.n_slots]].sum())


class CepOperator(StreamOperator):
    """Keyed CEP: buffer events to watermark, run per-key NFAs, emit matches.

    ``select_fn(match: Dict[stage_name, List[row_dict]]) -> row_dict``
    (``PatternSelectFunction`` analog).

    ``vectorized``: ``"auto"`` (default — eligible patterns use the batched
    array kernel when the process-wide calibration says it wins on this
    backend, like ``--device-probe``), ``"on"`` (force; raises on
    ineligible patterns), ``"off"`` (interpreted NFA).  Both engines are
    bit-identical on eligible patterns — same matches, same order, same
    snapshots.
    """

    def __init__(self, pattern: Pattern, key_column: str,
                 select_fn: Callable[[Dict[str, List[dict]]], dict],
                 name: str = "cep",
                 defer_conditions: bool = False,
                 prev_columns: Optional[List[str]] = None,
                 leftmost_order_column: Optional[str] = None,
                 vectorized: str = "auto"):
        last = pattern.stages[-1]
        if last.negated and last.contiguity != "strict" \
                and pattern.within_ms is None:
            # the reference's rule: NotFollowedBy can't end a pattern
            # without a within window (the match could never complete)
            raise ValueError("notFollowedBy cannot be the last pattern "
                             "stage without within()")
        if vectorized not in ("auto", "on", "off"):
            raise ValueError(f"vectorized must be auto|on|off, "
                             f"got {vectorized!r}")
        self.pattern = pattern
        self.key_column = key_column
        self.select_fn = select_fn
        self.name = name
        #: evaluate conditions at DRAIN time, per key over event-time-sorted
        #: rows, instead of at arrival — required when conditions reference
        #: order-dependent derived columns (MATCH_RECOGNIZE ``PREV(col)``:
        #: ``__prev_<col>`` = the previous row of the same key in rowtime
        #: order, which arrival order cannot provide)
        self.defer_conditions = defer_conditions or bool(prev_columns)
        self.prev_columns = list(prev_columns or [])
        #: MATCH_RECOGNIZE determinism: when several branches complete on
        #: the same event under SKIP PAST LAST ROW, SQL row-pattern
        #: matching emits only the match attempt with the EARLIEST start
        #: row (``SqlMatchRecognize`` leftmost semantics); CEP emits all.
        #: Names the rowtime column used to order starts.
        self.leftmost_order_column = leftmost_order_column
        self.vectorized = vectorized
        self._nfas: Dict[Any, NFA] = {}
        #: per key: list of (ts, event_id, stage_bits, until_bits|None) —
        #: rows live in the columnar ``_RowStore``, not here
        self._buffers: Dict[Any, List] = {}
        #: per key: last drained row (PREV continuity across drains)
        self._last_row: Dict[Any, dict] = {}
        self._next_event_id = 0
        self.watermark = LONG_MIN
        self._rowstore = _RowStore()
        self._engine: Optional[str] = None
        self._engine_reasons: List[str] = []
        self._vec: Optional[_VecState] = None
        self._stats = {"matches": 0, "partials_high_water": 0,
                       "vectorized_drains": 0, "interpreted_drains": 0,
                       "degraded": 0}
        self._partials_total = 0          # interpreted engine's live count
        if vectorized == "on":
            ok, reasons = self._classify()
            if not ok:
                raise ValueError(
                    "vectorized='on' but the pattern is not eligible for "
                    "the batched kernel: " + "; ".join(reasons))

    # -- engine resolution ---------------------------------------------------
    def _classify(self) -> Tuple[bool, List[str]]:
        from flink_tpu.cep.vectorized import classify_pattern

        ok, reasons = classify_pattern(self.pattern)
        if self.defer_conditions:
            ok = False
            reasons.append("drain-time (deferred/PREV) condition evaluation")
        if self.leftmost_order_column is not None:
            ok = False
            reasons.append("leftmost-match pruning (MATCH_RECOGNIZE "
                           "SKIP PAST LAST ROW)")
        return ok, reasons

    def _resolve_engine(self) -> None:
        if self._engine is not None:
            return
        from flink_tpu.cep import vectorized as V

        ok, reasons = self._classify()
        if self.vectorized == "off":
            self._engine = "interpreted"
            self._engine_reasons = ["vectorized='off'"]
        elif self.vectorized == "on":
            if not ok:
                raise ValueError("vectorized='on' but the pattern is not "
                                 "eligible: " + "; ".join(reasons))
            self._engine = "vectorized"
        else:
            if ok and V.calibrated_vectorized_cep():
                self._engine = "vectorized"
            else:
                self._engine = "interpreted"
                if ok:
                    reasons = ["calibration picked the interpreted NFA on "
                               "this backend"]
                self._engine_reasons = reasons
        if self._engine == "vectorized":
            self._vec = _VecState(V.compile_pattern(self.pattern),
                                  V.default_kernel())

    def cep_stats(self) -> Dict[str, Any]:
        """Monitoring-grade counters: engine, matches emitted, the
        partial-match high-water mark, drain counts per engine, and
        mid-job degradations (quarantine fallbacks).  Never blocks: an
        auto-mode operator that has not processed a batch yet reports
        ``engine="unresolved"`` instead of running the calibration A/B on
        the stats path."""
        out = dict(self._stats)
        out["engine"] = self._engine or "unresolved"
        out["fallback_reasons"] = list(self._engine_reasons)
        out.update(self._rowstore.stats())
        return out

    # -- ingestion -----------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        self._resolve_engine()
        cols = batch.columns
        if self.defer_conditions:
            bits = ubits = None
        else:
            # vectorized: all stage (and until) conditions over the batch —
            # these [B, S] planes are the kernel's condition inputs
            bits = np.stack([s.matches(cols) for s in self.pattern.stages],
                            axis=1)
            ubits = (np.stack([s.until_matches(cols)
                               for s in self.pattern.stages], axis=1)
                     if any(s.until is not None for s in self.pattern.stages)
                     else None)
        ts = (np.asarray(batch.timestamps, np.int64)
              if batch.timestamps is not None
              else np.arange(len(batch), dtype=np.int64) + self._next_event_id)
        base = self._next_event_id
        self._next_event_id += len(batch)
        self._rowstore.add_batch(cols, base, len(batch))
        keys = np.asarray(cols[self.key_column])
        if self._engine == "vectorized":
            slots = self._vec.map_keys(keys)
            piece = {"slot": slots,
                     "ts": ts.astype(np.int64),
                     "eid": base + np.arange(len(batch), dtype=np.int64),
                     "bits": bits,
                     "ubits": (ubits if ubits is not None
                               else np.zeros_like(bits))}
            self._vec.pending.append(piece)
        else:
            for i in range(len(batch)):
                k = (keys[i].item() if isinstance(keys[i], np.generic)
                     else keys[i])
                self._buffers.setdefault(k, []).append(
                    (int(ts[i]), base + i,
                     None if bits is None else bits[i],
                     None if ubits is None else ubits[i]))
        if batch.timestamps is None:
            # processing-time style: no watermarks will come, match eagerly
            return self._drain(2 ** 62)
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        self.watermark = max(self.watermark, watermark.timestamp)
        return self._drain(self.watermark)

    def end_input(self) -> List[StreamElement]:
        return self._drain(2 ** 62)

    # -- shared emission helpers ---------------------------------------------
    def _row(self, eid: int) -> dict:
        return self._rowstore.row(eid)

    def _emit_match(self, events, mts: int, out_rows, out_ts) -> None:
        self._stats["matches"] += 1
        named: Dict[str, List[dict]] = {}
        for stage_i, ev_id in events:
            named.setdefault(self.pattern.stages[stage_i].name,
                             []).append(self._row(ev_id))
        res = self.select_fn(named)
        if res is not None:
            out_rows.append(res)
            out_ts.append(mts)

    def _emit_batch(self, out_rows, out_ts) -> List[StreamElement]:
        if not out_rows:
            return []
        cols = {c: np.asarray([r[c] for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols, timestamps=np.asarray(out_ts, np.int64))]

    def _prune_rows_interpreted(self) -> None:
        referenced = {ev for nfa in self._nfas.values()
                      for pm in nfa.partials for _s, ev in pm.events}
        for buf in self._buffers.values():
            referenced.update(e[1] for e in buf)
        self._rowstore.prune(np.fromiter(referenced, np.int64,
                                         count=len(referenced)))

    # -- drain dispatch ------------------------------------------------------
    def _drain(self, up_to_ts: int) -> List[StreamElement]:
        self._resolve_engine()
        if self._engine == "vectorized":
            from flink_tpu.runtime import device_health
            mon = device_health.get_monitor(create=False)
            if mon is not None and mon.quarantined:
                self._degrade_to_interpreted("device quarantined")
                return self._drain_interpreted(up_to_ts)
            try:
                with tracing.span("cep.vectorized_drain", cat="cep",
                                  up_to_ts=int(up_to_ts)):
                    return self._drain_vectorized(up_to_ts)
            except device_health.DeviceQuarantinedError:
                self._degrade_to_interpreted(
                    "vectorized drain dispatch quarantined")
                return self._drain_interpreted(up_to_ts)
        return self._drain_interpreted(up_to_ts)

    # -- interpreted drain ---------------------------------------------------
    def _drain_interpreted(self, up_to_ts: int) -> List[StreamElement]:
        out_rows: List[dict] = []
        out_ts: List[int] = []

        for k, buf in self._buffers.items():
            ready = [e for e in buf if e[0] <= up_to_ts]
            if not ready:
                continue
            self._buffers[k] = [e for e in buf if e[0] > up_to_ts]
            ready.sort(key=lambda e: (e[0], e[1]))
            if self.defer_conditions:
                ready = self._evaluate_deferred(k, ready)
            nfa = self._nfas.get(k)
            if nfa is None:
                nfa = self._nfas[k] = NFA(self.pattern)
                self._partials_total += len(nfa.partials)
            for ts, eid, bits, ubits in ready:
                # a trailing notFollowedBy completes by TIME, which may
                # happen between events (the within window closing)
                before = len(nfa.partials)
                for match, cts in nfa.harvest_expired_negations(ts):
                    self._emit_match(match, cts, out_rows, out_ts)
                ms = nfa.advance(eid, ts, bits, ubits)
                self._partials_total += len(nfa.partials) - before
                if len(ms) > 1 and self.leftmost_order_column is not None \
                        and self.pattern.skip_strategy == \
                        AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT:
                    oc = self.leftmost_order_column
                    ms = [min(ms, key=lambda m: (
                        self._row(m[0][1]).get(oc), m[0][1]))]
                for match in ms:
                    self._emit_match(match, ts, out_rows, out_ts)
        # time-driven completions for EVERY key — including quiet ones whose
        # within window the watermark just closed
        for k, nfa in self._nfas.items():
            before = len(nfa.partials)
            for match, cts in nfa.harvest_expired_negations(up_to_ts):
                self._emit_match(match, cts, out_rows, out_ts)
            self._partials_total += len(nfa.partials) - before
        # SharedBuffer-style pruning: event rows only live as long as a
        # partial match (or a buffered event) references them — otherwise
        # host memory (and every checkpoint) grows with events processed
        self._prune_rows_interpreted()
        self._stats["interpreted_drains"] += 1
        self._stats["partials_high_water"] = max(
            self._stats["partials_high_water"], self._partials_total)
        return self._emit_batch(out_rows, out_ts)

    def _evaluate_deferred(self, k, ready):
        """Drain-time condition evaluation over the key's event-time-sorted
        rows: inject ``__prev_<col>`` columns (the previous row's values in
        ROWTIME order, seeded from the last drained row of this key), then
        run every stage condition vectorized over the chunk."""
        rows_ = [self._row(e[1]) for e in ready]
        cols = {c: np.asarray([r.get(c) for r in rows_])
                for c in rows_[0]}
        prev = self._last_row.get(k)
        for c in self.prev_columns:
            vals = []
            p = prev
            for r in rows_:
                vals.append(p.get(c) if p is not None else None)
                p = r
            arr = np.asarray(vals, object)
            try:
                # numeric prevs: None -> NaN so ordering comparisons are
                # well-defined (and False) on the partition's first row
                arr = arr.astype(np.float64)
            except (TypeError, ValueError):
                pass
            cols["__prev_" + c] = arr
        self._last_row[k] = rows_[-1]
        bits = np.stack([s.matches(cols) for s in self.pattern.stages],
                        axis=1)
        ubits = (np.stack([s.until_matches(cols)
                           for s in self.pattern.stages], axis=1)
                 if any(s.until is not None for s in self.pattern.stages)
                 else None)
        return [(ts, eid, bits[i], None if ubits is None else ubits[i])
                for i, (ts, eid, _b, _u) in enumerate(ready)]

    # -- vectorized drain ----------------------------------------------------
    def _drain_vectorized(self, up_to_ts: int) -> List[StreamElement]:
        from flink_tpu.runtime import device_health

        vec = self._vec
        pend = vec.consolidate()
        sect0: List[Tuple[tuple, tuple, int]] = []
        if pend is not None:
            ready_m = pend["ts"] <= up_to_ts
            if not ready_m.all():
                keep = ~ready_m
                vec.pending = [{k: v[keep] for k, v in pend.items()}]
            if ready_m.any():
                r = {k: v[ready_m] for k, v in pend.items()}
                order = np.lexsort((r["eid"], r["ts"], r["slot"]))
                r = {k: v[order] for k, v in r.items()}
                uniq, offsets, counts = np.unique(
                    r["slot"], return_index=True, return_counts=True)
                pos = (np.arange(r["ts"].size)
                       - np.repeat(offsets, counts))
                krow = np.repeat(np.arange(uniq.size), counts)
                # regroup keys by (partial-width bucket, ASCENDING event
                # count): chunks never span width buckets, so the kernel
                # runs each chunk at the narrow width ITS rows need (one
                # hot key with many partials must not widen everyone), and
                # within a bucket it steps only the suffix of keys still
                # holding an event at step t — total work tracks
                # sum(events), not keys x T_max.  Match ORDER is
                # unaffected: every match carries its original
                # (buffer-order, step) sort key.
                nl = np.maximum(vec.nlv[uniq], 1)
                wb = np.int64(1) << (
                    np.ceil(np.log2(np.maximum(nl, 4))).astype(np.int64))
                ksort = np.lexsort((counts, wb))
                inv = np.empty_like(ksort)
                inv[ksort] = np.arange(ksort.size)
                sc = counts[ksort]
                new_off = np.zeros(ksort.size, np.int64)
                np.cumsum(sc[:-1], out=new_off[1:])
                dest = new_off[inv[krow]] + pos
                r2 = {}
                for k, v in r.items():
                    out = np.empty_like(v)
                    out[dest] = v
                    r2[k] = out
                krow2 = np.empty(krow.size, np.int64)
                krow2[dest] = inv[krow]
                pos2 = np.empty_like(pos)
                pos2[dest] = pos
                wbs = wb[ksort]
                bounds = np.flatnonzero(
                    np.concatenate([[True], wbs[1:] != wbs[:-1]]))
                bounds = np.append(bounds, wbs.size)
                # ONE guarded dispatch per drain: the whole step loop is a
                # pure function of gathered copies — a watchdog-abandoned
                # (wedged) dispatch commits nothing; the ready events go
                # back to pending so the degrade path re-drains the
                # identical stream interpreted
                try:
                    chunks, sect0, m_cap, e_cap = \
                        device_health.guarded_dispatch(
                            lambda: self._vec_compute(
                                r2, uniq[ksort], sc, pos2, krow2, ksort,
                                bounds),
                            label="cep.vectorized_drain")
                except BaseException:
                    vec.pending.append(r)
                    raise
                vec.adopt(chunks, m_cap, e_cap)
                vec.mark_drained(uniq)
        out_rows: List[dict] = []
        out_ts: List[int] = []
        sect0.sort(key=lambda m: m[0])
        for _o, events, mts in sect0:
            self._emit_match(events, mts, out_rows, out_ts)
        for events, mts in self._vec_harvest_all(up_to_ts):
            self._emit_match(events, mts, out_rows, out_ts)
        self._stats["vectorized_drains"] += 1
        self._stats["partials_high_water"] = max(
            self._stats["partials_high_water"], vec.total_partials())
        self._prune_rows_vectorized()
        return self._emit_batch(out_rows, out_ts)

    def _vec_compute(self, r, uniq, counts, pos, krow, korder, bounds):
        """The drain's pure compute: advance every ready key's partials
        through its event steps, chunked over keys.  Keys arrive sorted by
        (partial-width bucket, ascending event count); ``korder[p]`` = the
        key's original buffer-order rank, the match sort key.  Chunks stay
        inside one width bucket (``bounds``) so each runs at the narrow
        partial capacity its own rows need, and the numpy kernel steps only
        the suffix of keys that still hold an event at step t.  Returns the
        advanced blocks + matches + grown caps; commits NOTHING
        (transactional — see the guarded dispatch above)."""
        from flink_tpu.cep import vectorized as V

        vec = self._vec
        tab = vec.tab
        S = tab.n_stages
        m_cap, e_cap = vec.m_cap, vec.e_cap
        chunk = 65536
        step = V.step_jit if vec.kernel == "jit" else V.step_numpy
        suffix = vec.kernel != "jit"      # jit needs shape-stable steps
        sect0: List[Tuple[tuple, tuple, int]] = []
        chunks = []
        spans = [(int(lo2), min(int(lo2) + chunk, int(bhi)))
                 for blo, bhi in zip(bounds[:-1], bounds[1:])
                 for lo2 in range(int(blo), int(bhi), chunk)]
        for lo, hi in spans:
            kc = hi - lo
            sel = (krow >= lo) & (krow < hi)
            ek = (krow[sel] - lo).astype(np.int64)
            ep = pos[sel].astype(np.int64)
            cchunk = counts[lo:hi]
            Tc = int(cchunk.max())
            ets = np.zeros((kc, Tc), np.int64)
            eid = np.zeros((kc, Tc), np.int64)
            val = np.zeros((kc, Tc), bool)
            bit = np.zeros((kc, Tc, S), bool)
            ubi = np.zeros((kc, Tc, S), bool)
            ets[ek, ep] = r["ts"][sel]
            eid[ek, ep] = r["eid"][sel]
            val[ek, ep] = True
            bit[ek, ep] = r["bits"][sel]
            ubi[ek, ep] = r["ubits"][sel]
            # chunk-local widths: exactly what THIS bucket's rows need
            slots = uniq[lo:hi]
            m_loc = _pow2_at_least(int(vec.nlv[slots].max(initial=1)), 4)
            e_loc = _pow2_at_least(
                int(vec.eln[slots].max(initial=0)) + 1, 4)
            block = vec.gather(slots, m_loc, e_loc)
            for t in range(Tc):
                # counts ascend within the chunk: keys with an event at
                # step t are exactly the suffix [s0:]
                s0 = int(np.searchsorted(cchunk, t, side="right")) \
                    if suffix else 0
                part = tuple(a[s0:] for a in block)
                if tab.trailing_negation:
                    part, harvested = _harvest_block(
                        tab, part, val[s0:, t], ets[s0:, t])
                    for i, (k, m, events, cts) in enumerate(harvested):
                        sect0.append(
                            ((korder[lo + s0 + k], t, 0, i), events, cts))
                inputs = (val[s0:, t], ets[s0:, t], eid[s0:, t],
                          bit[s0:, t, :], ubi[s0:, t, :])
                res, m_new = step(tab, m_loc, part, inputs)
                part = res.block
                m_grew = max(m_new, part[0].shape[1])
                e_grew = part[4].shape[2]
                if m_grew > m_loc or e_grew > e_loc:
                    m_loc = max(m_loc, m_grew)
                    e_loc = max(e_loc, e_grew)
                    block = _grow_block(block, m_loc, e_loc)
                    part = _grow_block(part, m_loc, e_loc)
                if s0:
                    block = tuple(np.concatenate([full[:s0], new])
                                  for full, new in zip(block, part))
                else:
                    block = part
                for i in range(res.match_kc.shape[0]):
                    k, _c = res.match_kc[i]
                    sect0.append(
                        ((korder[lo + s0 + int(k)], t, 1, i),
                         V.unpack_events(res.match_ev[i]),
                         int(ets[s0 + int(k), t])))
            chunks.append((slots, block))
            m_cap = max(m_cap, m_loc)
            e_cap = max(e_cap, e_loc)
        return chunks, sect0, m_cap, e_cap

    def _vec_harvest_all(self, now: int):
        """Drain-end trailing-negation harvest over every drained key, in
        first-drain order (the interpreted engine's second ``_nfas``
        loop)."""
        from flink_tpu.cep.vectorized import unpack_events

        vec = self._vec
        tab = vec.tab
        if not tab.trailing_negation or not vec.drained_order:
            return []
        n = vec.n_slots
        live = (np.arange(vec.m_cap)[None, :] < vec.nlv[:n, None])
        fst = vec.fst[:n]
        safe = np.where(fst == LONG_MIN, now, fst)
        mask = (live & vec.drained[:n, None]
                & (vec.st[:n] == tab.n_stages - 1)
                & (fst != LONG_MIN) & (now - safe > tab.within))
        if not mask.any():
            return []
        hits = np.argwhere(mask)
        hits = hits[np.lexsort((hits[:, 1], vec.rank[hits[:, 0]]))]
        out = []
        for k, m in hits:
            eln = int(vec.eln[k, m])
            out.append((unpack_events(vec.ev[k, m, :eln]),
                        int(vec.fst[k, m] + tab.within)))
        # remove the harvested partials (stable compaction of the rest)
        rows = np.unique(hits[:, 0])
        keep = live[rows] & ~mask[rows]
        order = np.argsort(~keep, axis=1, kind="stable")
        for name in ("st", "cnt", "fst", "eln", "evh"):
            a = getattr(vec, name)
            a[rows] = np.take_along_axis(a[rows], order, axis=1)
        vec.ev[rows] = np.take_along_axis(vec.ev[rows],
                                          order[:, :, None], axis=1)
        vec.nlv[rows] = keep.sum(axis=1).astype(np.int32)
        dead = (np.arange(vec.m_cap)[None, :] >= vec.nlv[rows, None])
        vec.st[rows] = np.where(dead, 0, vec.st[rows])
        vec.cnt[rows] = np.where(dead, 0, vec.cnt[rows])
        vec.fst[rows] = np.where(dead, LONG_MIN, vec.fst[rows])
        vec.eln[rows] = np.where(dead, 0, vec.eln[rows])
        vec.evh[rows] = np.where(dead, 0, vec.evh[rows])
        vec.ev[rows] = np.where(dead[:, :, None], 0, vec.ev[rows])
        return out

    def _prune_rows_vectorized(self) -> None:
        vec = self._vec
        parts = [vec.referenced_event_ids()]
        for p in vec.pending:
            parts.append(np.asarray(p["eid"], np.int64))
        self._rowstore.prune(np.concatenate(parts)
                             if parts else np.empty(0, np.int64))

    # -- degrade to the interpreted engine (quarantine fallback) -------------
    def _degrade_to_interpreted(self, reason: str) -> None:
        """Mid-job fallback: decode the array state into per-key NFAs and
        per-key buffers, then continue interpreted — digest-identical (the
        two engines share one logical state)."""
        from flink_tpu.cep.vectorized import decode_partials

        vec = self._vec
        self._buffers = {}
        self._nfas = {}
        self._partials_total = 0
        if vec is not None and vec.index is not None:
            # buffer dict insertion order = first-arrival order = slot id
            for slot in range(vec.n_slots):
                self._buffers[vec.key_of(slot)] = []
            pend = vec.consolidate()
            if pend is not None:
                order = np.lexsort((pend["eid"], pend["slot"]))
                for i in order:
                    slot = int(pend["slot"][i])
                    self._buffers[vec.key_of(slot)].append(
                        (int(pend["ts"][i]), int(pend["eid"][i]),
                         pend["bits"][i],
                         pend["ubits"][i] if vec.tab.has_until else None))
            for slot in vec.drained_order:
                nfa = NFA(self.pattern)
                nfa.partials = decode_partials(
                    (vec.st[slot], vec.cnt[slot], vec.fst[slot],
                     vec.eln[slot], vec.ev[slot]), int(vec.nlv[slot]))
                nfa.skip_until_ts = int(vec.skip[slot])
                self._nfas[vec.key_of(slot)] = nfa
                self._partials_total += len(nfa.partials)
        self._vec = None
        self._engine = "interpreted"
        self._engine_reasons = [f"degraded mid-job: {reason}"]
        self._stats["degraded"] += 1

    # -- checkpointing -------------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        """One snapshot format for BOTH engines (the interpreted layout —
        buffers carry materialized rows, NFAs carry partial lists), so
        checkpoints restore across engine choices and mid-job degradations
        never strand a savepoint."""
        self._resolve_engine()
        if self._engine == "vectorized":
            buffers, nfas = self._vec_snapshot_views()
        else:
            buffers = {k: [(ts, eid, bits, ubits, self._row(eid))
                           for ts, eid, bits, ubits in v]
                       for k, v in self._buffers.items()}
            nfas = {}
            for k, n in self._nfas.items():
                referenced = {ev for pm in n.partials
                              for _s, ev in pm.events}
                nfas[k] = (n.partials, n.skip_until_ts,
                           {e: self._row(e) for e in sorted(referenced)})
        return {
            "buffers": buffers,
            "nfas": nfas,
            "last_rows": dict(self._last_row),
            "next_event_id": self._next_event_id,
            "watermark": self.watermark,
        }

    def _vec_snapshot_views(self):
        from flink_tpu.cep.vectorized import decode_partials

        vec = self._vec
        buffers: Dict[Any, list] = {}
        if vec.index is not None:
            for slot in range(vec.n_slots):
                buffers[vec.key_of(slot)] = []
            pend = vec.consolidate()
            if pend is not None:
                vec.pending = [pend]         # snapshot must not consume
                order = np.lexsort((pend["eid"], pend["slot"]))
                for i in order:
                    slot = int(pend["slot"][i])
                    eid = int(pend["eid"][i])
                    buffers[vec.key_of(slot)].append(
                        (int(pend["ts"][i]), eid, pend["bits"][i],
                         pend["ubits"][i] if vec.tab.has_until else None,
                         self._row(eid)))
        nfas: Dict[Any, tuple] = {}
        for slot in vec.drained_order:
            partials = decode_partials(
                (vec.st[slot], vec.cnt[slot], vec.fst[slot],
                 vec.eln[slot], vec.ev[slot]), int(vec.nlv[slot]))
            referenced = sorted({ev for pm in partials
                                 for _s, ev in pm.events})
            nfas[vec.key_of(slot)] = (
                partials, int(vec.skip[slot]),
                {e: self._row(e) for e in referenced})
        return buffers, nfas

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._engine = None
        self._resolve_engine()
        self._rowstore = _RowStore()
        self._buffers = {}
        self._nfas = {}
        self._partials_total = 0
        for k, (partials, skip_ts, rows) in snap["nfas"].items():
            for e, row in rows.items():
                self._rowstore.put_row(e, row)
        if self._engine == "vectorized":
            self._vec_restore(snap)
        else:
            for k, v in snap["buffers"].items():
                entries = []
                for e in v:
                    # 5-tuple (with row) is the on-disk format; rows go to
                    # the columnar store, buffers stay slim
                    ts, eid, bits, ubits = e[0], e[1], e[2], e[3]
                    if len(e) > 4:
                        self._rowstore.put_row(eid, e[4])
                    entries.append((ts, eid, bits, ubits))
                self._buffers[k] = entries
            for k, (partials, skip_ts, _rows) in snap["nfas"].items():
                # the snapshot's rows already went into the row store's
                # restored-row map above — duplicating them on the NFA
                # would hold every row dict twice for the operator's life
                nfa = NFA(self.pattern)
                nfa.partials = list(partials)
                nfa.skip_until_ts = skip_ts
                self._nfas[k] = nfa
                self._partials_total += len(nfa.partials)
        self._last_row = dict(snap.get("last_rows", {}))
        self._next_event_id = snap["next_event_id"]
        self.watermark = snap["watermark"]

    # -- rescale -------------------------------------------------------------
    @staticmethod
    def split_snapshot(snap: Dict[str, Any], max_parallelism: int,
                       new_parallelism: int) -> List[Dict[str, Any]]:
        """One CEP snapshot -> ``new_parallelism`` snapshots, per-key
        entries (event buffers, NFA partials, PREV rows) routed by the
        key's key group — the same assignment the record router uses, so
        a key's partial matches land exactly where its future events will
        (ISSUE-15: scenarios rescale CEP jobs mid-stream).  Event ids stay
        as-is: each part keeps a disjoint key subset and every key's
        events came from this one operator, so ids stay unique per part;
        ``next_event_id``/``watermark`` ride to every part."""
        from flink_tpu.core import keygroups

        keys: List[Any] = list(snap.get("buffers", {}))
        known = set(keys)
        for src in (snap.get("nfas", {}), snap.get("last_rows", {})):
            for k in src:
                if k not in known:
                    known.add(k)
                    keys.append(k)
        if keys:
            karr = np.asarray(keys)
            if karr.dtype.kind not in "iu":
                karr = np.asarray(keys, object)
            owner = keygroups.route_raw_keys(karr, new_parallelism,
                                             max_parallelism)
        else:
            owner = np.zeros(0, np.int32)
        own_of = {k: int(owner[i]) for i, k in enumerate(keys)}
        out = []
        for p in range(new_parallelism):
            out.append({
                # preserve dict order: buffer order IS the vectorized
                # engine's slot (first-arrival) order
                "buffers": {k: v for k, v in snap.get("buffers", {}).items()
                            if own_of[k] == p},
                "nfas": {k: v for k, v in snap.get("nfas", {}).items()
                         if own_of[k] == p},
                "last_rows": {k: v
                              for k, v in snap.get("last_rows", {}).items()
                              if own_of[k] == p},
                "next_event_id": snap.get("next_event_id", 0),
                "watermark": snap.get("watermark", LONG_MIN),
            })
        return out

    @staticmethod
    def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Scale-down merge.  Keys are disjoint across parts (keyed
        state), but event ids are NOT — each part numbered its events
        independently, and the restore funnels every part's rows into ONE
        columnar row store keyed by event id, where a collision would
        silently alias two different events' rows.  Remap every event id
        to ``eid * n_parts + part_index`` (disjoint ranges; within-part
        order preserved, and all of one key's events come from one part,
        so per-key event order is untouched).  The merged watermark takes
        MIN — under an unaligned cut the parts sit at different
        watermarks, and the behind part's in-flight elements replay with
        their own watermark progression (the PR-5 ordering contract), so
        the lower bound is the safe restart point (the ahead part's
        already-drained keys hold post-drain state: nothing re-emits)."""
        import dataclasses

        live = [s for s in snaps if isinstance(s, dict) and s]
        if not live:
            return dict(snaps[0]) if snaps else {}
        P = max(1, len(snaps))

        def remap(eid: int, part: int) -> int:
            return int(eid) * P + part

        buffers: Dict[Any, list] = {}
        nfas: Dict[Any, tuple] = {}
        last_rows: Dict[Any, dict] = {}
        next_eid = 0
        wms = []
        for part, s in enumerate(snaps):
            if not isinstance(s, dict) or not s:
                continue
            for k, entries in s.get("buffers", {}).items():
                buffers[k] = [
                    (e[0], remap(e[1], part)) + tuple(e[2:])
                    for e in entries]
            for k, (partials, skip_ts, rows) in s.get("nfas", {}).items():
                nfas[k] = (
                    [dataclasses.replace(
                        pm, events=tuple((st, remap(e, part))
                                         for st, e in pm.events))
                     for pm in partials],
                    skip_ts,
                    {remap(e, part): r for e, r in rows.items()})
            last_rows.update(s.get("last_rows", {}))
            next_eid = max(next_eid,
                           remap(int(s.get("next_event_id", 0)), part) + 1)
            wms.append(int(s.get("watermark", LONG_MIN)))
        return {"buffers": buffers, "nfas": nfas, "last_rows": last_rows,
                "next_event_id": next_eid,
                "watermark": min(wms) if wms else LONG_MIN}

    def _vec_restore(self, snap: Dict[str, Any]) -> None:
        from flink_tpu.cep import vectorized as V

        self._vec = _VecState(V.compile_pattern(self.pattern),
                              V.default_kernel())
        vec = self._vec
        # slot order: buffers dict order IS the original first-arrival
        # order; any nfa-only keys (none in practice) follow
        keys = list(snap["buffers"].keys())
        known = set(keys)
        keys += [k for k in snap["nfas"] if k not in known]
        if not keys:
            return
        karr = np.asarray(keys)
        if karr.dtype.kind not in "iu":
            karr = np.asarray(keys, object)
        vec.map_keys(karr)
        slot_of = {k: i for i, k in enumerate(keys)}
        pieces = {"slot": [], "ts": [], "eid": [], "bits": [], "ubits": []}
        S = vec.tab.n_stages
        for k, v in snap["buffers"].items():
            for e in v:
                ts, eid, bits, ubits = e[0], e[1], e[2], e[3]
                if len(e) > 4:
                    self._rowstore.put_row(eid, e[4])
                pieces["slot"].append(slot_of[k])
                pieces["ts"].append(ts)
                pieces["eid"].append(eid)
                pieces["bits"].append(np.asarray(bits, bool))
                pieces["ubits"].append(np.zeros(S, bool) if ubits is None
                                       else np.asarray(ubits, bool))
        if pieces["slot"]:
            vec.pending = [{
                "slot": np.asarray(pieces["slot"], np.int64),
                "ts": np.asarray(pieces["ts"], np.int64),
                "eid": np.asarray(pieces["eid"], np.int64),
                "bits": np.stack(pieces["bits"]),
                "ubits": np.stack(pieces["ubits"]),
            }]
        for k, (partials, skip_ts, _rows) in snap["nfas"].items():
            slot = slot_of[k]
            row, m_cap, e_cap = V.encode_partials(
                list(partials), vec.m_cap, vec.e_cap)
            vec.grow_caps(m_cap, e_cap)
            st, cnt, fst, eln, ev, evh, n = row
            vec.st[slot, :st.size] = st
            vec.cnt[slot, :cnt.size] = cnt
            vec.fst[slot, :fst.size] = fst
            vec.eln[slot, :eln.size] = eln
            vec.ev[slot, :ev.shape[0], :ev.shape[1]] = ev
            vec.evh[slot, :evh.size] = evh
            vec.nlv[slot] = n
            vec.skip[slot] = skip_ts
            vec.mark_drained(np.asarray([slot]))


def _pow2_at_least(n: int, floor: int) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


def _grow_block(block, m_cap: int, e_cap: int):
    """Widen a gathered block to the given sticky caps (both axes)."""
    from flink_tpu.cep.vectorized import grow_partials

    block = grow_partials(block, m_cap)
    st, cnt, fst, eln, ev, evh, nlv, skip = block
    if ev.shape[2] < e_cap:
        wide = np.zeros(ev.shape[:2] + (e_cap,), np.int64)
        wide[:, :, :ev.shape[2]] = ev
        ev = wide
    return (st, cnt, fst, eln, ev, evh, nlv, skip)


def _harvest_block(tab, block, keymask, now):
    """Trailing-negation harvest for a gathered block, BEFORE the event
    advances (the interpreted drain calls ``harvest_expired_negations(ts)``
    per event): emits expired window-close completions in partial-list
    order and compacts them out.  Pure — returns the new block."""
    from flink_tpu.cep.vectorized import unpack_events

    st, cnt, fst, eln, ev, evh, nlv, skip = block
    M = st.shape[1]
    live = np.arange(M)[None, :] < nlv[:, None]
    safe = np.where(fst == LONG_MIN, now[:, None], fst)
    mask = (live & keymask[:, None] & (st == tab.n_stages - 1)
            & (fst != LONG_MIN) & (now[:, None] - safe > tab.within))
    if not mask.any():
        return block, []
    out = []
    for k, m in np.argwhere(mask):
        out.append((int(k), int(m),
                    unpack_events(ev[k, m, :int(eln[k, m])]),
                    int(fst[k, m] + tab.within)))
    keep = live & ~mask
    order = np.argsort(~keep, axis=1, kind="stable")
    t2 = lambda a: np.take_along_axis(a, order, axis=1)  # noqa: E731
    n_nlv = keep.sum(axis=1).astype(np.int32)
    n_st, n_cnt, n_fst = t2(st), t2(cnt), t2(fst)
    n_eln, n_evh = t2(eln), t2(evh)
    n_ev = np.take_along_axis(ev, order[:, :, None], axis=1)
    dead = np.arange(M)[None, :] >= n_nlv[:, None]
    n_st = np.where(dead, 0, n_st)
    n_cnt = np.where(dead, 0, n_cnt)
    n_fst = np.where(dead, LONG_MIN, n_fst)
    n_eln = np.where(dead, 0, n_eln)
    n_evh = np.where(dead, 0, n_evh)
    n_ev = np.where(dead[:, :, None], 0, n_ev)
    return (n_st, n_cnt, n_fst, n_eln, n_ev, n_evh, n_nlv, skip), out


class CEP:
    """Entry point (``CEP.java``): ``CEP.pattern(keyed_stream, pattern)``."""

    @staticmethod
    def pattern(keyed_stream, pattern: Pattern) -> "PatternStream":
        return PatternStream(keyed_stream, pattern)


class PatternStream:
    def __init__(self, keyed_stream, pattern: Pattern):
        self.keyed = keyed_stream
        self.pattern = pattern

    def select(self, fn: Callable[[Dict[str, List[dict]]], dict],
               name: str = "cep-select", vectorized: str = "auto"):
        from flink_tpu.datastream.api import DataStream
        key_col = self.keyed.key_column
        pat = self.pattern
        t = self.keyed._then(
            name, lambda _v=vectorized: CepOperator(pat, key_col, fn, name,
                                                    vectorized=_v))
        return DataStream(self.keyed.env, t)
