"""Complex Event Processing (CEP) library.

Analog of ``flink-libraries/flink-cep``: a fluent ``Pattern`` API compiled
to an NFA run over keyed streams, with vectorized condition evaluation per
batch and — for eligible patterns — batched array-kernel NFA transitions
advancing every key's partial matches at once (``cep/vectorized.py``;
``CEP.java``, ``nfa/NFA.java:86``).
"""

from flink_tpu.cep.operator import CEP, CepOperator, NFA, PatternStream
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern, Stage
from flink_tpu.cep.vectorized import (TransitionTable, classify_pattern,
                                      compile_pattern)

__all__ = ["AfterMatchSkipStrategy", "CEP", "CepOperator", "NFA", "Pattern",
           "PatternStream", "Stage", "TransitionTable", "classify_pattern",
           "compile_pattern"]
