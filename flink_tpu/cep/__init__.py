"""Complex Event Processing (CEP) library.

Analog of ``flink-libraries/flink-cep``: a fluent ``Pattern`` API compiled
to an NFA run over keyed streams, with vectorized condition evaluation per
batch and host-side transitions (``CEP.java``, ``nfa/NFA.java:86``).
"""

from flink_tpu.cep.operator import CEP, CepOperator, NFA, PatternStream
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern, Stage

__all__ = ["AfterMatchSkipStrategy", "CEP", "CepOperator", "NFA", "Pattern",
           "PatternStream", "Stage"]
