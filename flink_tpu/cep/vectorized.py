"""Vectorized CEP: batched NFA state transitions for ALL keys at once.

The interpreted matcher (``cep/operator.py``, ``NFA.advance``) walks one
event x one partial match at a time in Python — the last hot-path workload
still paying per-record host work (ROADMAP item 4).  This module compiles a
``Pattern`` into a dense :class:`TransitionTable` and advances **every
key's partial matches in one batched dispatch per event step**: the active
partials of all keys live in fixed-shape arrays ``[K, M]`` (stage index,
loop count, first timestamp, a bounded event-pointer ring), the per-stage
condition bits that ``process_batch`` already evaluates vectorized become
the kernel's input planes, and the NFA edges (take / ignore / die /
optional-forward / negation) become masked gather/scatter updates.
``within()`` expiry and the after-match skip barrier apply as vectorized
masks; host code touches only *completed* matches.

Equivalence contract: for every **eligible** pattern (see
:func:`classify_pattern`) the kernel produces bit-identical results to the
interpreted NFA — same matches, same order, same partial-match lists after
every event.  The candidate layout mirrors ``NFA.advance``'s generation
order exactly (per partial: take-stay, take-advance, keep; the fresh start
partial appended last), candidate dedup mirrors the ``seen`` set (exact
comparison, hash-prefiltered), and completed matches bypass dedup just as
``add()`` does.

Ineligible shapes — ``followedByAny`` (non-deterministic branch
explosion), ``greedy()`` loops, and drain-time/``PREV`` conditions
(MATCH_RECOGNIZE) — fall back to the interpreted NFA, decided once at plan
time.

Two kernel backends share one generic step (``xp`` = numpy or
``jax.numpy``):

- ``numpy``: the host-vectorized path (one pass of array ops per event
  step across all keys); the winner on CPU backends.
- ``jit``: the same step under ``jax.jit`` (int64 planes via scoped
  ``enable_x64``), one dispatched step per event position — the
  accelerator path.  Candidate dedup inside the jit is hash-prefiltered
  only; any hash collision raises a flag and the step replays on the
  numpy path with exact comparison, so bit-identity never rests on a
  hash.

:func:`calibrated_vectorized_cep` is the measured engine A/B behind
``CepOperator(vectorized="auto")`` — the same measure-don't-assume pattern
as ``--device-probe`` (``state/device_keyindex.calibrated_device_probe``).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import LONG_MIN
from flink_tpu.cep.pattern import AfterMatchSkipStrategy, Pattern

#: event pointers pack (stage << PACK_SHIFT) | event_id into one int64
PACK_SHIFT = 48
_PACK_MASK = (1 << PACK_SHIFT) - 1

#: sentinel for "no within window"
_NO_WITHIN = -1

_ENV_ENGINE = "FLINK_TPU_CEP_VECTORIZED"
_ENV_KERNEL = "FLINK_TPU_CEP_KERNEL"

#: rolling-hash multiplier for the per-partial event-list hash (int32 wrap)
_HASH_MUL = np.int32(1000003)


# ---------------------------------------------------------------------------
# plan-time classifier + transition table
# ---------------------------------------------------------------------------

def classify_pattern(pattern: Pattern) -> Tuple[bool, List[str]]:
    """Is this pattern eligible for the vectorized kernel?

    First cut keeps the branching bounded (<= 3 successor candidates per
    partial per event, mirroring ``NFA.advance``'s edge set):

    - ``followedByAny`` (``relaxed_any``) multiplies ignore edges for
      *matching* events — unbounded combination explosion.
    - ``greedy()`` loops couple a partial's fate to its *sibling's* bits
      (``greedy_from`` suppression), an extra cross-partial plane.

    Everything else — strict/relaxed contiguity, ``notNext`` /
    ``notFollowedBy`` (incl. trailing under ``within``), ``times`` /
    ``oneOrMore`` / ``optional``, ``until``, both after-match skip
    strategies — lowers exactly.  Returns ``(eligible, reasons)``.
    """
    reasons = []
    for s in pattern.stages:
        if s.contiguity == "relaxed_any":
            reasons.append(f"stage {s.name!r}: followedByAny (relaxed_any) "
                           f"contiguity")
        if s.greedy:
            reasons.append(f"stage {s.name!r}: greedy loop")
    return (not reasons), reasons


@dataclass(frozen=True)
class TransitionTable:
    """A ``Pattern`` compiled to dense per-stage planes (all numpy; the
    jit kernel closes over them as constants)."""

    n_stages: int
    strict: np.ndarray      # bool[S]: 'next' contiguity
    negated: np.ndarray     # bool[S]
    optional: np.ndarray    # bool[S]
    tmin: np.ndarray        # int64[S] quantifier lower bound
    tmax: np.ndarray        # int64[S] upper bound (LONG_MAX-ish = unbounded)
    within: int             # ms, or _NO_WITHIN
    skip_past: bool         # SKIP_PAST_LAST_EVENT
    trailing_negation: bool
    has_until: bool


def compile_pattern(pattern: Pattern) -> TransitionTable:
    stages = pattern.stages
    S = len(stages)
    unbounded = np.int64(2 ** 62)
    last = stages[-1]
    return TransitionTable(
        n_stages=S,
        strict=np.asarray([s.contiguity == "strict" for s in stages], bool),
        negated=np.asarray([s.negated for s in stages], bool),
        optional=np.asarray([s.optional for s in stages], bool),
        tmin=np.asarray([s.times_min for s in stages], np.int64),
        tmax=np.asarray([s.times_max if s.times_max is not None
                         else unbounded for s in stages], np.int64),
        within=(pattern.within_ms if pattern.within_ms is not None
                else _NO_WITHIN),
        skip_past=(pattern.skip_strategy
                   == AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT),
        trailing_negation=(last.negated and last.contiguity != "strict"
                           and pattern.within_ms is not None),
        has_until=any(s.until is not None for s in stages),
    )


# ---------------------------------------------------------------------------
# packing helpers
# ---------------------------------------------------------------------------

def pack_event(stage: int, event_id: int) -> int:
    return (int(stage) << PACK_SHIFT) | int(event_id)


def unpack_events(row: np.ndarray) -> Tuple[Tuple[int, int], ...]:
    r = np.asarray(row, np.int64)
    return tuple((int(p) >> PACK_SHIFT, int(p) & _PACK_MASK) for p in r)


def _fold32(packed):
    """int64 packed pointer -> int32 hash lane (both words folded)."""
    p = packed.astype(np.int64) if hasattr(packed, "astype") else packed
    lo = (p & np.int64(0xFFFFFFFF)).astype(np.int32)
    hi = (p >> np.int64(32)).astype(np.int32)
    return lo ^ (hi * np.int32(31))


def event_list_hash(packed_row) -> np.int32:
    """Rolling int32 hash of an event list — MUST match the kernel's
    incremental update (``h' = h * _HASH_MUL + fold32(packed)``).  Runs on
    1-element arrays so int32 wraparound stays silent (scalar overflow
    warns under ``-W error``)."""
    r = np.asarray(packed_row, np.int64).reshape(-1)
    folded = _fold32(r)
    h = np.zeros(1, np.int32)
    for i in range(r.size):
        h = h * _HASH_MUL + folded[i:i + 1]
    return np.int32(h[0])


# ---------------------------------------------------------------------------
# the generic per-event transition step (xp = numpy | jax.numpy)
# ---------------------------------------------------------------------------

def _stable_argsort(xp, a, axis):
    if xp is np:
        return np.argsort(a, axis=axis, kind="stable")
    return xp.argsort(a, axis=axis)      # jnp sorts are stable by default


def _gather_stage(xp, plane, stage, S):
    """plane[Ka, S] gathered at stage[Ka, M] -> [Ka, M] (clipped gather —
    out-of-range stages are masked off by callers)."""
    idx = xp.clip(stage, 0, S - 1)
    return xp.take_along_axis(plane, idx, axis=1)


def _candidates(xp, tab: TransitionTable, block, inputs):
    """One NFA event step for a block of keys: build the candidate arrays.

    ``block``: (st, cnt, fst, eln, ev, evh, nlv, skip) — [Ka, M] planes
    (+ ev [Ka, M, E], nlv/skip [Ka]).  ``inputs``: (active, ets, eid,
    bits, ubits) with bits/ubits [Ka, S].

    Returns candidate planes laid out ``[Ka, C=3M+1]`` in the interpreted
    generation order (per partial m: 3m+0 take-stay, 3m+1 take-advance,
    3m+2 keep; slot 3M = the fresh start partial appended last), plus
    ``stepping`` and the E-overflow flag.
    """
    st, cnt, fst, eln, ev, evh, nlv, skip = block
    active, ets, eid, bits, ubits = inputs
    Ka, M = st.shape
    E = ev.shape[2]
    S = tab.n_stages

    m_idx = xp.arange(M, dtype=np.int32)[None, :]
    live = m_idx < nlv[:, None]
    stepping = active & (ets > skip)                      # skip barrier
    act = stepping[:, None] & live
    ts_b = ets[:, None]

    # within-window expiry (guard LONG_MIN before subtracting)
    if tab.within != _NO_WITHIN:
        safe_fst = xp.where(fst == LONG_MIN, ts_b, fst)
        expired = (fst != LONG_MIN) & (ts_b - safe_fst > tab.within)
    else:
        expired = xp.zeros_like(live)
    alive = act & ~expired

    stage_c = xp.clip(st, 0, S - 1)
    neg_plane = xp.asarray(tab.negated)
    strict_plane = xp.asarray(tab.strict)
    opt_plane = xp.asarray(tab.optional)
    tmin_plane = xp.asarray(tab.tmin)
    tmax_plane = xp.asarray(tab.tmax)

    neg = neg_plane[stage_c] & alive
    strictneg = neg & strict_plane[stage_c]
    relaxneg = neg & ~strict_plane[stage_c]
    normal = alive & ~neg

    b_at = _gather_stage(xp, bits, st, S) & alive
    u_at = _gather_stage(xp, ubits, st, S) & alive

    neg_dead = neg & b_at                   # forbidden event: partial dies
    norm_until_dead = normal & u_at & (cnt > 0)
    normal_f = normal & ~norm_until_dead
    strictneg_f = strictneg & ~neg_dead
    relaxneg_f = relaxneg & ~neg_dead

    # ---- feed(): chain walk through optional stages to the take stage j.
    # own = the stage whose until() can close the loop (the partial's own
    # stage for normal partials; the advanced stage for notNext; never for
    # notFollowedBy — feed there starts past the partial's own stage).
    cs = xp.where(neg, st + 1, st)
    own = xp.where(relaxneg, xp.full_like(st, -1),
                   xp.where(strictneg, st + 1, st))
    took_nothing0 = xp.where(neg, xp.ones_like(live), cnt == 0)

    sn_complete = strictneg_f & (cs >= S)   # notNext ends the pattern
    feeding = (normal_f | strictneg_f | relaxneg_f) & (cs < S)

    jj = xp.clip(cs, 0, S - 1)
    remaining = feeding
    matched = xp.zeros_like(live)
    take_j = jj
    for _ in range(S):
        bj = xp.take_along_axis(bits, jj, axis=1)
        uj = xp.take_along_axis(ubits, jj, axis=1)
        negj = neg_plane[jj]
        ublock = (jj == own) & uj
        take_here = remaining & bj & ~negj & ~ublock
        tn = xp.where(jj == cs, took_nothing0, xp.ones_like(live))
        fwd = (remaining & ~take_here & ~negj & ~(bj & ublock)
               & ~bj & opt_plane[jj] & tn & (jj + 1 < S))
        take_j = xp.where(take_here, jj, take_j)
        matched = matched | take_here
        remaining = fwd
        jj = xp.where(fwd, jj + 1, jj)

    # ---- take candidates (stay in loop / advance pointer)
    cnt_at_j = xp.where((take_j == own) & ~neg, cnt, xp.zeros_like(cnt))
    newc = cnt_at_j + 1
    first_f = xp.where(fst == LONG_MIN, ts_b, fst)
    tmax_j = tmax_plane[take_j]
    tmin_j = tmin_plane[take_j]
    stay_ok = matched & (newc.astype(np.int64) < tmax_j)
    adv_ok = matched & (newc.astype(np.int64) >= tmin_j)
    adv_stage = take_j + 1
    adv_is_match = adv_ok & (adv_stage >= S)

    packed = ((take_j.astype(np.int64) << PACK_SHIFT)
              | eid[:, None].astype(np.int64))
    e_idx = xp.arange(E, dtype=np.int32)[None, None, :]
    ev_app = xp.where(e_idx == eln[:, :, None], packed[:, :, None], ev)
    evh_app = (evh * _HASH_MUL + _fold32(packed)).astype(np.int32)
    # E overflow: a take with a full ring cannot record its pointer
    overflow_e = xp.any((stay_ok | adv_ok) & (eln >= E))

    # ---- keep candidates
    keep_normal = normal_f & (((st == 0) & (cnt == 0))
                              | (~matched & ~strict_plane[stage_c]))
    nxt_c = xp.clip(cs, 0, S - 1)
    keep_sn = (strictneg_f & (cs < S) & ~matched & ~strict_plane[nxt_c])
    keep_rn = relaxneg_f & ~matched & ((cs >= S) | ~strict_plane[nxt_c])

    keep_valid = keep_normal | keep_rn | keep_sn | sn_complete
    # keep content: pm unchanged, EXCEPT notNext which keeps the advanced
    # partial (stage+1, count 0, first filled)
    sn_like = strictneg_f & (keep_sn | sn_complete)
    keep_st = xp.where(sn_like, cs, st)
    keep_cnt = xp.where(sn_like, xp.zeros_like(cnt), cnt)
    keep_fst = xp.where(sn_like, first_f, fst)

    # ---- assemble [Ka, C] candidate planes (C = 3M + 1)
    def lay(a0, a1, a2, start_val, dtype):
        tri = xp.stack([a0, a1, a2], axis=2).reshape(Ka, 3 * M)
        startc = xp.full((Ka, 1), start_val, dtype)
        return xp.concatenate([tri, startc], axis=1)

    zil = xp.zeros_like
    c_st = lay(take_j, adv_stage, keep_st, np.int32(0), np.int32)
    c_cnt = lay(newc, zil(newc), keep_cnt, np.int32(0), np.int32)
    c_fst = lay(first_f, first_f, keep_fst, np.int64(LONG_MIN), np.int64)
    c_eln = lay(eln + 1, eln + 1, eln, np.int32(0), np.int32)
    c_evh = lay(evh_app, evh_app, evh, np.int32(0), np.int32)
    c_valid = lay(stay_ok, adv_ok, keep_valid, False, bool)
    c_match = lay(zil(stay_ok), adv_is_match, sn_complete, False, bool)
    ev_tri = xp.stack([ev_app, ev_app, ev], axis=2).reshape(Ka, 3 * M, E)
    c_ev = xp.concatenate(
        [ev_tri, xp.zeros((Ka, 1, E), np.int64)], axis=1)

    # the fresh start partial is appended only when no surviving candidate
    # already sits at (stage 0, count 0) — interpreted NFA end-of-advance
    has_start = xp.any(c_valid[:, :3 * M] & ~c_match[:, :3 * M]
                       & (c_st[:, :3 * M] == 0) & (c_cnt[:, :3 * M] == 0),
                       axis=1)
    start_col_valid = stepping & ~has_start
    c_valid = xp.concatenate(
        [c_valid[:, :3 * M], start_col_valid[:, None]], axis=1)

    cand = dict(st=c_st, cnt=c_cnt, fst=c_fst, eln=c_eln, ev=c_ev,
                evh=c_evh, valid=c_valid, ismatch=c_match)
    return cand, stepping, overflow_e


def _cand_hash(xp, cand):
    """int32 identity hash per candidate: (stage, count, elen, event-list
    rolling hash) — the dedup prefilter."""
    h = (cand["st"].astype(np.int32) * np.int32(31)
         + cand["cnt"].astype(np.int32))
    h = h * _HASH_MUL + cand["eln"].astype(np.int32)
    return (h * _HASH_MUL + cand["evh"]).astype(np.int32)


def _dup_prefilter(xp, cand):
    """dup[k, c] = an EARLIER valid non-match candidate has the same hash —
    the vectorized ``seen``-set prefilter (exact verification is the
    caller's job on rows where this fires)."""
    h = _cand_hash(xp, cand)
    eligible = cand["valid"] & ~cand["ismatch"]
    C = h.shape[1]
    eq = (h[:, None, :] == h[:, :, None])          # [Ka, C(earlier), C]
    tri = xp.asarray(np.tril(np.ones((C, C), bool), -1)).T  # earlier < c
    hit = eq & tri[None, :, :] & eligible[:, :, None] & eligible[:, None, :]
    return xp.any(hit, axis=1)


def _dup_candidate_rows(cand) -> np.ndarray:
    """Numpy fast path: rows that MIGHT contain a duplicate candidate —
    detected by sorting each row's (valid, non-match) candidate hashes and
    looking for adjacent equals (O(C log C) instead of the [C, C] pairwise
    plane).  Invalid slots get per-position sentinels above the int32 hash
    range so they can never create a false adjacency."""
    h = _cand_hash(np, cand).astype(np.int64)
    eligible = cand["valid"] & ~cand["ismatch"]
    C = h.shape[1]
    sentinel = (np.arange(C, dtype=np.int64) + (np.int64(1) << 33))[None, :]
    hm = np.where(eligible, h, sentinel)
    hs = np.sort(hm, axis=1)
    return np.flatnonzero((hs[:, 1:] == hs[:, :-1]).any(axis=1))


def _finalize(xp, M_out: int, cand, dup, block, stepping, ets,
              skip_past: bool):
    """Compact surviving candidates (valid, non-match, non-dup) into the
    first ``M_out`` slots in candidate order; apply the after-match skip
    reset; keep non-stepping keys' rows untouched.  Returns the new block
    plus the M-overflow flag."""
    st, cnt, fst, eln, ev, evh, nlv, skip = block
    Ka, M = st.shape
    E = ev.shape[2]
    C = cand["st"].shape[1]

    keep = cand["valid"] & ~cand["ismatch"] & ~dup
    ncand = keep.sum(axis=1).astype(np.int32)
    overflow_m = xp.max(ncand, initial=0) if xp is np else xp.max(
        xp.concatenate([ncand, xp.zeros(1, np.int32)]))
    overflow_m = overflow_m > M_out

    # stable compaction: argsort(~keep) puts kept candidates first, in
    # order.  M_out may exceed C (a pow2 growth overshooting 3M+1 when a
    # step nearly triples the partial set): gather the min(M_out, C)
    # candidate columns that exist, then pad to M_out — the dead-slot
    # masking below restores the pristine pattern on the padding.
    W = min(M_out, C)
    order = _stable_argsort(xp, ~keep, axis=1)[:, :W]
    take2 = lambda a: xp.take_along_axis(a, order, axis=1)  # noqa: E731

    def padw(a, fill):
        if W >= M_out:
            return a
        return xp.concatenate(
            [a, xp.full((Ka, M_out - W) + a.shape[2:], fill, a.dtype)],
            axis=1)

    n_st = padw(take2(cand["st"]), np.int32(0))
    n_cnt = padw(take2(cand["cnt"]), np.int32(0))
    n_fst = padw(take2(cand["fst"]), np.int64(LONG_MIN))
    n_eln = padw(take2(cand["eln"]), np.int32(0))
    n_evh = padw(take2(cand["evh"]), np.int32(0))
    n_ev = padw(xp.take_along_axis(cand["ev"], order[:, :, None], axis=1),
                np.int64(0))

    # after-match skip: a completing match resets the key to one fresh
    # start partial and raises the skip barrier to the match event's ts
    any_match = xp.any(cand["ismatch"] & cand["valid"], axis=1) & stepping
    if skip_past:
        rst = any_match[:, None]
        n_st = xp.where(rst, xp.zeros_like(n_st), n_st)
        n_cnt = xp.where(rst, xp.zeros_like(n_cnt), n_cnt)
        n_fst = xp.where(rst, xp.full_like(n_fst, LONG_MIN), n_fst)
        n_eln = xp.where(rst, xp.zeros_like(n_eln), n_eln)
        n_evh = xp.where(rst, xp.zeros_like(n_evh), n_evh)
        n_ev = xp.where(rst[:, :, None], xp.zeros_like(n_ev), n_ev)
        n_nlv = xp.where(any_match, xp.ones_like(ncand), ncand)
        n_skip = xp.where(any_match, ets, skip)
    else:
        n_nlv = ncand
        n_skip = skip

    # pad target shapes to M_out, then keep non-stepping keys untouched
    def merge(new, old, fill):
        if new.shape[1] < M_out or old.shape[1] < M_out:
            pad_n = M_out - new.shape[1]
            pad_o = M_out - old.shape[1]
            if pad_n:
                new = xp.concatenate(
                    [new, xp.full((Ka, pad_n) + new.shape[2:], fill,
                                  new.dtype)], axis=1)
            if pad_o:
                old = xp.concatenate(
                    [old, xp.full((Ka, pad_o) + old.shape[2:], fill,
                                  old.dtype)], axis=1)
        cond = stepping[:, None]
        if new.ndim == 3:
            cond = cond[:, :, None]
        return xp.where(cond, new, old)

    # mask dead trailing slots to the pristine pattern so stale payloads
    # never alias into a later comparison or snapshot
    slot = xp.arange(M_out, dtype=np.int32)[None, :]
    dead = slot >= n_nlv[:, None]
    n_st = xp.where(dead, xp.zeros_like(n_st), n_st)
    n_cnt = xp.where(dead, xp.zeros_like(n_cnt), n_cnt)
    n_fst = xp.where(dead, xp.full_like(n_fst, LONG_MIN), n_fst)
    n_eln = xp.where(dead, xp.zeros_like(n_eln), n_eln)
    n_evh = xp.where(dead, xp.zeros_like(n_evh), n_evh)
    n_ev = xp.where(dead[:, :, None], xp.zeros_like(n_ev), n_ev)

    new_block = (
        merge(n_st, st, np.int32(0)),
        merge(n_cnt, cnt, np.int32(0)),
        merge(n_fst, fst, np.int64(LONG_MIN)),
        merge(n_eln, eln, np.int32(0)),
        merge(n_ev, ev, np.int64(0)),
        merge(n_evh, evh, np.int32(0)),
        xp.where(stepping, n_nlv, nlv),
        n_skip,
    )
    return new_block, overflow_m


# ---------------------------------------------------------------------------
# numpy driver: exact dedup + growth + match extraction
# ---------------------------------------------------------------------------

def _exact_dup(cand, dup_pre: np.ndarray) -> np.ndarray:
    """Resolve the hash prefilter to EXACT duplicates (the interpreted
    ``seen`` key is (stage, count, events, greedy_from); greedy_from is
    always -1 for eligible patterns)."""
    if not dup_pre.any():
        return dup_pre
    dup = np.zeros_like(dup_pre)
    h = _cand_hash(np, cand)
    eligible = cand["valid"] & ~cand["ismatch"]
    for k, c in np.argwhere(dup_pre):
        hc = h[k, c]
        for c2 in range(c):
            if not eligible[k, c2] or h[k, c2] != hc or dup[k, c2]:
                continue
            if (cand["st"][k, c2] == cand["st"][k, c]
                    and cand["cnt"][k, c2] == cand["cnt"][k, c]
                    and cand["eln"][k, c2] == cand["eln"][k, c]):
                n = int(cand["eln"][k, c])
                if np.array_equal(cand["ev"][k, c2, :n],
                                  cand["ev"][k, c, :n]):
                    dup[k, c] = True
                    break
    return dup


class StepResult:
    """One event step's outcome: the new block plus match extraction."""

    __slots__ = ("block", "match_kc", "match_ev", "match_eln")

    def __init__(self, block, match_kc, match_ev, match_eln):
        self.block = block
        self.match_kc = match_kc       # [n, 2] (key row, candidate order)
        self.match_ev = match_ev       # list of packed int64 rows
        self.match_eln = match_eln


def step_numpy(tab: TransitionTable, m_cap: int, block, inputs
               ) -> Tuple[StepResult, int]:
    """One exact event step on the numpy backend.  Returns the result and
    the (possibly grown) partial capacity — E growth is handled internally
    by re-running the candidate pass on widened rings."""
    while True:
        cand, stepping, overflow_e = _candidates(np, tab, block, inputs)
        if bool(overflow_e):
            block = grow_event_ring(block)
            continue
        break
    sus = _dup_candidate_rows(cand)
    dup = np.zeros_like(cand["valid"])
    if sus.size:
        sub = {k: v[sus] for k, v in cand.items()}
        dup[sus] = _exact_dup(sub, _dup_prefilter(np, sub))
    keep = cand["valid"] & ~cand["ismatch"] & ~dup
    need = int(keep.sum(axis=1).max(initial=0))
    m_out = m_cap
    while need > m_out:
        m_out *= 2
    new_block, _ = _finalize(np, m_out, cand, dup, block, stepping,
                             inputs[1], tab.skip_past)
    mm = cand["ismatch"] & cand["valid"]
    kc = np.argwhere(mm)               # row-major: candidate order per key
    evs, elns = [], []
    for k, c in kc:
        n = int(cand["eln"][k, c])
        evs.append(np.array(cand["ev"][k, c, :n], np.int64))
        elns.append(n)
    return StepResult(new_block, kc, evs, elns), m_out


def grow_event_ring(block):
    """Double the bounded event-pointer ring (sticky high-water)."""
    st, cnt, fst, eln, ev, evh, nlv, skip = block
    Ka, M, E = ev.shape
    wide = np.zeros((Ka, M, max(2 * E, 2)), np.int64)
    wide[:, :, :E] = ev
    return (st, cnt, fst, eln, wide, evh, nlv, skip)


def grow_partials(block, m_new: int):
    """Widen the partial axis to ``m_new`` slots (sticky high-water)."""
    st, cnt, fst, eln, ev, evh, nlv, skip = block
    Ka, M, E = ev.shape
    if m_new <= M:
        return block
    pad = m_new - M

    def w(a, fill):
        return np.concatenate(
            [a, np.full((Ka, pad) + a.shape[2:], fill, a.dtype)], axis=1)

    return (w(st, 0), w(cnt, 0), w(fst, LONG_MIN), w(eln, 0),
            w(ev, 0), w(evh, 0), nlv, skip)


# ---------------------------------------------------------------------------
# jit driver: same step under jax.jit, numpy replay on dup/overflow
# ---------------------------------------------------------------------------

_jit_cache: Dict[Tuple, Any] = {}
_jit_lock = threading.Lock()
_JIT_CACHE_MAX = 64


def _table_key(tab: TransitionTable) -> Tuple:
    """Content key for the jit cache: identical patterns share compiled
    steps across operators and restores (an ``id()`` key would recompile
    per operator and pin dead tables forever)."""
    return (tab.n_stages, tuple(tab.strict.tolist()),
            tuple(tab.negated.tolist()), tuple(tab.optional.tolist()),
            tuple(tab.tmin.tolist()), tuple(tab.tmax.tolist()),
            tab.within, tab.skip_past, tab.trailing_negation,
            tab.has_until)


def _make_jit_step(tab: TransitionTable, m_cap: int, e_cap: int):
    """Compile one event step for fixed (M, E) shapes.  The jitted step
    returns the new block plus the candidate match planes and the
    dup/overflow flags; the caller replays flagged steps on the numpy
    path (exact dedup, ring growth) so results stay bit-identical."""
    import jax
    import jax.numpy as jnp

    from jax.experimental import enable_x64

    key = (_table_key(tab), m_cap, e_cap)
    with _jit_lock:
        fn = _jit_cache.get(key)
        if fn is not None:
            return fn

    def step(st, cnt, fst, eln, ev, evh, nlv, skip,
             active, ets, eid, bits, ubits):
        block = (st, cnt, fst, eln, ev, evh, nlv, skip)
        inputs = (active, ets, eid, bits, ubits)
        cand, stepping, overflow_e = _candidates(jnp, tab, block, inputs)
        dup = _dup_prefilter(jnp, cand)
        keep = cand["valid"] & ~cand["ismatch"] & ~dup
        overflow_m = jnp.max(keep.sum(axis=1)) > m_cap
        new_block, _ = _finalize(jnp, m_cap, cand, dup, block, stepping,
                                 ets, tab.skip_past)
        mm = cand["ismatch"] & cand["valid"]
        # any hash-prefilter hit replays on the host: the jit never
        # commits a dedup decision that was not exactly verified
        flags = jnp.stack([overflow_e, overflow_m, jnp.any(dup)])
        return new_block, mm, cand["ev"], cand["eln"], flags

    with enable_x64():
        jitted = jax.jit(step)
    with _jit_lock:
        while len(_jit_cache) >= _JIT_CACHE_MAX:   # bounded: FIFO evict
            _jit_cache.pop(next(iter(_jit_cache)))
        _jit_cache[key] = jitted
    return jitted


def step_jit(tab: TransitionTable, m_cap: int, block, inputs
             ) -> Tuple[StepResult, int]:
    """One event step via the jitted kernel; falls back to
    :func:`step_numpy` when the dispatch flags dup/overflow."""
    from jax.experimental import enable_x64

    e_cap = block[4].shape[2]
    fn = _make_jit_step(tab, m_cap, e_cap)
    with enable_x64():
        new_block, mm, c_ev, c_eln, flags = fn(*block, *inputs)
        flags = np.asarray(flags)
        if flags.any():
            return step_numpy(tab, m_cap, block, inputs)
        mm = np.asarray(mm)
        if mm.any():
            c_ev = np.asarray(c_ev)
            c_eln = np.asarray(c_eln)
            kc = np.argwhere(mm)
            evs = [np.array(c_ev[k, c, :int(c_eln[k, c])], np.int64)
                   for k, c in kc]
            elns = [int(c_eln[k, c]) for k, c in kc]
        else:
            kc = np.empty((0, 2), np.int64)
            evs, elns = [], []
        new_block = tuple(np.asarray(a) for a in new_block)
    return StepResult(new_block, kc, evs, elns), m_cap


def default_kernel() -> str:
    """Kernel backend pick: ``FLINK_TPU_CEP_KERNEL=numpy|jit`` overrides;
    otherwise jit on accelerators, numpy on CPU (the XLA per-step dispatch
    loses to one fused numpy pass there, same verdict as the device
    probe's CPU calibration)."""
    env = os.environ.get(_ENV_KERNEL, "").lower()
    if env in ("numpy", "np", "host"):
        return "numpy"
    if env in ("jit", "jax", "device"):
        return "jit"
    try:
        import jax
        platform = jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — jax unavailable/uninitialized
        return "numpy"
    return "numpy" if platform == "cpu" else "jit"


# ---------------------------------------------------------------------------
# engine calibration (the --device-probe-style measured A/B)
# ---------------------------------------------------------------------------

_calibrated: Optional[bool] = None
_calib_lock = threading.Lock()


def calibrated_vectorized_cep() -> bool:
    """MEASURED verdict, cached process-wide: does the batched kernel beat
    the interpreted NFA on this host/backend?  ``vectorized="auto"`` asks
    this once; ``FLINK_TPU_CEP_VECTORIZED=on|off`` short-circuits (same
    contract as ``FLINK_TPU_DEVICE_PROBE``)."""
    global _calibrated
    if _calibrated is not None:
        return _calibrated
    with _calib_lock:
        if _calibrated is not None:
            return _calibrated
        env = os.environ.get(_ENV_ENGINE, "").lower()
        if env in ("on", "1", "true"):
            _calibrated = True
            return True
        if env in ("off", "0", "false"):
            _calibrated = False
            return False
        _calibrated = _measure_vectorized()
        return _calibrated


def _reset_calibration() -> None:
    """Test hook: drop the cached verdict."""
    global _calibrated
    with _calib_lock:
        _calibrated = None


def _measure_vectorized() -> bool:
    """A/B one synthetic drain (4k keys x 4 events, 2-stage pattern)
    through both engines; ties go to the kernel (it scales with keys,
    the interpreted loop does not)."""
    import time

    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.core.batch import RecordBatch, Watermark

    def build(mode):
        pat = (Pattern.begin("a")
               .where(lambda c: np.asarray(c["v"]) < 0.25)
               .followed_by("b")
               .where(lambda c: np.asarray(c["v"]) > 0.75))
        return CepOperator(pat, "k", lambda m: {"n": 1}, vectorized=mode)

    rng = np.random.default_rng(41)
    n_keys, n_ev = 4096, 4
    keys = np.repeat(np.arange(n_keys, dtype=np.int64), n_ev)
    rng.shuffle(keys)
    vals = rng.random(keys.size)
    ts = np.arange(keys.size, dtype=np.int64)

    def run(mode):
        op = build(mode)
        t0 = time.perf_counter()
        op.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))
        op.process_watermark(Watermark(int(ts[-1])))
        return time.perf_counter() - t0

    run("on")                    # warm compiles/caches outside the timing
    t_vec = min(run("on") for _ in range(2))
    t_int = min(run("off") for _ in range(2))
    return t_vec <= t_int


# ---------------------------------------------------------------------------
# interpreted-state bridge (degrade / snapshots / restore)
# ---------------------------------------------------------------------------

def encode_partials(partials, m_cap: int, e_cap: int):
    """Interpreted ``_Partial`` list -> one key's row planes (grown caps
    returned alongside; callers fold them into the sticky high-water)."""
    n = len(partials)
    while m_cap < max(n, 1):
        m_cap *= 2
    longest = max((len(p.events) for p in partials), default=0)
    while e_cap < max(longest, 1):
        e_cap *= 2
    st = np.zeros(m_cap, np.int32)
    cnt = np.zeros(m_cap, np.int32)
    fst = np.full(m_cap, LONG_MIN, np.int64)
    eln = np.zeros(m_cap, np.int32)
    ev = np.zeros((m_cap, e_cap), np.int64)
    evh = np.zeros(m_cap, np.int32)
    for m, p in enumerate(partials):
        st[m] = p.stage_i
        cnt[m] = p.count
        fst[m] = p.first_ts
        eln[m] = len(p.events)
        for e, (stage, eid) in enumerate(p.events):
            ev[m, e] = pack_event(stage, eid)
        evh[m] = event_list_hash(ev[m, :eln[m]])
    return (st, cnt, fst, eln, ev, evh, np.int32(n)), m_cap, e_cap


def decode_partials(row_block, nlive: int):
    """One key's row planes -> the interpreted ``_Partial`` list."""
    from flink_tpu.cep.operator import _Partial

    st, cnt, fst, eln, ev = row_block[:5]
    out = []
    for m in range(int(nlive)):
        out.append(_Partial(int(st[m]), int(cnt[m]),
                            unpack_events(ev[m, :int(eln[m])]),
                            int(fst[m])))
    return out
