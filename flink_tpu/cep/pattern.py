"""CEP Pattern API.

Analog of ``flink-libraries/flink-cep``'s fluent pattern builder
(``cep/pattern/Pattern.java``): a pattern is a sequence of *stages*, each
with a vectorized predicate (``SimpleCondition`` analog — here a columnar
closure over the batch, so condition evaluation is one vector op per stage
per batch), a contiguity mode (``next`` = strict, ``followedBy`` = relaxed,
``PatternStream`` semantics), a quantifier (``times``/``oneOrMore``/
``optional``, ``Quantifier.java``), and an optional ``within`` window.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Mapping, Optional

import numpy as np

#: predicate over the batch's columns dict -> bool mask [B]
Condition = Callable[[Mapping[str, Any]], np.ndarray]


class AfterMatchSkipStrategy:
    """What happens to partial matches after a match emits
    (``AfterMatchSkipStrategy.java``)."""

    NO_SKIP = "no_skip"
    SKIP_PAST_LAST_EVENT = "skip_past_last_event"


@dataclass(frozen=True)
class Stage:
    """One pattern element (``Pattern`` node + its ``Quantifier``)."""

    name: str
    condition: Optional[Condition] = None
    #: 'strict' (next), 'relaxed' (followedBy)
    contiguity: str = "relaxed"
    times_min: int = 1
    times_max: Optional[int] = 1   # None = unbounded (oneOrMore)
    optional: bool = False
    #: not-pattern (``notNext``/``notFollowedBy``): the condition must NOT
    #: match — strict checks exactly the next event, relaxed forbids any
    #: matching event before the following stage matches
    negated: bool = False
    #: greedy looping stage: when an event matches both the loop and the
    #: following stage, the loop consumes it (``Quantifier.greedy``)
    greedy: bool = False
    #: loop stop condition (``oneOrMore().until(cond)``): a matching event
    #: closes the loop without being taken into it
    until: Optional[Condition] = None

    def matches(self, cols: Mapping[str, Any]) -> np.ndarray:
        n = int(np.shape(next(iter(cols.values())))[0]) if cols else 0
        if self.condition is None:
            return np.ones(n, bool)
        return np.asarray(self.condition(cols), bool)

    def until_matches(self, cols: Mapping[str, Any]) -> np.ndarray:
        n = int(np.shape(next(iter(cols.values())))[0]) if cols else 0
        if self.until is None:
            return np.zeros(n, bool)
        return np.asarray(self.until(cols), bool)


class Pattern:
    """Fluent pattern builder: ``Pattern.begin("a").where(...).followed_by("b")...``"""

    def __init__(self, stages: List[Stage], within_ms: Optional[int] = None,
                 skip_strategy: str = AfterMatchSkipStrategy.NO_SKIP):
        self.stages = stages
        self.within_ms = within_ms
        self.skip_strategy = skip_strategy

    # -- construction --------------------------------------------------------
    @staticmethod
    def begin(name: str,
              skip_strategy: str = AfterMatchSkipStrategy.NO_SKIP) -> "Pattern":
        return Pattern([Stage(name, contiguity="relaxed")],
                       skip_strategy=skip_strategy)

    def _mod_last(self, **kw) -> "Pattern":
        stages = self.stages[:-1] + [replace(self.stages[-1], **kw)]
        return Pattern(stages, self.within_ms, self.skip_strategy)

    def where(self, condition: Condition) -> "Pattern":
        last = self.stages[-1]
        if last.condition is None:
            return self._mod_last(condition=condition)
        prev = last.condition  # AND with existing (Pattern.where chaining)
        return self._mod_last(condition=lambda cols: np.asarray(
            prev(cols), bool) & np.asarray(condition(cols), bool))

    def or_where(self, condition: Condition) -> "Pattern":
        last = self.stages[-1]
        if last.condition is None:
            return self._mod_last(condition=condition)
        prev = last.condition
        return self._mod_last(condition=lambda cols: np.asarray(
            prev(cols), bool) | np.asarray(condition(cols), bool))

    def next(self, name: str) -> "Pattern":
        """Strict contiguity: the very next event must match."""
        return Pattern(self.stages + [Stage(name, contiguity="strict")],
                       self.within_ms, self.skip_strategy)

    def followed_by(self, name: str) -> "Pattern":
        """Relaxed contiguity: non-matching events in between are skipped."""
        return Pattern(self.stages + [Stage(name, contiguity="relaxed")],
                       self.within_ms, self.skip_strategy)

    def followed_by_any(self, name: str) -> "Pattern":
        """Non-deterministic relaxed contiguity (``followedByAny``): matching
        events may also be skipped, yielding every combination."""
        return Pattern(self.stages + [Stage(name, contiguity="relaxed_any")],
                       self.within_ms, self.skip_strategy)

    def not_next(self, name: str) -> "Pattern":
        """``notNext``: the event IMMEDIATELY after the previous stage's
        match must not satisfy the condition (``NFA.java`` StateType.Stop
        via strict negation)."""
        if len(self.stages) == 0:
            raise ValueError("a pattern cannot begin with a not-stage")
        return Pattern(self.stages + [Stage(name, contiguity="strict",
                                            negated=True)],
                       self.within_ms, self.skip_strategy)

    def not_followed_by(self, name: str) -> "Pattern":
        """``notFollowedBy``: NO event matching the condition may occur
        between the previous stage's match and the following stage's match.
        As in the reference, it cannot END a pattern unless ``within`` is
        set (checked at operator build)."""
        if len(self.stages) == 0:
            raise ValueError("a pattern cannot begin with a not-stage")
        return Pattern(self.stages + [Stage(name, contiguity="relaxed",
                                            negated=True)],
                       self.within_ms, self.skip_strategy)

    def times(self, n: int, n_max: Optional[int] = None) -> "Pattern":
        if self.stages[-1].negated:
            raise ValueError("a not-stage cannot be quantified")
        return self._mod_last(times_min=n,
                              times_max=n_max if n_max is not None else n)

    def one_or_more(self) -> "Pattern":
        if self.stages[-1].negated:
            raise ValueError("a not-stage cannot be quantified")
        return self._mod_last(times_min=1, times_max=None)

    def greedy(self) -> "Pattern":
        """Looping quantifier consumes preferentially: an event matching
        both the loop and the next stage extends the loop
        (``Quantifier.greedy``)."""
        last = self.stages[-1]
        if last.times_max == 1 and last.times_min == 1:
            raise ValueError("greedy() applies to a looping stage "
                             "(times/one_or_more)")
        return self._mod_last(greedy=True)

    def until(self, condition: Condition) -> "Pattern":
        """Stop condition for ``one_or_more`` loops (``Pattern.until``):
        a matching event closes the loop and is not taken into it."""
        last = self.stages[-1]
        if last.times_max is not None:
            raise ValueError("until() applies to an unbounded loop "
                             "(one_or_more)")
        return self._mod_last(until=condition)

    def optional(self) -> "Pattern":
        if self.stages[-1].negated:
            raise ValueError("a not-stage cannot be optional")
        return self._mod_last(optional=True)

    def within(self, ms: int) -> "Pattern":
        return Pattern(self.stages, ms, self.skip_strategy)

    @property
    def names(self) -> List[str]:
        return [s.name for s in self.stages]
