"""flink-tpu: a TPU-native stream- and batch-processing framework.

A from-scratch re-design of Apache Flink's capabilities (reference at
/root/reference, v1.14-SNAPSHOT) around JAX/XLA/Pallas: records flow as
columnar micro-batches, keyed state lives as key-group-sharded dense arrays in
device HBM, windowed aggregation is an XLA-fused segment-combine, and
multi-chip scaling rides ``jax.sharding.Mesh`` + ``shard_map`` collectives
over ICI instead of a Netty shuffle.
"""

__version__ = "0.1.0"

from flink_tpu.config.config_option import ConfigOption, Configuration  # noqa: F401
