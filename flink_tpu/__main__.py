"""Command-line entrypoint — the ``flink`` CLI analog (``CliFrontend``).

    python -m flink_tpu run my_job.py [--parallelism N] [--cluster]
    python -m flink_tpu sql "SELECT ..." --table name=path.csv
    python -m flink_tpu info

``run`` executes a job script: the script either defines ``main(env)`` or
just uses a module-level ``env = StreamExecutionEnvironment()`` pipeline
(``env.execute()`` inside the script also works).
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys

# Cluster workers spawned from a CPU-forced test context must stay on CPU
# instead of dialing the one shared (possibly busy) real chip.
from flink_tpu.utils.platform import honor_jax_platforms

honor_jax_platforms()


def _cmd_run(args) -> int:
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    if args.workers:
        # multi-process execution: the job must be a module:function
        # reference (the jar-shipping model of cluster.distributed)
        if ":" not in args.script or args.script.endswith(".py"):
            print("error: --workers needs a module:function job reference "
                  "(e.g. my_job:build), importable in every worker",
                  file=sys.stderr)
            return 2
        import os as _os

        from flink_tpu.cluster.distributed import ProcessCluster
        from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage

        storage = (FileCheckpointStorage(args.checkpoint_dir)
                   if args.checkpoint_dir else None)
        ha_store = None
        if getattr(args, "ha_dir", None):
            from flink_tpu.runtime.ha import FileHaStore
            ha_store = FileHaStore(args.ha_dir)
        pc = ProcessCluster(
            args.script, n_workers=args.workers,
            checkpoint_storage=storage,
            checkpoint_interval_ms=args.checkpoint_interval,
            restart_attempts=args.restart_attempts,
            ha_store=ha_store,
            extra_sys_path=(_os.getcwd(),))
        res = pc.run(timeout_s=86400.0, restore=_load_restore(args))
        print(f"job finished: {res['state']} (attempts={res['attempts']}, "
              f"checkpoints={len(res['completed_checkpoints'])})")
        if res["state"] != "FINISHED":
            print(f"error: {res['error']}", file=sys.stderr)
            return 1
        return 0

    env = StreamExecutionEnvironment(parallelism=args.parallelism)
    ns = runpy.run_path(args.script, init_globals={"env": env})
    main = ns.get("main")
    if callable(main):
        main(env)
    if getattr(env, "_last_executor", None) is not None or \
            getattr(env, "_last_cluster", None) is not None:
        # the script executed itself: don't run the job a second time
        print("job executed by script")
        return 0
    if not env._sinks:
        print(f"error: {args.script} registered no sinks on the provided "
              f"'env' (use the injected env or define main(env)); "
              f"nothing to run", file=sys.stderr)
        return 2
    if args.cluster:
        res = env.execute_cluster(job_name=args.script)
        print(f"job finished: {res.state} in {res.net_runtime_ms:.0f} ms")
        return 0 if res.state == "FINISHED" else 1
    res = env.execute(job_name=args.script)
    print(f"job finished in {res.net_runtime_ms:.0f} ms "
          f"({res.records_emitted} records)")
    return 0


def _cmd_sql(args) -> int:
    from flink_tpu.sql.table_env import TableEnvironment

    tenv = TableEnvironment(parallelism=args.parallelism)
    for spec in args.table or []:
        name, path = spec.split("=", 1)
        fmt = path.rsplit(".", 1)[-1]
        from flink_tpu import formats
        from flink_tpu.core.batch import RecordBatch
        batches = list(formats.reader_for(fmt)(path))
        batch = RecordBatch.concat(batches) if batches else RecordBatch({})
        tenv.register_collection(name, columns=dict(batch.columns))
    tenv.execute_sql(args.query).print()
    return 0


def _cmd_repl(args) -> int:
    """Interactive shell with a preloaded environment — the Scala REPL
    (``FlinkShell.scala``) analog, Python-native."""
    import code

    import numpy as np

    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.sql.table_env import TableEnvironment

    env = StreamExecutionEnvironment()
    tenv = TableEnvironment()
    banner = ("flink-tpu shell\n"
              "  env  = StreamExecutionEnvironment()  "
              "(env.from_collection(...).key_by(...)...)\n"
              "  tenv = TableEnvironment()            "
              "(tenv.register_collection / execute_sql)\n"
              "  np   = numpy")
    code.interact(banner=banner, local={"env": env, "tenv": tenv, "np": np},
                  exitmsg="")
    return 0


def _cmd_rest(args) -> int:
    """Cluster commands against a running REST endpoint
    (``flink list/cancel/savepoint`` parity)."""
    import json
    import urllib.error
    import urllib.request

    base = args.url.rstrip("/")

    def req(path, method="GET"):
        """-> (status_code, parsed body); non-2xx responses are DATA here
        (the server answers 404/409 with JSON bodies), not tracebacks."""
        rq = urllib.request.Request(base + path, method=method)
        try:
            with urllib.request.urlopen(rq, timeout=60) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.fp.read())
            except (ValueError, OSError):
                return e.code, {"error": str(e)}

    if args.cmd == "list":
        _st, body = req("/jobs")
        for j in body.get("jobs", []):
            print(f"{j['id']}  {j['state']:<10} {j['name']}")
        return 0
    if args.cmd == "status":
        st, body = req(f"/jobs/{args.job_id}")
        print(json.dumps(body, indent=2))
        return 0 if st == 200 else 1
    if args.cmd == "cancel":
        st, body = req(f"/jobs/{args.job_id}", "PATCH")
        print(body.get("status", body.get("error")))
        return 0 if st < 400 else 1
    if args.cmd == "savepoint":
        st, body = req(f"/jobs/{args.job_id}/savepoints", "POST")
        if body.get("status") == "completed":
            print(f"completed: checkpoint {body.get('checkpoint_id')}")
            return 0
        print(body.get("status", body.get("error")))
        return 1
    if args.cmd == "stop":
        # stop-with-savepoint (`flink stop` analog)
        st, body = req(f"/jobs/{args.job_id}/stop", "POST")
        if body.get("status") == "stopped":
            print(f"stopped: checkpoint {body.get('checkpoint_id')}")
            return 0
        print(body.get("status", body.get("error")))
        return 1
    return 2


def _cmd_info(_args) -> int:
    import jax

    import flink_tpu
    from flink_tpu.native import build_error, native_available

    print(f"flink-tpu {getattr(flink_tpu, '__version__', 'dev')}")
    print(f"jax {jax.__version__}; devices: "
          f"{[f'{d.platform}:{d.id}' for d in jax.devices()]}")
    print(f"native layer: {'ok' if native_available() else build_error()}")
    return 0


def _cmd_worker(args) -> int:
    from flink_tpu.cluster.distributed import _WorkerRuntime

    host, port = args.coordinator.rsplit(":", 1)
    return _WorkerRuntime(args.index, args.workers, args.job,
                          host, int(port), bind_host=args.bind,
                          advertise_host=args.advertise).run()


def _cmd_logservice(args) -> int:
    from flink_tpu.connectors.log_service import LogServiceBroker

    broker = LogServiceBroker(args.dir, host=args.host, port=args.port)
    print(f"log service broker on {broker.url} (dir={args.dir})")
    try:
        broker.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _cmd_s3(args) -> int:
    from flink_tpu.filesystems import S3CompatibleServer

    srv = S3CompatibleServer(args.dir, access_key=args.access_key,
                             secret_key=args.secret_key,
                             region=args.region,
                             host=args.host, port=args.port)
    print(f"S3-compatible endpoint on {srv.url} (dir={args.dir}, "
          f"SigV4 region={args.region})")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


_QUICKSTART_JOB = '''\
"""__NAME__: streaming windowed wordcount (the SocketWindowWordCount shape).

Run it:            python job.py
Multi-process:     python -m flink_tpu run --workers 2 job:build
With checkpoints:  see README.md
"""

import numpy as np

from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def build():
    env = StreamExecutionEnvironment()
    # demo input: replace with env.from_source(KafkaWireSource(...)) /
    # LogServiceSource / a file source for real data
    n = 10_000
    words = np.asarray(["tpu", "flink", "stream"], object)[
        np.arange(n) % 3]
    from flink_tpu.core.functions import CountAggregator
    (env.from_collection(columns={"word": words,
                                  "ts": np.arange(n, dtype=np.int64)},
                         batch_size=512, timestamp_column="ts")
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1_000))
        .aggregate(CountAggregator(), value_column="ts",
                   output_column="count")
        .print())
    return env


if __name__ == "__main__":
    build().execute()
'''

_QUICKSTART_TEST = '''\
"""Operator-level test for the quickstart job (the
KeyedOneInputOperatorTestHarness pattern — no cluster needed)."""

import numpy as np

from flink_tpu.core.functions import CountAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.testing import KeyedOneInputOperatorHarness
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def test_counts_per_window():
    # the jitted update step needs a NUMERIC value column (string keys
    # stay host-side)
    op = WindowAggOperator(TumblingEventTimeWindows.of(1_000),
                           CountAggregator(), key_column="word",
                           value_column="one")
    h = KeyedOneInputOperatorHarness(op)
    h.process_elements([{"word": "tpu", "one": 1},
                        {"word": "tpu", "one": 1},
                        {"word": "flink", "one": 1}], [10, 20, 30])
    h.process_watermark(999)
    got = {r["word"]: r["result"] for r in h.extract_output_rows()}
    assert got == {"tpu": 2, "flink": 1}
'''

_QUICKSTART_README = '''\
# __NAME__

A flink-tpu project skeleton (the quickstart-archetype analog).

## Run

    python job.py                       # local, single process
    python -m pytest test_job.py -q     # operator-level test

## Scale out

    python -m flink_tpu run --workers 2 job:build

## Checkpointing + restore

    from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage
    env.enable_checkpointing(1000, storage=FileCheckpointStorage("./ckpt"))

Savepoints, REST, SQL, the device mesh (`env.set_mesh(...)`), Kafka and
S3 integration: see `docs/quickstart.md` in the framework repo.
'''


def _cmd_quickstart(args) -> int:
    import os

    os.makedirs(args.dir, exist_ok=True)
    wrote = []
    for fname, tpl in (("job.py", _QUICKSTART_JOB),
                       ("test_job.py", _QUICKSTART_TEST),
                       ("README.md", _QUICKSTART_README)):
        path = os.path.join(args.dir, fname)
        if os.path.exists(path) and not args.force:
            print(f"skip {path} (exists; --force to overwrite)")
            continue
        with open(path, "w") as f:
            f.write(tpl.replace("__NAME__", args.name))
        wrote.append(fname)
    print(f"quickstart project in {args.dir}: {', '.join(wrote)}")
    print(f"  cd {args.dir} && python job.py")
    return 0


def _cmd_kafka(args) -> int:
    from flink_tpu.connectors.kafka import KafkaWireBroker

    b = KafkaWireBroker(host=args.host, port=args.port,
                        directory=args.dir)
    for t in args.topic or []:
        name, _, parts = t.partition(":")
        b.create_topic(name, int(parts or 1))
    b.start()
    print(f"kafka-wire broker on {b.host}:{b.port} (dir={args.dir})")
    try:
        b._thread.join()
    except KeyboardInterrupt:
        b.stop()
    return 0


def _cmd_objectstore(args) -> int:
    from flink_tpu.runtime.checkpoint.objectstore import ObjectStoreServer

    store = ObjectStoreServer(args.dir, host=args.host, port=args.port)
    print(f"object store on {store.url} (dir={args.dir})")
    try:
        store.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _load_restore(args):
    """--restore/-s: explicit savepoint/checkpoint path (or None)."""
    if not getattr(args, "restore", None):
        return None
    from flink_tpu.runtime.checkpoint.storage import read_savepoint
    return read_savepoint(args.restore)


def _cmd_coordinate(args) -> int:
    import json as _json

    from flink_tpu.cluster.distributed import (ProcessCluster,
                                               _security_from_env)
    from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage

    storage = (FileCheckpointStorage(args.checkpoint_dir)
               if args.checkpoint_dir else None)
    ha_store = None
    if getattr(args, "ha_dir", None):
        from flink_tpu.runtime.ha import FileHaStore
        ha_store = FileHaStore(args.ha_dir)
    host, port = args.listen.rsplit(":", 1)
    # same FLINK_TPU_SSL_*/FLINK_TPU_AUTH_TOKEN env contract as workers —
    # on k8s both containers receive the secrets the same way
    try:
        pc = ProcessCluster(args.job, n_workers=args.workers,
                            checkpoint_storage=storage,
                            checkpoint_interval_ms=args.checkpoint_interval,
                            spawn=False, bind_host=host,
                            listen_port=int(port),
                            ha_store=ha_store,
                            security=_security_from_env())
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    res = pc.run(timeout_s=args.timeout, restore=_load_restore(args))
    print(_json.dumps({k: v for k, v in res.items() if k != "rows"},
                      default=str))
    return 0 if res["state"] == "FINISHED" else 1


def build_parser() -> "argparse.ArgumentParser":
    """The full CLI surface (exposed so deployment renderers can validate
    the commands they emit against the real parser)."""
    p = argparse.ArgumentParser(prog="flink_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("run", help="run a job script")
    pr.add_argument("script",
                    help="a .py script (local/MiniCluster) or, with "
                         "--workers, a module:function job reference")
    pr.add_argument("--parallelism", "-p", type=int, default=1)
    pr.add_argument("--cluster", action="store_true",
                    help="run on the in-process MiniCluster (parallel subtasks)")
    pr.add_argument("--workers", type=int, default=0,
                    help="run on a MULTI-PROCESS cluster with this many "
                         "worker processes")
    pr.add_argument("--checkpoint-dir", default=None)
    pr.add_argument("--checkpoint-interval", type=int, default=0)
    pr.add_argument("--restart-attempts", type=int, default=0)
    pr.add_argument("--restore", "-s", default=None,
                    help="savepoint/checkpoint path to restore from "
                         "(a fresh run never resumes implicitly)")
    pr.add_argument("--ha-dir", default=None,
                    help="FileHaStore directory enabling coordinator HA: "
                         "leader lease + epoch fencing + job recovery "
                         "(high-availability.storageDir)")
    pr.set_defaults(fn=_cmd_run)
    ps = sub.add_parser("sql", help="run a SQL query")
    ps.add_argument("query")
    ps.add_argument("--table", action="append",
                    help="name=path.csv|jsonl|ftb (repeatable)")
    ps.add_argument("--parallelism", "-p", type=int, default=1)
    ps.set_defaults(fn=_cmd_sql)
    pi = sub.add_parser("info", help="environment info")
    pi.set_defaults(fn=_cmd_info)
    prl = sub.add_parser("repl", help="interactive shell with a preloaded "
                         "environment (Scala-shell analog)")
    prl.set_defaults(fn=_cmd_repl)
    pw = sub.add_parser(
        "worker", help="TaskExecutor worker process (spawned by "
        "cluster.distributed.ProcessCluster)")
    pw.add_argument("--index", type=int, required=True)
    pw.add_argument("--workers", type=int, required=True)
    pw.add_argument("--job", required=True)
    pw.add_argument("--coordinator", required=True)
    pw.add_argument("--bind", default="127.0.0.1",
                    help="data-plane bind address (0.0.0.0 on k8s)")
    pw.add_argument("--advertise", default=None,
                    help="address peers dial (pod IP on k8s)")
    pw.set_defaults(fn=_cmd_worker)
    pco = sub.add_parser(
        "coordinate", help="cluster coordinator that WAITS for externally "
        "started workers (k8s / multi-host deployments); non-loopback "
        "--listen requires TLS env vars (FLINK_TPU_SSL_*) or "
        "FLINK_TPU_ALLOW_INSECURE=1")
    pco.add_argument("--job", required=True)
    pco.add_argument("--workers", type=int, required=True)
    pco.add_argument("--listen", default="0.0.0.0:6123")
    pco.add_argument("--checkpoint-dir", default=None)
    pco.add_argument("--checkpoint-interval", type=int, default=0)
    pco.add_argument("--restore", "-s", default=None,
                    help="savepoint/checkpoint path to restore from")
    pco.add_argument("--ha-dir", default=None,
                     help="FileHaStore directory enabling coordinator HA "
                          "(a standby coordinator pointed at the same dir "
                          "takes over at epoch + 1)")
    pco.add_argument("--timeout", type=float, default=86400.0)
    pco.set_defaults(fn=_cmd_coordinate)
    pls = sub.add_parser("logservice", help="standalone durable log broker "
                         "(Kafka-analog service any process can dial)")
    pls.add_argument("--dir", required=True)
    pls.add_argument("--host", default="127.0.0.1")
    pls.add_argument("--port", type=int, default=9092)
    pls.set_defaults(fn=_cmd_logservice)
    pos = sub.add_parser("objectstore", help="standalone HTTP object store "
                         "(S3-analog checkpoint/savepoint backend)")
    pos.add_argument("--dir", required=True)
    pos.add_argument("--host", default="127.0.0.1")
    pos.add_argument("--port", type=int, default=9000)
    pos.set_defaults(fn=_cmd_objectstore)
    ps3 = sub.add_parser("s3", help="S3-compatible endpoint (real SigV4 "
                         "REST dialect) over a local directory")
    ps3.add_argument("--dir", required=True)
    ps3.add_argument("--access-key", required=True)
    ps3.add_argument("--secret-key", required=True)
    ps3.add_argument("--region", default="us-east-1")
    ps3.add_argument("--host", default="127.0.0.1")
    ps3.add_argument("--port", type=int, default=9001)
    ps3.set_defaults(fn=_cmd_s3)
    pk = sub.add_parser("kafka", help="broker speaking the Kafka v0 binary "
                        "wire protocol over per-partition logs")
    pk.add_argument("--dir", default=None)
    pk.add_argument("--host", default="127.0.0.1")
    pk.add_argument("--port", type=int, default=9092)
    pk.add_argument("--topic", action="append",
                    help="name[:partitions], repeatable")
    pk.set_defaults(fn=_cmd_kafka)
    pq = sub.add_parser("quickstart", help="generate a runnable project "
                        "skeleton (job + test + README)")
    pq.add_argument("dir")
    pq.add_argument("--name", default="my-flink-tpu-job")
    pq.add_argument("--force", action="store_true")
    pq.set_defaults(fn=_cmd_quickstart)
    for name, needs_job in (("list", False), ("status", True),
                            ("cancel", True), ("savepoint", True),
                            ("stop", True)):
        pc = sub.add_parser(name, help=f"{name} jobs via the REST endpoint")
        pc.add_argument("--url", required=True,
                        help="REST endpoint, e.g. http://127.0.0.1:8081")
        if needs_job:
            pc.add_argument("job_id")
        pc.set_defaults(fn=_cmd_rest)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
