"""Queryable state: point lookups against live keyed state.

Analog of ``flink-queryable-state`` (``KvStateServerImpl`` +
``KvStateServerHandler`` on each TM, ``KvStateRegistry`` in the runtime,
client proxy with location lookup): states registered as queryable get point
reads over a TCP server while the job runs.

Protocol: length-prefixed JSON ``[state_name, key]`` request ->
length-prefixed JSON ``[status, value]`` (``ok/missing/err``).  JSON, not
pickle: requests arrive over the network from untrusted clients, and
unpickling attacker bytes is remote code execution.  Keys are therefore
limited to JSON scalars (str/int/float/bool).
Reads are dirty by design — same consistency contract as the reference
(queries see live, uncommitted state) — and read-only: lookups use the
non-inserting key index path so the query thread never mutates the task
thread's backend (single-writer preserved).
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

_LEN = struct.Struct("<I")


class KvStateRegistry:
    """Registered queryable states (``KvStateRegistry.java`` analog).

    ``register(name, backend, state)`` exposes a state instance; lookups
    read through the backend's NON-mutating path.
    """

    def __init__(self):
        self._entries: Dict[str, Tuple[Any, Any]] = {}
        self._lock = threading.Lock()

    def register(self, state_name: str, backend, state) -> None:
        with self._lock:
            self._entries[state_name] = (backend, state)

    def unregister(self, state_name: str) -> None:
        with self._lock:
            self._entries.pop(state_name, None)

    def names(self):
        with self._lock:
            return sorted(self._entries)

    def lookup(self, state_name: str, key) -> Tuple[str, Any]:
        with self._lock:
            entry = self._entries.get(state_name)
        if entry is None:
            return "err", f"unknown state {state_name!r}; have {self.names()}"
        backend, state = entry
        idx = getattr(backend, "_index", None)
        if idx is None:
            return "missing", None
        slots = idx.lookup(np.asarray([key]))    # NON-inserting
        slot = int(slots[0])
        if slot < 0:
            return "missing", None
        got = state.get_rows(np.asarray([slot]))
        if isinstance(got, tuple):               # (values, alive)
            vals, alive = got
            if not bool(np.asarray(alive)[0]):
                return "missing", None
            return "ok", _plain(np.asarray(vals)[0])
        return "ok", _plain(list(got)[0])


def _plain(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def _json_safe(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return str(v)


class QueryableStateServer:
    """TCP server answering point queries (``KvStateServerImpl`` analog)."""

    def __init__(self, registry: KvStateRegistry, host: str = "127.0.0.1",
                 port: int = 0):
        self.registry = registry
        registry_ref = registry

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        hdr = _recv_exact(self.request, _LEN.size)
                        if hdr is None:
                            return
                        (n,) = _LEN.unpack(hdr)
                        payload = _recv_exact(self.request, n)
                        if payload is None:
                            return
                        try:
                            state_name, key = json.loads(payload)
                        except (ValueError, TypeError):
                            resp = ("err", "malformed request")
                        else:
                            resp = registry_ref.lookup(state_name, key)
                        data = json.dumps(resp, default=_json_safe).encode()
                        self.request.sendall(_LEN.pack(len(data)) + data)
                except (ConnectionError, OSError):
                    return

        self._server = socketserver.ThreadingTCPServer((host, port), Handler,
                                                       bind_and_activate=True)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="kv-state-server", daemon=True)

    def start(self) -> "QueryableStateServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class QueryableStateClient:
    """``QueryableStateClient`` analog: connect + get."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)

    def get(self, state_name: str, key) -> Any:
        """Point lookup; raises KeyError if the key has no state."""
        payload = json.dumps([state_name, key]).encode()
        self._sock.sendall(_LEN.pack(len(payload)) + payload)
        hdr = _recv_exact(self._sock, _LEN.size)
        if hdr is None:
            raise ConnectionError("server closed")
        (n,) = _LEN.unpack(hdr)
        data = _recv_exact(self._sock, n)
        if data is None:
            raise ConnectionError("server closed mid-response")
        status, value = json.loads(data)
        if status == "ok":
            return value
        if status == "missing":
            raise KeyError(key)
        raise RuntimeError(value)

    def close(self) -> None:
        self._sock.close()


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
