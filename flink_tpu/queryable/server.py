"""Queryable state: the wire layer of the serving tier.

Analog of ``flink-queryable-state`` (``KvStateServerImpl`` +
``KvStateServerHandler`` on each TM, ``KvStateRegistry`` in the runtime,
client proxy with location lookup), grown into the read path of ISSUE-9:
the registry fronts three entry kinds —

- **live views** (``view.WindowReadView``): barrier-free fire-time
  snapshots published by the operator, sharded per subtask and routed by
  the record's own key-group assignment;
- **checkpoint replicas** (``replica.CheckpointReplica``): lookups at the
  last-completed-checkpoint consistency level, never touching the hot path;
- **legacy backend states** (``register(name, backend, state)``): the
  original dirty point-read against a keyed backend's non-inserting index
  path, kept for compatibility.

Protocols (negotiated per request by one byte peek):

- **binary columnar** (``wire.py``, ISSUE-13): dtype-tagged ndarray
  columns off the immutable view/replica segments, zero per-key Python
  objects — the production-QPS path;
- **length-prefixed JSON** (the PR-9 protocol, kept as the fallback so old
  clients keep working): ``[state_name, key]`` (legacy point read)
  -> ``[status, value]``; ``{"state": s, "keys": [...], "consistency":
  "live"|"checkpoint"}`` (batched read) -> ``["ok", {"found": [...],
  "values": [...], "tags": {...}}]``; ``{"routing": true}`` -> ``["ok",
  <routing table>]`` (the key-group -> endpoint map clients fan out on).

JSON/binary, not pickle: requests arrive over the network from untrusted
clients, and unpickling attacker bytes is remote code execution.  Keys are
therefore limited to JSON scalars (str/int/float/bool) or raw int64.

Security: an unknown-state error reply names NOTHING — the registered
state list is logged server-side only (the old reply echoed the full list
to untrusted network clients).
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.cluster.net import recv_exact as _recv_exact
from flink_tpu.queryable import wire
from flink_tpu.queryable.view import plain as _plain

_LEN = struct.Struct("<I")
_LOG = logging.getLogger("flink_tpu.queryable")

#: batched requests are bounded: a hostile 100M-key request must not make
#: the server materialize 100M answers
MAX_BATCH_KEYS = 1 << 16


class _LiveEntry:
    """Per-subtask live views of ONE registered state + the routing
    geometry (a query routes to the owning subtask exactly like a
    record: murmur key group -> contiguous key-group range)."""

    __slots__ = ("views", "parallelism", "max_parallelism")

    def __init__(self, views: List, parallelism: int, max_parallelism: int):
        self.views = list(views)
        self.parallelism = int(parallelism)
        self.max_parallelism = int(max_parallelism)

    @property
    def has_views(self) -> bool:
        """False for a pure routing placeholder (every view None — a
        coordinator advertising worker endpoints holds no views): live
        lookups against one must ERROR, not answer all-not-found."""
        return any(v is not None for v in self.views)

    @property
    def epoch(self) -> int:
        """Content version across every subtask's view (publish counter
        sum) — the hot-key cache's live invalidation signal."""
        return sum(v.epoch for v in self.views if v is not None)

    def lookup_batch(self, keys) -> Dict[str, Any]:
        from flink_tpu.queryable.view import coerce_keys, route_keys
        keys = coerce_keys(keys)
        n = len(keys)
        found = np.zeros(n, bool)
        values: List[Optional[Dict[str, Any]]] = [None] * n
        owner = route_keys(keys, self.parallelism, self.max_parallelism)
        tags: List[Dict[str, Any]] = []
        for sub in np.unique(owner).tolist():
            if not (0 <= sub < len(self.views)):
                continue
            view = self.views[int(sub)]
            if view is None:       # per-worker registry: foreign subtask
                continue
            sel = np.flatnonzero(owner == sub)
            f, v, t = view.lookup_batch(np.asarray(keys)[sel])
            tags.append(t)
            for j, qi in enumerate(sel.tolist()):
                if f[j]:
                    found[qi] = True
                    values[qi] = v[j]
        return {"found": found.tolist(), "values": values,
                "tags": merge_live_tags(tags)}

    def lookup_batch_columnar(self, keys) -> Tuple[np.ndarray,
                                                   Dict[str, np.ndarray],
                                                   Dict[str, Any]]:
        """Binary-wire twin of :meth:`lookup_batch`: per-subtask columnar
        gathers merged into dense answer columns, zero per-key objects."""
        from flink_tpu.queryable.view import coerce_keys, route_keys
        keys = coerce_keys(keys)
        n = len(keys)
        found = np.zeros(n, bool)
        cols: Dict[str, np.ndarray] = {}
        owner = route_keys(keys, self.parallelism, self.max_parallelism)
        tags: List[Dict[str, Any]] = []
        for sub in np.unique(owner).tolist():
            if not (0 <= sub < len(self.views)):
                continue
            view = self.views[int(sub)]
            if view is None:
                continue
            sel = np.flatnonzero(owner == sub)
            f, c, t = view.lookup_batch_columnar(np.asarray(keys)[sel])
            tags.append(t)
            hit = np.flatnonzero(f)
            if hit.size == 0:
                continue
            qsel = sel[hit]
            for name, arr in c.items():
                out = cols.get(name)
                if out is None:
                    out = cols[name] = (np.empty(n, object)
                                        if arr.dtype.kind == "O"
                                        else np.zeros(n, arr.dtype))
                got = arr[hit]
                out[qsel] = got if out.dtype == arr.dtype \
                    else got.astype(out.dtype)
            found[qsel] = True
        return found, cols, merge_live_tags(tags)


def merge_live_tags(tags: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One live answer's tags from several subtask views (or fanned-out
    sub-batches — the routed client merges with the same rule): the
    conservative reading, i.e. the OLDEST watermark/checkpoint any
    contributing view reflects."""
    wm = [t["watermark"] for t in tags if t.get("watermark") is not None]
    ck = [t["checkpoint_id"] for t in tags
          if t.get("checkpoint_id") is not None]
    return {"consistency": "live",
            "watermark": min(wm) if wm else None,
            "checkpoint_id": min(ck) if ck else None}


class KvStateRegistry:
    """Registered queryable states (``KvStateRegistry.java`` analog),
    extended with live views and checkpoint replicas."""

    def __init__(self):
        self._entries: Dict[str, Tuple[Any, Any]] = {}
        self._live: Dict[str, _LiveEntry] = {}
        self._replicas: Dict[str, Any] = {}
        #: client-side routing surface: per-state subtask -> (host, port)
        #: (per-worker serving), plus a default endpoint (this registry's
        #: own server) for states with no explicit map
        self._endpoints: Dict[str, Dict[int, Tuple[str, int]]] = {}
        self._default_endpoint: Optional[Tuple[str, int]] = None
        self._routing_epoch = 0
        self._lock = threading.Lock()

    # -- registration --------------------------------------------------------
    def register(self, state_name: str, backend, state) -> None:
        with self._lock:
            self._entries[state_name] = (backend, state)
            self._routing_epoch += 1

    def register_views(self, state_name: str, views: List,
                       parallelism: int, max_parallelism: int) -> None:
        """Expose per-subtask :class:`~flink_tpu.queryable.view.
        WindowReadView` instances under one state name (re-registering
        replaces — region restarts rebuild operators).  ``views`` entries
        may be None for subtasks served elsewhere (a worker-local registry
        fronts only its own subtasks; the routing table sends clients to
        each subtask's owner)."""
        with self._lock:
            self._live[state_name] = _LiveEntry(views, parallelism,
                                                max_parallelism)
            self._routing_epoch += 1

    def register_replica(self, state_name: str, replica) -> None:
        with self._lock:
            self._replicas[state_name] = replica
            self._routing_epoch += 1

    def unregister(self, state_name: str) -> None:
        with self._lock:
            self._entries.pop(state_name, None)
            self._live.pop(state_name, None)
            self._replicas.pop(state_name, None)
            self._endpoints.pop(state_name, None)
            self._routing_epoch += 1

    # -- client-side routing surface -----------------------------------------
    def set_state_endpoints(self, state_name: str,
                            endpoints: Dict[int, Tuple[str, int]],
                            parallelism: Optional[int] = None,
                            max_parallelism: Optional[int] = None) -> None:
        """Advertise which server owns each subtask's state (the
        ``KvStateLocation`` analog).  ``parallelism``/``max_parallelism``
        register the routing geometry for states whose views live in
        OTHER processes (a coordinator advertising worker servers holds no
        views itself)."""
        with self._lock:
            cur = self._endpoints.setdefault(state_name, {})
            cur.update({int(i): (str(h), int(p))
                        for i, (h, p) in endpoints.items()})
            if parallelism is not None \
                    and state_name not in self._live:
                self._live[state_name] = _LiveEntry(
                    [None] * parallelism, parallelism,
                    max_parallelism or 128)
            self._routing_epoch += 1

    def set_default_endpoint(self, endpoint: Tuple[str, int]) -> None:
        """This registry's own server address — the fallback endpoint for
        every state without an explicit per-subtask map (the in-process
        MiniCluster: one server owns every subtask's view)."""
        with self._lock:
            self._default_endpoint = (str(endpoint[0]), int(endpoint[1]))
            self._routing_epoch += 1

    def routing_table(self) -> Dict[str, Any]:
        """The key-group -> endpoint map a client fans out on: per state,
        the routing geometry (parallelism / max_parallelism — the client
        runs the SAME murmur key-group assignment the operators route
        records with) and each subtask's owning server.  States with no
        per-subtask endpoints advertise every subtask at the default
        endpoint; replica-only states advertise kind="scan" (any endpoint
        answers the whole batch)."""
        with self._lock:
            states: Dict[str, Any] = {}
            names = set(self._live) | set(self._replicas) \
                | set(self._entries)
            for name in names:
                live = self._live.get(name)
                eps = dict(self._endpoints.get(name, {}))
                if live is None:
                    entry: Dict[str, Any] = {"kind": "scan"}
                    if self._default_endpoint is not None:
                        entry["endpoints"] = {0: list(
                            self._default_endpoint)}
                    states[name] = entry
                    continue
                if not eps and self._default_endpoint is not None:
                    eps = {i: self._default_endpoint
                           for i in range(live.parallelism)}
                states[name] = {
                    "kind": "subtask",
                    "parallelism": live.parallelism,
                    "max_parallelism": live.max_parallelism,
                    "endpoints": {int(i): list(ep)
                                  for i, ep in eps.items()},
                }
            return {"version": 1, "epoch": self._routing_epoch,
                    "states": states}

    def epoch_of(self, state_name: str, consistency: str):
        """Content version for the hot-key response cache: the replica's
        serving checkpoint id (checkpoint reads) or the live views'
        publish counter (live reads).  None = not cacheable."""
        with self._lock:
            if consistency == "checkpoint":
                rep = self._replicas.get(state_name)
                return None if rep is None else rep.epoch
            live = self._live.get(state_name)
            return None if live is None else live.epoch

    def names(self):
        with self._lock:
            return sorted(set(self._entries) | set(self._live)
                          | set(self._replicas))

    def replicas(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._replicas)

    def _unknown(self, state_name) -> Tuple[str, str]:
        # the registered-state list is logged SERVER-side only: echoing it
        # to an untrusted network client leaked the job's state topology
        _LOG.warning("queryable lookup for unknown state %r "
                     "(registered states: %s)", state_name, self.names())
        return "err", "unknown state"

    # -- point lookup (legacy protocol) --------------------------------------
    def lookup(self, state_name: str, key) -> Tuple[str, Any]:
        from flink_tpu.queryable.view import is_scalar_key
        if not is_scalar_key(key):
            return "err", "key must be a JSON scalar (str/int/float/bool)"
        with self._lock:
            entry = self._entries.get(state_name)
            live = self._live.get(state_name)
            has_replica = state_name in self._replicas
        if entry is not None:
            return self._lookup_backend(entry, key)
        if live is not None and not live.has_views:
            return "err", "state's live views are served by per-worker " \
                          "endpoints — use a routing client (or the " \
                          "batched protocol with consistency=checkpoint)"
        if live is not None:
            got = live.lookup_batch([key])
            if got["found"][0]:
                return "ok", got["values"][0]
            return "missing", None
        if has_replica:
            # registered, but replica-only (e.g. a coordinator-side
            # serving tier): say so instead of "unknown state"
            return "err", "state served at checkpoint consistency only " \
                          "— use the batched protocol with " \
                          "consistency=checkpoint"
        return self._unknown(state_name)

    @staticmethod
    def _lookup_backend(entry, key) -> Tuple[str, Any]:
        backend, state = entry
        idx = getattr(backend, "_index", None)
        if idx is None:
            return "missing", None
        slots = idx.lookup(np.asarray([key]))    # NON-inserting
        slot = int(slots[0])
        if slot < 0:
            return "missing", None
        got = state.get_rows(np.asarray([slot]))
        if isinstance(got, tuple):               # (values, alive)
            vals, alive = got
            if not bool(np.asarray(alive)[0]):
                return "missing", None
            return "ok", _plain(np.asarray(vals)[0])
        return "ok", _plain(list(got)[0])

    # -- batched lookup ------------------------------------------------------
    def lookup_batch(self, state_name: str, keys,
                     consistency: str = "live") -> Tuple[str, Any]:
        from flink_tpu.queryable.view import is_scalar_key
        if consistency not in ("live", "checkpoint"):
            return "err", f"unknown consistency {consistency!r} " \
                          f"(live|checkpoint)"
        if len(keys) > MAX_BATCH_KEYS:
            return "err", f"batch too large (max {MAX_BATCH_KEYS} keys)"
        if not all(is_scalar_key(k) for k in keys):
            # validate BEFORE hashing/routing: a list/dict/null key from
            # an untrusted client must be a clean error, not a handler-
            # thread exception that drops the connection mid-stream
            return "err", "keys must be JSON scalars (str/int/float/bool)"
        with self._lock:
            live = self._live.get(state_name)
            replica = self._replicas.get(state_name)
            legacy = self._entries.get(state_name)
        if live is None and replica is None and legacy is None:
            return self._unknown(state_name)
        if consistency == "checkpoint":
            if replica is None:
                return "err", "consistency 'checkpoint' not served for " \
                              "this state (no replica registered)"
            found, values, tags = replica.lookup_batch(keys)
            return "ok", {"found": found.tolist(), "values": values,
                          "tags": tags}
        if live is not None and not live.has_views:
            # routing placeholder (endpoints advertised, no local views):
            # an all-not-found answer would silently lie to old
            # non-routing clients — name the real situation instead
            return "err", "state's live views are served by per-worker " \
                          "endpoints — use a routing client (or query " \
                          "with consistency=checkpoint)"
        if live is not None:
            return "ok", live.lookup_batch(keys)
        if legacy is not None:
            found, values = [], []
            for k in keys:
                status, v = self._lookup_backend(legacy, k)
                found.append(status == "ok")
                values.append(v if status == "ok" else None)
            return "ok", {"found": found, "values": values,
                          "tags": {"consistency": "live"}}
        return "err", "state has no live read path (replica only — " \
                      "query with consistency=checkpoint)"

    # -- batched columnar lookup (binary wire) -------------------------------
    def lookup_batch_columnar(self, state_name: str, keys,
                              consistency: str = "live"
                              ) -> Tuple[str, Any]:
        """Binary-wire twin of :meth:`lookup_batch`: ``("ok", (found,
        cols, tags))`` with dense ndarray columns, or ``("err", msg)``
        with the SAME error texts as the JSON path (one contract, two
        encodings)."""
        from flink_tpu.queryable.view import is_scalar_key
        if consistency not in ("live", "checkpoint"):
            return "err", f"unknown consistency {consistency!r} " \
                          f"(live|checkpoint)"
        if len(keys) > MAX_BATCH_KEYS:
            return "err", f"batch too large (max {MAX_BATCH_KEYS} keys)"
        if not (isinstance(keys, np.ndarray)
                and keys.dtype.kind in "iu") \
                and not all(is_scalar_key(k) for k in keys):
            return "err", "keys must be JSON scalars (str/int/float/bool)"
        with self._lock:
            live = self._live.get(state_name)
            replica = self._replicas.get(state_name)
            legacy = self._entries.get(state_name)
        if live is None and replica is None and legacy is None:
            return self._unknown(state_name)
        if consistency == "checkpoint":
            if replica is None:
                return "err", "consistency 'checkpoint' not served for " \
                              "this state (no replica registered)"
            return "ok", replica.lookup_batch_columnar(keys)
        if live is not None and not live.has_views:
            return "err", "state's live views are served by per-worker " \
                          "endpoints — use a routing client (or query " \
                          "with consistency=checkpoint)"
        if live is not None:
            return "ok", live.lookup_batch_columnar(keys)
        if legacy is not None:
            # legacy backend states have no columnar read path: answer
            # binary clients through the dict path (slow, compatible)
            status, got = self.lookup_batch(state_name, list(keys),
                                            consistency)
            if status != "ok":
                return status, got
            found = np.asarray(got["found"], bool)
            cols = wire.columnar_from_values(found, got["values"])
            return "ok", (found, cols, got.get("tags", {}))
        return "err", "state has no live read path (replica only — " \
                      "query with consistency=checkpoint)"


def _json_safe(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return str(v)


def _answer_binary(registry, payload: bytes) -> bytes:
    """One binary request -> one binary response (never an exception: the
    same never-kill-the-connection contract as the JSON path)."""
    try:
        state, keys, consistency = wire.decode_request(payload)
    except (wire.WireError, ValueError, TypeError, KeyError, IndexError,
            struct.error, UnicodeDecodeError) as e:
        # truncated/corrupt frames surface as struct.error / bad-UTF-8 /
        # json errors, not just WireError — all must answer, never kill
        # the connection (the pooled client would burn retries on a
        # poison frame)
        return wire.encode_error(f"malformed request: {e}")
    try:
        status, out = registry.lookup_batch_columnar(state, keys,
                                                     consistency)
        if status != "ok":
            return wire.encode_error(out)
        found, cols, tags = out
        return wire.encode_response(found, cols, tags)
    except Exception:  # noqa: BLE001
        _LOG.exception("queryable binary lookup failed")
        return wire.encode_error("internal error")


class QueryableStateServer:
    """TCP server answering point + batched queries (``KvStateServerImpl``
    analog).  ``registry`` may be a :class:`KvStateRegistry` or anything
    exposing the same ``lookup``/``lookup_batch`` (the serving tier passes
    its instrumented :class:`~flink_tpu.queryable.service.
    QueryableStateService`)."""

    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        registry_ref = registry

        #: live handler connections — stop() severs them so a stopped
        #: server goes DARK immediately (daemon handler threads would
        #: otherwise keep answering on established sockets, hiding a
        #: worker restart from routed clients)
        active: set = set()
        active_lock = threading.Lock()

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with active_lock:
                    active.add(self.request)

            def finish(self):
                with active_lock:
                    active.discard(self.request)

            def handle(self):
                try:
                    while True:
                        hdr = _recv_exact(self.request, _LEN.size)
                        if hdr is None:
                            return
                        (n,) = _LEN.unpack(hdr)
                        payload = _recv_exact(self.request, n)
                        if payload is None:
                            return
                        # server-side SERVICE time: the whole answer —
                        # lookup AND serialization — measured where the
                        # GIL can't hide it behind a slow client (the
                        # client-side p99 is a different number on a
                        # loaded box; the panel shows both)
                        t0 = time.perf_counter()
                        if wire.is_binary(payload):
                            data = _answer_binary(registry_ref, payload)
                            proto = "binary"
                        else:
                            resp = self._answer(payload)
                            data = json.dumps(
                                resp, default=_json_safe).encode()
                            proto = "json"
                        rec = getattr(registry_ref, "record_serve", None)
                        if rec is not None:
                            rec((time.perf_counter() - t0) * 1e3, proto)
                        self.request.sendall(_LEN.pack(len(data)) + data)
                except (ConnectionError, OSError):
                    return

            @staticmethod
            def _answer(payload: bytes):
                try:
                    req = json.loads(payload)
                except (ValueError, TypeError):
                    return ("err", "malformed request")
                try:
                    if isinstance(req, dict):
                        if req.get("routing"):
                            return ("ok", registry_ref.routing_table())
                        state = req.get("state")
                        keys = req.get("keys")
                        if not isinstance(state, str) \
                                or not isinstance(keys, list):
                            return ("err", "malformed request")
                        return registry_ref.lookup_batch(
                            state, keys, req.get("consistency", "live"))
                    state_name, key = req
                    return registry_ref.lookup(state_name, key)
                except (ValueError, TypeError):
                    return ("err", "malformed request")
                except Exception:  # noqa: BLE001 — an untrusted request
                    # must never kill the connection without a reply (the
                    # pooled client would burn retries on a poison pill)
                    _LOG.exception("queryable lookup failed")
                    return ("err", "internal error")

        self._server = socketserver.ThreadingTCPServer((host, port), Handler,
                                                       bind_and_activate=True)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address
        # the registry's default routing endpoint IS this server (states
        # with a per-subtask map — per-worker serving — override it)
        sde = getattr(registry, "set_default_endpoint", None)
        if sde is not None:
            sde((self.host, self.port))
        self._active = active
        self._active_lock = active_lock
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="kv-state-server", daemon=True)

    def start(self) -> "QueryableStateServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # sever established connections too: a stopped server must go
        # dark, not linger answering on old sockets
        with self._active_lock:
            conns = list(self._active)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class QueryableStateClient:
    """``QueryableStateClient`` analog: connect + get.  Single socket, no
    retry — the original client, kept working; use
    :class:`QueryableStateClientPool` for pooling/retry/backoff."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)

    def get(self, state_name: str, key) -> Any:
        """Point lookup; raises KeyError if the key has no state."""
        payload = json.dumps([state_name, key]).encode()
        self._sock.sendall(_LEN.pack(len(payload)) + payload)
        hdr = _recv_exact(self._sock, _LEN.size)
        if hdr is None:
            raise ConnectionError("server closed")
        (n,) = _LEN.unpack(hdr)
        data = _recv_exact(self._sock, n)
        if data is None:
            raise ConnectionError("server closed mid-response")
        status, value = json.loads(data)
        if status == "ok":
            return value
        if status == "missing":
            raise KeyError(key)
        raise RuntimeError(value)

    def close(self) -> None:
        self._sock.close()


class QueryableStateClientPool:
    """Connection-pooled client with retry/timeout/backoff, per-endpoint
    pools, protocol negotiation and client-side key-group routing (the
    serving tier's front-door client, ISSUE-13).

    **Protocols** — ``protocol=``:

    - ``"json"`` (default): the PR-9 length-prefixed-JSON protocol, wire-
      compatible with old servers;
    - ``"binary"``: the columnar wire (``wire.py``) — fails loudly against
      a server that only speaks JSON;
    - ``"auto"``: binary first, silently downgrading an endpoint to JSON
      when its server answers a binary frame with a JSON error (old
      server) — the negotiation that lets fleets upgrade one side at a
      time.

    **Routing** — ``routing=True`` fetches the server's routing table
    (``{"routing": true}``) and hash-partitions every batch with the SAME
    murmur key-group assignment the operators route records with
    (``view.route_keys`` == ``ShardLayout.route_keys``), fanning each
    sub-batch straight to the worker that owns the keys' key groups and
    skipping the coordinator hop entirely.

    **Failure handling**: lookups are idempotent reads, so a request that
    dies mid-stream EVICTS the broken socket first, then marks the routing
    table stale, THEN retries — eviction strictly precedes the retry's
    routing-table refresh, so a refreshed map can never hand the retry a
    dead pooled connection (a worker restarted on a new port is one
    refresh away)."""

    def __init__(self, host: str, port: int, size: int = 4,
                 timeout_s: float = 5.0, retries: int = 1,
                 backoff_s: float = 0.05, protocol: str = "json",
                 routing: bool = False):
        if protocol not in ("json", "binary", "auto"):
            raise ValueError(f"unknown protocol {protocol!r} "
                             f"(json|binary|auto)")
        self.host = host
        self.port = port
        self.bootstrap = (host, int(port))
        self.size = max(1, int(size))
        self.timeout_s = timeout_s
        self.retries = max(0, int(retries))
        self.backoff_s = backoff_s
        self.protocol = protocol
        self.routing = bool(routing)
        self._idle: Dict[Tuple[str, int], List[socket.socket]] = {}
        self._json_only: set = set()        # endpoints negotiated down
        self._routing_table: Optional[Dict[str, Any]] = None
        self._no_routing = False            # server predates routing
        self._lock = threading.Lock()
        self._closed = False
        self.stats = {"requests": 0, "retries": 0, "evictions": 0,
                      "routing_refreshes": 0, "routed_batches": 0,
                      "fanout_requests": 0, "json_fallbacks": 0}

    # -- pool plumbing -------------------------------------------------------
    def _checkout(self, ep: Tuple[str, int]) -> socket.socket:
        with self._lock:
            if self._closed:
                raise RuntimeError("client pool is closed")
            pool = self._idle.get(ep)
            if pool:
                return pool.pop()
        return socket.create_connection(ep, timeout=self.timeout_s)

    def _checkin(self, ep: Tuple[str, int], sock: socket.socket) -> None:
        with self._lock:
            if not self._closed:
                pool = self._idle.setdefault(ep, [])
                if len(pool) < self.size:
                    pool.append(sock)
                    return
        sock.close()

    def _evict(self, sock: socket.socket) -> None:
        self.stats["evictions"] += 1
        try:
            sock.close()
        except OSError:
            pass

    def _rpc(self, ep: Tuple[str, int], payload: bytes) -> bytes:
        """One framed round trip on a pooled connection.  A broken stream
        evicts the socket BEFORE the error propagates — the evict-then-
        retry ordering the routed retry path depends on."""
        sock = None
        try:
            sock = self._checkout(ep)
            sock.sendall(_LEN.pack(len(payload)) + payload)
            hdr = _recv_exact(sock, _LEN.size)
            if hdr is None:
                raise ConnectionError("server closed")
            (n,) = _LEN.unpack(hdr)
            data = _recv_exact(sock, n)
            if data is None:
                raise ConnectionError("server closed mid-response")
        except (ConnectionError, OSError):
            # broken mid-stream: the socket may hold half a response —
            # NEVER back in the pool
            if sock is not None:
                self._evict(sock)
            raise
        self._checkin(ep, sock)
        self.stats["requests"] += 1
        return data

    def _request(self, obj) -> Any:
        """Bootstrap-endpoint JSON round trip with eviction + bounded
        retry (the legacy point-lookup path)."""
        payload = json.dumps(obj).encode()
        last_err: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                self.stats["retries"] += 1
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            try:
                data = self._rpc(self.bootstrap, payload)
            except (ConnectionError, OSError) as e:
                last_err = e
                continue
            return json.loads(data)
        raise ConnectionError(
            f"queryable lookup failed after {self.retries + 1} attempts: "
            f"{last_err}") from last_err

    # -- routing -------------------------------------------------------------
    def refresh_routing(self) -> Dict[str, Any]:
        """Re-fetch the key-group -> endpoint map from the bootstrap
        server.  Raises RuntimeError when the server predates routing."""
        data = self._rpc(self.bootstrap,
                         json.dumps({"routing": True}).encode())
        status, table = json.loads(data)
        if status != "ok":
            raise RuntimeError(table)
        with self._lock:
            self._routing_table = table
        self.stats["routing_refreshes"] += 1
        return table

    def invalidate_routing(self) -> None:
        with self._lock:
            self._routing_table = None

    def _routing_for(self, state: str) -> Optional[Dict[str, Any]]:
        if self._no_routing:
            return None
        table = self._routing_table
        if table is None:
            try:
                table = self.refresh_routing()
            except (ConnectionError, OSError):
                raise
            except RuntimeError:
                self._no_routing = True     # old server: stop asking
                return None
        return (table.get("states") or {}).get(state)

    def _split_by_endpoint(self, state: str, keys):
        """{endpoint: query-index array} under the advertised routing
        geometry, or None when the batch should go to the bootstrap
        endpoint whole (no map / scan-kind state / incomplete map)."""
        ent = self._routing_for(state)
        if not ent or ent.get("kind") != "subtask":
            return None
        eps = ent.get("endpoints") or {}
        if not eps:
            return None
        from flink_tpu.queryable.view import route_keys
        arr = keys if isinstance(keys, np.ndarray) \
            else np.asarray(list(keys), object)
        owner = route_keys(arr, int(ent["parallelism"]),
                           int(ent["max_parallelism"]))
        groups: Dict[Tuple[str, int], List[np.ndarray]] = {}
        for sub in np.unique(owner).tolist():
            ep = eps.get(str(sub), eps.get(sub))
            if ep is None:
                return None    # incomplete map: serve via bootstrap
            key_ep = (str(ep[0]), int(ep[1]))
            groups.setdefault(key_ep, []).append(
                np.flatnonzero(owner == sub))
        return {ep: np.concatenate(sels) for ep, sels in groups.items()}

    # -- one endpoint, one sub-batch ----------------------------------------
    def _fetch_columnar(self, ep: Tuple[str, int], state: str, keys,
                        consistency: str):
        """-> (found, cols, tags) from one endpoint, negotiating the
        protocol per endpoint."""
        if self.protocol != "json" and ep not in self._json_only:
            data = self._rpc(ep, wire.encode_request(state, keys,
                                                     consistency))
            if wire.is_binary(data):
                return wire.decode_response(data)    # RuntimeError on err
            if self.protocol == "binary":
                raise RuntimeError(
                    "server does not speak the binary wire protocol "
                    "(use protocol='auto' to negotiate down to JSON)")
            self._json_only.add(ep)
            self.stats["json_fallbacks"] += 1
        key_list = keys.tolist() if isinstance(keys, np.ndarray) \
            else list(keys)
        data = self._rpc(ep, json.dumps(
            {"state": state, "keys": key_list,
             "consistency": consistency}).encode())
        status, value = json.loads(data)
        if status != "ok":
            raise RuntimeError(value)
        found = np.asarray(value["found"], bool)
        cols = wire.columnar_from_values(found, value["values"])
        return found, cols, value.get("tags", {})

    def _dispatch_columnar(self, state: str, keys, consistency: str):
        groups = self._split_by_endpoint(state, keys) \
            if self.routing else None
        if groups is None:
            return self._fetch_columnar(self.bootstrap, state, keys,
                                        consistency)
        self.stats["routed_batches"] += 1
        n = len(keys)
        found = np.zeros(n, bool)
        cols: Dict[str, np.ndarray] = {}
        tag_list: List[Dict[str, Any]] = []
        for ep, sel in groups.items():
            sub = keys[sel] if isinstance(keys, np.ndarray) \
                else [keys[i] for i in sel.tolist()]
            f, c, t = self._fetch_columnar(ep, state, sub, consistency)
            self.stats["fanout_requests"] += 1
            tag_list.append(t)
            found[sel] = f
            hit = np.flatnonzero(f)
            if hit.size == 0:
                continue
            qsel = sel[hit]
            for name, arr in c.items():
                out = cols.get(name)
                if out is None:
                    out = cols[name] = (np.empty(n, object)
                                        if arr.dtype.kind == "O"
                                        else np.zeros(n, arr.dtype))
                got = arr[hit]
                out[qsel] = got if out.dtype == arr.dtype \
                    else got.astype(out.dtype)
        return found, cols, _merge_client_tags(tag_list, consistency)

    # -- API -----------------------------------------------------------------
    def get(self, state_name: str, key) -> Any:
        status, value = self._request([state_name, key])
        if status == "ok":
            return value
        if status == "missing":
            raise KeyError(key)
        raise RuntimeError(value)

    def get_batch_columnar(self, state_name: str, keys,
                           consistency: str = "live"
                           ) -> Tuple[np.ndarray, Dict[str, np.ndarray],
                                      Dict[str, Any]]:
        """The production read API: one batch in, ``(found bool[n],
        {col: ndarray[n]}, tags)`` out — zero per-key Python objects end
        to end on the binary protocol, routed per key group when routing
        is on.  Retries evict first, refresh the routing map second, and
        only then re-dispatch (a stale endpoint map self-heals)."""
        if isinstance(keys, np.ndarray):
            karr = keys
        else:
            keys = list(keys)
            karr = np.asarray(keys, np.int64) \
                if keys and all(isinstance(k, (int, np.integer))
                                and not isinstance(k, bool)
                                for k in keys) else keys
        last_err: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                self.stats["retries"] += 1
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
                if self.routing:
                    # the broken socket was already evicted by _rpc —
                    # refresh the map NOW so the retry dials the current
                    # owner, not the endpoint that just died
                    try:
                        self.refresh_routing()
                    except (ConnectionError, OSError, RuntimeError):
                        self.invalidate_routing()
            try:
                return self._dispatch_columnar(state_name, karr,
                                               consistency)
            except (ConnectionError, OSError) as e:
                last_err = e
                continue
        raise ConnectionError(
            f"queryable lookup failed after {self.retries + 1} attempts: "
            f"{last_err}") from last_err

    def get_batch(self, state_name: str, keys,
                  consistency: str = "live") -> Dict[str, Any]:
        """One request, N keys: ``{"found": [...], "values": [...],
        "tags": {...}}`` (the PR-9 answer shape, whatever protocol/routing
        serves it underneath)."""
        if self.protocol == "json" and not self.routing:
            # the PR-9 wire path, byte-for-byte (old servers included)
            status, value = self._request({"state": state_name,
                                           "keys": list(keys),
                                           "consistency": consistency})
            if status == "ok":
                return value
            raise RuntimeError(value)
        found, cols, tags = self.get_batch_columnar(state_name, keys,
                                                    consistency)
        return {"found": found.tolist(),
                "values": wire.values_from_columnar(found, cols),
                "tags": tags}

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, {}
        for pool in idle.values():
            for s in pool:
                try:
                    s.close()
                except OSError:
                    pass


def _merge_client_tags(tags: List[Dict[str, Any]],
                       consistency: str) -> Dict[str, Any]:
    """Fanned-out sub-batch tags -> one answer's tags: the conservative
    merge (oldest watermark/checkpoint, worst replica lag)."""
    if len(tags) == 1:
        return tags[0]
    out: Dict[str, Any] = {"consistency": consistency}
    for k in ("watermark", "checkpoint_id"):
        vals = [t[k] for t in tags if t.get(k) is not None]
        if vals or any(k in t for t in tags):
            out[k] = min(vals) if vals else None
    for k in ("replica_lag_checkpoints", "replica_lag_ms"):
        vals = [t[k] for t in tags if t.get(k) is not None]
        if vals or any(k in t for t in tags):
            out[k] = max(vals) if vals else 0
    return out


