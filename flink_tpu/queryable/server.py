"""Queryable state: the wire layer of the serving tier.

Analog of ``flink-queryable-state`` (``KvStateServerImpl`` +
``KvStateServerHandler`` on each TM, ``KvStateRegistry`` in the runtime,
client proxy with location lookup), grown into the read path of ISSUE-9:
the registry fronts three entry kinds —

- **live views** (``view.WindowReadView``): barrier-free fire-time
  snapshots published by the operator, sharded per subtask and routed by
  the record's own key-group assignment;
- **checkpoint replicas** (``replica.CheckpointReplica``): lookups at the
  last-completed-checkpoint consistency level, never touching the hot path;
- **legacy backend states** (``register(name, backend, state)``): the
  original dirty point-read against a keyed backend's non-inserting index
  path, kept for compatibility.

Protocol: length-prefixed JSON.  ``[state_name, key]`` (legacy point read)
-> ``[status, value]``; ``{"state": s, "keys": [...], "consistency":
"live"|"checkpoint"}`` (batched read) -> ``["ok", {"found": [...],
"values": [...], "tags": {...}}]`` — one request, N keys, columnar answer.
JSON, not pickle: requests arrive over the network from untrusted clients,
and unpickling attacker bytes is remote code execution.  Keys are therefore
limited to JSON scalars (str/int/float/bool).

Security: an unknown-state error reply names NOTHING — the registered
state list is logged server-side only (the old reply echoed the full list
to untrusted network clients).
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.queryable.view import plain as _plain

_LEN = struct.Struct("<I")
_LOG = logging.getLogger("flink_tpu.queryable")

#: batched requests are bounded: a hostile 100M-key request must not make
#: the server materialize 100M answers
MAX_BATCH_KEYS = 1 << 16


class _LiveEntry:
    """Per-subtask live views of ONE registered state + the routing
    geometry (a query routes to the owning subtask exactly like a
    record: murmur key group -> contiguous key-group range)."""

    __slots__ = ("views", "parallelism", "max_parallelism")

    def __init__(self, views: List, parallelism: int, max_parallelism: int):
        self.views = list(views)
        self.parallelism = int(parallelism)
        self.max_parallelism = int(max_parallelism)

    def lookup_batch(self, keys) -> Dict[str, Any]:
        from flink_tpu.queryable.view import coerce_keys, route_keys
        keys = coerce_keys(keys)
        n = len(keys)
        found = np.zeros(n, bool)
        values: List[Optional[Dict[str, Any]]] = [None] * n
        owner = route_keys(keys, self.parallelism, self.max_parallelism)
        tags: List[Dict[str, Any]] = []
        for sub in np.unique(owner).tolist():
            if not (0 <= sub < len(self.views)):
                continue
            view = self.views[int(sub)]
            sel = np.flatnonzero(owner == sub)
            f, v, t = view.lookup_batch(np.asarray(keys)[sel])
            tags.append(t)
            for j, qi in enumerate(sel.tolist()):
                if f[j]:
                    found[qi] = True
                    values[qi] = v[j]
        wm = [t["watermark"] for t in tags if t.get("watermark") is not None]
        ck = [t["checkpoint_id"] for t in tags
              if t.get("checkpoint_id") is not None]
        return {"found": found.tolist(), "values": values,
                "tags": {"consistency": "live",
                         "watermark": min(wm) if wm else None,
                         "checkpoint_id": min(ck) if ck else None}}


class KvStateRegistry:
    """Registered queryable states (``KvStateRegistry.java`` analog),
    extended with live views and checkpoint replicas."""

    def __init__(self):
        self._entries: Dict[str, Tuple[Any, Any]] = {}
        self._live: Dict[str, _LiveEntry] = {}
        self._replicas: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # -- registration --------------------------------------------------------
    def register(self, state_name: str, backend, state) -> None:
        with self._lock:
            self._entries[state_name] = (backend, state)

    def register_views(self, state_name: str, views: List,
                       parallelism: int, max_parallelism: int) -> None:
        """Expose per-subtask :class:`~flink_tpu.queryable.view.
        WindowReadView` instances under one state name (re-registering
        replaces — region restarts rebuild operators)."""
        with self._lock:
            self._live[state_name] = _LiveEntry(views, parallelism,
                                                max_parallelism)

    def register_replica(self, state_name: str, replica) -> None:
        with self._lock:
            self._replicas[state_name] = replica

    def unregister(self, state_name: str) -> None:
        with self._lock:
            self._entries.pop(state_name, None)
            self._live.pop(state_name, None)
            self._replicas.pop(state_name, None)

    def names(self):
        with self._lock:
            return sorted(set(self._entries) | set(self._live)
                          | set(self._replicas))

    def replicas(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._replicas)

    def _unknown(self, state_name) -> Tuple[str, str]:
        # the registered-state list is logged SERVER-side only: echoing it
        # to an untrusted network client leaked the job's state topology
        _LOG.warning("queryable lookup for unknown state %r "
                     "(registered states: %s)", state_name, self.names())
        return "err", "unknown state"

    # -- point lookup (legacy protocol) --------------------------------------
    def lookup(self, state_name: str, key) -> Tuple[str, Any]:
        from flink_tpu.queryable.view import is_scalar_key
        if not is_scalar_key(key):
            return "err", "key must be a JSON scalar (str/int/float/bool)"
        with self._lock:
            entry = self._entries.get(state_name)
            live = self._live.get(state_name)
            has_replica = state_name in self._replicas
        if entry is not None:
            return self._lookup_backend(entry, key)
        if live is not None:
            got = live.lookup_batch([key])
            if got["found"][0]:
                return "ok", got["values"][0]
            return "missing", None
        if has_replica:
            # registered, but replica-only (e.g. a coordinator-side
            # serving tier): say so instead of "unknown state"
            return "err", "state served at checkpoint consistency only " \
                          "— use the batched protocol with " \
                          "consistency=checkpoint"
        return self._unknown(state_name)

    @staticmethod
    def _lookup_backend(entry, key) -> Tuple[str, Any]:
        backend, state = entry
        idx = getattr(backend, "_index", None)
        if idx is None:
            return "missing", None
        slots = idx.lookup(np.asarray([key]))    # NON-inserting
        slot = int(slots[0])
        if slot < 0:
            return "missing", None
        got = state.get_rows(np.asarray([slot]))
        if isinstance(got, tuple):               # (values, alive)
            vals, alive = got
            if not bool(np.asarray(alive)[0]):
                return "missing", None
            return "ok", _plain(np.asarray(vals)[0])
        return "ok", _plain(list(got)[0])

    # -- batched lookup ------------------------------------------------------
    def lookup_batch(self, state_name: str, keys,
                     consistency: str = "live") -> Tuple[str, Any]:
        from flink_tpu.queryable.view import is_scalar_key
        if consistency not in ("live", "checkpoint"):
            return "err", f"unknown consistency {consistency!r} " \
                          f"(live|checkpoint)"
        if len(keys) > MAX_BATCH_KEYS:
            return "err", f"batch too large (max {MAX_BATCH_KEYS} keys)"
        if not all(is_scalar_key(k) for k in keys):
            # validate BEFORE hashing/routing: a list/dict/null key from
            # an untrusted client must be a clean error, not a handler-
            # thread exception that drops the connection mid-stream
            return "err", "keys must be JSON scalars (str/int/float/bool)"
        with self._lock:
            live = self._live.get(state_name)
            replica = self._replicas.get(state_name)
            legacy = self._entries.get(state_name)
        if live is None and replica is None and legacy is None:
            return self._unknown(state_name)
        if consistency == "checkpoint":
            if replica is None:
                return "err", "consistency 'checkpoint' not served for " \
                              "this state (no replica registered)"
            found, values, tags = replica.lookup_batch(keys)
            return "ok", {"found": found.tolist(), "values": values,
                          "tags": tags}
        if live is not None:
            return "ok", live.lookup_batch(keys)
        if legacy is not None:
            found, values = [], []
            for k in keys:
                status, v = self._lookup_backend(legacy, k)
                found.append(status == "ok")
                values.append(v if status == "ok" else None)
            return "ok", {"found": found, "values": values,
                          "tags": {"consistency": "live"}}
        return "err", "state has no live read path (replica only — " \
                      "query with consistency=checkpoint)"


def _json_safe(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return str(v)


class QueryableStateServer:
    """TCP server answering point + batched queries (``KvStateServerImpl``
    analog).  ``registry`` may be a :class:`KvStateRegistry` or anything
    exposing the same ``lookup``/``lookup_batch`` (the serving tier passes
    its instrumented :class:`~flink_tpu.queryable.service.
    QueryableStateService`)."""

    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        registry_ref = registry

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        hdr = _recv_exact(self.request, _LEN.size)
                        if hdr is None:
                            return
                        (n,) = _LEN.unpack(hdr)
                        payload = _recv_exact(self.request, n)
                        if payload is None:
                            return
                        resp = self._answer(payload)
                        data = json.dumps(resp, default=_json_safe).encode()
                        self.request.sendall(_LEN.pack(len(data)) + data)
                except (ConnectionError, OSError):
                    return

            @staticmethod
            def _answer(payload: bytes):
                try:
                    req = json.loads(payload)
                except (ValueError, TypeError):
                    return ("err", "malformed request")
                try:
                    if isinstance(req, dict):
                        state = req.get("state")
                        keys = req.get("keys")
                        if not isinstance(state, str) \
                                or not isinstance(keys, list):
                            return ("err", "malformed request")
                        return registry_ref.lookup_batch(
                            state, keys, req.get("consistency", "live"))
                    state_name, key = req
                    return registry_ref.lookup(state_name, key)
                except (ValueError, TypeError):
                    return ("err", "malformed request")
                except Exception:  # noqa: BLE001 — an untrusted request
                    # must never kill the connection without a reply (the
                    # pooled client would burn retries on a poison pill)
                    _LOG.exception("queryable lookup failed")
                    return ("err", "internal error")

        self._server = socketserver.ThreadingTCPServer((host, port), Handler,
                                                       bind_and_activate=True)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="kv-state-server", daemon=True)

    def start(self) -> "QueryableStateServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class QueryableStateClient:
    """``QueryableStateClient`` analog: connect + get.  Single socket, no
    retry — the original client, kept working; use
    :class:`QueryableStateClientPool` for pooling/retry/backoff."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)

    def get(self, state_name: str, key) -> Any:
        """Point lookup; raises KeyError if the key has no state."""
        payload = json.dumps([state_name, key]).encode()
        self._sock.sendall(_LEN.pack(len(payload)) + payload)
        hdr = _recv_exact(self._sock, _LEN.size)
        if hdr is None:
            raise ConnectionError("server closed")
        (n,) = _LEN.unpack(hdr)
        data = _recv_exact(self._sock, n)
        if data is None:
            raise ConnectionError("server closed mid-response")
        status, value = json.loads(data)
        if status == "ok":
            return value
        if status == "missing":
            raise KeyError(key)
        raise RuntimeError(value)

    def close(self) -> None:
        self._sock.close()


class QueryableStateClientPool:
    """Connection-pooled client with retry/timeout/backoff (the serving
    tier's front-door client).

    Lookups are idempotent reads, so a request that dies mid-stream
    (server restart, partition reset, timeout) EVICTS the broken socket
    from the pool and retries once on a fresh connection after a short
    backoff — the failure mode the single-socket client surfaces as a bare
    ``ConnectionError`` with an unusable socket left behind."""

    def __init__(self, host: str, port: int, size: int = 4,
                 timeout_s: float = 5.0, retries: int = 1,
                 backoff_s: float = 0.05):
        self.host = host
        self.port = port
        self.size = max(1, int(size))
        self.timeout_s = timeout_s
        self.retries = max(0, int(retries))
        self.backoff_s = backoff_s
        self._idle: List[socket.socket] = []
        self._lock = threading.Lock()
        self._closed = False
        self.stats = {"requests": 0, "retries": 0, "evictions": 0}

    # -- pool plumbing -------------------------------------------------------
    def _checkout(self) -> socket.socket:
        with self._lock:
            if self._closed:
                raise RuntimeError("client pool is closed")
            if self._idle:
                return self._idle.pop()
        return socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < self.size:
                self._idle.append(sock)
                return
        sock.close()

    def _evict(self, sock: socket.socket) -> None:
        self.stats["evictions"] += 1
        try:
            sock.close()
        except OSError:
            pass

    def _request(self, obj) -> Any:
        """One request/response round trip with eviction + bounded retry."""
        payload = json.dumps(obj).encode()
        last_err: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                self.stats["retries"] += 1
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            sock = None
            try:
                sock = self._checkout()
                sock.sendall(_LEN.pack(len(payload)) + payload)
                hdr = _recv_exact(sock, _LEN.size)
                if hdr is None:
                    raise ConnectionError("server closed")
                (n,) = _LEN.unpack(hdr)
                data = _recv_exact(sock, n)
                if data is None:
                    raise ConnectionError("server closed mid-response")
            except (ConnectionError, OSError) as e:
                # broken mid-stream: the socket may hold half a response —
                # NEVER back in the pool
                if sock is not None:
                    self._evict(sock)
                last_err = e
                continue
            self._checkin(sock)
            self.stats["requests"] += 1
            return json.loads(data)
        raise ConnectionError(
            f"queryable lookup failed after {self.retries + 1} attempts: "
            f"{last_err}") from last_err

    # -- API -----------------------------------------------------------------
    def get(self, state_name: str, key) -> Any:
        status, value = self._request([state_name, key])
        if status == "ok":
            return value
        if status == "missing":
            raise KeyError(key)
        raise RuntimeError(value)

    def get_batch(self, state_name: str, keys,
                  consistency: str = "live") -> Dict[str, Any]:
        """One request, N keys: ``{"found": [...], "values": [...],
        "tags": {...}}`` (columnar answer)."""
        status, value = self._request({"state": state_name,
                                       "keys": list(keys),
                                       "consistency": consistency})
        if status == "ok":
            return value
        raise RuntimeError(value)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for s in idle:
            try:
                s.close()
            except OSError:
                pass


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
