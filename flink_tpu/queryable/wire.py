"""Binary columnar wire protocol: production-QPS frames for the serving tier.

ISSUE-13's throughput rebuild of the read path.  The PR-9 protocol spent its
whole budget on Python objects — one dict per key, one ``json.dumps`` per
response — which capped the tier at ~7.6k lookups/s.  This codec serializes
**dtype-tagged ndarray columns straight off the immutable view/replica
segments**: a 256-key answer is one ``found`` byte plane plus a handful of
raw column buffers (``np.frombuffer`` on the client), with zero per-key
Python objects on either side.

Framing (all little-endian, inside the transport's usual ``u32`` length
prefix; ``MAGIC`` = 0xFB cannot begin a JSON document, so one peek
negotiates the protocol — JSON requests keep getting JSON answers and old
clients never notice the server got faster):

Request  (kind ``REQ_LOOKUP``)::

    MAGIC u8 | version u8 | kind u8 | consistency u8 | keytag u8 |
    state_len u16 | state utf-8 | nkeys u32 | key payload

    keytag 0: raw int64 keys (nkeys * 8 bytes — the dense-key fast path)
    keytag 1: JSON array utf-8 (payload_len u32 | bytes) — object keys

Response::

    MAGIC u8 | version u8 | status u8
    status OK   : nkeys u32 | found uint8[nkeys] | ncols u16 |
                  ncols x [name_len u16 | name | dtag_len u8 | dtag |
                           nbytes u32 | raw column bytes] |
                  tags_len u32 | tags JSON
    status ERR  : msg_len u32 | msg utf-8

Column rules: every column covers all ``nkeys`` query positions (rows whose
``found`` bit is 0 are zero/None filler — the client masks them), numeric
columns ship their C-contiguous bytes with the numpy dtype string as the
tag, and object-dtype columns (string results) fall back to a JSON-encoded
list under the reserved tag ``obj``.  Unknown versions fail loudly; new
columns are forward-compatible by construction (clients index by name).

``values_from_columnar`` reconstructs the PR-9 per-key dict answers from a
columnar payload through the same :func:`~flink_tpu.queryable.view.plain`
coercion the JSON path uses — the mechanism behind the bench's
binary==JSON answer-equality gate.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = 0xFB
WIRE_VERSION = 1

REQ_LOOKUP = 1

_OK, _ERR = 0, 1
_KEY_I64, _KEY_JSON = 0, 1

_REQ_HEAD = struct.Struct("<BBBBBH")     # magic ver kind consistency keytag
_U32 = struct.Struct("<I")               # state_len
_U16 = struct.Struct("<H")
_COL_HEAD = struct.Struct("<H")          # name_len

#: consistency levels on the wire
_CONS = ("live", "checkpoint")

#: object-dtype columns ride as JSON (reserved dtype tag)
OBJ_TAG = b"obj"


def is_binary(payload: bytes) -> bool:
    """Protocol negotiation: one byte peek.  0xFB can never start a JSON
    document, so a JSON request (old client) falls through untouched."""
    return bool(payload) and payload[0] == MAGIC


class WireError(ValueError):
    """Malformed or version-incompatible binary frame."""


def encode_request(state: str, keys, consistency: str = "live") -> bytes:
    """Batched lookup request.  Integer key arrays take the raw-int64 fast
    path (no per-key Python objects); anything else ships as JSON."""
    try:
        cons = _CONS.index(consistency)
    except ValueError:
        raise WireError(f"unknown consistency {consistency!r}")
    sb = state.encode()
    karr = keys if isinstance(keys, np.ndarray) else None
    if karr is None and isinstance(keys, (list, tuple)) \
            and keys and all(isinstance(k, (int, np.integer))
                             and not isinstance(k, bool) for k in keys):
        karr = np.asarray(keys, np.int64)
    if karr is not None and karr.dtype.kind in "iu":
        karr = np.ascontiguousarray(karr, np.int64)
        head = _REQ_HEAD.pack(MAGIC, WIRE_VERSION, REQ_LOOKUP, cons,
                              _KEY_I64, len(sb))
        return head + sb + _U32.pack(len(karr)) + karr.tobytes()
    kjson = json.dumps(list(keys)).encode()
    head = _REQ_HEAD.pack(MAGIC, WIRE_VERSION, REQ_LOOKUP, cons,
                          _KEY_JSON, len(sb))
    return head + sb + _U32.pack(len(list(keys))) \
        + _U32.pack(len(kjson)) + kjson


def decode_request(payload: bytes) -> Tuple[str, Any, str]:
    """-> (state, keys — int64 ndarray or list, consistency)."""
    if len(payload) < _REQ_HEAD.size:
        raise WireError("short frame")
    magic, ver, kind, cons, keytag, slen = _REQ_HEAD.unpack_from(payload, 0)
    if magic != MAGIC:
        raise WireError("not a binary frame")
    if ver != WIRE_VERSION:
        raise WireError(f"unsupported wire version {ver} "
                        f"(this server speaks {WIRE_VERSION})")
    if kind != REQ_LOOKUP:
        raise WireError(f"unknown request kind {kind}")
    if not 0 <= cons < len(_CONS):
        raise WireError(f"unknown consistency code {cons}")
    off = _REQ_HEAD.size
    state = payload[off:off + slen].decode()
    off += slen
    (nkeys,) = _U32.unpack_from(payload, off)
    off += _U32.size
    if keytag == _KEY_I64:
        end = off + 8 * nkeys
        if end > len(payload):
            raise WireError("truncated key payload")
        keys = np.frombuffer(payload, np.dtype("<i8"), nkeys, off)
        return state, keys, _CONS[cons]
    if keytag == _KEY_JSON:
        (jlen,) = _U32.unpack_from(payload, off)
        off += _U32.size
        keys = json.loads(payload[off:off + jlen])
        if not isinstance(keys, list) or len(keys) != nkeys:
            raise WireError("key payload does not match declared count")
        return state, keys, _CONS[cons]
    raise WireError(f"unknown key tag {keytag}")


def encode_response(found: np.ndarray, cols: Dict[str, np.ndarray],
                    tags: Dict[str, Any]) -> bytes:
    """OK answer: the columnar payload, zero per-key objects.  ``cols``
    arrays must be 1-D and cover every query position."""
    n = len(found)
    parts = [bytes((MAGIC, WIRE_VERSION, _OK)), _U32.pack(n),
             np.ascontiguousarray(found, np.uint8).tobytes(),
             _U16.pack(len(cols))]
    for name, arr in cols.items():
        nb = name.encode()
        parts.append(_COL_HEAD.pack(len(nb)))
        parts.append(nb)
        arr = np.asarray(arr)
        if arr.dtype.kind == "O":
            raw = json.dumps([None if v is None else _py(v)
                              for v in arr.tolist()]).encode()
            tag = OBJ_TAG
        else:
            arr = np.ascontiguousarray(arr)
            raw = arr.tobytes()
            tag = arr.dtype.str.encode()
        parts.append(bytes((len(tag),)))
        parts.append(tag)
        parts.append(_U32.pack(len(raw)))
        parts.append(raw)
    tj = json.dumps(tags, default=_py).encode()
    parts.append(_U32.pack(len(tj)))
    parts.append(tj)
    return b"".join(parts)


def encode_error(msg: str) -> bytes:
    mb = str(msg).encode()
    return bytes((MAGIC, WIRE_VERSION, _ERR)) + _U32.pack(len(mb)) + mb


def decode_response(payload: bytes) -> Tuple[np.ndarray,
                                             Dict[str, np.ndarray],
                                             Dict[str, Any]]:
    """-> (found bool[n], {col: ndarray[n]}, tags).  Raises
    :class:`WireError` on malformed frames, ``RuntimeError`` on a server
    error reply (mirrors the JSON client's error contract)."""
    if len(payload) < 3 or payload[0] != MAGIC:
        raise WireError("not a binary frame")
    if payload[1] != WIRE_VERSION:
        raise WireError(f"unsupported wire version {payload[1]}")
    status = payload[2]
    off = 3
    if status == _ERR:
        (mlen,) = _U32.unpack_from(payload, off)
        off += _U32.size
        raise RuntimeError(payload[off:off + mlen].decode())
    if status != _OK:
        raise WireError(f"unknown response status {status}")
    (n,) = _U32.unpack_from(payload, off)
    off += _U32.size
    found = np.frombuffer(payload, np.uint8, n, off).astype(bool)
    off += n
    (ncols,) = _U16.unpack_from(payload, off)
    off += _U16.size
    cols: Dict[str, np.ndarray] = {}
    for _ in range(ncols):
        (nlen,) = _COL_HEAD.unpack_from(payload, off)
        off += _COL_HEAD.size
        name = payload[off:off + nlen].decode()
        off += nlen
        tlen = payload[off]
        off += 1
        tag = payload[off:off + tlen]
        off += tlen
        (nbytes,) = _U32.unpack_from(payload, off)
        off += _U32.size
        raw = payload[off:off + nbytes]
        off += nbytes
        if tag == OBJ_TAG:
            cols[name] = np.asarray(json.loads(raw), object)
        else:
            cols[name] = np.frombuffer(raw, np.dtype(tag.decode()), n)
    (tlen,) = _U32.unpack_from(payload, off)
    off += _U32.size
    tags = json.loads(payload[off:off + tlen])
    return found, cols, tags


def _py(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def values_from_columnar(found: np.ndarray, cols: Dict[str, np.ndarray]
                         ) -> List[Optional[Dict[str, Any]]]:
    """Columnar answer -> the PR-9 per-key value dicts (None where not
    found), through the same scalar coercion the JSON path uses — the
    binary==JSON equality bridge, and the slow-but-compatible accessor for
    callers that want dict rows off a binary response."""
    n = len(found)
    values: List[Optional[Dict[str, Any]]] = [None] * n
    if not cols:
        return values
    names = list(cols)
    lists = [cols[c].tolist() for c in names]
    for i in np.flatnonzero(np.asarray(found)).tolist():
        values[i] = {c: lst[i] for c, lst in zip(names, lists)}
    return values


def columnar_from_values(found, values: List[Optional[Dict[str, Any]]]
                         ) -> Dict[str, np.ndarray]:
    """Per-key dict rows -> dense columns (the legacy-backend fallback:
    states with no columnar read path still answer binary clients)."""
    n = len(found)
    cols: Dict[str, List[Any]] = {}
    for v in values:
        if v is not None:
            for c in v:
                cols.setdefault(c, [None] * n)
    for i, v in enumerate(values):
        if v is not None:
            for c, cv in v.items():
                cols[c][i] = cv
    out: Dict[str, np.ndarray] = {}
    for c, lst in cols.items():
        filler = [x for x in lst if x is not None]
        if filler and all(isinstance(x, (int, np.integer))
                          and not isinstance(x, bool) for x in filler):
            out[c] = np.asarray([0 if x is None else x for x in lst],
                                np.int64)
        elif filler and all(isinstance(x, (int, float, np.number))
                            and not isinstance(x, bool) for x in filler):
            out[c] = np.asarray([0.0 if x is None else x for x in lst],
                                np.float64)
        else:
            out[c] = np.asarray(lst, object)
    return out
