from flink_tpu.queryable.cache import HotKeyCache
from flink_tpu.queryable.replica import (CheckpointReplica,
                                         QueryableStateSpec, ReplicaGroup)
from flink_tpu.queryable.server import (KvStateRegistry, QueryableStateClient,
                                        QueryableStateClientPool,
                                        QueryableStateServer)
from flink_tpu.queryable.service import QueryableStateService
from flink_tpu.queryable.view import WindowReadView

__all__ = ["KvStateRegistry", "QueryableStateClient",
           "QueryableStateClientPool", "QueryableStateServer",
           "QueryableStateService", "QueryableStateSpec",
           "CheckpointReplica", "ReplicaGroup", "HotKeyCache",
           "WindowReadView"]
