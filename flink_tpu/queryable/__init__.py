from flink_tpu.queryable.replica import (CheckpointReplica,
                                         QueryableStateSpec)
from flink_tpu.queryable.server import (KvStateRegistry, QueryableStateClient,
                                        QueryableStateClientPool,
                                        QueryableStateServer)
from flink_tpu.queryable.service import QueryableStateService
from flink_tpu.queryable.view import WindowReadView

__all__ = ["KvStateRegistry", "QueryableStateClient",
           "QueryableStateClientPool", "QueryableStateServer",
           "QueryableStateService", "QueryableStateSpec",
           "CheckpointReplica", "WindowReadView"]
