from flink_tpu.queryable.server import (KvStateRegistry, QueryableStateClient,
                                        QueryableStateServer)

__all__ = ["KvStateRegistry", "QueryableStateClient", "QueryableStateServer"]
