"""The serving-tier facade: registry + replicas + metrics in one object.

``QueryableStateService`` is what a cluster wires up per job: it owns the
:class:`~flink_tpu.queryable.server.KvStateRegistry`, feeds registered
:class:`~flink_tpu.queryable.replica.CheckpointReplica` instances from the
cluster's checkpoint stream (on a dedicated ingest thread — the acking
task thread only enqueues), instruments every lookup with per-state
latency/qps accounting, and exposes ``stats()`` for
``job_status()["queryable"]``, the ``queryable.*`` gauges, and the REST
panel.  It answers the same ``lookup``/``lookup_batch`` interface as the
registry, so the TCP server and REST handlers serve through it and every
read is measured.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.observability import tracing
from flink_tpu.queryable.cache import HotKeyCache
from flink_tpu.queryable.replica import (CheckpointReplica, QueryableStateSpec,
                                         ReplicaGroup)
from flink_tpu.queryable.server import KvStateRegistry, QueryableStateServer


class _LookupStats:
    """Per-state latency ring + counters (monitoring-grade: a bounded
    numpy ring, percentile math only when read)."""

    __slots__ = ("lookups", "batches", "_lat", "_n", "_i", "_t0", "_lock")

    RING = 4096

    def __init__(self):
        self.lookups = 0
        self.batches = 0
        self._lat = np.zeros(self.RING, np.float64)
        self._n = 0
        self._i = 0
        self._t0 = time.monotonic()
        self._lock = threading.Lock()

    def record(self, n_keys: int, elapsed_ms: float) -> None:
        with self._lock:
            self.lookups += n_keys
            self.batches += 1
            self._lat[self._i] = elapsed_ms
            self._i = (self._i + 1) % self.RING
            self._n = min(self._n + 1, self.RING)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = self._lat[: self._n].copy()
            lookups, batches = self.lookups, self.batches
            elapsed = max(time.monotonic() - self._t0, 1e-9)
        out = {"lookups": lookups, "batches": batches,
               "lookups_per_sec": round(lookups / elapsed, 1)}
        if lat.size:
            out["lookup_p50_ms"] = round(float(np.percentile(lat, 50)), 3)
            out["lookup_p99_ms"] = round(float(np.percentile(lat, 99)), 3)
        else:
            out["lookup_p50_ms"] = out["lookup_p99_ms"] = None
        return out


class QueryableStateService:
    """One job's queryable serving tier."""

    def __init__(self, registry: Optional[KvStateRegistry] = None,
                 cache: Optional[HotKeyCache] = None,
                 cache_enabled: bool = True):
        self.registry = registry or KvStateRegistry()
        self._stats: Dict[str, _LookupStats] = {}
        self._stats_lock = threading.Lock()
        #: hot-key response cache, keyed (state, key, consistency) and
        #: invalidated by content epoch (completed-checkpoint id /
        #: fired-window counter)
        self.cache: Optional[HotKeyCache] = \
            (cache or HotKeyCache()) if cache_enabled else None
        #: server-side SERVICE time (lookup + serialization), recorded by
        #: the TCP handler — the number the client-side p99 can't see
        #: honestly on a GIL-loaded box; plus per-protocol volume
        self._serve = _LookupStats()
        self._protocols = {"binary": 0, "json": 0}
        #: checkpoint feed: the coordinator enqueues (cid, assembled) and
        #: returns immediately; this thread runs the replica ingests so
        #: snapshot parsing never runs on an acking task thread
        self._feed: "queue.Queue[Optional[Tuple[int, Dict]]]" = queue.Queue()
        self._feed_thread: Optional[threading.Thread] = None
        self._server: Optional[QueryableStateServer] = None
        self._closed = False

    # -- registration --------------------------------------------------------
    def register_views(self, name: str, views: List, parallelism: int,
                       max_parallelism: int) -> None:
        self.registry.register_views(name, views, parallelism,
                                     max_parallelism)
        # a rebuilt operator's fresh views restart their publish counter
        # at 0 — rows cached under the OLD views' epochs would otherwise
        # read as valid again the moment the new counter catches up
        if self.cache is not None:
            self.cache.clear()

    def add_replica(self, name: str, spec: QueryableStateSpec,
                    storage=None, replicas: int = 1, **kw):
        """Create + register the checkpoint replica tier for ``name``.
        With a ``storage`` it can tail independently; without, it is fed
        by :meth:`on_checkpoint_complete`.  ``replicas=N`` registers an
        N-member :class:`~flink_tpu.queryable.replica.ReplicaGroup`
        instead of a single replica — reads load-balance across the
        freshest members and fail over past a partitioned one."""
        if self.cache is not None:
            self.cache.clear()   # fresh replica: old epochs may recur
        if replicas > 1:
            group = ReplicaGroup([
                CheckpointReplica(spec, storage=storage,
                                  name=f"{name}#r{i}", **kw)
                for i in range(replicas)])
            self.registry.register_replica(name, group)
            return group
        replica = CheckpointReplica(spec, storage=storage, **kw)
        self.registry.register_replica(name, replica)
        return replica

    # -- checkpoint feed -----------------------------------------------------
    def on_checkpoint_complete(self, checkpoint_id: int,
                               assembled: Dict[str, Any]) -> None:
        """Non-blocking: advertise to every replica (lag gauges move now)
        and enqueue the payload for the ingest thread."""
        for r in self.registry.replicas().values():
            r.observe_completed(checkpoint_id)
        if self._closed:
            return
        self._feed.put((checkpoint_id, assembled))
        if self._feed_thread is None:
            self._feed_thread = threading.Thread(
                target=self._feed_loop, name="queryable-replica-feed",
                daemon=True)
            self._feed_thread.start()

    def _feed_loop(self) -> None:
        while True:
            item = self._feed.get()
            try:
                if item is None:
                    return
                cid, assembled = item
                for r in self.registry.replicas().values():
                    try:
                        r.ingest_assembled(cid, assembled)
                    except Exception:  # noqa: BLE001 — a malformed state
                        pass           # must not kill the feed for others
            finally:
                self._feed.task_done()

    def drain_feed(self, timeout_s: float = 10.0) -> bool:
        """Block until enqueued checkpoints are ingested (tests/bench)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._feed.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    # -- instrumented lookups -----------------------------------------------
    def _stat(self, name: str) -> _LookupStats:
        with self._stats_lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _LookupStats()
            return st

    def lookup(self, state_name: str, key) -> Tuple[str, Any]:
        t0 = time.perf_counter()
        out = self.registry.lookup(state_name, key)
        self._stat(state_name).record(1, (time.perf_counter() - t0) * 1e3)
        return out

    def lookup_batch(self, state_name: str, keys,
                     consistency: str = "live") -> Tuple[str, Any]:
        t0 = time.perf_counter_ns()
        out = self._lookup_batch_cached(state_name, keys, consistency)
        t1 = time.perf_counter_ns()
        self._stat(state_name).record(len(keys), (t1 - t0) / 1e6)
        tracing.complete("queryable.serve", t0, t1, cat="queryable",
                         state=state_name, keys=len(keys),
                         consistency=consistency, protocol="json")
        return out

    def _lookup_batch_cached(self, state_name: str, keys,
                             consistency: str) -> Tuple[str, Any]:
        """The dict-path lookup through the hot-key cache: per-key hits
        (valid under the state's current content epoch) answer from the
        cache; only the misses touch the registry, and their rows are
        memoized for the next reader of the same hot key."""
        cache = self.cache
        epoch = self.registry.epoch_of(state_name, consistency) \
            if cache is not None else None
        if epoch is None:
            return self.registry.lookup_batch(state_name, keys, consistency)
        keys = list(keys)
        hits, missing = cache.get_many(state_name, consistency, epoch, keys)
        if not missing:
            found = [hits[i][0] for i in range(len(keys))]
            values = [hits[i][1] for i in range(len(keys))]
            return "ok", {"found": found, "values": values,
                          "tags": self._tags_of(state_name, consistency)}
        if not hits:
            status, got = self.registry.lookup_batch(state_name, keys,
                                                     consistency)
            if status == "ok":
                cache.put_many(state_name, consistency, epoch, keys,
                               list(zip(got["found"], got["values"])))
            return status, got
        miss_keys = [keys[i] for i in missing]
        status, got = self.registry.lookup_batch(state_name, miss_keys,
                                                 consistency)
        if status != "ok":
            return status, got
        cache.put_many(state_name, consistency, epoch, miss_keys,
                       list(zip(got["found"], got["values"])))
        found: List[bool] = [False] * len(keys)
        values: List[Any] = [None] * len(keys)
        for i, (f, v) in hits.items():
            found[i], values[i] = f, v
        for j, i in enumerate(missing):
            found[i] = bool(got["found"][j])
            values[i] = got["values"][j]
        return "ok", {"found": found, "values": values,
                      "tags": got.get("tags",
                                      self._tags_of(state_name,
                                                    consistency))}

    def _tags_of(self, state_name: str, consistency: str) -> Dict[str, Any]:
        """Current tags for a fully-cache-served answer (tags are cheap —
        only the VALUES needed the locate/gather the cache skipped)."""
        status, got = self.registry.lookup_batch(state_name, [], consistency)
        if status == "ok":
            if isinstance(got, dict):
                return got.get("tags", {"consistency": consistency})
            return got[2]
        return {"consistency": consistency}

    # -- binary columnar path ------------------------------------------------
    def lookup_batch_columnar(self, state_name: str, keys,
                              consistency: str = "live") -> Tuple[str, Any]:
        """The binary wire's instrumented serve path (zero per-key Python
        objects; the hot-key cache applies to the dict path — the columnar
        gather is already cheaper than per-key cache assembly)."""
        t0 = time.perf_counter_ns()
        out = self.registry.lookup_batch_columnar(state_name, keys,
                                                  consistency)
        t1 = time.perf_counter_ns()
        self._stat(state_name).record(len(keys), (t1 - t0) / 1e6)
        tracing.complete("queryable.serve", t0, t1, cat="queryable",
                         state=state_name, keys=len(keys),
                         consistency=consistency, protocol="binary")
        return out

    # -- server-side service time (recorded by the TCP handler) -------------
    def record_serve(self, elapsed_ms: float, protocol: str) -> None:
        self._serve.record(1, elapsed_ms)
        if protocol in self._protocols:
            self._protocols[protocol] += 1

    def routing_table(self) -> Dict[str, Any]:
        return self.registry.routing_table()

    def set_default_endpoint(self, endpoint) -> None:
        self.registry.set_default_endpoint(endpoint)

    def set_state_endpoints(self, name: str, endpoints,
                            parallelism: Optional[int] = None,
                            max_parallelism: Optional[int] = None) -> None:
        self.registry.set_state_endpoints(name, endpoints,
                                          parallelism=parallelism,
                                          max_parallelism=max_parallelism)

    # -- server lifecycle ----------------------------------------------------
    def start_server(self, host: str = "127.0.0.1",
                     port: int = 0) -> QueryableStateServer:
        if self._server is None:
            self._server = QueryableStateServer(self, host=host,
                                                port=port).start()
        return self._server

    @property
    def server(self) -> Optional[QueryableStateServer]:
        return self._server

    def close(self) -> None:
        self._closed = True
        if self._feed_thread is not None:
            self._feed.put(None)
            self._feed_thread.join(timeout=5)
            self._feed_thread = None
        for r in self.registry.replicas().values():
            r.stop()
        if self._server is not None:
            self._server.stop()
            self._server = None

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """``job_status()["queryable"]`` / gauge / REST-panel shape: the
        per-state lookup accounting + every replica's staleness view, plus
        job-level aggregates (max lag across replicas — the gauges)."""
        with self._stats_lock:
            per_state = {n: s.snapshot() for n, s in self._stats.items()}
        replicas = {n: r.stats()
                    for n, r in self.registry.replicas().items()}
        for name, r in replicas.items():
            per_state.setdefault(name, {})["replica"] = r
        lookups = sum(s.get("lookups", 0) for s in per_state.values())
        qps = sum(s.get("lookups_per_sec", 0) or 0
                  for s in per_state.values())
        p50 = [s["lookup_p50_ms"] for s in per_state.values()
               if s.get("lookup_p50_ms") is not None]
        p99 = [s["lookup_p99_ms"] for s in per_state.values()
               if s.get("lookup_p99_ms") is not None]
        serve = self._serve.snapshot()
        return {
            "states": sorted(self.registry.names()),
            "per_state": per_state,
            "lookups_total": lookups,
            "lookups_per_sec": round(qps, 1),
            "lookup_p50_ms": max(p50) if p50 else None,
            "lookup_p99_ms": max(p99) if p99 else None,
            # server-side service time (lookup + serialization, measured
            # in the TCP handler) — the honest latency on a loaded box,
            # shown NEXT TO the client-side numbers, never instead
            "serve_p50_ms": serve["lookup_p50_ms"],
            "serve_p99_ms": serve["lookup_p99_ms"],
            "served_requests": serve["batches"],
            "protocols": dict(self._protocols),
            "cache": self.cache.stats() if self.cache is not None else None,
            "cache_hit_rate": (self.cache.stats()["hit_rate"]
                               if self.cache is not None else 0.0),
            "replica_lag_checkpoints": max(
                (r["replica_lag_checkpoints"] for r in replicas.values()),
                default=0),
            "replica_lag_ms": max(
                (r["replica_lag_ms"] for r in replicas.values()),
                default=0.0),
        }
