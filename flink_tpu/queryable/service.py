"""The serving-tier facade: registry + replicas + metrics in one object.

``QueryableStateService`` is what a cluster wires up per job: it owns the
:class:`~flink_tpu.queryable.server.KvStateRegistry`, feeds registered
:class:`~flink_tpu.queryable.replica.CheckpointReplica` instances from the
cluster's checkpoint stream (on a dedicated ingest thread — the acking
task thread only enqueues), instruments every lookup with per-state
latency/qps accounting, and exposes ``stats()`` for
``job_status()["queryable"]``, the ``queryable.*`` gauges, and the REST
panel.  It answers the same ``lookup``/``lookup_batch`` interface as the
registry, so the TCP server and REST handlers serve through it and every
read is measured.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.queryable.replica import CheckpointReplica, QueryableStateSpec
from flink_tpu.queryable.server import KvStateRegistry, QueryableStateServer


class _LookupStats:
    """Per-state latency ring + counters (monitoring-grade: a bounded
    numpy ring, percentile math only when read)."""

    __slots__ = ("lookups", "batches", "_lat", "_n", "_i", "_t0", "_lock")

    RING = 4096

    def __init__(self):
        self.lookups = 0
        self.batches = 0
        self._lat = np.zeros(self.RING, np.float64)
        self._n = 0
        self._i = 0
        self._t0 = time.monotonic()
        self._lock = threading.Lock()

    def record(self, n_keys: int, elapsed_ms: float) -> None:
        with self._lock:
            self.lookups += n_keys
            self.batches += 1
            self._lat[self._i] = elapsed_ms
            self._i = (self._i + 1) % self.RING
            self._n = min(self._n + 1, self.RING)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = self._lat[: self._n].copy()
            lookups, batches = self.lookups, self.batches
            elapsed = max(time.monotonic() - self._t0, 1e-9)
        out = {"lookups": lookups, "batches": batches,
               "lookups_per_sec": round(lookups / elapsed, 1)}
        if lat.size:
            out["lookup_p50_ms"] = round(float(np.percentile(lat, 50)), 3)
            out["lookup_p99_ms"] = round(float(np.percentile(lat, 99)), 3)
        else:
            out["lookup_p50_ms"] = out["lookup_p99_ms"] = None
        return out


class QueryableStateService:
    """One job's queryable serving tier."""

    def __init__(self, registry: Optional[KvStateRegistry] = None):
        self.registry = registry or KvStateRegistry()
        self._stats: Dict[str, _LookupStats] = {}
        self._stats_lock = threading.Lock()
        #: checkpoint feed: the coordinator enqueues (cid, assembled) and
        #: returns immediately; this thread runs the replica ingests so
        #: snapshot parsing never runs on an acking task thread
        self._feed: "queue.Queue[Optional[Tuple[int, Dict]]]" = queue.Queue()
        self._feed_thread: Optional[threading.Thread] = None
        self._server: Optional[QueryableStateServer] = None
        self._closed = False

    # -- registration --------------------------------------------------------
    def register_views(self, name: str, views: List, parallelism: int,
                       max_parallelism: int) -> None:
        self.registry.register_views(name, views, parallelism,
                                     max_parallelism)

    def add_replica(self, name: str, spec: QueryableStateSpec,
                    storage=None, **kw) -> CheckpointReplica:
        """Create + register a checkpoint replica for ``name``.  With a
        ``storage`` it can tail independently; without, it is fed by
        :meth:`on_checkpoint_complete`."""
        replica = CheckpointReplica(spec, storage=storage, **kw)
        self.registry.register_replica(name, replica)
        return replica

    # -- checkpoint feed -----------------------------------------------------
    def on_checkpoint_complete(self, checkpoint_id: int,
                               assembled: Dict[str, Any]) -> None:
        """Non-blocking: advertise to every replica (lag gauges move now)
        and enqueue the payload for the ingest thread."""
        for r in self.registry.replicas().values():
            r.observe_completed(checkpoint_id)
        if self._closed:
            return
        self._feed.put((checkpoint_id, assembled))
        if self._feed_thread is None:
            self._feed_thread = threading.Thread(
                target=self._feed_loop, name="queryable-replica-feed",
                daemon=True)
            self._feed_thread.start()

    def _feed_loop(self) -> None:
        while True:
            item = self._feed.get()
            try:
                if item is None:
                    return
                cid, assembled = item
                for r in self.registry.replicas().values():
                    try:
                        r.ingest_assembled(cid, assembled)
                    except Exception:  # noqa: BLE001 — a malformed state
                        pass           # must not kill the feed for others
            finally:
                self._feed.task_done()

    def drain_feed(self, timeout_s: float = 10.0) -> bool:
        """Block until enqueued checkpoints are ingested (tests/bench)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._feed.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    # -- instrumented lookups -----------------------------------------------
    def _stat(self, name: str) -> _LookupStats:
        with self._stats_lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _LookupStats()
            return st

    def lookup(self, state_name: str, key) -> Tuple[str, Any]:
        t0 = time.perf_counter()
        out = self.registry.lookup(state_name, key)
        self._stat(state_name).record(1, (time.perf_counter() - t0) * 1e3)
        return out

    def lookup_batch(self, state_name: str, keys,
                     consistency: str = "live") -> Tuple[str, Any]:
        t0 = time.perf_counter()
        out = self.registry.lookup_batch(state_name, keys, consistency)
        self._stat(state_name).record(len(keys),
                                      (time.perf_counter() - t0) * 1e3)
        return out

    # -- server lifecycle ----------------------------------------------------
    def start_server(self, host: str = "127.0.0.1",
                     port: int = 0) -> QueryableStateServer:
        if self._server is None:
            self._server = QueryableStateServer(self, host=host,
                                                port=port).start()
        return self._server

    @property
    def server(self) -> Optional[QueryableStateServer]:
        return self._server

    def close(self) -> None:
        self._closed = True
        if self._feed_thread is not None:
            self._feed.put(None)
            self._feed_thread.join(timeout=5)
            self._feed_thread = None
        for r in self.registry.replicas().values():
            r.stop()
        if self._server is not None:
            self._server.stop()
            self._server = None

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """``job_status()["queryable"]`` / gauge / REST-panel shape: the
        per-state lookup accounting + every replica's staleness view, plus
        job-level aggregates (max lag across replicas — the gauges)."""
        with self._stats_lock:
            per_state = {n: s.snapshot() for n, s in self._stats.items()}
        replicas = {n: r.stats()
                    for n, r in self.registry.replicas().items()}
        for name, r in replicas.items():
            per_state.setdefault(name, {})["replica"] = r
        lookups = sum(s.get("lookups", 0) for s in per_state.values())
        qps = sum(s.get("lookups_per_sec", 0) or 0
                  for s in per_state.values())
        p50 = [s["lookup_p50_ms"] for s in per_state.values()
               if s.get("lookup_p50_ms") is not None]
        p99 = [s["lookup_p99_ms"] for s in per_state.values()
               if s.get("lookup_p99_ms") is not None]
        return {
            "states": sorted(self.registry.names()),
            "per_state": per_state,
            "lookups_total": lookups,
            "lookups_per_sec": round(qps, 1),
            "lookup_p50_ms": max(p50) if p50 else None,
            "lookup_p99_ms": max(p99) if p99 else None,
            "replica_lag_checkpoints": max(
                (r["replica_lag_checkpoints"] for r in replicas.values()),
                default=0),
            "replica_lag_ms": max(
                (r["replica_lag_ms"] for r in replicas.values()),
                default=0.0),
        }
