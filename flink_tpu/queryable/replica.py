"""Read replicas fed by the checkpoint stream (ISSUE-9 layer 2).

A :class:`CheckpointReplica` serves point/batch lookups at the
**last-completed-checkpoint** consistency level without ever touching the
job's hot path: it tails completed checkpoints (the per-shard slices +
key-group-range manifests of ``state/shard_layout.py`` when the writer was
mesh-sharded, dense gid-indexed snapshots otherwise), pre-combines each
key's retained panes into the final aggregate result AT INGEST, and answers
queries from those frozen arrays.  The reference designs are Flink's
queryable state (which reads the LIVE backend — dirty) and Kafka Streams
Interactive Queries' standby replicas (which serve committed store state);
this replica is the latter with an explicit consistency tag: every answer
carries the checkpoint id it reflects plus the replica's current lag.

Sharding mirrors the job's own state layout:

- a parallelism-P writer produces one replica shard per subtask, carrying
  the subtask's key-group range (``compute_key_group_range``), and a query
  routes to the owning shard **exactly like a record does** (murmur key
  group -> contiguous range — ``view.route_keys``);
- a mesh-sharded writer's slices become one replica shard per mesh shard,
  carrying the manifest's key-group range and row range (slot-range tiled,
  so lookups scan slices — the mesh routes records by slot block, not by
  key-group hash).

Catch-up on restore/rescale is manifest-driven and automatic: every ingest
replaces the shard set wholesale with whatever layout the checkpoint
carries, so a job rescaled from 4 shards to 2 (or to parallelism 3)
re-shards the replica at its next completed checkpoint — any mesh size,
either direction.  A topology change is counted in ``catch_ups``.

Staleness is first-class: ``queryable.replica_lag_checkpoints`` (completed
checkpoints newer than the one being served) and ``queryable.replica_lag_ms``
(how long the replica has been behind) are exported as gauges and returned
in every lookup's tags — a partitioned replica keeps serving at its
advertised staleness instead of failing, and re-converges after heal.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.queryable.view import (_Segment, coerce_keys, plain,
                                      route_keys)
from flink_tpu.state.shard_layout import LAYOUT_KEY, SLICES_KEY
from flink_tpu.testing import chaos
from flink_tpu.utils import clock

#: fault point of the replica's bulk checkpoint fetch (the data plane the
#: stale-replica nemeses cut): ``Partition(direction="storage->replica")``
#: blackholes fetches while the metadata listing stays visible — the
#: replica keeps serving, lag gauges grow, heal re-converges
REPLICA_FETCH_POINT = "queryable.replica_fetch"


class QueryableStateSpec:
    """How to interpret one registered state's keyed snapshot: the
    aggregate's ACC spec + combine kinds (to merge retained panes) and its
    result function (ACC -> emitted value)."""

    def __init__(self, name: str, uid: str, key_column: str, agg,
                 output_column: str = "result"):
        self.name = name
        self.uid = uid
        self.key_column = key_column
        self.output_column = output_column
        self.agg = agg
        self.acc_spec = agg.acc_spec()
        self.kinds = agg.scatter_kind_leaves()

    @classmethod
    def from_operator(cls, name: str, uid: str, op) -> "QueryableStateSpec":
        return cls(name, uid, op.key_column, op.agg,
                   output_column=op.output_column)

    def result_columns(self, combined_leaves: List[np.ndarray]
                       ) -> Dict[str, np.ndarray]:
        acc = self.acc_spec.unflatten(combined_leaves)
        try:
            result = self.agg.host_get_result(acc)
        except (AttributeError, NotImplementedError):
            result = self.agg.get_result(acc)
        if isinstance(result, dict):
            return {c: np.asarray(v) for c, v in result.items()}
        return {self.output_column: np.asarray(result)}


class ReplicaShard:
    """One shard's pre-combined keyed rows + its manifest metadata."""

    __slots__ = ("index", "key_groups", "row_range", "rows", "n_keys")

    def __init__(self, index: int, key_groups: Tuple[int, int],
                 row_range: Optional[Tuple[int, int]], keys: np.ndarray,
                 cols: Dict[str, np.ndarray]):
        self.index = index
        self.key_groups = key_groups
        self.row_range = row_range
        self.n_keys = int(len(keys))
        # reuse the live view's frozen columnar index (lazy sort/dict)
        self.rows = _Segment(0, 0, keys, cols, 0, None)

    def manifest(self) -> Dict[str, Any]:
        return {"shard": self.index, "key_groups": list(self.key_groups),
                "row_range": (list(self.row_range)
                              if self.row_range is not None else None),
                "keys": self.n_keys}


def _is_keyed(tree: Dict[str, Any]) -> bool:
    # dense gid-indexed ("counts") or mesh per-shard-slice layout
    return "key_index" in tree and ("counts" in tree or SLICES_KEY in tree)


def _find_keyed_snapshot(tree) -> Optional[Dict[str, Any]]:
    """Locate the keyed window state inside a subtask snapshot (the chain
    wraps members as ``{"operator": {"op0": ...}}``; channel-state and
    source sections ride alongside)."""
    if isinstance(tree, dict):
        if _is_keyed(tree):
            return tree
        if "operator" in tree:
            got = _find_keyed_snapshot(tree["operator"])
            if got is not None:
                return got
        for v in tree.values():
            if isinstance(v, dict) and _is_keyed(v):
                return v
        for v in tree.values():
            if isinstance(v, dict):
                got = _find_keyed_snapshot(v)
                if got is not None:
                    return got
    return None


def _restore_keys(snap: Dict[str, Any]) -> np.ndarray:
    """Slot-ordered raw keys from a keyed snapshot's key-index section."""
    from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex
    if snap.get("key_index_kind") == "ObjectKeyIndex":
        return np.asarray(ObjectKeyIndex.restore(snap["key_index"])
                          .reverse_keys())
    idx = KeyIndex.restore(snap["key_index"])
    try:
        return np.asarray(idx.reverse_keys()).copy()
    finally:
        del idx


class CheckpointReplica:
    """Sharded read replica of ONE registered state, fed by the checkpoint
    stream — either pushed (:meth:`ingest_assembled`, the in-process
    MiniCluster feed) or pulled (:meth:`start_tailing` a checkpoint
    storage, the cross-process deployment)."""

    def __init__(self, spec: QueryableStateSpec, storage=None,
                 poll_interval_s: float = 0.25, max_parallelism: int = 128,
                 name: Optional[str] = None):
        self.spec = spec
        #: replica identity — distinguishes fan-out siblings in chaos
        #: scoping (``Partition(replica=...)``) and in the staleness stats
        self.name = name or spec.name
        self.storage = storage
        self.poll_interval_s = poll_interval_s
        self.max_parallelism = max_parallelism
        self._lock = threading.Lock()
        self._shards: Tuple[ReplicaShard, ...] = ()
        self._parallelism = 0            # writer parallelism (subtask shards)
        self._serving_cid: Optional[int] = None
        self._serving_since_ms: Optional[int] = None
        self._advertised: set = set()    # completed cids seen advertised
        self._ingests = 0
        self._catch_ups = 0
        self._fetch_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- feeding
    def observe_completed(self, checkpoint_id: int) -> None:
        """Advertise a completed checkpoint WITHOUT its payload: the lag
        gauges count advertised-but-not-served checkpoints."""
        with self._lock:
            if self._serving_cid is None or checkpoint_id > self._serving_cid:
                self._advertised.add(int(checkpoint_id))

    def ingest_assembled(self, checkpoint_id: int,
                         assembled: Dict[str, Any]) -> bool:
        """Build the shard set from one assembled checkpoint
        (``{uid: {"subtasks": [...]}}``).  Returns False when the
        checkpoint carries no keyed state for the registered uid (e.g. a
        checkpoint taken before the operator saw data)."""
        import time as _time

        from flink_tpu.observability import tracing
        t0 = _time.perf_counter_ns()
        ok = self._ingest_assembled(checkpoint_id, assembled)
        tracing.complete("queryable.replica_ingest", t0,
                         _time.perf_counter_ns(), cat="queryable",
                         replica=self.name, checkpoint=int(checkpoint_id),
                         ingested=bool(ok))
        return ok

    def _ingest_assembled(self, checkpoint_id: int,
                          assembled: Dict[str, Any]) -> bool:
        self.observe_completed(checkpoint_id)
        entry = assembled.get(self.spec.uid)
        if entry is None:
            # uid not found verbatim: tolerate chained/prefixed uids
            for uid, val in assembled.items():
                if isinstance(val, dict) and str(self.spec.uid) in str(uid):
                    entry = val
                    break
        if not isinstance(entry, dict):
            return False
        sub_snaps = entry.get("subtasks", [entry])
        shards: List[ReplicaShard] = []
        for i, sub in enumerate(sub_snaps):
            keyed = _find_keyed_snapshot(sub)
            if keyed is None:
                # a subtask that saw no records yet has no key index — it
                # still OWNS its key-group range, so the ROUTING
                # parallelism below stays len(sub_snaps) (routing with a
                # keyed-only count would send its neighbours' keys to the
                # wrong shard)
                continue
            shards.extend(self._shards_of(i, len(sub_snaps), keyed))
        with self._lock:
            old_topo = tuple((s.index, s.key_groups, s.row_range is not None)
                             for s in self._shards)
            new_topo = tuple((s.index, s.key_groups, s.row_range is not None)
                             for s in shards)
            if self._shards and old_topo != new_topo:
                self._catch_ups += 1     # restore/rescale: re-sharded
            self._shards = tuple(shards)
            self._parallelism = max(len(sub_snaps), 1)
            self._serving_cid = int(checkpoint_id)
            self._serving_since_ms = clock.now_ms()
            self._ingests += 1
            # ids at or below the serving point can never contribute to
            # lag again: prune so the advertised set (and the lag scan
            # under this lock) stays O(lag), not O(lifetime checkpoints)
            self._advertised = {c for c in self._advertised
                                if c > self._serving_cid}
        return bool(shards)

    def _shards_of(self, subtask: int, parallelism: int,
                   keyed: Dict[str, Any]) -> List[ReplicaShard]:
        from flink_tpu.core.keygroups import compute_key_group_range
        keys = _restore_keys(keyed)
        if SLICES_KEY in keyed:
            # mesh writer: one replica shard per slice, manifest-driven
            out = []
            for s in sorted(keyed[SLICES_KEY], key=lambda s: s["shard"]):
                lo, hi = s["row_range"]
                cols, live = self._combine(np.asarray(s["counts"]),
                                           [np.asarray(l)
                                            for l in s["leaves"]])
                out.append(ReplicaShard(
                    int(s["shard"]), tuple(s["key_groups"]), (int(lo),
                                                              int(hi)),
                    keys[lo:hi][live], cols))
            return out
        counts = np.asarray(keyed["counts"])
        leaves = [np.asarray(l) for l in keyed["leaves"]] \
            if "leaves" in keyed else []
        if counts.size == 0 or not leaves:
            cols: Dict[str, np.ndarray] = {}
            live = np.zeros(0, np.int64)
            keys = keys[:0]
        else:
            cols, live = self._combine(counts, leaves)
            keys = keys[: counts.shape[0]][live]
        kg = compute_key_group_range(self.max_parallelism, parallelism,
                                     subtask)
        return [ReplicaShard(subtask, (kg.start, kg.end), None, keys, cols)]

    def _combine(self, counts: np.ndarray, leaves: List[np.ndarray]
                 ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """Merge retained panes per key (identity cells are no-ops by
        construction) and evaluate the aggregate's result — the same pane
        combine a host-tier fire runs.  Returns (result columns over LIVE
        keys, live-row index)."""
        from flink_tpu.core.functions import SCATTER_UFUNCS
        total = counts.sum(axis=1)
        live = np.flatnonzero(total > 0)
        combined = []
        for kind, leaf in zip(self.spec.kinds, leaves):
            ufunc = SCATTER_UFUNCS[kind]
            combined.append(ufunc.reduce(leaf[live], axis=1))
        cols = self.spec.result_columns(combined) if live.size else {}
        return cols, live

    # ------------------------------------------------------------- tailing
    def start_tailing(self) -> "CheckpointReplica":
        """Poll the checkpoint storage for new completed checkpoints on a
        daemon thread (the cross-process feed).  The metadata listing
        (``checkpoint_ids``) always runs — lag stays advertised — while the
        bulk fetch fires :data:`REPLICA_FETCH_POINT` first, so partition/
        slow-disk nemeses act on the data plane only."""
        if self.storage is None:
            raise ValueError("start_tailing needs a checkpoint storage")
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._tail_loop,
                                        name=f"replica-{self.spec.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None

    def poll_once(self) -> bool:
        """One tail round: advertise the head, fetch+ingest if behind.
        Returns True when an ingest happened."""
        try:
            ids = self.storage.checkpoint_ids()
        except Exception:  # noqa: BLE001 — listing flake: retry next round
            return False
        for cid in ids:
            self.observe_completed(cid)
        if not ids:
            return False
        head = max(ids)
        with self._lock:
            if self._serving_cid is not None and head <= self._serving_cid:
                return False
        if not chaos.fire(REPLICA_FETCH_POINT, checkpoint_id=head,
                          direction="storage->replica",
                          replica=self.name):
            return False                 # partitioned: keep serving stale
        try:
            snap = self.storage.load(head)
        except Exception:  # noqa: BLE001 — fetch flake/corruption: the
            self._fetch_errors += 1      # replica keeps serving, retries
            return False
        return self.ingest_assembled(head, snap)

    def _tail_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the tailer must survive
                self._fetch_errors += 1

    # ------------------------------------------------------------- queries
    def lookup_batch(self, keys) -> Tuple[np.ndarray,
                                          List[Optional[Dict[str, Any]]],
                                          Dict[str, Any]]:
        keys = coerce_keys(keys)
        with self._lock:
            shards = self._shards
            parallelism = self._parallelism
        n = len(keys)
        found = np.zeros(n, bool)
        values: List[Optional[Dict[str, Any]]] = [None] * n
        if shards:
            sliced = any(s.row_range is not None for s in shards)
            if not sliced and parallelism > 1:
                # hash-partitioned writer: route to the owning shard exactly
                # like a record (key group -> contiguous range)
                owner = route_keys(keys, parallelism, self.max_parallelism)
                by_subtask = {s.index: s for s in shards}
                for sub in np.unique(owner).tolist():
                    shard = by_subtask.get(int(sub))
                    if shard is None:
                        continue
                    sel = np.flatnonzero(owner == sub)
                    self._serve(shard, keys, sel, found, values)
            else:
                # slot-range tiled slices (mesh writer) or parallelism 1:
                # scan shards; a key lives in exactly one
                for shard in shards:
                    pending = np.flatnonzero(~found)
                    if pending.size == 0:
                        break
                    self._serve(shard, keys, pending, found, values)
        return found, values, self.tags()

    @property
    def epoch(self) -> Optional[int]:
        """Content version for the hot-key response cache: the serving
        checkpoint id (cache entries die the moment a newer checkpoint is
        ingested — the invalidation contract)."""
        return self._serving_cid

    def lookup_batch_columnar(self, keys) -> Tuple[np.ndarray,
                                                   Dict[str, np.ndarray],
                                                   Dict[str, Any]]:
        """Binary-wire twin of :meth:`lookup_batch`: dense result columns
        gathered per shard with zero per-key Python objects."""
        keys = coerce_keys(keys)
        with self._lock:
            shards = self._shards
            parallelism = self._parallelism
        n = len(keys)
        found = np.zeros(n, bool)
        cols: Dict[str, np.ndarray] = {}
        if shards:
            sliced = any(s.row_range is not None for s in shards)
            if not sliced and parallelism > 1:
                owner = route_keys(keys, parallelism, self.max_parallelism)
                by_subtask = {s.index: s for s in shards}
                for sub in np.unique(owner).tolist():
                    shard = by_subtask.get(int(sub))
                    if shard is None:
                        continue
                    sel = np.flatnonzero(owner == sub)
                    self._serve_columnar(shard, keys, sel, found, cols)
            else:
                for shard in shards:
                    pending = np.flatnonzero(~found)
                    if pending.size == 0:
                        break
                    self._serve_columnar(shard, keys, pending, found, cols)
        return found, cols, self.tags()

    @staticmethod
    def _serve_columnar(shard: ReplicaShard, keys: np.ndarray,
                        sel: np.ndarray, found: np.ndarray,
                        cols: Dict[str, np.ndarray]) -> None:
        idx = shard.rows.locate(np.asarray(keys)[sel])
        hit = idx >= 0
        if not hit.any():
            return
        qsel = sel[hit]
        rows = idx[hit]
        n = len(keys)
        for c, a in shard.rows.cols.items():
            out = cols.get(c)
            if out is None:
                out = cols[c] = (np.empty(n, object)
                                 if a.dtype.kind == "O"
                                 else np.zeros(n, a.dtype))
            got = a[rows]
            out[qsel] = got if out.dtype == a.dtype \
                else got.astype(out.dtype)
        found[qsel] = True

    @staticmethod
    def _serve(shard: ReplicaShard, keys: np.ndarray, sel: np.ndarray,
               found: np.ndarray, values: List) -> None:
        idx = shard.rows.locate(np.asarray(keys)[sel])
        hit = idx >= 0
        if not hit.any():
            return
        for qi, row in zip(sel[hit].tolist(), idx[hit].tolist()):
            values[qi] = {c: plain(a[row])
                          for c, a in shard.rows.cols.items()}
            found[qi] = True

    def tags(self) -> Dict[str, Any]:
        with self._lock:
            lag = self._lag_locked()
            return {"consistency": "checkpoint",
                    "checkpoint_id": self._serving_cid,
                    "replica_lag_checkpoints": lag,
                    "replica_lag_ms": self._lag_ms_locked(lag)}

    def _lag_locked(self) -> int:
        # the set is pruned to ids > serving at every ingest/observe
        return len(self._advertised)

    def _lag_ms_locked(self, lag: int) -> float:
        if lag <= 0 or self._serving_since_ms is None:
            return 0.0
        return float(max(0, clock.now_ms() - self._serving_since_ms))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lag = self._lag_locked()
            return {
                "serving_checkpoint_id": self._serving_cid,
                "advertised_pending_checkpoints": len(self._advertised),
                "replica_lag_checkpoints": lag,
                "replica_lag_ms": self._lag_ms_locked(lag),
                "ingests": self._ingests,
                "catch_ups": self._catch_ups,
                "fetch_errors": self._fetch_errors,
                "keys": sum(s.n_keys for s in self._shards),
                "shards": [s.manifest() for s in self._shards],
            }


class ReplicaGroup:
    """N-replica read fan-out for ONE state (ISSUE-13): reads load-balance
    across member :class:`CheckpointReplica` instances and always prefer
    the FRESHEST members — a member partitioned from the checkpoint stream
    (or simply behind) sees its traffic fail over to a sibling without a
    single read error, and the staleness stats NAME the laggards so the
    lag gauge points at the dead replica, not at an average.

    The group answers the exact replica interface the registry, the feed
    thread, and the wire layer already speak (``observe_completed`` /
    ``ingest_assembled`` / ``lookup_batch[{_columnar}]`` / ``tags`` /
    ``stats`` / ``start_tailing`` / ``stop``), so one registered entry is
    transparently one replica or N."""

    def __init__(self, members: List[CheckpointReplica]):
        if not members:
            raise ValueError("ReplicaGroup needs at least one member")
        self.members = list(members)
        # member names must be unique: the stats/laggards surface is
        # name-keyed, and chaos scoping (Partition(replica=...)) targets
        # by name — suffix duplicates (the CheckpointReplica default name
        # is the state name for every member)
        seen: Dict[str, int] = {}
        for m in self.members:
            n = seen.get(m.name, 0)
            seen[m.name] = n + 1
            if n:
                m.name = f"{m.name}#r{n}"
        self._rr = 0
        self._lock = threading.Lock()

    @property
    def spec(self):
        return self.members[0].spec

    # ---------------------------------------------------------------- feed
    def observe_completed(self, checkpoint_id: int) -> None:
        for m in self.members:
            m.observe_completed(checkpoint_id)

    def ingest_assembled(self, checkpoint_id: int,
                         assembled: Dict[str, Any]) -> bool:
        ok = False
        for m in self.members:
            ok = m.ingest_assembled(checkpoint_id, assembled) or ok
        return ok

    def start_tailing(self) -> "ReplicaGroup":
        for m in self.members:
            m.start_tailing()
        return self

    def stop(self) -> None:
        for m in self.members:
            m.stop()

    # -------------------------------------------------------------- queries
    def _pick(self) -> CheckpointReplica:
        """Freshest-first load balancing: candidates are the members
        serving the newest checkpoint id (None = never ingested sorts
        last); ties rotate round-robin so read load spreads evenly across
        the healthy siblings."""
        best: List[CheckpointReplica] = []
        best_cid = None
        for m in self.members:
            cid = m.epoch
            rank = -1 if cid is None else int(cid)
            if best_cid is None or rank > best_cid:
                best_cid, best = rank, [m]
            elif rank == best_cid:
                best.append(m)
        with self._lock:
            self._rr += 1
            return best[self._rr % len(best)]

    @property
    def epoch(self) -> Optional[int]:
        return self._pick_epoch()

    def _pick_epoch(self) -> Optional[int]:
        cids = [m.epoch for m in self.members if m.epoch is not None]
        return max(cids) if cids else None

    def lookup_batch(self, keys):
        return self._pick().lookup_batch(keys)

    def lookup_batch_columnar(self, keys):
        return self._pick().lookup_batch_columnar(keys)

    def tags(self) -> Dict[str, Any]:
        return self._pick().tags()

    # -------------------------------------------------------------- surface
    def stats(self) -> Dict[str, Any]:
        """The freshest member's serving view (what reads actually see),
        plus per-member staleness and the NAMES of the members lagging
        behind it — the failover observability contract."""
        per = {m.name: m.stats() for m in self.members}
        head = self._pick_epoch()
        laggards = sorted(
            m.name for m in self.members
            if head is not None and (m.epoch is None or m.epoch < head))
        serving = self._pick().stats()
        out = dict(serving)
        out["replicas"] = len(self.members)
        out["members"] = per
        out["laggards"] = laggards
        return out
