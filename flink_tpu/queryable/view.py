"""Fire-time published live-read views: barrier-free queryable window state.

The live consistency level of the queryable serving tier (ISSUE-9 layer 1).
Instead of probing the operator's key index from a foreign thread (the old
``server.py`` stub — a read racing the task thread's backend), the operator
PUBLISHES an immutable columnar view of every window it fires: the very
``(keys, values)`` arrays the fire emitted downstream, tagged with the
watermark and last-completed-checkpoint id they reflect.  Those values come
off the host value mirror after the pane-granular device-delta catch-up
(``_fire_window_host`` -> ``_devprobe_sync_mirror`` -> ``wm_apply_delta``),
so a live read is **bit-equal to the operator's own fire-time values** for
already-fired panes — on any tier (host/device/deferred), at any mesh size,
and through a quarantine degrade, because every fire path funnels through
the same publish hook.

Concurrency contract: publishing swaps one tuple reference on the task
thread (queries never see a half-built segment); lookups read that
reference once and then touch only frozen arrays.  No locks, no pipeline
barrier, no operator state reads — the ``paging_stats()`` monitoring
contract, extended to values.  The per-segment sort index is built lazily
on the FIRST query (never on the hot path) and memoized; a benign race
builds it twice with identical results.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class _Segment:
    """One fired window's emissions, frozen: ``keys[i]`` emitted the value
    row ``{col: cols[col][i]}`` when the window fired."""

    __slots__ = ("window_start", "window_end", "keys", "cols", "watermark",
                 "checkpoint_id", "_order", "_sorted_keys", "_key_map")

    def __init__(self, window_start: int, window_end: int, keys: np.ndarray,
                 cols: Dict[str, np.ndarray], watermark: int,
                 checkpoint_id: Optional[int]):
        self.window_start = int(window_start)
        self.window_end = int(window_end)
        self.keys = keys
        self.cols = cols
        self.watermark = int(watermark)
        self.checkpoint_id = checkpoint_id
        self._order = None        # lazy argsort (int keys)
        self._sorted_keys = None
        self._key_map = None      # lazy dict (object keys)

    def locate(self, keys: np.ndarray) -> np.ndarray:
        """Row index per queried key, -1 where absent."""
        out = np.full(len(keys), -1, np.int64)
        if self.keys.size == 0 or len(keys) == 0:
            return out
        if self.keys.dtype.kind in "iu" and \
                np.asarray(keys).dtype.kind in "iu":
            if self._order is None:
                order = np.argsort(self.keys, kind="stable")
                self._sorted_keys = self.keys[order]
                self._order = order       # publish AFTER sorted_keys exists
            q = np.asarray(keys, self.keys.dtype)
            pos = np.searchsorted(self._sorted_keys, q)
            pos = np.minimum(pos, self._sorted_keys.size - 1)
            hit = self._sorted_keys[pos] == q
            out[hit] = self._order[pos[hit]]
            return out
        if self._key_map is None:
            self._key_map = {k: i for i, k in enumerate(self.keys.tolist())}
        kmap = self._key_map
        for i, k in enumerate(np.asarray(keys, object).tolist()):
            out[i] = kmap.get(k, -1)
        return out


class WindowReadView:
    """Per-operator live-read view: a bounded ring of fired-window segments.

    ``publish`` is called by the firing operator on its task thread (cost:
    one tuple rebuild per fired window — fires are orders of magnitude
    rarer than records); ``lookup_batch`` is called by query threads and
    serves each key's value from the NEWEST segment containing it."""

    def __init__(self, key_column: str, retain_windows: int = 4):
        self.key_column = key_column
        self.retain_windows = max(1, int(retain_windows))
        self._segments: Tuple[_Segment, ...] = ()
        self.published_windows = 0

    @property
    def epoch(self) -> int:
        """Monotone content version: bumps on every publish.  The hot-key
        response cache keys its live entries on this (checkpoint-replica
        entries key on the serving checkpoint id)."""
        return self.published_windows

    # ----------------------------------------------------------- task thread
    def publish(self, keys: np.ndarray, cols: Dict[str, Any], window,
                watermark: int, checkpoint_id: Optional[int]) -> None:
        """Retain one fire's emissions (zero-copy: the emitted arrays are
        shared, never mutated after emission)."""
        seg = _Segment(window.start, window.end, np.asarray(keys),
                       {c: np.asarray(v) for c, v in cols.items()},
                       watermark, checkpoint_id)
        segs = (seg,) + self._segments
        # retain the newest few distinct windows (chunked fires — spilled
        # keys, paged tiers — publish several segments for one window)
        starts: List[int] = []
        keep: List[_Segment] = []
        for s in segs:
            if s.window_start not in starts:
                starts.append(s.window_start)
            if len(starts) > self.retain_windows:
                break
            keep.append(s)
        self._segments = tuple(keep)   # atomic swap
        # epoch bumps AFTER the swap: a cached lookup racing publish may
        # memoize the old segments under the old epoch (correct — the
        # next epoch read invalidates it), never old data under the new
        # epoch (which nothing would ever invalidate)
        self.published_windows += 1

    # ---------------------------------------------------------- query threads
    def tags(self) -> Dict[str, Any]:
        segs = self._segments
        if not segs:
            return {"watermark": None, "checkpoint_id": None,
                    "window_start": None, "window_end": None}
        newest = segs[0]
        return {"watermark": newest.watermark,
                "checkpoint_id": newest.checkpoint_id,
                "window_start": newest.window_start,
                "window_end": newest.window_end}

    def lookup_batch(self, keys: np.ndarray
                     ) -> Tuple[np.ndarray, List[Optional[Dict[str, Any]]],
                                Dict[str, Any]]:
        """(found mask, per-key value dict or None, tags).  Each key's value
        comes from the newest segment containing it — the last fired window
        the key contributed to."""
        segs = self._segments
        n = len(keys)
        found = np.zeros(n, bool)
        values: List[Optional[Dict[str, Any]]] = [None] * n
        remaining = np.arange(n)
        for seg in segs:
            if remaining.size == 0:
                break
            idx = seg.locate(np.asarray(keys)[remaining])
            hit = idx >= 0
            if not hit.any():
                continue
            rows = idx[hit]
            for qi, row in zip(remaining[hit].tolist(), rows.tolist()):
                v = {c: plain(a[row]) for c, a in seg.cols.items()}
                v["window_start"] = seg.window_start
                v["window_end"] = seg.window_end
                values[qi] = v
            found[remaining[hit]] = True
            remaining = remaining[~hit]
        return found, values, self.tags()

    def lookup_batch_columnar(self, keys: np.ndarray
                              ) -> Tuple[np.ndarray, Dict[str, np.ndarray],
                                         Dict[str, Any]]:
        """The binary-wire fast path: (found mask, dense result columns,
        tags) with ZERO per-key Python objects — each segment's hits are
        gathered with one fancy-index per column.  Unfound rows are
        zero filler (the wire ships the found plane alongside).  Window
        bounds ride as two extra int64 columns so the answer carries the
        same information as the dict path's per-key values."""
        segs = self._segments
        keys = np.asarray(keys)
        n = len(keys)
        found = np.zeros(n, bool)
        cols: Dict[str, np.ndarray] = {}
        remaining = np.arange(n)
        for seg in segs:
            if remaining.size == 0:
                break
            idx = seg.locate(keys[remaining])
            hit = idx >= 0
            if not hit.any():
                continue
            qsel = remaining[hit]
            rows = idx[hit]
            if not cols:
                for c, a in seg.cols.items():
                    cols[c] = (np.empty(n, object) if a.dtype.kind == "O"
                               else np.zeros(n, a.dtype))
                cols["window_start"] = np.zeros(n, np.int64)
                cols["window_end"] = np.zeros(n, np.int64)
            for c, a in seg.cols.items():
                out = cols.get(c)
                if out is None:
                    continue
                got = a[rows]
                out[qsel] = got if out.dtype == a.dtype \
                    else got.astype(out.dtype)
            cols["window_start"][qsel] = seg.window_start
            cols["window_end"][qsel] = seg.window_end
            found[qsel] = True
            remaining = remaining[~hit]
        return found, cols, self.tags()


def plain(v):
    """numpy scalar/array -> JSON-serializable python value (the one
    wire-coercion rule of the queryable package — view, replica, and
    legacy backend answers all go through here)."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def is_scalar_key(k) -> bool:
    """The protocol's key contract: JSON scalars only (str/int/float/
    bool) — lists/dicts/null would crash hashing/routing deep in a
    handler thread instead of returning a clean error."""
    return isinstance(k, (str, int, float, bool))


def coerce_keys(keys) -> np.ndarray:
    """Wire-format (JSON) keys -> the lookup key array: all-int batches
    become int64 (the dense key-index dtype), anything else stays object
    (string/mixed keys route through the object key path)."""
    if isinstance(keys, np.ndarray):
        return keys
    if all(isinstance(k, (int, np.integer))
           and not isinstance(k, bool) for k in keys):
        return np.asarray(keys, np.int64)
    return np.asarray(list(keys), object)


def route_keys(keys: np.ndarray, parallelism: int,
               max_parallelism: int) -> np.ndarray:
    """Owning subtask per key — EXACTLY the record route (one shared
    implementation: ``core/keygroups.route_raw_keys``).  A query for key
    k lands on the operator instance whose state holds k because both
    sides run the same assignment."""
    from flink_tpu.core.keygroups import route_raw_keys
    return route_raw_keys(keys, parallelism, max_parallelism)
