"""Hot-key response cache for the queryable serving tier (ISSUE-13).

Production read traffic is zipfian: a handful of hot keys absorb most of
the lookup volume, and re-running the segment/shard locate + gather for
the same (state, key, consistency) between state changes is pure waste.
This cache memoizes **per-key answer rows** under an explicit content
epoch:

- ``checkpoint`` consistency: the epoch is the replica's serving
  checkpoint id — every completed-checkpoint ingest silently invalidates
  all of the state's cached rows (an entry whose stored epoch no longer
  matches reads as a miss and is dropped);
- ``live`` consistency: the epoch is the view's publish counter — every
  fired window invalidates, so a cached row can never outlive the value
  it memoized.

Entries are ``(found, row)`` pairs — a *negative* answer (key absent) is
cacheable under the same epoch rule.  Bounded LRU; thread-safe; reads are
batched (``get_many``/``put_many``) so the serve path pays one lock
round-trip per request, not per key.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

#: default capacity: enough for a serious hot set, bounded against
#: high-cardinality scans evicting rather than growing
DEFAULT_CAPACITY = 1 << 16


class HotKeyCache:
    """Bounded LRU of per-key lookup answers, invalidated by epoch."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._d: "OrderedDict[Tuple, Tuple[Any, bool, Any]]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def get_many(self, state: str, consistency: str, epoch,
                 keys) -> Tuple[Dict[int, Tuple[bool, Any]], List[int]]:
        """-> ({query index: (found, row)}, [missing query indices]).
        Entries stored under a different epoch count as invalidations and
        are evicted on sight."""
        hits: Dict[int, Tuple[bool, Any]] = {}
        missing: List[int] = []
        with self._lock:
            d = self._d
            for i, k in enumerate(keys):
                ck = (state, consistency, k)
                got = d.get(ck)
                if got is None:
                    missing.append(i)
                elif got[0] != epoch:
                    del d[ck]
                    self.invalidations += 1
                    missing.append(i)
                else:
                    d.move_to_end(ck)
                    hits[i] = (got[1], got[2])
            self.hits += len(hits)
            self.misses += len(missing)
        return hits, missing

    def put_many(self, state: str, consistency: str, epoch, keys,
                 entries) -> None:
        """Store ``entries[i] = (found, row)`` for each key (row is an
        opaque value — the dict path stores value dicts, the columnar
        path stores per-key column tuples)."""
        with self._lock:
            d = self._d
            for k, (found, row) in zip(keys, entries):
                d[(state, consistency, k)] = (epoch, found, row)
                d.move_to_end((state, consistency, k))
            while len(d) > self.capacity:
                d.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {"entries": len(self._d),
                    "capacity": self.capacity,
                    "hits": self.hits,
                    "misses": self.misses,
                    "invalidations": self.invalidations,
                    "hit_rate": round(self.hits / total, 4) if total else 0.0}
