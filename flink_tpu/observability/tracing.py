"""Structured span journal: a lock-free per-process tracing ring.

Analog of the reference's (FLIP-165 era) always-on runtime observability,
in the spirit of Dapper: instrumentation sites call :func:`span` /
:func:`instant` and pay **one module-attribute read plus a None check**
when tracing is off — the journal is a module singleton installed with
:func:`install` and every emit helper early-outs on ``_JOURNAL is None``,
so the hot paths can afford unconditional instrumentation.

Design points:

- **Lock-free bounded ring**: span slots are reserved with one
  ``next()`` on an ``itertools.count`` — a single C call, atomic under
  the GIL — so concurrent recorders never contend on a mutex and every
  reserved slot has exactly one writer; once the capacity is exhausted
  new spans are DROPPED and counted (:attr:`SpanJournal.dropped`) —
  memory stays bounded no matter how hot the instrumented site is, and
  the drop counter makes truncation loud instead of silent.
- **Timestamps**: span begin/end use ``time.perf_counter_ns`` (monotone,
  ns precision — hot-stage phases are sub-ms); the journal anchors that
  clock to wall time THROUGH the ``utils/clock.py`` seam at creation, so
  exported timelines live on the (chaos-skewable) wall clock and
  cross-process assembly can align per-worker anchors.
- **Chrome trace-event export**: :func:`to_chrome` renders a journal
  snapshot as the trace-event JSON dialect Perfetto / chrome://tracing
  load directly (``ph: "X"`` complete spans, ``ph: "i"`` instants,
  metadata events naming processes/threads).

This module imports only the standard library (plus the clock seam), so
every runtime layer can import it without cycles or import cost.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional

from flink_tpu.utils import clock

__all__ = ["SpanJournal", "install", "uninstall", "active", "enabled",
           "span", "instant", "complete", "to_chrome",
           "acquire_for_execution", "release_after_execution"]

#: default ring capacity — ~8k spans cover minutes of checkpoint/phase
#: traffic; bench --trace installs a much larger ring explicitly
DEFAULT_CAPACITY = 8192


class SpanJournal:
    """Bounded per-process ring of structured spans.

    Each entry is a tuple ``(ph, ts_ns, dur_ns, name, cat, tid, args)``
    with ``ph`` one of ``"X"`` (complete span) / ``"i"`` (instant),
    ``ts_ns`` a ``perf_counter_ns`` reading, ``tid`` the recording
    thread's name and ``args`` a small dict of scalars (or None).
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock_: Optional["clock.Clock"] = None):
        self._cap = max(1, int(capacity))
        self._clock = clock_ if clock_ is not None else clock.SYSTEM_CLOCK
        self._buf: List[Optional[tuple]] = [None] * self._cap
        #: lock-free slot reservation: ``next()`` is one atomic C call,
        #: so the reservation count is exact under concurrent recording
        self._reserve = itertools.count()
        #: wall/perf anchor pair: maps perf_counter_ns readings onto the
        #: (chaos-skewable) wall clock at export time
        self.anchor_wall_us = int(self._clock.now_ms() * 1000)
        self.anchor_perf_ns = time.perf_counter_ns()

    # -- recording ---------------------------------------------------------
    def record(self, ph: str, ts_ns: int, dur_ns: int, name: str,
               cat: str, args: Optional[Dict[str, Any]] = None) -> None:
        i = next(self._reserve)        # atomic slot reservation
        if i >= self._cap:
            return                     # full: drop, counted via _reserved
        self._buf[i] = (ph, ts_ns, dur_ns, name, cat,
                        threading.current_thread().name, args)

    def _reserved(self) -> int:
        """Total reservations so far WITHOUT consuming a slot —
        ``itertools.count`` exposes its next value only through the
        pickle protocol (``count(n).__reduce__() == (count, (n,))``).
        Cold-path reads only (properties, snapshot)."""
        return self._reserve.__reduce__()[1][0]

    def reset(self) -> None:
        """Fresh ring + drop counter + anchors: a new job execution in the
        same process starts from an empty timeline instead of inheriting
        (or being starved by) the previous job's spans.  Spans a racing
        recorder is mid-writing when reset lands may bleed into the new
        ring — one stray span beats a dead or leaked trace."""
        fresh: List[Optional[tuple]] = [None] * self._cap
        # counter first, buffer second: a racing recorder that reserved
        # from the OLD counter writes a stale high slot into whichever
        # buffer it sees — spans() skips the stale None-gaps either way
        self._reserve = itertools.count()
        self._buf = fresh
        self.anchor_wall_us = int(self._clock.now_ms() * 1000)
        self.anchor_perf_ns = time.perf_counter_ns()

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def recorded(self) -> int:
        return min(self._reserved(), self._cap)

    @property
    def dropped(self) -> int:
        return max(0, self._reserved() - self._cap)

    # -- reading -----------------------------------------------------------
    def spans(self) -> List[tuple]:
        """Recorded spans in reservation order (in-flight writes — slots
        reserved but not yet stored by another thread — are skipped)."""
        return [s for s in self._buf[:self.recorded] if s is not None]

    def snapshot(self) -> Dict[str, Any]:
        """Picklable journal dump — the unit cross-process assembly ships
        (``assembly.merge_timelines``) and exporters render."""
        return {"anchor_wall_us": self.anchor_wall_us,
                "anchor_perf_ns": self.anchor_perf_ns,
                "spans": self.spans(),
                "dropped": self.dropped,
                "capacity": self._cap}

    def summary(self) -> Dict[str, Any]:
        """Monitoring-grade rollup (``job_status()["trace"]`` backing):
        span/drop counts plus per-category tallies."""
        cats: Dict[str, int] = {}
        for s in self.spans():
            cats[s[4]] = cats.get(s[4], 0) + 1
        return {"enabled": True, "spans": self.recorded,
                "dropped": self.dropped, "capacity": self._cap,
                "categories": cats}


# ---------------------------------------------------------------------------
# module singleton + emit helpers (the instrumentation-site API)
# ---------------------------------------------------------------------------

_JOURNAL: Optional[SpanJournal] = None


def install(journal: Optional[SpanJournal] = None,
            capacity: int = DEFAULT_CAPACITY) -> SpanJournal:
    """Install ``journal`` (or a fresh ring of ``capacity``) as THE
    process journal; returns it.  Instrumentation all over the runtime
    starts recording immediately."""
    global _JOURNAL
    _JOURNAL = journal if journal is not None else SpanJournal(capacity)
    return _JOURNAL


def uninstall() -> Optional[SpanJournal]:
    """Disable tracing; returns the journal that was installed (so its
    contents can still be exported)."""
    global _JOURNAL
    j, _JOURNAL = _JOURNAL, None
    return j


def active() -> Optional[SpanJournal]:
    return _JOURNAL


def enabled() -> bool:
    return _JOURNAL is not None


def adopt_or_install(capacity: int) -> "tuple[SpanJournal, bool]":
    """Constructor-time arm of the ownership state machine (shared by
    both cluster frontends): adopt the live ring — its installer owns its
    lifetime and capacity choice — else install an owned ring of
    ``capacity``.  Unlike :func:`acquire_for_execution` this never
    resets: construction must not clear a ring another job is still
    recording into."""
    act = active()
    if act is not None:
        return act, False
    return install(capacity=int(capacity)), True


def acquire_for_execution(journal: Optional[SpanJournal], owned: bool,
                          capacity: Optional[int] = None
                          ) -> "tuple[SpanJournal, bool]":
    """Claim the process journal for one job execution; returns the
    ``(journal, owned)`` pair the run will record into and report from.

    Both cluster frontends (MiniCluster.execute, ProcessCluster.run) go
    through this one state machine so the ownership invariants live in a
    single place:

    - **own ring, singleton free or ours**: re-install (a previous
      execution released it) and reset — job B must not inherit job A's
      spans or start against A's already-consumed capacity (the ring
      drops when full, so a long-lived process would go trace-dead).
    - **own ring, FOREIGN ring live**: re-adopt the live ring — our ring
      is not the one instrumentation records into, so installing or
      reporting from it would serve a stale timeline as this job's.
    - **adopted ring, singleton free**: its owner released it — stand up
      a fresh OWNED ring (``capacity`` or the adopted ring's) instead of
      running trace-dead while reporting the stale adopted spans.
    - **adopted or foreign ring live**: (re-)adopt it; the installer
      resets/releases it, not us.
    """
    act = active()
    if owned:
        if act is None or act is journal:
            install(journal)
            journal.reset()
            return journal, True
        return act, False
    if act is None:
        if capacity is None:
            capacity = (journal.capacity if journal is not None
                        else DEFAULT_CAPACITY)
        return install(capacity=int(capacity)), True
    return act, False


def release_after_execution(journal: Optional[SpanJournal],
                            owned: bool) -> None:
    """Release an OWNED ring at execution end so the next tracing-enabled
    cluster in this process installs fresh instead of adopting (and
    reporting) this job's spans; the caller's handle keeps serving
    job_status()/trace exports afterwards.  Adopted rings are the
    installer's to release — left untouched."""
    if owned and active() is journal:
        uninstall()


class _SpanCtx:
    """``with span("name", cat=...):`` — records one complete span on
    exit; a no-op (no clock reads) when tracing is off at entry."""

    __slots__ = ("_name", "_cat", "_args", "_t0", "_j")

    def __init__(self, name: str, cat: str, args: Optional[dict]):
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._j = _JOURNAL
        if self._j is not None:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        j = self._j
        if j is not None:
            t1 = time.perf_counter_ns()
            j.record("X", self._t0, t1 - self._t0, self._name, self._cat,
                     self._args)
        return False


def span(name: str, cat: str = "runtime", **args) -> _SpanCtx:
    """Begin/end span context manager (``ph: "X"`` complete event)."""
    return _SpanCtx(name, cat, args or None)


def instant(name: str, cat: str = "runtime", **args) -> None:
    """Point-in-time event (``ph: "i"``)."""
    j = _JOURNAL
    if j is not None:
        j.record("i", time.perf_counter_ns(), 0, name, cat, args or None)


def complete(name: str, start_ns: int, end_ns: int,
             cat: str = "runtime", **args) -> None:
    """Complete span with explicit ``perf_counter_ns`` endpoints — for
    sites that already timed themselves (phase timers, checkpoint
    trigger→complete)."""
    j = _JOURNAL
    if j is not None:
        j.record("X", start_ns, max(0, end_ns - start_ns), name, cat,
                 args or None)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

def to_chrome(snap: Dict[str, Any], pid: int = 0,
              process_name: str = "flink-tpu",
              offset_us: float = 0.0) -> List[Dict[str, Any]]:
    """Render a journal snapshot as Chrome trace-event dicts
    (Perfetto-loadable).  ``offset_us`` shifts this journal's wall
    timeline — cross-process assembly passes the estimated per-worker
    clock offset so every process lands on ONE job timeline."""
    wall0 = snap["anchor_wall_us"] + offset_us
    perf0 = snap["anchor_perf_ns"]
    events: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": process_name}}]
    seen_tids: Dict[str, int] = {}
    for ph, ts_ns, dur_ns, name, cat, tname, args in snap["spans"]:
        tid = seen_tids.setdefault(tname, len(seen_tids) + 1)
        ev: Dict[str, Any] = {
            "name": name, "cat": cat, "ph": ph, "pid": pid, "tid": tid,
            "ts": round(wall0 + (ts_ns - perf0) / 1000.0, 3)}
        if ph == "X":
            ev["dur"] = round(dur_ns / 1000.0, 3)
        elif ph == "i":
            ev["s"] = "t"                  # thread-scoped instant
        if args:
            ev["args"] = dict(args)
        events.append(ev)
    for tname, tid in seen_tids.items():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
    return events
