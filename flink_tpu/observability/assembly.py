"""Cross-process trace assembly: per-worker journals → ONE job timeline.

Each process records spans against its own wall clock (the journal's
anchor).  To merge worker rings into the coordinator's timeline the
clocks must be aligned; the coordinator estimates each worker's offset
with the classic NTP midpoint: it stamps ``t0`` when the trace request
leaves, the worker stamps its own wall ``w`` when dumping, the
coordinator stamps ``t1`` on receipt — ``offset = w - (t0 + t1) / 2``,
accurate to half the request round trip (µs–ms on the loopback control
plane, far below the ms-scale spans being aligned).

:func:`merge_timelines` renders everything as one Chrome trace-event
JSON document (Perfetto-loadable): the coordinator is pid 0, worker ``i``
is pid ``i + 1``, and every worker's events are shifted by its estimated
offset so one "why was THIS window fire slow" question reads across
process boundaries.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.observability.tracing import to_chrome

__all__ = ["estimate_offset_ms", "merge_timelines"]


def estimate_offset_ms(t0_ms: float, t1_ms: float,
                       worker_wall_ms: float) -> float:
    """Worker-clock minus coordinator-clock estimate (NTP midpoint):
    positive = the worker's wall clock runs ahead."""
    return worker_wall_ms - (t0_ms + t1_ms) / 2.0


def merge_timelines(local_snapshot: Optional[Dict[str, Any]],
                    worker_dumps: List[Tuple[int, Dict[str, Any], float]],
                    t0_ms: Optional[float] = None,
                    process_name: str = "coordinator") -> Dict[str, Any]:
    """Assemble one Chrome trace document from the coordinator's journal
    snapshot plus ``(worker_index, dump, t1_ms)`` tuples, where ``dump``
    is a worker's ``trace_dump`` payload (``journal`` snapshot +
    ``wall_now_ms`` + optional ``latency`` panel) and ``t1_ms`` the
    coordinator wall time its reply arrived.  ``t0_ms`` is the wall time
    the requests went out (one broadcast — shared by all workers)."""
    events: List[Dict[str, Any]] = []
    dropped = 0
    if local_snapshot is not None:
        events += to_chrome(local_snapshot, pid=0,
                            process_name=process_name)
        dropped += local_snapshot.get("dropped", 0)
    offsets: Dict[int, float] = {}
    latency: List[Dict[str, Any]] = []
    for idx, dump, t1_ms in sorted(worker_dumps, key=lambda d: d[0]):
        off_ms = 0.0
        if t0_ms is not None and dump.get("wall_now_ms") is not None:
            off_ms = estimate_offset_ms(t0_ms, t1_ms, dump["wall_now_ms"])
        offsets[idx] = round(off_ms, 3)
        snap = dump.get("journal")
        if snap is not None:
            # shift the worker's wall anchor BACK by its estimated offset
            # so its events land on the coordinator's timeline
            events += to_chrome(snap, pid=idx + 1,
                                process_name=f"worker-{idx}",
                                offset_us=-off_ms * 1000.0)
            dropped += snap.get("dropped", 0)
        for row in dump.get("latency") or []:
            latency.append({**row, "worker": idx})
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"workers": len(worker_dumps),
                          "clock_offsets_ms": offsets,
                          "dropped_spans": dropped,
                          "latency": latency}}
