"""End-to-end latency tracking from ``LatencyMarker`` flow.

Analog of the reference's ``LatencyStats`` / ``LatencyMarker`` pipeline:
sources emit markers on the ``metrics.latency.interval`` cadence (through
the injectable clock seam, so the ClockSkew nemesis covers latency
tracking like it covers timers); the markers ride the dataflow AROUND
user functions — through chains, host channels and the cross-process data
plane — and every subtask that sees one records ``now - marked_time``
into a per-``(source, source_subtask, hop)`` histogram here.  The sink
hop's histogram is therefore the end-to-end source→sink latency
distribution the paper's p99 story needs; intermediate hops decompose it
per operator.

Histograms register on a (job-scope) metric group when one is bound, so
every reporter — Prometheus summaries with ``quantile`` labels included —
exports ``latency.*`` series, alongside explicit p50/p99 gauges; the REST
latency panel and ``job_status()["latency"]`` read :meth:`panel`.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.metrics.core import Histogram
from flink_tpu.observability import tracing
from flink_tpu.utils import clock

__all__ = ["LatencyTracker", "latency_metric_name"]


def latency_metric_name(source: str, source_subtask: int, hop: str) -> str:
    """``latency.source.<src>.<i>.op.<hop>`` — the reference's
    ``latency.source_id.X.operator_id.Y.latency`` scope, readable."""
    return f"latency.source.{source}.{source_subtask}.op.{hop}"


class LatencyTracker:
    """Per-(source, operator-hop) latency histograms (``LatencyStats``)."""

    def __init__(self, clock_: Optional["clock.Clock"] = None,
                 histogram_size: int = 2048):
        self._clock = clock_ if clock_ is not None else clock.SYSTEM_CLOCK
        self._size = histogram_size
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, int, str], Histogram] = {}
        #: hops from previous executions, cleared but still REGISTERED on
        #: the metric group — a reappearing hop must reuse its registered
        #: Histogram object (``MetricGroup._register`` keeps the first
        #: metric per name) or panel and reporters would diverge
        self._retired: Dict[Tuple[str, int, str], Histogram] = {}
        self._group = None

    # -- metric-group binding ---------------------------------------------
    def bind_group(self, group) -> "LatencyTracker":
        """Register existing and future hop histograms (+ p50/p99 gauges)
        on ``group`` so the metric reporters export them."""
        with self._lock:
            self._group = group
            for key, hist in self._hists.items():
                self._register_locked(key, hist)
        return self

    def _register_locked(self, key: Tuple[str, int, str],
                         hist: Histogram) -> None:
        if self._group is None:
            return
        base = latency_metric_name(*key)
        self._group._register(base, hist)
        self._group.gauge(f"{base}.p50_ms",
                          lambda h=hist: h.get_statistics()["p50"])
        self._group.gauge(f"{base}.p99_ms",
                          lambda h=hist: h.get_statistics()["p99"])

    # -- recording ---------------------------------------------------------
    def record(self, marker, hop: str) -> float:
        """Record one marker observation at ``hop`` (a vertex uid /
        operator name); returns the sample in ms.  Negative readings
        (clock skew between emitting and observing process) clamp to 0 —
        a latency histogram must not absorb skew as negative time."""
        now_s = self._clock.now_ms_f() / 1000.0
        lat_ms = max(0.0, (now_s - marker.marked_time) * 1000.0)
        source = getattr(marker, "source", "") or \
            f"source-{marker.source_id}"
        key = (source, int(marker.subtask_index), hop)
        # parallel subtasks of one vertex share a (source, hop) histogram
        # (markers BROADCAST to every downstream subtask), and
        # Histogram.update is a multi-step mutation — serialize it.
        # Markers flow on a ms-scale cadence, so the lock is off any hot
        # path.
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = self._retired.pop(key, None)
                if hist is None:
                    hist = Histogram(size=self._size)
                self._hists[key] = hist
                self._register_locked(key, hist)
            hist.update(lat_ms)
            n = hist.get_count()
        # timeline dots are SAMPLED 1-in-64 per hop (first sample kept):
        # the span ring fills once and never wraps, and at the documented
        # ms-scale marker cadences an instant per marker would exhaust it
        # in about a minute, starving the checkpoint/hot-stage spans the
        # trace exists for — the full distribution lives in the histogram
        if n % 64 == 1:
            tracing.instant("latency.marker", cat="latency", source=source,
                            hop=hop, latency_ms=round(lat_ms, 3))
        return lat_ms

    def reset(self) -> None:
        """Start a new execution's latency view: every hop row leaves the
        panel/summary and its samples are cleared, mirroring the span
        journal's per-execution reset — job B must not report job A's
        hops or percentiles.  The Histogram objects stay registered on
        the bound metric group (retired, cleared); a hop that reappears
        reuses its registered object so reporters and the panel keep
        reading the same reservoir."""
        with self._lock:
            for key, hist in self._hists.items():
                hist.clear()
                self._retired[key] = hist
            self._hists = {}

    # -- views -------------------------------------------------------------
    def panel(self) -> List[Dict[str, Any]]:
        """Per-hop latency rows for the REST panel /
        ``job_status()["latency"]``: source identity, hop, sample count
        and p50/p95/p99/max in ms."""
        with self._lock:
            items = sorted(self._hists.items())
        out = []
        for (source, subtask, hop), hist in items:
            s = hist.get_statistics()
            out.append({"source": source, "source_subtask": subtask,
                        "hop": hop, "count": s["count"],
                        "p50_ms": round(s["p50"], 3),
                        "p95_ms": round(s["p95"], 3),
                        "p99_ms": round(s["p99"], 3),
                        "max_ms": round(s["max"], 3)})
        return out

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            hists = list(self._hists.values())
        return {"hops": len(hists),
                "samples": sum(h.get_count() for h in hists)}
