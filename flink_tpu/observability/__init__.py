"""Observability: structured span tracing + end-to-end latency tracking.

Two cooperating layers (ISSUE-10), both cheap enough to leave on:

- :mod:`flink_tpu.observability.tracing` — a per-process ring-buffer
  **span journal** (begin/end/instant events through the injectable clock
  seam, bounded memory, drop counter) with instrumentation at the
  runtime's load-bearing sites: hot-stage phases, the checkpoint
  lifecycle, device-health transitions, pager traffic, mesh exchange
  dispatch and CEP vectorized drains.  Exports Chrome trace-event JSON
  (Perfetto-viewable); :mod:`flink_tpu.observability.assembly` merges
  per-worker journals into ONE job timeline with clock-offset estimation.
- :mod:`flink_tpu.observability.latency` — Dapper-style always-on
  latency tracking: ``LatencyMarker`` probes emitted by sources on the
  ``metrics.latency.interval`` cadence are recorded at every operator hop
  into per-(source, hop) histograms, exported through the metric
  reporters (Prometheus summaries included) and the REST latency panel.
"""

from flink_tpu.observability.latency import LatencyTracker
from flink_tpu.observability.tracing import SpanJournal

__all__ = ["SpanJournal", "LatencyTracker"]
