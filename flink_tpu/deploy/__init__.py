from flink_tpu.deploy.kubernetes import render_job_cluster

__all__ = ["render_job_cluster"]
