"""Kubernetes deployment: manifest generation for a job cluster.

Analog of the reference's ``flink-kubernetes``
(``KubernetesClusterDescriptor.java:68`` + the pod/ConfigMap builders in
``kubeclient/decorators/``) — redesigned for the process model here: instead
of an in-cluster client creating resources imperatively, this module RENDERS
the manifests (the `kubectl apply` workflow), because the coordinator and
workers are plain CLI entrypoints:

- **coordinator**: a ``Job`` running ``flink_tpu coordinate --job M:F
  --workers N`` with ``spawn=False`` — it listens for worker registrations
  and drives deploy/checkpoints/shutdown;
- **workers**: an indexed ``StatefulSet`` of ``flink_tpu worker`` pods, each
  dialing the coordinator Service and serving its data plane on the pod IP
  (``--bind 0.0.0.0 --advertise $(POD_IP)``);
- a headless ``Service`` fronts the coordinator's control port.

TPU pods: set ``tpu_resource`` (e.g. ``google.com/tpu: 8``) to attach
accelerators to workers — the ``ExternalResourceOptions``/GPU-driver slot of
the reference (SURVEY §2.2 "External resource framework").
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def render_job_cluster(name: str, image: str, job: str, n_workers: int = 2,
                       namespace: str = "default",
                       control_port: int = 6123,
                       checkpoint_dir: Optional[str] = None,
                       checkpoint_interval_ms: int = 0,
                       tpu_resource: Optional[Dict[str, Any]] = None,
                       env: Optional[Dict[str, str]] = None,
                       worker_args: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    """Render the manifest list (Service, coordinator Job, worker
    StatefulSet) for one job cluster.  ``job`` is the ``module:function``
    reference baked into ``image``."""
    labels = {"app": name, "managed-by": "flink-tpu"}
    envs = [{"name": k, "value": v} for k, v in (env or {}).items()]

    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"{name}-coordinator", "namespace": namespace,
                     "labels": labels},
        "spec": {
            "clusterIP": "None",
            "selector": {**labels, "component": "coordinator"},
            "ports": [{"name": "control", "port": control_port}],
        },
    }

    coord_cmd = ["python", "-m", "flink_tpu", "coordinate",
                 "--job", job, "--workers", str(n_workers),
                 "--listen", f"0.0.0.0:{control_port}"]
    if checkpoint_dir:
        coord_cmd += ["--checkpoint-dir", checkpoint_dir,
                      "--checkpoint-interval", str(checkpoint_interval_ms)]
    coordinator = {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": f"{name}-coordinator", "namespace": namespace,
                     "labels": labels},
        "spec": {
            "backoffLimit": 0,
            "template": {
                "metadata": {"labels": {**labels,
                                        "component": "coordinator"}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [{
                        "name": "coordinator",
                        "image": image,
                        "command": coord_cmd,
                        "env": envs,
                        "ports": [{"containerPort": control_port}],
                    }],
                },
            },
        },
    }

    worker_container: Dict[str, Any] = {
        "name": "worker",
        "image": image,
        "command": ["/bin/sh", "-c",
                    " ".join([
                        "exec python -m flink_tpu worker",
                        "--index ${POD_INDEX}",
                        f"--workers {n_workers}",
                        f"--job {job}",
                        f"--coordinator {name}-coordinator:{control_port}",
                        "--bind 0.0.0.0 --advertise ${POD_IP}",
                        *(worker_args or [])])],
        "env": envs + [
            {"name": "POD_IP",
             "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}}},
            {"name": "POD_INDEX",
             "valueFrom": {"fieldRef": {"fieldPath":
                                        "metadata.labels['apps.kubernetes."
                                        "io/pod-index']"}}},
        ],
    }
    if tpu_resource:
        worker_container["resources"] = {"limits": dict(tpu_resource)}

    worker_svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"{name}-worker", "namespace": namespace,
                     "labels": labels},
        "spec": {
            # governing headless Service of the StatefulSet: gives workers
            # stable per-pod DNS ({name}-worker-0.{name}-worker...)
            "clusterIP": "None",
            "selector": {**labels, "component": "worker"},
            "ports": [{"name": "data", "port": 6124}],
        },
    }

    workers = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": f"{name}-worker", "namespace": namespace,
                     "labels": labels},
        "spec": {
            "serviceName": f"{name}-worker",
            "replicas": n_workers,
            "selector": {"matchLabels": {**labels, "component": "worker"}},
            "template": {
                "metadata": {"labels": {**labels, "component": "worker"}},
                "spec": {"containers": [worker_container]},
            },
        },
    }
    return [svc, worker_svc, coordinator, workers]


def to_yaml(manifests: List[Dict[str, Any]]) -> str:
    """Multi-document YAML for ``kubectl apply -f -``."""
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False) for m in manifests)
