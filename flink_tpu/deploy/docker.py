"""Container image glue: Dockerfile + compose rendering for the cluster
entrypoints.

The reference ships ``flink-container/`` (Dockerfile, ``docker-compose``
templates, ``docker-entrypoint.sh`` dispatching jobmanager/taskmanager
roles).  Same shape here, for the ``python -m flink_tpu coordinate`` /
``worker`` entrypoints already used by the Kubernetes manifests
(``deploy/kubernetes.py``): :func:`render_dockerfile` emits a
reproducible image recipe, :func:`render_entrypoint` the role-dispatch
script, :func:`render_compose` a coordinator + N workers compose file
sharing a checkpoint volume, and :func:`write_context` lays the whole
build context down on disk.  Rendering is pure (testable in-repo; the
docker daemon is not available here) — the emitted files are standard
and build anywhere."""

from __future__ import annotations

import os
from typing import Dict, List, Optional


def render_dockerfile(python: str = "3.12",
                      extras: Optional[List[str]] = None) -> str:
    """A minimal reproducible image: the package, its baked deps, one
    non-root user, both cluster roles reachable through the entrypoint."""
    lines = [
        f"FROM python:{python}-slim",
        "",
        "# native layer: the C++ runtime components build on first import",
        "RUN apt-get update && apt-get install -y --no-install-recommends \\",
        "        g++ && rm -rf /var/lib/apt/lists/*",
        "",
        "RUN useradd --create-home flink",
        "WORKDIR /opt/flink-tpu",
        "COPY pyproject.toml README.md ./",
        "COPY flink_tpu ./flink_tpu",
        "COPY native ./native",
        "RUN pip install --no-cache-dir .",
    ]
    for e in extras or []:
        lines.append(f"RUN pip install --no-cache-dir {e}")
    lines += [
        "",
        "# pre-build the native library into the image (first-use cache);",
        "# a failed C++ build must FAIL the image build, not ship a silent",
        "# fallback (native_available returns False rather than raising)",
        "RUN python -c \"from flink_tpu.native import native_available, "
        "build_error; assert native_available(), build_error()\"",
        "",
        "COPY docker-entrypoint.sh /docker-entrypoint.sh",
        "RUN chmod +x /docker-entrypoint.sh",
        "USER flink",
        "ENV JAX_PLATFORMS=cpu",
        "EXPOSE 6123 8081",
        'ENTRYPOINT ["/docker-entrypoint.sh"]',
        'CMD ["help"]',
        "",
    ]
    return "\n".join(lines)


def render_entrypoint() -> str:
    """Role dispatch (``docker-entrypoint.sh`` analog): coordinate |
    worker | sql | repl | any module args verbatim."""
    return """#!/bin/sh
# flink-tpu container entrypoint: dispatch the cluster role.
set -e

ROLE="$1"
[ $# -gt 0 ] && shift

case "$ROLE" in
    run|sql|info|repl|worker|coordinate|logservice|objectstore|s3|kafka|\
quickstart|list|status|cancel|savepoint|stop)
        # every CLI subcommand (flink_tpu.__main__ build_parser surface)
        exec python -m flink_tpu "$ROLE" "$@"
        ;;
    help|"")
        echo "usage: <any flink_tpu subcommand|shell cmd> [args...]"
        exec python -m flink_tpu --help
        ;;
    *)
        # arbitrary command (debugging shells, custom drivers)
        exec "$ROLE" "$@"
        ;;
esac
"""


def coordinator_command(job: str, n_workers: int, port: int,
                        checkpoint_dir: Optional[str]) -> List[str]:
    """The coordinate role's entrypoint args — the SAME flag surface the
    Kubernetes renderer emits (``deploy/kubernetes.py``), validated
    against the real CLI parser in tests."""
    cmd = ["coordinate", "--job", job, "--workers", str(n_workers),
           "--listen", f"0.0.0.0:{port}"]
    if checkpoint_dir:
        cmd += ["--checkpoint-dir", checkpoint_dir]
    return cmd


def worker_command(index: int, job: str, n_workers: int,
                   coordinator: str) -> List[str]:
    """One worker replica's entrypoint args (``--index`` is per-service:
    compose has no pod-index analog, so each worker renders as its own
    service)."""
    return ["worker", "--index", str(index), "--workers", str(n_workers),
            "--job", job, "--coordinator", coordinator,
            "--bind", "0.0.0.0", "--advertise", f"worker-{index}"]


def _yq(v: str) -> str:
    """A YAML double-quoted scalar (json.dumps escapes quotes/backslashes
    exactly as YAML flow scalars require)."""
    import json

    return json.dumps(str(v))


def _yaml_cmd(args: List[str]) -> str:
    return "[" + ", ".join(_yq(a) for a in args) + "]"


def render_compose(job: str, image: str = "flink-tpu:latest",
                   n_workers: int = 2, coordinator_port: int = 6123,
                   environment: Optional[Dict[str, str]] = None) -> str:
    """docker-compose: one coordinator + one service PER worker index
    (each worker needs a distinct ``--index``; compose replicas cannot
    vary args), sharing a checkpoint volume.  The compose network is the
    trust boundary, so the non-loopback TLS guard is relaxed via
    ``FLINK_TPU_ALLOW_INSECURE`` — set ``FLINK_TPU_SSL_*`` instead for
    untrusted networks.  Healthcheck: a TCP dial of the control port (the
    coordinate role serves the binary control plane, not HTTP)."""
    env_lines = "".join(f"      {k}: {_yq(v)}\n"
                        for k, v in (environment or {}).items())
    base_env = ("      FLINK_TPU_ALLOW_INSECURE: \"1\"\n"
                "      JAX_PLATFORMS: \"cpu\"\n" + env_lines)
    coord = coordinator_command(job, n_workers, coordinator_port,
                                "/checkpoints")
    parts = [f"""services:
  coordinator:
    image: {image}
    command: {_yaml_cmd(coord)}
    expose:
      - "{coordinator_port}"
    environment:
{base_env}    volumes:
      - checkpoints:/checkpoints
    healthcheck:
      test: ["CMD", "python", "-c",
             "import socket; socket.create_connection(('127.0.0.1', {coordinator_port}), 5).close()"]
      interval: 10s
      retries: 6
"""]
    for i in range(n_workers):
        wcmd = worker_command(i, job, n_workers,
                              f"coordinator:{coordinator_port}")
        parts.append(f"""
  worker-{i}:
    image: {image}
    command: {_yaml_cmd(wcmd)}
    depends_on:
      coordinator:
        condition: service_healthy
    restart: on-failure
    environment:
{base_env}    volumes:
      - checkpoints:/checkpoints
""")
    parts.append("""
volumes:
  checkpoints:
""")
    return "".join(parts)


def write_context(directory: str, job: str, image: str = "flink-tpu:latest",
                  n_workers: int = 2, python: str = "3.12",
                  repo_root: Optional[str] = None) -> List[str]:
    """Lay a SELF-CONTAINED build context on disk: Dockerfile, entrypoint,
    compose, plus the package sources the Dockerfile COPYs
    (``pyproject.toml``, ``README.md``, ``flink_tpu/``, ``native/``) —
    ``docker build <directory>`` works as-is.  ``repo_root`` defaults to
    this installation's root."""
    import shutil

    os.makedirs(directory, exist_ok=True)
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    out = []
    for fname in ("pyproject.toml", "README.md"):
        src = os.path.join(repo_root, fname)
        if os.path.exists(src):
            shutil.copy2(src, os.path.join(directory, fname))
            out.append(os.path.join(directory, fname))
    for pkg in ("flink_tpu", "native"):
        src = os.path.join(repo_root, pkg)
        dst = os.path.join(directory, pkg)
        if os.path.isdir(src):
            shutil.copytree(
                src, dst, dirs_exist_ok=True,
                ignore=shutil.ignore_patterns("__pycache__", "_build",
                                              "*.so", "*.pyc"))
            out.append(dst)
    files = {
        "Dockerfile": render_dockerfile(python=python),
        "docker-entrypoint.sh": render_entrypoint(),
        "docker-compose.yml": render_compose(job, image=image,
                                             n_workers=n_workers),
    }
    for name, content in files.items():
        path = os.path.join(directory, name)
        with open(path, "w") as f:
            f.write(content)
        if name.endswith(".sh"):
            os.chmod(path, 0o755)
        out.append(path)
    return out
