"""Container image glue: Dockerfile + compose rendering for the cluster
entrypoints.

The reference ships ``flink-container/`` (Dockerfile, ``docker-compose``
templates, ``docker-entrypoint.sh`` dispatching jobmanager/taskmanager
roles).  Same shape here, for the ``python -m flink_tpu coordinate`` /
``worker`` entrypoints already used by the Kubernetes manifests
(``deploy/kubernetes.py``): :func:`render_dockerfile` emits a
reproducible image recipe, :func:`render_entrypoint` the role-dispatch
script, :func:`render_compose` a coordinator + N workers compose file
sharing a checkpoint volume, and :func:`write_context` lays the whole
build context down on disk.  Rendering is pure (testable in-repo; the
docker daemon is not available here) — the emitted files are standard
and build anywhere."""

from __future__ import annotations

import os
from typing import Dict, List, Optional


def render_dockerfile(python: str = "3.12",
                      extras: Optional[List[str]] = None) -> str:
    """A minimal reproducible image: the package, its baked deps, one
    non-root user, both cluster roles reachable through the entrypoint."""
    lines = [
        f"FROM python:{python}-slim",
        "",
        "# native layer: the C++ runtime components build on first import",
        "RUN apt-get update && apt-get install -y --no-install-recommends \\",
        "        g++ && rm -rf /var/lib/apt/lists/*",
        "",
        "RUN useradd --create-home flink",
        "WORKDIR /opt/flink-tpu",
        "COPY pyproject.toml README.md ./",
        "COPY flink_tpu ./flink_tpu",
        "COPY native ./native",
        "RUN pip install --no-cache-dir .",
    ]
    for e in extras or []:
        lines.append(f"RUN pip install --no-cache-dir {e}")
    lines += [
        "",
        "# pre-build the native library into the image (first-use cache)",
        "RUN python -c \"from flink_tpu.native import native_available; "
        "native_available()\"",
        "",
        "COPY docker-entrypoint.sh /docker-entrypoint.sh",
        "RUN chmod +x /docker-entrypoint.sh",
        "USER flink",
        "ENV JAX_PLATFORMS=cpu",
        "EXPOSE 6123 8081",
        'ENTRYPOINT ["/docker-entrypoint.sh"]',
        'CMD ["help"]',
        "",
    ]
    return "\n".join(lines)


def render_entrypoint() -> str:
    """Role dispatch (``docker-entrypoint.sh`` analog): coordinate |
    worker | sql | repl | any module args verbatim."""
    return """#!/bin/sh
# flink-tpu container entrypoint: dispatch the cluster role.
set -e

ROLE="$1"
[ $# -gt 0 ] && shift

case "$ROLE" in
    coordinate)
        exec python -m flink_tpu coordinate "$@"
        ;;
    worker)
        exec python -m flink_tpu worker "$@"
        ;;
    sql|repl|kafka|s3|run)
        exec python -m flink_tpu "$ROLE" "$@"
        ;;
    help|"")
        echo "usage: <coordinate|worker|sql|repl|kafka|s3|run> [args...]"
        exec python -m flink_tpu --help
        ;;
    *)
        # arbitrary command (debugging shells, custom drivers)
        exec "$ROLE" "$@"
        ;;
esac
"""


def coordinator_command(job: str, n_workers: int, port: int,
                        checkpoint_dir: Optional[str]) -> List[str]:
    """The coordinate role's entrypoint args — the SAME flag surface the
    Kubernetes renderer emits (``deploy/kubernetes.py``), validated
    against the real CLI parser in tests."""
    cmd = ["coordinate", "--job", job, "--workers", str(n_workers),
           "--listen", f"0.0.0.0:{port}"]
    if checkpoint_dir:
        cmd += ["--checkpoint-dir", checkpoint_dir]
    return cmd


def worker_command(index: int, job: str, n_workers: int,
                   coordinator: str) -> List[str]:
    """One worker replica's entrypoint args (``--index`` is per-service:
    compose has no pod-index analog, so each worker renders as its own
    service)."""
    return ["worker", "--index", str(index), "--workers", str(n_workers),
            "--job", job, "--coordinator", coordinator,
            "--bind", "0.0.0.0", "--advertise", f"worker-{index}"]


def _yaml_cmd(args: List[str]) -> str:
    return "[" + ", ".join(f'"{a}"' for a in args) + "]"


def render_compose(job: str, image: str = "flink-tpu:latest",
                   n_workers: int = 2, coordinator_port: int = 6123,
                   environment: Optional[Dict[str, str]] = None) -> str:
    """docker-compose: one coordinator + one service PER worker index
    (each worker needs a distinct ``--index``; compose replicas cannot
    vary args), sharing a checkpoint volume.  The compose network is the
    trust boundary, so the non-loopback TLS guard is relaxed via
    ``FLINK_TPU_ALLOW_INSECURE`` — set ``FLINK_TPU_SSL_*`` instead for
    untrusted networks.  Healthcheck: a TCP dial of the control port (the
    coordinate role serves the binary control plane, not HTTP)."""
    env_lines = "".join(f"      {k}: \"{v}\"\n"
                        for k, v in (environment or {}).items())
    base_env = ("      FLINK_TPU_ALLOW_INSECURE: \"1\"\n"
                "      JAX_PLATFORMS: \"cpu\"\n" + env_lines)
    coord = coordinator_command(job, n_workers, coordinator_port,
                                "/checkpoints")
    parts = [f"""services:
  coordinator:
    image: {image}
    command: {_yaml_cmd(coord)}
    expose:
      - "{coordinator_port}"
    environment:
{base_env}    volumes:
      - checkpoints:/checkpoints
    healthcheck:
      test: ["CMD", "python", "-c",
             "import socket; socket.create_connection(('127.0.0.1', {coordinator_port}), 5).close()"]
      interval: 10s
      retries: 6
"""]
    for i in range(n_workers):
        wcmd = worker_command(i, job, n_workers,
                              f"coordinator:{coordinator_port}")
        parts.append(f"""
  worker-{i}:
    image: {image}
    command: {_yaml_cmd(wcmd)}
    depends_on:
      - coordinator
    environment:
{base_env}    volumes:
      - checkpoints:/checkpoints
""")
    parts.append("""
volumes:
  checkpoints:
""")
    return "".join(parts)


def write_context(directory: str, job: str, image: str = "flink-tpu:latest",
                  n_workers: int = 2, python: str = "3.12") -> List[str]:
    """Lay the build context on disk: Dockerfile, entrypoint, compose.
    Returns the written paths (the package itself is copied by the
    Dockerfile's COPY directives at build time)."""
    os.makedirs(directory, exist_ok=True)
    files = {
        "Dockerfile": render_dockerfile(python=python),
        "docker-entrypoint.sh": render_entrypoint(),
        "docker-compose.yml": render_compose(job, image=image,
                                             n_workers=n_workers),
    }
    out = []
    for name, content in files.items():
        path = os.path.join(directory, name)
        with open(path, "w") as f:
            f.write(content)
        if name.endswith(".sh"):
            os.chmod(path, 0o755)
        out.append(path)
    return out
