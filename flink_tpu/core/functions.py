"""User function contracts, batch-vectorized for TPU execution.

Analog of ``flink-core/src/main/java/org/apache/flink/api/common/functions/``
(``AggregateFunction.java:114`` — createAccumulator/add/getResult/merge,
``ReduceFunction``, ``MapFunction``, …) re-designed for a batched device
runtime: instead of a per-record ``add(acc, value)`` call, an aggregate is
expressed as a **commutative monoid over accumulator pytrees**:

    lift(values)            [B, ...] record columns -> [B, ...] accumulators
    combine(a, b)           associative+commutative elementwise merge
    identity()              the neutral accumulator
    get_result(acc)         accumulator -> output value

so the runtime can fold a whole micro-batch with one fused
``segment-combine`` on device, merge panes at fire time with ``combine``, and
merge session windows with the same ``combine`` (the reference requires
``merge`` for session windows for exactly this reason).  Every built-in
reference aggregation (sum/count/min/max/avg — see
``flink-streaming-java/.../api/functions/aggregation/SumAggregator.java``,
``ComparableAggregator.java``) factors this way.

All lift/combine/get_result bodies must be jax-traceable (they run inside the
jitted micro-batch step); MapFunction/FilterFunction et al. come in two
flavors: jax-traceable (chained into the device step, the analog of operator
chaining ``OperatorChain.java:88``) or host-side numpy (the analog of a
non-chainable boundary).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


#: numpy ufunc per scatter kind — the host-tier mirror of ops/scatter.py's
#: device kinds; shared by every host fold path (heap backend, sessions)
SCATTER_UFUNCS = {"add": np.add, "min": np.minimum, "max": np.maximum}


def canonical_acc_dtype(dtype) -> jnp.dtype:
    """The dtype the BACKEND will actually store for an accumulator leaf:
    float64/int64 requests canonicalize to 32-bit when jax x64 is off.
    Aggregator constructors resolve through this instead of carrying the
    raw request, so ``identity()`` never asks ``jnp.zeros`` for a dtype the
    backend truncates (the per-call float64 UserWarning that spammed every
    MULTICHIP tail).  The numeric result is unchanged — the backend stored
    32 bits either way; the host mirror keeps its own f64/i64 twins."""
    return jnp.dtype(jax.dtypes.canonicalize_dtype(np.dtype(dtype)))


def default_float_dtype() -> jnp.dtype:
    """Widest float the backend supports (f64 under x64, else f32) — the
    default for datastream ``.sum()``/``.min()``/``.max()`` aggregates."""
    return canonical_acc_dtype(np.float64)


class Function:
    """Marker base for all user functions (``Function.java``)."""


class RuntimeContext:
    """Runtime info handed to rich functions (``RuntimeContext.java`` analog)."""

    def __init__(self, task_name: str = "task", subtask_index: int = 0,
                 parallelism: int = 1, max_parallelism: int = 128,
                 metrics=None, external_resources: Optional[Dict[str, Any]] = None,
                 memory_manager=None):
        self.task_name = task_name
        self.subtask_index = subtask_index
        self.parallelism = parallelism
        self.max_parallelism = max_parallelism
        self.metrics = metrics
        self._external_resources = external_resources or {}
        #: this slot's managed-memory accountant (runtime/memory.py), or
        #: None outside a managed slot — budgeted operators reserve here
        self.memory_manager = memory_manager

    def get_external_resource_infos(self, name: str):
        """``RuntimeContext.getExternalResourceInfos`` analog (TPU driver plugs in here)."""
        return self._external_resources.get(name, [])

    # -- accumulators (user counters, ``Accumulator``/``IntCounter`` analog)
    def add_accumulator(self, name: str, start: float = 0.0) -> "Accumulator":
        accs = getattr(self, "_accumulators", None)
        if accs is None:
            accs = self._accumulators = {}
        if name not in accs:
            accs[name] = Accumulator(name, start)
        return accs[name]

    def get_accumulator(self, name: str) -> "Accumulator":
        return self.add_accumulator(name)

    def accumulator_results(self) -> Dict[str, float]:
        return {n: a.value for n, a in
                getattr(self, "_accumulators", {}).items()}


class Accumulator:
    """Distributed user counter (``IntCounter``/``DoubleCounter`` analog):
    per-subtask adds merge at job completion (JobExecutionResult)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, start: float = 0.0):
        self.name = name
        self.value = start

    def add(self, v: float = 1.0) -> None:
        self.value += v


class RichFunction(Function):
    """open/close lifecycle (``RichFunction.java``)."""

    def open(self, ctx: RuntimeContext) -> None:  # noqa: D401
        pass

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Aggregates
# ---------------------------------------------------------------------------

class AggregateFunction(RichFunction, abc.ABC):
    """Batch-vectorized aggregate (reference contract: AggregateFunction.java:114).

    Correspondence to the reference contract:
      createAccumulator() -> identity()
      add(value, acc)     -> combine(acc, lift(value))   (computed batched)
      merge(a, b)         -> combine(a, b)
      getResult(acc)      -> get_result(acc)
    """

    @abc.abstractmethod
    def identity(self):
        """Neutral accumulator: a pytree of scalars / small arrays (jax-typed)."""

    @abc.abstractmethod
    def lift(self, values):
        """Vectorized: record value columns ``[B, ...]`` -> accumulator pytree with
        a leading batch dim on every leaf."""

    @abc.abstractmethod
    def combine(self, a, b):
        """Associative, commutative merge of two accumulator pytrees (elementwise,
        any leading batch dims broadcast)."""

    def get_result(self, acc):
        """Accumulator pytree -> output value (default: the accumulator itself)."""
        return acc

    # -- host emit tier (numpy evaluation) -----------------------------------
    # The window backend can keep a write-through HOST mirror of the ACC
    # column and serve window fires from it with zero device->host traffic
    # (operators/window_agg.py ``emit_tier``) — decisive on egress-constrained
    # links where downloads cost ~100ms+ each.  That requires evaluating the
    # same monoid in numpy.  ``host_lift``/``host_get_result`` are the numpy
    # twins of ``lift``/``get_result``; combine is covered by
    # ``scatter_kinds`` (add/min/max ufuncs).  Return NotImplemented to keep
    # an aggregate device-only.

    def host_lift(self, values):
        """numpy ``lift``: np column(s) -> ACC pytree of np arrays [B, ...].
        Default: unsupported (jnp ``lift`` bodies would bounce every batch
        off the device)."""
        return NotImplemented

    def host_get_result(self, acc):
        """numpy ``get_result``: ACC pytree of np arrays -> output values."""
        return NotImplemented

    def supports_host_emit(self) -> bool:
        """True when the backend may evaluate fires on the host: kinds are
        declared (add/min/max combine) and both numpy twins are overridden."""
        return (self.scatter_kind_leaves() is not None
                and type(self).host_lift is not AggregateFunction.host_lift
                and type(self).host_get_result
                is not AggregateFunction.host_get_result)

    def supports_retraction(self) -> bool:
        """True when every ACC leaf combines by ADDITION (sum/count/avg):
        the aggregate is invertible, so a fired window's contents can be
        'purged' logically by subtracting a per-(key, window) value
        baseline — the enabler for FIRE_AND_PURGE count triggers over
        pane-shared (sliding) windows, where a physical purge would
        corrupt overlapping neighbours."""
        kinds = self.scatter_kind_leaves()
        return kinds is not None and all(k == "add" for k in kinds)

    # -- introspection used by the state backend ----------------------------
    def scatter_kinds(self):
        """Optional fast-path declaration: a pytree matching ``identity()``'s
        structure with one of ``"add"/"min"/"max"`` per leaf, meaning
        ``combine`` is that elementwise op on that leaf — lets the backend use
        a single XLA scatter instead of the generic segmented-scan fold.
        Return None (default) for arbitrary combines."""
        return None

    def scatter_kind_leaves(self) -> "Optional[Tuple[str, ...]]":
        kinds = self.scatter_kinds()
        if kinds is None:
            return None
        is_leaf = lambda x: isinstance(x, str)  # noqa: E731
        if (jax.tree_util.tree_structure(kinds, is_leaf=is_leaf)
                != self.acc_spec().treedef):
            raise ValueError("scatter_kinds structure does not match identity()")
        return tuple(jax.tree_util.tree_leaves(kinds, is_leaf=is_leaf))

    def combine_leaves(self, a_leaves, b_leaves):
        """Leaf-tuple view of ``combine`` (used by the scatter kernels)."""
        spec = self.acc_spec()
        out = self.combine(spec.unflatten(a_leaves), spec.unflatten(b_leaves))
        return tuple(jax.tree_util.tree_leaves(out))

    def acc_spec(self) -> "AccSpec":
        # cached: identity() creates arrays, which must happen eagerly (calling
        # it inside a jit trace would stage the constants as tracers)
        cached = getattr(self, "_acc_spec_cache", None)
        if cached is None:
            ident = self.identity()
            leaves, treedef = jax.tree_util.tree_flatten(ident)
            # stable leaf identities from pytree key paths ("['sum']", "[0]"):
            # snapshots record them so composite accumulators can evolve by
            # field name (add/remove/widen), the POJO-evolution analog
            paths = jax.tree_util.tree_flatten_with_path(ident)[0]
            names = tuple(jax.tree_util.keystr(p) for p, _ in paths)
            cached = AccSpec(treedef=treedef,
                             leaf_shapes=tuple(np.shape(l) for l in leaves),
                             leaf_dtypes=tuple(jnp.asarray(l).dtype for l in leaves),
                             leaf_inits=tuple(np.asarray(l) for l in leaves),
                             leaf_names=names)
            self._acc_spec_cache = cached
        return cached


@dataclass(frozen=True)
class AccSpec:
    """Static description of an accumulator pytree (shapes/dtypes/identity)."""

    treedef: Any
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    leaf_dtypes: Tuple[Any, ...]
    leaf_inits: Tuple[np.ndarray, ...]
    #: pytree key path per leaf — the schema-evolution identity
    leaf_names: Tuple[str, ...] = ()

    @property
    def num_leaves(self) -> int:
        return len(self.leaf_shapes)

    def unflatten(self, leaves):
        return jax.tree_util.tree_unflatten(self.treedef, list(leaves))


class ReduceFunction(AggregateFunction):
    """Associative reduce over values (``ReduceFunction.java``): ACC == value type.

    Subclasses implement ``reduce(a, b)`` (vectorized, elementwise) and
    ``identity()``.
    """

    def lift(self, values):
        return values

    def combine(self, a, b):
        return self.reduce(a, b)

    # reduces are shape-preserving, so the numpy twins are identities
    def host_lift(self, values):
        return values

    def host_get_result(self, acc):
        return acc

    @abc.abstractmethod
    def reduce(self, a, b):
        ...


class LambdaReduce(ReduceFunction):
    def __init__(self, fn: Callable, identity_value):
        self._fn = fn
        self._identity = identity_value

    def identity(self):
        return self._identity

    def reduce(self, a, b):
        return self._fn(a, b)


class SumAggregator(ReduceFunction):
    """``.sum()`` (SumAggregator.java analog): elementwise sum, identity 0."""

    def __init__(self, dtype=jnp.float32):
        self._dtype = canonical_acc_dtype(dtype)

    def identity(self):
        return jnp.zeros((), self._dtype)

    def reduce(self, a, b):
        return a + b

    def scatter_kinds(self):
        return "add"


class MinAggregator(ReduceFunction):
    def __init__(self, dtype=jnp.float32):
        self._dtype = canonical_acc_dtype(dtype)

    def identity(self):
        if jnp.issubdtype(self._dtype, jnp.integer):
            return jnp.array(jnp.iinfo(self._dtype).max, self._dtype)
        return jnp.array(jnp.inf, self._dtype)

    def reduce(self, a, b):
        return jnp.minimum(a, b)

    def scatter_kinds(self):
        return "min"


class MaxAggregator(ReduceFunction):
    def __init__(self, dtype=jnp.float32):
        self._dtype = canonical_acc_dtype(dtype)

    def identity(self):
        if jnp.issubdtype(self._dtype, jnp.integer):
            return jnp.array(jnp.iinfo(self._dtype).min, self._dtype)
        return jnp.array(-jnp.inf, self._dtype)

    def reduce(self, a, b):
        return jnp.maximum(a, b)

    def scatter_kinds(self):
        return "max"


class CountAggregator(AggregateFunction):
    def identity(self):
        return jnp.zeros((), jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)

    def lift(self, values):
        leaf = jax.tree_util.tree_leaves(values)[0]
        return jnp.ones(jnp.shape(leaf)[:1], self.identity().dtype)

    def combine(self, a, b):
        return a + b

    def host_lift(self, values):
        leaf = jax.tree_util.tree_leaves(values)[0]
        return np.ones(np.shape(leaf)[:1], np.int64)

    def host_get_result(self, acc):
        return acc

    def scatter_kinds(self):
        return "add"


class AvgAggregator(AggregateFunction):
    """Average: ACC = (sum, count) — the canonical non-trivial ACC from the
    reference javadoc example (AggregateFunction.java:60-100)."""

    def __init__(self, dtype=jnp.float32):
        self._dtype = canonical_acc_dtype(dtype)

    def identity(self):
        return {"sum": jnp.zeros((), self._dtype), "count": jnp.zeros((), jnp.int32)}

    def lift(self, values):
        v = jnp.asarray(values, self._dtype)
        return {"sum": v, "count": jnp.ones(v.shape[:1], jnp.int32)}

    def combine(self, a, b):
        return {"sum": a["sum"] + b["sum"], "count": a["count"] + b["count"]}

    def get_result(self, acc):
        cnt = jnp.maximum(acc["count"], 1)
        return acc["sum"] / cnt.astype(self._dtype)

    def host_lift(self, values):
        v = np.asarray(values, np.float64)
        return {"sum": v, "count": np.ones(v.shape[:1], np.int64)}

    def host_get_result(self, acc):
        cnt = np.maximum(np.asarray(acc["count"]), 1)
        return np.asarray(acc["sum"]) / cnt

    def scatter_kinds(self):
        return {"sum": "add", "count": "add"}


class TupleAggregator(AggregateFunction):
    """Combine several aggregates over named value columns into one ACC dict —
    the 'multi-field AggregateFunction' of baseline config #3."""

    def __init__(self, aggs: Dict[str, Tuple[str, AggregateFunction]]):
        """aggs: out_name -> (value_column, AggregateFunction)."""
        self._aggs = aggs

    def identity(self):
        return {name: agg.identity() for name, (_, agg) in self._aggs.items()}

    def lift(self, values):
        return {name: agg.lift(values[col]) for name, (col, agg) in self._aggs.items()}

    def combine(self, a, b):
        return {name: agg.combine(a[name], b[name]) for name, (_, agg) in self._aggs.items()}

    def get_result(self, acc):
        return {name: agg.get_result(acc[name]) for name, (_, agg) in self._aggs.items()}

    def host_lift(self, values):
        if not all(agg.supports_host_emit() for _, agg in self._aggs.values()):
            return NotImplemented
        return {name: agg.host_lift(values[col])
                for name, (col, agg) in self._aggs.items()}

    def host_get_result(self, acc):
        if not all(agg.supports_host_emit() for _, agg in self._aggs.values()):
            return NotImplemented
        return {name: agg.host_get_result(acc[name])
                for name, (_, agg) in self._aggs.items()}

    def supports_host_emit(self) -> bool:
        return (self.scatter_kind_leaves() is not None
                and all(agg.supports_host_emit()
                        for _, agg in self._aggs.values()))

    def scatter_kinds(self):
        kinds = {}
        for name, (_, agg) in self._aggs.items():
            k = agg.scatter_kinds()
            if k is None:
                return None
            kinds[name] = k
        return kinds


# ---------------------------------------------------------------------------
# Elementwise / host functions
# ---------------------------------------------------------------------------

class MapFunction(Function):
    """Vectorized map over batch columns (``MapFunction.java``). ``map`` receives
    the batch's column dict and returns a new column dict."""

    def map(self, columns: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    #: if True the body is jax-traceable and is chained into the device step
    jax_traceable: bool = False


class FilterFunction(Function):
    """Vectorized predicate: returns a boolean mask ``[B]``."""

    def filter(self, columns: Dict[str, Any]):
        raise NotImplementedError

    jax_traceable: bool = False


class FlatMapFunction(Function):
    """Host-side flatmap: columns -> (columns, repeats[B]) or arbitrary re-batch."""

    def flat_map(self, columns: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError


class ProcessFunction(RichFunction):
    """Low-level host-side per-batch processing with timer access (analog of
    ``ProcessFunction``/``KeyedProcessFunction``). Batched: receives the column
    dict, timestamps, and a ``TimerService``-like context."""

    def process_batch(self, columns: Dict[str, Any], timestamps, ctx) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_timer(self, timestamp: int, ctx) -> Optional[Dict[str, Any]]:
        return None


def as_map(fn: Callable, jax_traceable: bool = False) -> MapFunction:
    m = MapFunction()
    m.map = fn  # type: ignore[method-assign]
    m.jax_traceable = jax_traceable
    return m


def as_filter(fn: Callable, jax_traceable: bool = False) -> FilterFunction:
    f = FilterFunction()
    f.filter = fn  # type: ignore[method-assign]
    f.jax_traceable = jax_traceable
    return f
