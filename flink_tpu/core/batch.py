"""Stream elements, batched.

The reference moves one ``StreamElement`` at a time through the dataflow
(records, watermarks, barriers, latency markers — see
``flink-streaming-java/.../streamrecord/``).  The TPU-native unit of flow is a
**columnar RecordBatch** (dense numpy/jax arrays, one device micro-step per
batch); control elements (``Watermark``, ``CheckpointBarrier``,
``LatencyMarker``, ``StreamStatus``) stay individual and flow *in order*
between batches — boundary-exactness for checkpoints falls out of that
ordering exactly as it does from the reference's in-band barriers
(``SingleCheckpointBarrierHandler.java:194``).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Iterable, List, Mapping, Optional

import numpy as np

LONG_MIN = -(2 ** 63)
LONG_MAX = 2 ** 63 - 1

#: Watermark value meaning "end of stream" (reference: Watermark.MAX_WATERMARK)
MAX_WATERMARK = LONG_MAX


class StreamElement:
    __slots__ = ()

    def is_batch(self) -> bool:
        return False


@dataclass(frozen=True)
class Watermark(StreamElement):
    """Event-time watermark: no element with ts <= this will arrive later."""

    timestamp: int

    def is_batch(self) -> bool:
        return False


@dataclass(frozen=True)
class StreamStatus(StreamElement):
    """Channel idleness marker (``StreamStatus`` analog): idle channels are
    excluded from watermark alignment."""

    idle: bool


@dataclass(frozen=True)
class LatencyMarker(StreamElement):
    """Latency-tracking probe (``LatencyMarker.java:32``): flows through
    operators without entering user functions; every hop records
    marked_time→now (``observability/latency.py``), sinks included.
    ``source`` names the emitting vertex so per-(source, hop) histograms
    attribute samples without an id registry."""

    marked_time: float
    source_id: int = 0
    subtask_index: int = 0
    source: str = ""


@dataclass(frozen=True)
class CheckpointBarrier(StreamElement):
    """In-band checkpoint barrier (``CheckpointBarrier.java``)."""

    checkpoint_id: int
    timestamp: int
    is_savepoint: bool = False


@dataclass(frozen=True)
class EndOfInput(StreamElement):
    """End of a bounded stream."""


@dataclass(frozen=True)
class OutputTag:
    """Names a side output (``OutputTag`` analog)."""

    name: str


class TaggedBatch(StreamElement):
    """A batch destined for a side output: routed only to the matching
    ``SideOutputOperator`` (``ProcessOperator`` side-output emission analog);
    every other consumer drops it."""

    __slots__ = ("tag", "batch")

    def __init__(self, tag: str, batch: "RecordBatch"):
        self.tag = tag
        self.batch = batch


class RecordBatch(StreamElement):
    """Columnar record batch.

    columns:    name -> array [B, ...] (numpy on host, jax on device paths)
    timestamps: int64[B] event timestamps in ms, or None (no time semantics yet)
    key_ids:    int32[B] dense key-slot ids (present after keying), or None
    key_groups: int32[B] key-group per record (present after keying), or None
    """

    __slots__ = ("columns", "timestamps", "key_ids", "key_groups", "_size")

    def __init__(self, columns: Mapping[str, Any], timestamps=None,
                 key_ids=None, key_groups=None):
        self.columns: Dict[str, Any] = dict(columns)
        self.timestamps = timestamps
        self.key_ids = key_ids
        self.key_groups = key_groups
        if self.columns:
            first = next(iter(self.columns.values()))
            self._size = int(np.shape(first)[0])
        elif timestamps is not None:
            self._size = int(np.shape(timestamps)[0])
        else:
            self._size = 0
        # Row-alignment invariant: a size-changing map that keeps stale
        # timestamps/key_ids would silently attribute rows to wrong keys.
        for attr in ("timestamps", "key_ids", "key_groups"):
            v = getattr(self, attr)
            if v is not None and int(np.shape(v)[0]) != self._size:
                raise ValueError(
                    f"{attr} length {int(np.shape(v)[0])} != batch size {self._size}")
        for n, v in self.columns.items():
            if int(np.shape(v)[0]) != self._size:
                raise ValueError(
                    f"column {n!r} length {int(np.shape(v)[0])} != batch size {self._size}")

    def is_batch(self) -> bool:
        return True

    def __len__(self) -> int:
        return self._size

    @property
    def size(self) -> int:
        return self._size

    def column(self, name: str):
        return self.columns[name]

    def with_columns(self, columns: Mapping[str, Any]) -> "RecordBatch":
        return RecordBatch(columns, self.timestamps, self.key_ids, self.key_groups)

    def with_keys(self, key_ids, key_groups=None) -> "RecordBatch":
        return RecordBatch(self.columns, self.timestamps, key_ids, key_groups)

    def with_timestamps(self, timestamps) -> "RecordBatch":
        return RecordBatch(self.columns, timestamps, self.key_ids, self.key_groups)

    def select(self, mask: np.ndarray) -> "RecordBatch":
        """Host-side row filter by boolean mask."""
        cols = {k: np.asarray(v)[mask] for k, v in self.columns.items()}
        ts = None if self.timestamps is None else np.asarray(self.timestamps)[mask]
        kid = None if self.key_ids is None else np.asarray(self.key_ids)[mask]
        kg = None if self.key_groups is None else np.asarray(self.key_groups)[mask]
        return RecordBatch(cols, ts, kid, kg)

    def take(self, indices: np.ndarray) -> "RecordBatch":
        cols = {k: np.asarray(v)[indices] for k, v in self.columns.items()}
        ts = None if self.timestamps is None else np.asarray(self.timestamps)[indices]
        kid = None if self.key_ids is None else np.asarray(self.key_ids)[indices]
        kg = None if self.key_groups is None else np.asarray(self.key_groups)[indices]
        return RecordBatch(cols, ts, kid, kg)

    @staticmethod
    def concat(batches: Iterable["RecordBatch"]) -> "RecordBatch":
        all_batches = list(batches)
        batches = [b for b in all_batches if len(b)]
        if not batches:
            # Preserve schema/keyed-ness of an all-empty flush so downstream
            # presence checks (timestamps/key_ids is not None) stay stable.
            return all_batches[0] if all_batches else RecordBatch({})
        if len(batches) == 1:
            return batches[0]
        first = batches[0]
        names = set(first.columns)
        for b in batches[1:]:
            if set(b.columns) != names:
                raise ValueError(f"concat of heterogeneous batches: {sorted(names)} vs {sorted(b.columns)}")
            for attr in ("timestamps", "key_ids", "key_groups"):
                if (getattr(b, attr) is None) != (getattr(first, attr) is None):
                    raise ValueError(f"concat of batches with inconsistent {attr} presence")
        cols = {n: np.concatenate([np.asarray(b.columns[n]) for b in batches]) for n in first.columns}
        ts = (np.concatenate([np.asarray(b.timestamps) for b in batches])
              if first.timestamps is not None else None)
        kid = (np.concatenate([np.asarray(b.key_ids) for b in batches])
               if first.key_ids is not None else None)
        kg = (np.concatenate([np.asarray(b.key_groups) for b in batches])
              if first.key_groups is not None else None)
        return RecordBatch(cols, ts, kid, kg)

    @staticmethod
    def from_rows(rows: List[Mapping[str, Any]], timestamps: Optional[List[int]] = None) -> "RecordBatch":
        """Test/connector convenience: list of dict rows -> columnar batch."""
        if not rows:
            return RecordBatch({})
        names = rows[0].keys()
        cols = {n: np.asarray([r[n] for r in rows]) for n in names}
        ts = np.asarray(timestamps, np.int64) if timestamps is not None else None
        return RecordBatch(cols, ts)

    def to_rows(self) -> List[Dict[str, Any]]:
        arrs = {k: np.asarray(v) for k, v in self.columns.items()}

        def cell(a, i):
            x = a[i]
            if isinstance(x, np.generic):
                return x.item()
            return x  # object cells (strings) or sub-arrays pass through

        return [{k: cell(a, i) for k, a in arrs.items()} for i in range(self._size)]

    def __repr__(self) -> str:
        cols = {k: f"{np.asarray(v).dtype}{list(np.shape(v))}" for k, v in self.columns.items()}
        return f"RecordBatch(n={self._size}, cols={cols}, keyed={self.key_ids is not None})"
