"""Watermark strategies and generation.

Analog of ``flink-core/.../eventtime/WatermarkStrategy`` +
``flink-streaming-java/.../runtime/operators/TimestampsAndWatermarksOperator.java``:
sources (or an explicit assign step) stamp event timestamps per record and
periodically emit watermarks; here generation is batched — a strategy sees a
whole timestamp column and yields the new watermark after the batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from flink_tpu.core.batch import LONG_MIN


class WatermarkGenerator:
    """Stateful per-source-subtask generator; ``on_batch`` returns the
    watermark to emit after the batch (or None)."""

    def on_batch(self, timestamps: np.ndarray) -> Optional[int]:
        raise NotImplementedError

    def on_periodic(self) -> Optional[int]:
        return None


class BoundedOutOfOrdernessWatermarks(WatermarkGenerator):
    """max_seen_ts - out_of_orderness - 1 (``BoundedOutOfOrdernessWatermarks.java``)."""

    def __init__(self, max_out_of_orderness_ms: int):
        self._delay = int(max_out_of_orderness_ms)
        self._max_ts = LONG_MIN + self._delay + 1

    def on_batch(self, timestamps: np.ndarray) -> Optional[int]:
        if timestamps is None or len(timestamps) == 0:
            return None
        self._max_ts = max(self._max_ts, int(np.max(timestamps)))
        return self._max_ts - self._delay - 1

    def on_periodic(self) -> Optional[int]:
        return self._max_ts - self._delay - 1


class MonotonousTimestampsWatermarks(BoundedOutOfOrdernessWatermarks):
    """Ascending timestamps (``AscendingTimestampsWatermarks``)."""

    def __init__(self):
        super().__init__(0)


class NoWatermarks(WatermarkGenerator):
    def on_batch(self, timestamps):
        return None


@dataclass
class WatermarkStrategy:
    """Factory bundling a generator + timestamp assigner (column or callable)."""

    generator_factory: Callable[[], WatermarkGenerator]
    timestamp_assigner: Optional[object] = None  # column name or fn(columns)->int64[B]

    @staticmethod
    def for_bounded_out_of_orderness(ms: int) -> "WatermarkStrategy":
        return WatermarkStrategy(lambda: BoundedOutOfOrdernessWatermarks(ms))

    @staticmethod
    def for_monotonous_timestamps() -> "WatermarkStrategy":
        return WatermarkStrategy(MonotonousTimestampsWatermarks)

    @staticmethod
    def no_watermarks() -> "WatermarkStrategy":
        return WatermarkStrategy(NoWatermarks)

    def with_timestamp_assigner(self, assigner) -> "WatermarkStrategy":
        return WatermarkStrategy(self.generator_factory, assigner)

    def extract_timestamps(self, columns) -> Optional[np.ndarray]:
        if self.timestamp_assigner is None:
            return None
        if callable(self.timestamp_assigner):
            return np.asarray(self.timestamp_assigner(columns), np.int64)
        return np.asarray(columns[self.timestamp_assigner], np.int64)
