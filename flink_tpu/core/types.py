"""Type information for record columns.

Light-weight analog of the reference's type system
(``flink-core/src/main/java/org/apache/flink/api/common/typeinfo/TypeInformation``
→ ``TypeSerializer``): here a record type is a named tuple of columns, each
with a numpy dtype (or ``object`` for strings); serialization rides
numpy/arrow buffers instead of per-record serializers.  Schema evolution
(``TypeSerializerSnapshot.java:73``) maps to the snapshot carrying each
column's dtype + a compatibility check on restore (see
``flink_tpu/runtime/checkpoint/snapshot.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class FieldType:
    name: str
    dtype: np.dtype

    @property
    def is_object(self) -> bool:
        return self.dtype == np.dtype(object)


@dataclass(frozen=True)
class RowType:
    """Schema of a RecordBatch: ordered named columns."""

    fields: Tuple[FieldType, ...]

    @staticmethod
    def of(**kwargs) -> "RowType":
        return RowType(tuple(FieldType(k, np.dtype(v)) for k, v in kwargs.items()))

    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def dtype(self, name: str) -> np.dtype:
        for f in self.fields:
            if f.name == name:
                return f.dtype
        raise KeyError(name)

    def with_field(self, name: str, dtype) -> "RowType":
        return RowType(self.fields + (FieldType(name, np.dtype(dtype)),))

    def project(self, names: Sequence[str]) -> "RowType":
        by = {f.name: f for f in self.fields}
        return RowType(tuple(by[n] for n in names))

    def is_compatible_with(self, other: "RowType") -> bool:
        """Restore-time schema compatibility: same names, castable dtypes
        (``TypeSerializerSnapshot.resolveSchemaCompatibility:132`` analog)."""
        if self.names() != other.names():
            return False
        return all(np.can_cast(a.dtype, b.dtype, casting="same_kind") or a.dtype == b.dtype
                   for a, b in zip(self.fields, other.fields))

    def to_meta(self) -> List[Dict[str, str]]:
        return [{"name": f.name, "dtype": str(f.dtype)} for f in self.fields]

    @staticmethod
    def from_meta(meta: List[Dict[str, str]]) -> "RowType":
        return RowType(tuple(FieldType(m["name"], np.dtype(m["dtype"])) for m in meta))


class Types:
    """Shorthand dtype constants (``Types.java`` analog)."""

    BOOL = np.dtype(np.bool_)
    INT = np.dtype(np.int32)
    LONG = np.dtype(np.int64)
    FLOAT = np.dtype(np.float32)
    DOUBLE = np.dtype(np.float64)
    STRING = np.dtype(object)
    BYTE = np.dtype(np.int8)
    SHORT = np.dtype(np.int16)

    @staticmethod
    def infer(batch_columns: Dict[str, Any]) -> RowType:
        return RowType(tuple(FieldType(k, np.asarray(v).dtype) for k, v in batch_columns.items()))
