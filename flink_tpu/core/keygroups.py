"""Key groups: the state-sharding / rescaling unit, and the TPU sharding axis.

Mirrors the contract of the reference's key-group assignment
(``flink-runtime/src/main/java/org/apache/flink/runtime/state/KeyGroupRangeAssignment.java:50-84``
and ``flink-core/src/main/java/org/apache/flink/util/MathUtils.java:137`` murmur
finalizer): ``key_group = murmur(key_hash) % max_parallelism`` and contiguous
key-group *ranges* per parallel subtask, so state laid out by key group can be
rescaled/resharded without rehashing keys.

Everything here is vectorized numpy over ``int32`` key hashes — the host-side
router uses it to split record batches across device shards (the analog of
``KeyGroupStreamPartitioner``), and snapshots index state by key-group range.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(5)
_N = np.uint32(0xE6546B64)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def murmur_hash(code: np.ndarray | int) -> np.ndarray:
    """Vectorized equivalent of ``MathUtils.murmurHash(int)`` (MathUtils.java:137).

    Accepts int32-like input, returns non-negative int32 values with identical
    results to the reference for every input (including the
    ``Integer.MIN_VALUE -> 0`` edge case).
    """
    code = np.asarray(code, dtype=np.int64).astype(np.uint32)
    with np.errstate(over="ignore"):
        code = code * _C1
        code = _rotl32(code, 15)
        code = code * _C2
        code = _rotl32(code, 13)
        code = code * _M5 + _N
        code = code ^ np.uint32(4)
        # bitMix (MathUtils.java:194)
        code ^= code >> np.uint32(16)
        code = code * np.uint32(0x85EBCA6B)
        code ^= code >> np.uint32(13)
        code = code * np.uint32(0xC2B2AE35)
        code ^= code >> np.uint32(16)
    signed = code.astype(np.int32)
    out = np.where(signed >= 0, signed, np.where(signed == np.int32(-2147483648), 0, -signed))
    return out.astype(np.int32)


def java_int_hash(values: np.ndarray) -> np.ndarray:
    """``Integer.hashCode`` / ``Long.hashCode`` analog for numpy int arrays."""
    v = np.asarray(values)
    if v.dtype in (np.int64, np.uint64):
        u = v.astype(np.uint64)
        return (u ^ (u >> np.uint64(32))).astype(np.uint32).astype(np.int32)
    return v.astype(np.int32)


def assign_to_key_group(key_hashes: np.ndarray, max_parallelism: int) -> np.ndarray:
    """``KeyGroupRangeAssignment.computeKeyGroupForKeyHash:75``: murmur % maxParallelism."""
    return murmur_hash(key_hashes) % np.int32(max_parallelism)


_string_hash_cache: dict = {}
_STRING_HASH_CACHE_MAX = 1 << 22  # bound: reset rather than leak unboundedly


def java_string_hash(values: np.ndarray) -> np.ndarray:
    """``String.hashCode`` (s[0]*31^(n-1) + ...) per element of an object array.

    Cache persists across batches (hot path: keyBy on string keys re-sees the
    same key universe every batch); size-bounded against high-cardinality
    streams."""
    if len(_string_hash_cache) > _STRING_HASH_CACHE_MAX:
        _string_hash_cache.clear()
    cache = _string_hash_cache
    out = np.empty(len(values), np.int64)
    for i, s in enumerate(values):
        h = cache.get(s)
        if h is None:
            acc = 0
            for ch in str(s):
                acc = (acc * 31 + ord(ch)) & 0xFFFFFFFF
            cache[s] = h = acc
        out[i] = h
    return out.astype(np.uint32).astype(np.int32)


def hash_keys(keys: np.ndarray) -> np.ndarray:
    """Key column (int or object dtype) -> int32 hashes (``Object.hashCode``)."""
    keys = np.asarray(keys)
    if keys.dtype.kind in "iu":
        return java_int_hash(keys)
    if keys.dtype.kind == "V" and keys.dtype.itemsize % 8 == 0:
        # packed composite keys (void bytes, see dataset _composite_key):
        # vectorized polynomial mix over the 8-byte words
        words = keys.view(np.int64).reshape(len(keys), -1)
        h = np.zeros(len(keys), np.int64)
        with np.errstate(over="ignore"):
            for j in range(words.shape[1]):
                h = h * np.int64(31) + words[:, j]
        return java_int_hash(h)
    return java_string_hash(keys)


@dataclass(frozen=True)
class KeyGroupRange:
    """Inclusive [start, end] range of key groups (``KeyGroupRange.java``)."""

    start: int
    end: int

    def __post_init__(self):
        if self.end < self.start:
            object.__setattr__(self, "start", 0)
            object.__setattr__(self, "end", -1)

    @property
    def num_key_groups(self) -> int:
        return self.end - self.start + 1

    def contains(self, key_group: int) -> bool:
        return self.start <= key_group <= self.end

    def __iter__(self) -> Iterator[int]:
        return iter(range(self.start, self.end + 1))

    def intersection(self, other: "KeyGroupRange") -> "KeyGroupRange":
        return KeyGroupRange(max(self.start, other.start), min(self.end, other.end))


def compute_key_group_range(max_parallelism: int, parallelism: int, operator_index: int) -> KeyGroupRange:
    """``KeyGroupRangeAssignment.computeKeyGroupRangeForOperatorIndex``."""
    if parallelism > max_parallelism:
        raise ValueError(f"parallelism {parallelism} > max_parallelism {max_parallelism}")
    start = (operator_index * max_parallelism + parallelism - 1) // parallelism
    end = ((operator_index + 1) * max_parallelism - 1) // parallelism
    return KeyGroupRange(start, end)


def compute_operator_index_for_key_group(max_parallelism: int, parallelism: int, key_group: int) -> int:
    """``KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup``."""
    return key_group * parallelism // max_parallelism


def assign_key_to_parallel_operator(key_hashes: np.ndarray, max_parallelism: int, parallelism: int) -> np.ndarray:
    """Vectorized ``assignKeyToParallelOperator:50`` — subtask index per key."""
    kg = assign_to_key_group(key_hashes, max_parallelism)
    return (kg.astype(np.int64) * parallelism // max_parallelism).astype(np.int32)


def key_group_ranges(max_parallelism: int, parallelism: int) -> List[KeyGroupRange]:
    return [compute_key_group_range(max_parallelism, parallelism, i) for i in range(parallelism)]


def route_raw_keys(keys: np.ndarray, parallelism: int,
                   max_parallelism: int = 128) -> np.ndarray:
    """RAW key column -> owning parallel-operator/shard index per key
    (key hash -> murmur key group -> contiguous range): THE single
    routing assignment shared by the record router, the queryable tier's
    client-side routing (``queryable/view.route_keys``) and
    ``ShardLayout.route_keys`` — one implementation so client routing can
    never desynchronize from state ownership."""
    if parallelism <= 1:
        return np.zeros(len(keys), np.int32)
    return assign_key_to_parallel_operator(hash_keys(np.asarray(keys)),
                                           max_parallelism, parallelism)
