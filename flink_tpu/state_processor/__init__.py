from flink_tpu.state_processor.savepoint import Savepoint, SavepointWriter

__all__ = ["Savepoint", "SavepointWriter"]
