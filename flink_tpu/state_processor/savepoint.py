"""State Processor API: read / bootstrap / modify savepoints offline.

Analog of ``flink-libraries/flink-state-processing-api``
(``Savepoint.load(...)``, ``WindowReader.java``, ``SavepointWriter``):
checkpoints/savepoints become DataSets — list the operators, read any
operator's keyed state as rows, read WindowAggOperator pane state, rewrite
or bootstrap state from a DataSet, and write a new restorable savepoint.

Handles both snapshot layouts: the LocalExecutor's ``{uid: op_snapshot}``
and the MiniCluster's ``{uid: {"subtasks": [op_snapshot, ...]}}`` (subtask
snapshots are merged through the key-group redistribute path on read).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.state.heap import HeapKeyedStateBackend
from flink_tpu.state.redistribute import (merge_keyed_snapshots,
                                          snapshot_operator_class)


def _is_subtask_layout(entry: Any) -> bool:
    return isinstance(entry, dict) and "subtasks" in entry


def _is_keyed(o: Any) -> bool:
    return isinstance(o, dict) and ("key_index" in o or "keys" in o)


def _is_mergeable(o: Any) -> bool:
    """Does this member snapshot have a rescale-aware merge?  Beyond the
    generic keyed layout, every kind in the shared dispatch table
    (window aggregate, session windows, CEP per-key state,
    two-phase-commit sinks) merges consistently across subtasks."""
    return _is_keyed(o) or snapshot_operator_class(o) is not None


def _merge_keyed_group(ops: List[Dict[str, Any]]) -> Dict[str, Any]:
    # empty members (a fresh subtask with no state yet) contribute
    # nothing; dispatch through the SAME kind table the rescale split
    # uses (state/redistribute.snapshot_operator_class), so a member's
    # split and merge can never land on different operators
    ops = [o for o in ops if isinstance(o, dict) and o] or list(ops)
    for o in ops:
        cls = snapshot_operator_class(o)
        if cls is not None:
            return cls.merge_snapshots(ops)
    fields = sorted({f for o in ops for f in o
                     if f.startswith("state.") or f == "leaves"})
    return merge_keyed_snapshots(ops, fields)


def _merged_operator_snapshot(entry: Any, strict: bool = False
                              ) -> Dict[str, Any]:
    """Merge one vertex's subtask snapshots into a single-operator view.

    ``strict=True`` (the RESCALE path) propagates keyed-member merge
    failures: silently keeping subtask 0's copy there would drop every
    other subtask's state from the redeployed job — a quiet
    exactly-once violation.  The default stays best-effort for offline
    savepoint READS, where a heterogeneous member is merely unreadable,
    not redeployed."""
    if not _is_subtask_layout(entry):
        return entry
    subs = [s for s in entry["subtasks"] if s is not None]
    ops = [s.get("operator", s) for s in subs]
    if not ops:
        return {}
    if all(_is_mergeable(o) for o in ops):
        return _merge_keyed_group(ops)
    # chained vertex: merge the mergeable chain members across subtasks,
    # best-effort (other non-keyed members keep subtask 0's copy); empty
    # members (a subtask that held no state for this member yet) are
    # compatible with any mergeable sibling
    member_keys = [k for k in ops[0]
                   if k.startswith("op") and k[2:].isdigit()]
    if member_keys and all(set(member_keys) <= set(o) for o in ops
                           if isinstance(o, dict)):
        out = dict(ops[0])
        for mk in member_keys:
            members = [o[mk] for o in ops]
            live = [m for m in members
                    if isinstance(m, dict) and m]
            if live and all(_is_mergeable(m) for m in live):
                try:
                    out[mk] = _merge_keyed_group(members)
                except (ValueError, KeyError, IndexError):
                    if strict:
                        raise
                    pass  # heterogeneous member layout: keep subtask 0
        return out
    return ops[0]


class Savepoint:
    """``Savepoint.load`` analog."""

    @staticmethod
    def load(storage, checkpoint_id: Optional[int] = None) -> "SavepointReader":
        snap = (storage.load(checkpoint_id) if checkpoint_id is not None
                else storage.load_latest())
        if snap is None:
            raise ValueError("no checkpoint found in storage")
        return SavepointReader(snap)

    @staticmethod
    def from_snapshot(snapshot: Dict[str, Any]) -> "SavepointReader":
        return SavepointReader(snapshot)


def _chain_members(op_snap: Dict[str, Any]):
    """A chained vertex snapshot nests member snapshots under op0/op1/...;
    yield the vertex snapshot itself plus every chain member."""
    yield op_snap
    for k in sorted(op_snap):
        if k.startswith("op") and k[2:].isdigit() and isinstance(op_snap[k], dict):
            yield op_snap[k]


def _find_member(op_snap: Dict[str, Any], *fields: str) -> Optional[Dict[str, Any]]:
    for m in _chain_members(op_snap):
        if any(f in m for f in fields):
            return m
    return None


class SavepointReader:
    def __init__(self, snapshot: Dict[str, Any]):
        self.snapshot = snapshot

    def operator_uids(self) -> List[str]:
        return sorted(u for u in self.snapshot
                      if not u.startswith("__"))

    def raw(self, uid: str) -> Dict[str, Any]:
        return _merged_operator_snapshot(self.snapshot[uid])

    # -- keyed state ---------------------------------------------------------
    def _keyed_member(self, uid: str) -> Dict[str, Any]:
        snap = self.raw(uid)
        op_snap = snap.get("operator", snap) if isinstance(snap, dict) else snap
        m = _find_member(op_snap, "key_index", "keys")
        if m is None:
            raise ValueError(f"{uid}: no keyed state in snapshot")
        return m

    def _backend_for(self, uid: str) -> HeapKeyedStateBackend:
        member = dict(self._keyed_member(uid))
        member.pop("timers", None)
        if "key_index" not in member and "keys" in member:
            # operators like KeyedReduce store the index under "keys"
            member["key_index"] = member.pop("keys")
        be = HeapKeyedStateBackend()
        be.restore(member)
        return be

    def keyed_state_names(self, uid: str) -> List[str]:
        return sorted(self._keyed_member(uid).get("state_names", []))

    def read_keyed_state(self, uid: str, state_name: str,
                         descriptor=None):
        """All (key, value) rows of one named state as a DataSet
        (``Savepoint.readKeyedState`` analog)."""
        from flink_tpu.dataset import ExecutionEnvironment
        from flink_tpu.state.api import ValueStateDescriptor

        be = self._backend_for(uid)
        n = be.num_keys
        env = ExecutionEnvironment()
        if n == 0:
            return env.from_columns({"key": np.zeros(0, np.int64),
                                     "value": np.zeros(0)})
        desc = descriptor or ValueStateDescriptor(state_name)
        st = be.get_state(desc)
        slots = np.arange(n)
        keys = be.slot_keys(slots)
        got = st.get_rows(slots)
        if isinstance(got, tuple):       # (values, alive) states
            vals, alive = got
            keys, vals = np.asarray(keys)[alive], np.asarray(vals)[alive]
        else:
            vals = got
        return env.from_columns({"key": np.asarray(keys),
                                 "value": np.asarray(vals, dtype=object)
                                 if isinstance(vals, list) else np.asarray(vals)})

    # -- window state (WindowReader analog) ----------------------------------
    def read_window_state(self, uid: str):
        """WindowAggOperator pane state as rows (key, pane, acc leaves) —
        ``WindowReader`` reads WindowOperator state offline the same way."""
        from flink_tpu.dataset import ExecutionEnvironment
        from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex

        snap = self.raw(uid)
        root = snap.get("operator", snap)
        op_snap = _find_member(root, "leaves", "shard_slices")
        if op_snap is None:
            raise ValueError(f"{uid}: not a window-aggregate snapshot "
                             f"(fields: {sorted(root)[:8]})")
        # mesh snapshots carry per-shard slices with key-group manifests
        # (state/shard_layout) instead of dense arrays: merge first
        from flink_tpu.state.shard_layout import densify_keyed_snapshot
        op_snap = densify_keyed_snapshot(op_snap)
        cls = (ObjectKeyIndex if op_snap.get("key_index_kind") == "ObjectKeyIndex"
               else KeyIndex)
        idx = cls.restore(op_snap["key_index"])
        keys = idx.reverse_keys()
        counts = np.asarray(op_snap["counts"])          # [K, n_live_panes]
        leaves = [np.asarray(l) for l in op_snap["leaves"]]
        panes_arr = np.asarray(op_snap["panes"], np.int64)
        k_ids, pcols = np.nonzero(counts > 0)
        cols: Dict[str, Any] = {
            "key": np.asarray(keys)[k_ids],
            "pane": panes_arr[pcols],
            "count": counts[k_ids, pcols],
        }
        for i, leaf in enumerate(leaves):
            cols[f"acc{i}"] = leaf[k_ids, pcols]
        env = ExecutionEnvironment()
        return env.from_columns(cols)

    # -- sources -------------------------------------------------------------
    def read_source_positions(self) -> Dict[str, Dict[str, Any]]:
        out = dict(self.snapshot.get("__sources__", {}))
        for uid, entry in self.snapshot.items():
            if _is_subtask_layout(entry):
                offs = {f"{i}": s.get("source_offset")
                        for i, s in enumerate(entry["subtasks"])
                        if s and "source_offset" in s}
                if offs:
                    out[uid] = offs
        return out


class SavepointWriter:
    """Bootstrap/modify savepoints (``SavepointWriter``/``Savepoint.create``)."""

    def __init__(self, base: Optional[Dict[str, Any]] = None):
        self.snapshot: Dict[str, Any] = dict(base or {})

    @staticmethod
    def new_savepoint() -> "SavepointWriter":
        return SavepointWriter()

    @staticmethod
    def from_existing(reader: SavepointReader) -> "SavepointWriter":
        return SavepointWriter(reader.snapshot)

    def remove_operator(self, uid: str) -> "SavepointWriter":
        self.snapshot.pop(uid, None)
        return self

    def with_keyed_state(self, uid: str, dataset, key_column: str,
                         value_column: str, state_name: str,
                         descriptor=None) -> "SavepointWriter":
        """Bootstrap one ValueState from a DataSet of (key, value) rows
        (``KeyedStateBootstrapFunction`` analog, vectorized)."""
        from flink_tpu.state.api import ValueStateDescriptor

        b = dataset.collect_batch()
        be = HeapKeyedStateBackend()
        desc = descriptor or ValueStateDescriptor(state_name)
        st = be.get_state(desc)
        keys = np.asarray(b.column(key_column))
        slots = be.key_slots(keys)
        st.put_rows(slots, np.asarray(b.column(value_column)))
        self.snapshot[uid] = be.snapshot()
        return self

    def transform_keyed_state(self, uid: str, state_name: str,
                              fn, descriptor=None) -> "SavepointWriter":
        """Rewrite every (key, value) through ``fn(key, value) -> value``."""
        from flink_tpu.state.api import ValueStateDescriptor

        # never mutate the caller's snapshot tree (from_existing shares it)
        import copy as _copy
        self.snapshot[uid] = _copy.deepcopy(self.snapshot[uid])
        entry = self.snapshot[uid]
        # an UNALIGNED checkpoint's persisted in-flight channel state must
        # survive the offline rewrite even though the merge collapses the
        # subtask snapshots: redistribute it to a SINGLE logical subtask
        # (the merged layout's parallelism) — restoring the rewritten
        # savepoint re-splits it by key through the rescale path.  Legacy
        # v1 sections with elements still fail loudly (no routing
        # metadata), never silently drop.
        carried_cs = None
        if _is_subtask_layout(entry):
            from flink_tpu.state.redistribute import (
                redistribute_channel_state)
            sections = [(s or {}).get("channel_state")
                        for s in entry["subtasks"]]
            if any((cs.get("elements") if isinstance(cs, dict) else cs)
                   for cs in sections):
                carried_cs = redistribute_channel_state(
                    sections, 1, context="savepoint transform")[0]
        # strict: the rewritten savepoint REDEPLOYS — a keyed member that
        # cannot merge must fail the rewrite, not silently keep only
        # subtask 0's key-group ranges
        op_snap = _merged_operator_snapshot(entry, strict=True)
        inner = op_snap.get("operator", op_snap)
        member = _find_member(inner, "key_index", "keys")
        if member is None:
            raise ValueError(f"{uid}: no keyed state to transform")
        if not any(k.startswith(f"state.{state_name}.") for k in member):
            raise ValueError(
                f"{uid}: no heap state named {state_name!r} in the snapshot "
                f"(fields: {sorted(member)[:8]}); operators that keep state "
                f"in dense row fields (e.g. keyed reduce 'leaves') are not "
                f"transformable via transform_keyed_state")
        restorable = {k: v for k, v in member.items() if k != "timers"}
        if "key_index" not in restorable and "keys" in restorable:
            restorable["key_index"] = restorable.pop("keys")
        be = HeapKeyedStateBackend()
        be.restore(restorable)
        desc = descriptor or ValueStateDescriptor(state_name)
        st = be.get_state(desc)
        n = be.num_keys
        slots = np.arange(n)
        keys = be.slot_keys(slots)
        got = st.get_rows(slots)
        vals, alive = got if isinstance(got, tuple) else (got, np.ones(n, bool))
        new_vals = [fn(k, v) if a else v
                    for k, v, a in zip(np.asarray(keys).tolist(), list(vals),
                                       np.asarray(alive).tolist())]
        st.put_rows(slots, new_vals)
        new_snap = be.snapshot()
        # non-backend member fields (timers, watermarks) must survive the
        # rewrite — dropping them would silently cancel pending timers
        for k, v in member.items():
            if k not in new_snap and not k.startswith("state."):
                new_snap[k] = v
        if member is inner:
            if "operator" in op_snap:
                op_snap = dict(op_snap)
                op_snap["operator"] = new_snap
                self.snapshot[uid] = op_snap
            else:
                self.snapshot[uid] = new_snap
        else:
            member.clear()
            member.update(new_snap)
            self.snapshot[uid] = op_snap
        if carried_cs is not None:
            # merged-to-parallelism-1 subtask layout: the rewritten state
            # plus the redistributed in-flight elements; restore at any
            # parallelism goes through maybe_rescale_restore/rescale_snapshot
            rewritten = self.snapshot[uid]
            sub = (rewritten if isinstance(rewritten, dict)
                   and "operator" in rewritten
                   else {"operator": rewritten, "valve": None})
            sub["channel_state"] = carried_cs
            self.snapshot[uid] = {"subtasks": [sub]}
        return self

    def write(self, storage, checkpoint_id: int = 1) -> Dict[str, Any]:
        storage.store(checkpoint_id, self.snapshot)
        return self.snapshot
