"""DataStream API — the fluent program-construction surface.

Analog of ``flink-streaming-java/.../api/datastream/`` +
``StreamExecutionEnvironment.java:1873``: each call appends a
``Transformation`` node; ``env.execute()`` translates the DAG through
``StreamGraph`` (chaining) into an ``ExecutionPlan`` and runs it on the
configured executor.  Records are columnar batches, so user functions are
vectorized (columns-dict in/out) — see ``flink_tpu/operators/basic.py``.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Union

import numpy as np

from flink_tpu.config.config_option import Configuration
from flink_tpu.connectors.sinks import CollectSink, PrintSink, Sink
from flink_tpu.connectors.sources import (CollectionSource, GeneratorSource,
                                          IteratorSource, SocketTextSource,
                                          Source)
from flink_tpu.core.functions import (AggregateFunction, AvgAggregator,
                                      CountAggregator, LambdaReduce,
                                      MaxAggregator, MinAggregator,
                                      ReduceFunction, SumAggregator)
from flink_tpu.core.watermarks import (BoundedOutOfOrdernessWatermarks,
                                       MonotonousTimestampsWatermarks,
                                       WatermarkGenerator)
from flink_tpu.graph.stream_graph import ExecutionPlan, StreamGraph
from flink_tpu.graph.transformations import Partitioning, Transformation
from flink_tpu.operators.basic import (FilterOperator, FlatMapOperator,
                                       KeyByOperator, KeyedReduceOperator,
                                       MapOperator, SinkOperator,
                                       TimestampsAndWatermarksOperator)
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.runtime.executor import JobExecutionResult, LocalExecutor
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.triggers import Trigger


class StreamExecutionEnvironment:
    """``StreamExecutionEnvironment`` analog: source factories + execute()."""

    def __init__(self, config: Optional[Configuration] = None,
                 parallelism: int = 1, max_parallelism: int = 128,
                 mesh=None):
        self.config = config or Configuration()
        self.parallelism = parallelism
        self.max_parallelism = max_parallelism
        self._sinks: List[Transformation] = []
        self.checkpoint_interval_ms = 0
        self.checkpoint_storage = None
        #: jax.sharding.Mesh: keyed window state shards over it and keyed
        #: records ride the all_to_all device exchange (parallel/mesh_runtime)
        self.mesh = mesh

    def set_mesh(self, mesh=None, n_devices: Optional[int] = None
                 ) -> "StreamExecutionEnvironment":
        """Execute keyed window aggregations sharded over a device mesh —
        the TPU scale-out axis (key groups -> devices, SURVEY §2.7).  With
        no arguments, a mesh over all visible devices."""
        if mesh is None:
            from flink_tpu.parallel.mesh import make_mesh
            mesh = make_mesh(n_devices)
        self.mesh = mesh
        return self

    @staticmethod
    def get_execution_environment(
            config: Optional[Configuration] = None) -> "StreamExecutionEnvironment":
        return StreamExecutionEnvironment(config)

    def set_parallelism(self, p: int) -> "StreamExecutionEnvironment":
        self.parallelism = p
        return self

    def set_max_parallelism(self, p: int) -> "StreamExecutionEnvironment":
        self.max_parallelism = p
        return self

    def enable_checkpointing(self, interval_ms: int,
                             storage=None) -> "StreamExecutionEnvironment":
        self.checkpoint_interval_ms = interval_ms
        self.checkpoint_storage = storage
        return self

    # ------------------------------------------------------------- sources
    def from_source(self, source: Source, name: str = "source") -> "DataStream":
        t = Transformation(name=name, operator_factory=None, is_source=True,
                           source=source, chainable=True,
                           parallelism=self.parallelism,
                           max_parallelism=self.max_parallelism)
        # source vertices need a pass-through operator for the chain head
        t.operator_factory = _identity_operator_factory(name)
        return DataStream(self, t)

    def from_collection(self, rows: Optional[Sequence[Mapping[str, Any]]] = None,
                        columns: Optional[Mapping[str, Any]] = None,
                        timestamp_column: Optional[str] = None,
                        batch_size: int = 4096,
                        name: str = "collection-source") -> "DataStream":
        return self.from_source(
            CollectionSource(rows, columns, timestamp_column, batch_size), name)

    def socket_text_stream(self, host: str, port: int,
                           batch_size: int = 4096) -> "DataStream":
        return self.from_source(SocketTextSource(host, port, batch_size),
                                f"socket:{host}:{port}")

    def generate_sequence(self, start: int, end: int,
                          batch_size: int = 4096) -> "DataStream":
        return self.from_collection(
            columns={"value": np.arange(start, end + 1, dtype=np.int64)},
            batch_size=batch_size, name="sequence-source")

    # ------------------------------------------------------------- execute
    def _register_sink(self, t: Transformation) -> None:
        self._sinks.append(t)

    def get_stream_graph(self, job_name: str = "job") -> StreamGraph:
        if not self._sinks:
            raise ValueError("no sinks registered — nothing to execute")
        return StreamGraph.from_sinks(self._sinks, self.parallelism,
                                      self.max_parallelism, job_name)

    def execute(self, job_name: str = "job",
                restore: Optional[Dict[str, Any]] = None,
                max_records: Optional[int] = None,
                max_wall_ms: Optional[int] = None,
                drain: bool = True) -> JobExecutionResult:
        plan = self.get_stream_graph(job_name).to_plan()
        executor = LocalExecutor(
            checkpoint_interval_ms=self.checkpoint_interval_ms,
            checkpoint_storage=self.checkpoint_storage,
            max_records=max_records, max_wall_ms=max_wall_ms,
            config=self.config)
        # publish BEFORE the blocking run so another thread can cancel()
        self._last_executor = executor
        return executor.execute(plan, restore=restore, drain=drain)

    def execute_cluster(self, job_name: str = "job",
                        restore: Optional[Dict[str, Any]] = None,
                        checkpoint_interval_ms: Optional[int] = None,
                        storage=None, unaligned: bool = False,
                        restart_attempts: int = 0, timeout_s: float = 300.0,
                        tolerable_failed_checkpoints: int = 0,
                        checkpoint_timeout_s: float = 60.0,
                        alignment_timeout_ms: Optional[float] = None,
                        alignment_queue_max: Optional[int] = None,
                        channel_capacity: int = 32,
                        incremental: bool = False):
        """Run on the in-process MiniCluster with REAL parallelism (one
        thread per subtask, channels + partitioners between them) — the
        multi-node semantics path (``MiniCluster.java`` analog).

        ``alignment_timeout_ms`` enables aligned-with-timeout unaligned
        checkpoints (0 = unaligned from the first barrier, like
        ``unaligned=True``); ``alignment_queue_max`` caps the per-subtask
        blocked-channel alignment buffer."""
        from flink_tpu.cluster.minicluster import MiniCluster

        plan = self.get_stream_graph(job_name).to_plan()
        cluster = MiniCluster(
            checkpoint_storage=storage or self.checkpoint_storage,
            checkpoint_interval_ms=(
                checkpoint_interval_ms if checkpoint_interval_ms is not None
                else self.checkpoint_interval_ms),
            unaligned=unaligned, restart_attempts=restart_attempts,
            tolerable_failed_checkpoints=tolerable_failed_checkpoints,
            checkpoint_timeout_s=checkpoint_timeout_s,
            alignment_timeout_ms=alignment_timeout_ms,
            alignment_queue_max=alignment_queue_max,
            channel_capacity=channel_capacity, config=self.config,
            incremental=incremental)
        self._last_cluster = cluster
        return cluster.execute(plan, restore=restore, timeout_s=timeout_s)


def _identity_operator_factory(name: str):
    from flink_tpu.operators.base import StreamOperator

    class _Identity(StreamOperator):
        is_stateless = True

        def process_batch(self, batch):
            return [batch]

    def make():
        op = _Identity()
        op.name = name
        return op

    return make


class DataStream:
    """Fluent stream handle appending transformations (``DataStream.java``)."""

    def __init__(self, env: StreamExecutionEnvironment, transformation: Transformation):
        self.env = env
        self.transformation = transformation

    def _then(self, name: str, factory, partitioning: str = Partitioning.FORWARD,
              key_column: Optional[str] = None, chainable: bool = True) -> Transformation:
        return Transformation(name=name, operator_factory=factory,
                              inputs=[self.transformation],
                              partitioning=partitioning,
                              key_column=key_column, chainable=chainable,
                              parallelism=self.env.parallelism,
                              max_parallelism=self.env.max_parallelism)

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]],
            name: str = "map") -> "DataStream":
        return DataStream(self.env, self._then(name, lambda: MapOperator(fn, name)))

    def filter(self, fn: Callable[[Dict[str, Any]], np.ndarray],
               name: str = "filter") -> "DataStream":
        return DataStream(self.env, self._then(name, lambda: FilterOperator(fn, name)))

    def flat_map(self, fn, name: str = "flat-map") -> "DataStream":
        return DataStream(self.env, self._then(name, lambda: FlatMapOperator(fn, name)))

    def assign_timestamps_and_watermarks(
            self, generator_or_ooo: Union[WatermarkGenerator, int],
            timestamp_column: Optional[str] = None,
            timestamp_fn=None, name: str = "timestamps") -> "DataStream":
        if isinstance(generator_or_ooo, WatermarkGenerator):
            gen_proto = generator_or_ooo
        else:
            gen_proto = BoundedOutOfOrdernessWatermarks(int(generator_or_ooo))
        import copy

        def factory():
            return TimestampsAndWatermarksOperator(
                copy.deepcopy(gen_proto), timestamp_column, timestamp_fn, name)

        return DataStream(self.env, self._then(name, factory))

    def key_by(self, key_column: str) -> "KeyedStream":
        t = self._then(f"key-by:{key_column}",
                       lambda: KeyByOperator(key_column,
                                             self.env.max_parallelism),
                       partitioning=Partitioning.HASH, key_column=key_column)
        return KeyedStream(self.env, t, key_column)

    def union(self, *others: "DataStream") -> "DataStream":
        t = Transformation(
            name="union", operator_factory=_identity_operator_factory("union"),
            inputs=[self.transformation] + [o.transformation for o in others],
            parallelism=self.env.parallelism,
            max_parallelism=self.env.max_parallelism)
        return DataStream(self.env, t)

    def rebalance(self) -> "DataStream":
        t = self._then("rebalance", _identity_operator_factory("rebalance"),
                       partitioning=Partitioning.REBALANCE, chainable=False)
        return DataStream(self.env, t)

    def broadcast(self) -> "DataStream":
        t = self._then("broadcast", _identity_operator_factory("broadcast"),
                       partitioning=Partitioning.BROADCAST, chainable=False)
        return DataStream(self.env, t)

    def shuffle(self) -> "DataStream":
        """Uniform-random redistribution (``ShufflePartitioner`` analog)."""
        t = self._then("shuffle", _identity_operator_factory("shuffle"),
                       partitioning=Partitioning.SHUFFLE, chainable=False)
        return DataStream(self.env, t)

    def rescale(self) -> "DataStream":
        """Round-robin within the producer's local consumer group
        (``RescalePartitioner`` analog)."""
        t = self._then("rescale", _identity_operator_factory("rescale"),
                       partitioning=Partitioning.RESCALE, chainable=False)
        return DataStream(self.env, t)

    def global_(self) -> "DataStream":
        """Route everything to subtask 0 (``GlobalPartitioner`` analog)."""
        t = self._then("global", _identity_operator_factory("global"),
                       partitioning=Partitioning.GLOBAL, chainable=False)
        return DataStream(self.env, t)

    def iterate(self, max_wait_ms: int = 200) -> "IterativeStream":
        """Streaming iteration (``DataStream.iterate`` analog): returns a
        stream that unions this one with a feedback edge; wire the loop body
        back with ``close_with(feedback_stream)``."""
        from flink_tpu.operators.iteration import FeedbackQueue, FeedbackSource

        q = FeedbackQueue()
        fb = self.env.from_source(FeedbackSource(q, max_wait_ms),
                                  "iteration-head")
        unioned = self.union(fb)
        return IterativeStream(self.env, unioned.transformation, q)

    # ------------------------------------------------- two-input operations
    def connect(self, other: "DataStream") -> "ConnectedStreams":
        """Two streams, one two-input operator (``ConnectedStreams`` analog)."""
        return ConnectedStreams(self.env, self, other)

    def connect_broadcast(self, rules: "DataStream", fn,
                          name: str = "broadcast-connect") -> "DataStream":
        """Broadcast state pattern: ``rules`` replicates to every subtask;
        ``fn`` is a BroadcastProcessFunction."""
        from flink_tpu.operators.co import BroadcastConnectOperator

        t = Transformation(
            name=name, operator_factory=lambda: BroadcastConnectOperator(fn, name),
            inputs=[self.transformation, rules.transformation],
            input_partitionings=[Partitioning.FORWARD, Partitioning.BROADCAST],
            input_key_columns=[None, None],
            parallelism=self.env.parallelism, chainable=False,
            max_parallelism=self.env.max_parallelism)
        return DataStream(self.env, t)

    def join(self, other: "DataStream") -> "JoinBuilder":
        """``a.join(b).where(k).equal_to(k2).window(w).apply(fn)``."""
        return JoinBuilder(self.env, self, other, cogroup=False)

    def co_group(self, other: "DataStream") -> "JoinBuilder":
        return JoinBuilder(self.env, self, other, cogroup=True)

    def get_side_output(self, tag) -> "DataStream":
        """Side-output stream of an upstream process function
        (``getSideOutput`` analog). ``tag``: OutputTag or name."""
        from flink_tpu.core.batch import OutputTag
        from flink_tpu.operators.basic import SideOutputOperator

        name = tag.name if isinstance(tag, OutputTag) else str(tag)
        t = self._then(f"side-output:{name}",
                       lambda: SideOutputOperator(name), chainable=False)
        return DataStream(self.env, t)

    def async_wait(self, fn, capacity: int = 16, timeout_ms: int = 60_000,
                   ordered: bool = True, name: str = "async-wait") -> "DataStream":
        """Async I/O (``AsyncDataStream.orderedWait/unorderedWait`` analog):
        ``fn(cols) -> cols`` runs on a worker pool per batch."""
        from flink_tpu.operators.async_io import AsyncWaitOperator

        t = self._then(name, lambda: AsyncWaitOperator(
            fn, capacity=capacity, timeout_ms=timeout_ms, ordered=ordered,
            name=name), chainable=False)
        return DataStream(self.env, t)

    # -------------------------------------------------------------- sinks
    def add_sink(self, sink: Sink, name: str = "sink") -> "DataStreamSink":
        t = self._then(name, lambda: SinkOperator(sink, name))
        t.is_sink = True
        self.env._register_sink(t)
        return DataStreamSink(self.env, t, sink)

    sink_to = add_sink

    def print(self, prefix: str = "") -> "DataStreamSink":
        return self.add_sink(PrintSink(prefix), name="print")

    def collect(self) -> CollectSink:
        """Attach a CollectSink and return it (executeAndCollect helper)."""
        sink = CollectSink()
        self.add_sink(sink, name="collect")
        return sink

    def execute_and_collect(self, job_name: str = "collect-job") -> List[Dict[str, Any]]:
        sink = self.collect()
        self.env.execute(job_name)
        return sink.rows()


class IterativeStream(DataStream):
    """Result of ``iterate()``: a stream with an open feedback edge."""

    def __init__(self, env, transformation, queue):
        super().__init__(env, transformation)
        self.queue = queue

    def close_with(self, feedback: DataStream) -> None:
        """Attach the feedback edge (``IterativeStream.closeWith``)."""
        from flink_tpu.operators.iteration import FeedbackSinkOperator

        q = self.queue
        t = feedback._then("iteration-tail",
                           lambda: FeedbackSinkOperator(q), chainable=False)
        t.is_sink = True
        self.env._register_sink(t)


class ConnectedStreams:
    """``DataStream.connect`` result: map/flat_map/process over two inputs."""

    def __init__(self, env: StreamExecutionEnvironment, left: DataStream,
                 right: DataStream):
        self.env = env
        self.left = left
        self.right = right

    def _two_input(self, name: str, factory,
                   partitionings=None, key_columns=None) -> DataStream:
        t = Transformation(
            name=name, operator_factory=factory,
            inputs=[self.left.transformation, self.right.transformation],
            input_partitionings=partitionings,
            input_key_columns=key_columns,
            parallelism=self.env.parallelism, chainable=False,
            max_parallelism=self.env.max_parallelism)
        return DataStream(self.env, t)

    def map(self, fn1, fn2, name: str = "co-map") -> DataStream:
        from flink_tpu.operators.co import CoMapOperator
        return self._two_input(name, lambda: CoMapOperator(fn1, fn2, name))

    def flat_map(self, fn1, fn2, name: str = "co-flat-map") -> DataStream:
        from flink_tpu.operators.co import CoFlatMapOperator
        return self._two_input(name, lambda: CoFlatMapOperator(fn1, fn2, name))

    def process(self, fn, name: str = "co-process") -> DataStream:
        from flink_tpu.operators.co import CoProcessOperator
        return self._two_input(name, lambda: CoProcessOperator(fn, name))


class JoinBuilder:
    """``a.join(b).where(k).equal_to(k).window(w).apply(fn)`` — the
    JoinedStreams/CoGroupedStreams fluent chain."""

    def __init__(self, env, left: DataStream, right: DataStream, cogroup: bool):
        self.env = env
        self.left = left
        self.right = right
        self.cogroup = cogroup
        self._left_key: Optional[str] = None
        self._right_key: Optional[str] = None

    def where(self, key_column: str) -> "JoinBuilder":
        self._left_key = key_column
        return self

    def equal_to(self, key_column: str) -> "JoinBuilder":
        self._right_key = key_column
        return self

    def window(self, assigner: WindowAssigner) -> "JoinBuilder":
        self._assigner = assigner
        return self

    def apply(self, fn=None, name: str = "window-join") -> DataStream:
        from flink_tpu.operators.joins import WindowJoinOperator

        if self._left_key is None or self._right_key is None:
            raise ValueError("join needs .where(...) and .equal_to(...)")
        assigner = getattr(self, "_assigner", None)
        if assigner is None:
            raise ValueError("join needs .window(...)")
        if self.cogroup and fn is None:
            raise ValueError("co_group needs an apply function "
                             "fn(key, window, left_rows, right_rows)")
        lk, rk, cg = self._left_key, self._right_key, self.cogroup
        t = Transformation(
            name=name,
            operator_factory=lambda: WindowJoinOperator(
                assigner, lk, rk, apply_fn=fn, cogroup=cg, name=name),
            inputs=[self.left.transformation, self.right.transformation],
            input_partitionings=[Partitioning.HASH, Partitioning.HASH],
            input_key_columns=[lk, rk],
            parallelism=self.env.parallelism, chainable=False,
            max_parallelism=self.env.max_parallelism)
        return DataStream(self.env, t)


class IntervalJoinBuilder:
    def __init__(self, env, left: "KeyedStream", right: "KeyedStream"):
        self.env = env
        self.left = left
        self.right = right
        self._lower = 0
        self._upper = 0

    def between(self, lower_ms: int, upper_ms: int) -> "IntervalJoinBuilder":
        self._lower, self._upper = lower_ms, upper_ms
        return self

    def process(self, fn=None, name: str = "interval-join") -> DataStream:
        from flink_tpu.operators.joins import IntervalJoinOperator

        lk = self.left.key_column
        rk = self.right.key_column
        lo, hi = self._lower, self._upper
        t = Transformation(
            name=name,
            operator_factory=lambda: IntervalJoinOperator(
                lk, rk, lo, hi, output_fn=fn, name=name),
            inputs=[self.left.transformation, self.right.transformation],
            input_partitionings=[Partitioning.HASH, Partitioning.HASH],
            input_key_columns=[lk, rk],
            parallelism=self.env.parallelism, chainable=False,
            max_parallelism=self.env.max_parallelism)
        return DataStream(self.env, t)


class DataStreamSink:
    def __init__(self, env: StreamExecutionEnvironment, transformation: Transformation,
                 sink: Sink):
        self.env = env
        self.transformation = transformation
        self.sink = sink

    def name(self, name: str) -> "DataStreamSink":
        self.transformation.name = name
        return self

    def uid(self, uid: str) -> "DataStreamSink":
        self.transformation.uid = uid
        return self


class KeyedStream(DataStream):
    """``KeyedStream.java`` analog: windowing + keyed aggregations."""

    def __init__(self, env: StreamExecutionEnvironment, transformation: Transformation,
                 key_column: str):
        super().__init__(env, transformation)
        self.key_column = key_column

    def interval_join(self, other: "KeyedStream") -> "IntervalJoinBuilder":
        """``a.interval_join(b).between(lo, hi).process()`` (IntervalJoin)."""
        return IntervalJoinBuilder(self.env, self, other)

    def count_window(self, size: int, slide: Optional[int] = None):
        """``countWindow(size[, slide])`` analog.  Without ``slide``:
        GlobalWindows + purging CountTrigger — fires every ``size``
        elements per key with that batch's aggregate, then clears.  With
        ``slide``: every ``slide`` elements per key, emit the aggregate
        of the key's last ``size`` elements (the reference's CountTrigger
        + CountEvictor composition, implemented as a per-key value ring —
        ``operators/count_window.py``; mini-batch fire semantics)."""
        if slide is not None:
            return SlidingCountWindowedStream(self, int(size), int(slide))
        from flink_tpu.windowing.assigners import GlobalWindows
        from flink_tpu.windowing.triggers import CountTrigger

        assigner = GlobalWindows.create()
        assigner.is_event_time = False  # counts, not timestamps, drive fires
        return self.window(assigner).trigger(CountTrigger.of(size,
                                                             purge=True))

    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        return WindowedStream(self, assigner)


    def process(self, fn, name: str = "keyed-process") -> "DataStream":
        """Run a ``KeyedProcessFunction`` (keyed state + timers) on this
        stream (``KeyedStream.process`` analog).  The keyed backend follows
        ``state.backend`` in the environment config (heap / spill /
        changelog)."""
        from flink_tpu.operators.process import KeyedProcessOperator
        from flink_tpu.state import make_keyed_backend
        key_col = self.key_column
        cfg = self.env.config
        maxp = self.env.max_parallelism
        return DataStream(self.env, self._then(
            name, lambda: KeyedProcessOperator(
                fn, key_col, name,
                backend=make_keyed_backend(cfg, max_parallelism=maxp))))

    def reduce(self, fn: Union[ReduceFunction, Callable], identity_value=None,
               value_column: Optional[str] = None,
               output_column: str = "result") -> "DataStream":
        agg = fn if isinstance(fn, ReduceFunction) else LambdaReduce(fn, identity_value)
        key_col = self.key_column

        def factory():
            return KeyedReduceOperator(agg, key_col, value_column, output_column)

        return DataStream(self.env, self._then("keyed-reduce", factory))

    def sum(self, value_column: str, output_column: Optional[str] = None,
            dtype=None) -> "DataStream":
        import jax.numpy as jnp
        agg = SumAggregator(dtype or jnp.float64)
        return self.reduce(agg, value_column=value_column,
                           output_column=output_column or value_column)

    def min(self, value_column: str, output_column: Optional[str] = None,
            dtype=None) -> "DataStream":
        import jax.numpy as jnp
        agg = MinAggregator(dtype or jnp.float64)
        return self.reduce(agg, value_column=value_column,
                           output_column=output_column or value_column)

    def max(self, value_column: str, output_column: Optional[str] = None,
            dtype=None) -> "DataStream":
        import jax.numpy as jnp
        agg = MaxAggregator(dtype or jnp.float64)
        return self.reduce(agg, value_column=value_column,
                           output_column=output_column or value_column)

    def min_by(self, value_column: str, name: str = "min-by") -> "DataStream":
        """Running FULL ROW of the minimum element per key
        (``minBy(field)`` analog; ties keep the first arrival)."""
        from flink_tpu.operators.basic import ExtremumByOperator
        kc = self.key_column
        t = self._then(name, lambda: ExtremumByOperator(
            kc, value_column, is_min=True, name=name), chainable=False)
        return DataStream(self.env, t)

    def max_by(self, value_column: str, name: str = "max-by") -> "DataStream":
        """Running FULL ROW of the maximum element per key (``maxBy``)."""
        from flink_tpu.operators.basic import ExtremumByOperator
        kc = self.key_column
        t = self._then(name, lambda: ExtremumByOperator(
            kc, value_column, is_min=False, name=name), chainable=False)
        return DataStream(self.env, t)


class SlidingCountWindowedStream:
    """``count_window(size, slide)``: terminal aggregate ops over the
    per-key last-``size`` ring (``WindowedStream.countWindow(size, slide)``
    analog; no time semantics, so only aggregate-family terminals)."""

    def __init__(self, keyed: "KeyedStream", size: int, slide: int):
        self.keyed = keyed
        self.size = size
        self.slide = slide

    def aggregate(self, agg: AggregateFunction,
                  value_column: Optional[str] = None,
                  output_column: str = "result",
                  name: str = "count-slide-window") -> "DataStream":
        from flink_tpu.operators.count_window import CountSlideWindowOperator

        if value_column is None:
            raise ValueError("count_window(size, slide).aggregate needs "
                             "value_column")
        # validate EAGERLY (the factory is deferred to execute time):
        # the ring combine needs the aggregate's numpy twins
        if self.size <= 0 or self.slide <= 0:
            raise ValueError("count_window size and slide must be positive")
        if not agg.supports_host_emit():
            raise ValueError(
                "count_window(size, slide) needs an aggregate with numpy "
                "twins and declared combine kinds (all built-ins qualify; "
                "a bare lambda reduce does not — use sum/min/max or an "
                "AggregateFunction with host_lift/host_get_result/"
                "scatter_kinds)")
        keyed, size, slide = self.keyed, self.size, self.slide

        def factory():
            return CountSlideWindowOperator(
                agg, key_column=keyed.key_column, value_column=value_column,
                size=size, slide=slide, output_column=output_column,
                name=name)

        return DataStream(keyed.env, keyed._then(name, factory))

    def reduce(self, fn: Union[ReduceFunction, Callable],
               identity_value=None, value_column: Optional[str] = None,
               output_column: str = "result") -> "DataStream":
        agg = fn if isinstance(fn, ReduceFunction) \
            else LambdaReduce(fn, identity_value)
        return self.aggregate(agg, value_column=value_column,
                              output_column=output_column)

    def sum(self, value_column: str,
            output_column: Optional[str] = None) -> "DataStream":
        return self.aggregate(SumAggregator(np.float64),
                              value_column=value_column,
                              output_column=output_column or value_column)

    def min(self, value_column: str,
            output_column: Optional[str] = None) -> "DataStream":
        return self.aggregate(MinAggregator(np.float64),
                              value_column=value_column,
                              output_column=output_column or value_column)

    def max(self, value_column: str,
            output_column: Optional[str] = None) -> "DataStream":
        return self.aggregate(MaxAggregator(np.float64),
                              value_column=value_column,
                              output_column=output_column or value_column)


class WindowedStream:
    """``WindowedStream.java`` analog (``reduce:162``, ``aggregate:283``)."""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner):
        self.keyed = keyed
        self.assigner = assigner
        self._trigger: Optional[Trigger] = None
        self._allowed_lateness = 0

    def trigger(self, trigger: Trigger) -> "WindowedStream":
        self._trigger = trigger
        return self

    def allowed_lateness(self, ms: int) -> "WindowedStream":
        self._allowed_lateness = ms
        return self

    def side_output_late_data(self, tag) -> "WindowedStream":
        """Route beyond-lateness records to a side output instead of
        dropping them (``sideOutputLateData`` analog); read them downstream
        with ``get_side_output(tag)``."""
        from flink_tpu.core.batch import OutputTag

        self._late_tag = tag.name if isinstance(tag, OutputTag) else str(tag)
        return self

    def evictor(self, evictor) -> "WindowedStream":
        """Raw-element window path with eviction (``evictor(...)`` analog).
        Terminal ops: ``aggregate``/``sum``/``count``/... with a
        Count/Time evictor run the DEVICE fast lane (columnar elements,
        mask eviction, on-device combine); any evictor works with the
        host ``apply`` path."""
        self._evictor = evictor
        return self

    def apply(self, fn, name: str = "window-apply") -> DataStream:
        """``fn(key, window, rows) -> row dict`` over the window's raw
        (evicted) rows — the WindowFunction path (buffers elements; use
        ``aggregate``/``reduce`` for the incremental-ACC fast path)."""
        from flink_tpu.operators.evicting_window import EvictingWindowOperator

        if self._trigger is not None:
            raise ValueError("custom triggers are not supported on the "
                             "raw-element apply() path yet; use aggregate()")
        if getattr(self, "_late_tag", None) is not None:
            raise ValueError("side_output_late_data is not supported on the "
                             "raw-element apply() path yet; use aggregate()")
        # raw-element windows keep their buffers host-side by design — the
        # fire-time compute is the user's row function (the reference's
        # evictor also inspects individual elements).  In PROCESS-parallel
        # deployments the keyed exchange partitions rows per subtask and
        # snapshots split/merge by key group
        # (EvictingWindowOperator.split_snapshot); an in-process device
        # mesh adds no parallelism to a host UDF, so say so.
        if self.keyed.env.mesh is not None:
            import warnings
            warnings.warn(
                "raw-element apply() buffers and fires on the host (user "
                "row function): the env mesh adds no device parallelism to "
                "this operator; scale it with process parallelism (key-group"
                " partitioned, rescale-safe)", stacklevel=2)
        assigner = self.assigner
        key_col = self.keyed.key_column
        ev = getattr(self, "_evictor", None)
        lateness = self._allowed_lateness

        def factory():
            # evictors can hold per-fire scratch (DeltaEvictor.bind_values):
            # every subtask needs its OWN instance
            return EvictingWindowOperator(assigner, copy.deepcopy(ev),
                                          key_col, fn, name,
                                          allowed_lateness_ms=lateness)

        return DataStream(self.keyed.env, self.keyed._then(name, factory))

    def aggregate(self, agg: AggregateFunction,
                  value_column: Optional[str] = None,
                  value_selector=None,
                  output_column: str = "result",
                  name: str = "window-agg",
                  emit_tier: Optional[str] = None,
                  paging=None,
                  pipeline_depth: int = 0,
                  native_shards: int = 0,
                  device_probe: str = "auto",
                  queryable: Optional[str] = None,
                  superbatch: int = 1) -> DataStream:
        """``paging``: a :class:`flink_tpu.state.paging.PagingConfig` caps
        the operator's resident key capacity — cold keys page out to the
        spill tier (state larger than HBM).  ``emit_tier`` overrides the
        operator's auto tier pick ("host"/"device").  ``pipeline_depth`` >
        0 runs the operator's hot stage (probe/mirror + device dispatch)
        as a bounded software pipeline overlapping the task driver;
        ``native_shards`` partitions the native probe across cores (0 =
        auto) — both bit-identical to the serial defaults.
        ``device_probe`` gates the device-resident key probe
        (``state/device_keyindex.py``: warm keys resolve inside the jitted
        step, the host C fold touches only misses) — "auto" runs a
        measured A/B calibration, "on"/"off" force; bit-identical fires
        and snapshots either way.  ``queryable`` registers the operator's
        state under that name with the queryable serving tier (ISSUE-9):
        fired values become readable over the batched lookup protocol /
        REST at ``live`` and (when checkpoints run) ``checkpoint``
        consistency.  ``superbatch`` stages N micro-batches into one
        fused megastep pass (ISSUE-11: one scan dispatch / one fused C
        super-pass per N batches; 0 = measured auto-calibration, 1 = off)
        — bit-identical fires, snapshots, and counters either way."""
        keyed, assigner = self.keyed, self.assigner
        trigger, lateness = self._trigger, self._allowed_lateness
        late_tag = getattr(self, "_late_tag", None)
        ev = getattr(self, "_evictor", None)
        if (paging is not None or emit_tier is not None) and (
                ev is not None or keyed.env.mesh is not None
                or not hasattr(assigner, "pane_of")):
            raise ValueError("paging/emit_tier apply to the (unsharded) "
                             "pane-ring window operator — not evictors, "
                             "session windows or mesh-sharded state")
        if queryable is not None and (ev is not None
                                      or not hasattr(assigner, "pane_of")):
            raise ValueError("queryable= is served by the pane-ring window "
                             "operator — not evictors or session windows")
        if ev is not None:
            # evictor + aggregate: the DEVICE fast lane for the common
            # cases (Count/Time evictors + built-in aggregates) — raw
            # elements columnar on device, evict by mask, combine on
            # device, download only fired results.  No host-UDF warning
            # applies: the fire-time compute is device-side.
            from flink_tpu.core.functions import CountAggregator
            from flink_tpu.operators.evicting_device import (
                DeviceEvictingWindowOperator, device_evictor_supported)
            if not device_evictor_supported(ev, agg):
                raise ValueError(
                    "evictor()+aggregate() runs on the device lane for "
                    "CountEvictor/TimeEvictor with built-in aggregates; "
                    "for other evictors use .apply(fn) (raw-element host "
                    "path)")
            if not hasattr(assigner, "pane_of"):
                raise ValueError(
                    "evictors require a pane-based window assigner "
                    "(tumbling/sliding); session windows do not support "
                    "evictors")
            if trigger is not None or late_tag is not None:
                raise ValueError("custom triggers / side outputs are not "
                                 "supported with evictors")
            if value_column is None:
                if isinstance(agg, CountAggregator):
                    # count() needs no value column; the buffer still needs
                    # SOME column — the key column is always present
                    value_column = keyed.key_column
                else:
                    raise ValueError(
                        "evictor()+aggregate() needs value_column")
            if keyed.env.mesh is not None:
                import warnings
                warnings.warn(
                    "evictor()+aggregate() runs on a single device (the "
                    "element buffer is not mesh-sharded yet); the env mesh "
                    "is ignored for this operator", stacklevel=2)
            evictor_proto, evictor_vc = ev, value_column

            def factory():
                return DeviceEvictingWindowOperator(
                    assigner, copy.deepcopy(evictor_proto), agg,
                    key_column=keyed.key_column, value_column=evictor_vc,
                    output_column=output_column,
                    allowed_lateness_ms=lateness, name=name)

            return DataStream(keyed.env, keyed._then(name, factory))

        from flink_tpu.windowing.assigners import SessionGap
        if isinstance(assigner, SessionGap):
            if trigger is not None:
                raise ValueError(
                    "custom triggers are not supported on session windows "
                    "(sessions fire when the gap closes); remove .trigger()")
            from flink_tpu.operators.session_window import SessionWindowOperator
            session_mesh = keyed.env.mesh

            def factory():
                kwargs = dict(
                    key_column=keyed.key_column,
                    value_column=value_column, value_selector=value_selector,
                    allowed_lateness_ms=lateness,
                    output_column=output_column, name=name,
                    late_output_tag=late_tag)
                if session_mesh is not None:
                    from flink_tpu.parallel.mesh_runtime import (
                        MeshSessionWindowOperator)
                    return MeshSessionWindowOperator(
                        assigner, agg, mesh=session_mesh, **kwargs)
                return SessionWindowOperator(assigner, agg, **kwargs)
        else:
            mesh = keyed.env.mesh

            def factory():
                kwargs = dict(
                    assigner=assigner, agg=agg, key_column=keyed.key_column,
                    value_column=value_column, value_selector=value_selector,
                    allowed_lateness_ms=lateness, trigger=trigger,
                    output_column=output_column, name=name,
                    late_output_tag=late_tag)
                if mesh is not None:
                    from flink_tpu.parallel.mesh_runtime import (
                        MeshWindowAggOperator)
                    return MeshWindowAggOperator(mesh=mesh,
                                                 device_probe=device_probe,
                                                 queryable=queryable,
                                                 superbatch=superbatch,
                                                 **kwargs)
                if emit_tier is not None:
                    kwargs["emit_tier"] = emit_tier
                return WindowAggOperator(paging=paging,
                                         pipeline_depth=pipeline_depth,
                                         native_shards=native_shards,
                                         device_probe=device_probe,
                                         queryable=queryable,
                                         superbatch=superbatch,
                                         **kwargs)

        t = keyed._then(name, factory)
        return DataStream(keyed.env, t)

    def reduce(self, fn: Union[ReduceFunction, Callable], identity_value=None,
               value_column: Optional[str] = None,
               output_column: str = "result") -> DataStream:
        agg = fn if isinstance(fn, ReduceFunction) else LambdaReduce(fn, identity_value)
        return self.aggregate(agg, value_column=value_column,
                              output_column=output_column, name="window-reduce")

    def sum(self, value_column: str, output_column: Optional[str] = None,
            dtype=None) -> DataStream:
        import jax.numpy as jnp
        return self.aggregate(SumAggregator(dtype or jnp.float64),
                              value_column=value_column,
                              output_column=output_column or value_column,
                              name="window-sum")

    def min(self, value_column: str, output_column: Optional[str] = None,
            dtype=None) -> DataStream:
        import jax.numpy as jnp
        return self.aggregate(MinAggregator(dtype or jnp.float64),
                              value_column=value_column,
                              output_column=output_column or value_column,
                              name="window-min")

    def max(self, value_column: str, output_column: Optional[str] = None,
            dtype=None) -> DataStream:
        import jax.numpy as jnp
        return self.aggregate(MaxAggregator(dtype or jnp.float64),
                              value_column=value_column,
                              output_column=output_column or value_column,
                              name="window-max")

    def count(self, output_column: str = "count") -> DataStream:
        def ones(cols):
            n = len(np.asarray(next(iter(cols.values()))))
            return np.ones(n, np.int32)

        return self.aggregate(CountAggregator(), value_column=None,
                              value_selector=ones,
                              output_column=output_column, name="window-count")

    def avg(self, value_column: str, output_column: Optional[str] = None,
            dtype=None) -> DataStream:
        import jax.numpy as jnp
        return self.aggregate(AvgAggregator(dtype or jnp.float64),
                              value_column=value_column,
                              output_column=output_column or value_column,
                              name="window-avg")
