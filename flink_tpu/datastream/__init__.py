from flink_tpu.datastream.api import (
    DataStream,
    DataStreamSink,
    KeyedStream,
    StreamExecutionEnvironment,
    WindowedStream,
)

__all__ = [
    "DataStream",
    "DataStreamSink",
    "KeyedStream",
    "StreamExecutionEnvironment",
    "WindowedStream",
]
