"""Coordinator high availability: leader lease, epoch fencing, job recovery.

Analog of the reference's ZooKeeper HA services
(``ZooKeeperLeaderElectionDriver`` + ``DefaultCompletedCheckpointStore`` +
``JobGraphStore``): a durable :class:`FileHaStore` holds

  * a **leader lease** with a monotone **leader epoch** — the fencing
    token every control message carries (``JobMasterId`` analog).  A
    new/standby coordinator acquires the lease at ``epoch + 1``; workers
    and the store itself reject traffic from any lower epoch, so a
    zombie ex-leader can never complete a checkpoint, commit a 2PC
    transaction, or deploy a second incarnation over the new leader's;
  * the **registered job plans** (serialized payloads — what the new
    leader redeploys);
  * the **completed-checkpoint pointer** per job — the authoritative
    "latest completed cut" consulted BEFORE any ``load_latest``
    directory scan on recovery.

Durability discipline is the repo's S1 standard
(``FileCheckpointStorage`` / ``IncrementalCheckpointStorage``): every
record is staged to a tmp file and published by one atomic
``os.replace``, carries its own CRC32, and a torn/corrupt record reads
as *absent* (lease) or raises loudly (job payload) — never as silently
wrong data.

Epoch monotonicity does NOT depend on the lease file surviving: a
separate ``epoch.json`` counter is bumped (and published) BEFORE each
acquisition's lease write, so even a lease torn by a crash or an
injected ``ha.lease`` truncation cannot hand two leaders the same
epoch.  Lease renewal verifies its own write back (re-read + CRC): a
renewal that did not durably land raises :class:`LeaseLostError` — the
holder demotes LOUDLY instead of limping into dual leadership.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
import zlib
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.testing import chaos


class StaleEpochError(RuntimeError):
    """A fenced write: the acting epoch is older than the store's
    authoritative leader epoch (or than an already-published record's).
    The caller is a zombie ex-leader and must stand down."""


class LeaseLostError(RuntimeError):
    """The holder's lease is no longer its own (superseded, corrupt, or a
    renewal failed to land durably).  Raised on the renew path so the
    ex-leader demotes loudly instead of acting on stale authority."""


@dataclass(frozen=True)
class Lease:
    """One acquired leadership grant.  ``deadline`` is wall-clock unix
    seconds — cross-process comparable, unlike a monotonic clock."""

    epoch: int
    holder: str
    deadline: float


def _wall() -> float:
    return time.time()


def _crc_payload(record: Dict[str, Any]) -> bytes:
    return json.dumps(record, sort_keys=True).encode()


class FileHaStore:
    """File-backed HA services: lease + job registry + checkpoint pointer.

    Single-host scope (matching ``ProcessCluster``'s deployment model):
    atomic renames give record-level atomicity across processes; the
    in-process lock serializes same-process contenders (the scenario
    harness runs leader and standby in one process)."""

    LEASE_FILE = "lease.json"
    EPOCH_FILE = "epoch.json"

    def __init__(self, directory: str,
                 clock: Callable[[], float] = _wall):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._clock = clock
        self._lock = threading.RLock()

    # -- low-level records ---------------------------------------------------
    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def _write_record(self, name: str, record: Dict[str, Any],
                      chaos_point: Optional[str] = None) -> None:
        payload = _crc_payload(record)
        keep = len(payload)
        if chaos_point is not None:
            # fault point (``ha.lease``): a TruncatedWrite schedule tears
            # the published record short — the CRC gate below turns that
            # into "record absent", and renew's verify-back into a loud
            # LeaseLostError demotion
            keep = chaos.truncated(chaos_point, len(payload))
        doc = json.dumps({"record": json.loads(payload.decode()),
                          "crc32": zlib.crc32(payload),
                          "size": len(payload)})
        data = doc.encode()[:max(0, len(doc) - (len(payload) - keep))] \
            if keep < len(payload) else doc.encode()
        tmp = self._path("." + name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._path(name))

    def _read_record(self, name: str) -> Optional[Dict[str, Any]]:
        """The verified record, or None when missing/torn/corrupt (a
        broken record is indistinguishable from no record — callers act
        on the intact epoch counter instead)."""
        try:
            with open(self._path(name), "rb") as f:
                doc = json.loads(f.read().decode())
        except (OSError, ValueError, UnicodeDecodeError):
            return None
        record = doc.get("record")
        if not isinstance(record, dict):
            return None
        payload = _crc_payload(record)
        if doc.get("size") != len(payload) or \
                doc.get("crc32") != zlib.crc32(payload):
            return None
        return record

    # -- leader epoch --------------------------------------------------------
    def current_epoch(self) -> int:
        """The authoritative leader epoch: max of the monotone counter
        and any intact lease record (either alone survives a torn write
        of the other)."""
        with self._lock:
            counter = self._read_record(self.EPOCH_FILE) or {}
            lease = self._read_record(self.LEASE_FILE) or {}
            return max(int(counter.get("epoch", 0)),
                       int(lease.get("epoch", 0)))

    # -- lease lifecycle -----------------------------------------------------
    def read_lease(self) -> Optional[Lease]:
        rec = self._read_record(self.LEASE_FILE)
        if rec is None:
            return None
        try:
            return Lease(int(rec["epoch"]), str(rec["holder"]),
                         float(rec["deadline"]))
        except (KeyError, TypeError, ValueError):
            return None

    def try_acquire(self, holder: str, ttl_s: float) -> Optional[Lease]:
        """Acquire leadership at ``current_epoch + 1`` — None while a
        live foreign lease holds.  The epoch counter publishes BEFORE the
        lease, so a crash between the two wastes an epoch number but can
        never mint a duplicate."""
        with self._lock:
            now = self._clock()
            live = self.read_lease()
            if live is not None and live.holder != holder \
                    and live.deadline > now:
                return None
            epoch = self.current_epoch() + 1
            self._write_record(self.EPOCH_FILE, {"epoch": epoch})
            lease = Lease(epoch, holder, now + ttl_s)
            self._write_record(self.LEASE_FILE, {
                "epoch": lease.epoch, "holder": lease.holder,
                "deadline": lease.deadline})
            return lease

    def acquire(self, holder: str, ttl_s: float,
                timeout_s: float = 30.0,
                poll_s: float = 0.05) -> Lease:
        """Poll :meth:`try_acquire` until granted (standby takeover waits
        out the incumbent's TTL) or ``timeout_s`` elapses."""
        deadline = self._clock() + timeout_s
        while True:
            lease = self.try_acquire(holder, ttl_s)
            if lease is not None:
                return lease
            if self._clock() >= deadline:
                raise TimeoutError(
                    f"lease not acquired within {timeout_s}s "
                    f"(held by {self.read_lease()})")
            time.sleep(poll_s)

    def renew(self, lease: Lease, ttl_s: float) -> Lease:
        """Extend the holder's own lease.  Verifies ownership BEFORE the
        write and verifies the write back AFTER it — a superseded epoch,
        a foreign holder, or a torn renewal (the ``ha.lease`` fault
        point) all raise :class:`LeaseLostError`: loud demotion, never
        silent dual leadership."""
        with self._lock:
            on_disk = self.read_lease()
            if on_disk is None or on_disk.epoch != lease.epoch \
                    or on_disk.holder != lease.holder:
                raise LeaseLostError(
                    f"lease (epoch {lease.epoch}, holder {lease.holder!r}) "
                    f"superseded or gone: on disk {on_disk}")
            if self.current_epoch() > lease.epoch:
                raise LeaseLostError(
                    f"epoch {lease.epoch} fenced: store is at "
                    f"{self.current_epoch()}")
            renewed = replace(lease, deadline=self._clock() + ttl_s)
            self._write_record(self.LEASE_FILE, {
                "epoch": renewed.epoch, "holder": renewed.holder,
                "deadline": renewed.deadline}, chaos_point="ha.lease")
            back = self.read_lease()
            if back is None or back.epoch != renewed.epoch \
                    or back.holder != renewed.holder \
                    or back.deadline != renewed.deadline:
                raise LeaseLostError(
                    f"lease renewal did not land durably (read back "
                    f"{back}); demoting")
            return renewed

    def is_current(self, lease: Lease) -> bool:
        on_disk = self.read_lease()
        return on_disk is not None and on_disk.epoch == lease.epoch \
            and on_disk.holder == lease.holder \
            and self.current_epoch() <= lease.epoch

    def release(self, lease: Lease) -> None:
        """Voluntary stand-down: drop the lease file iff it is still this
        holder's (a successor's lease is never touched)."""
        with self._lock:
            on_disk = self.read_lease()
            if on_disk is not None and on_disk.epoch == lease.epoch \
                    and on_disk.holder == lease.holder:
                try:
                    os.remove(self._path(self.LEASE_FILE))
                except OSError:
                    pass

    # -- epoch fence ---------------------------------------------------------
    def check_epoch(self, epoch: int) -> None:
        """Raise :class:`StaleEpochError` when ``epoch`` is older than
        the store's authoritative leader epoch."""
        current = self.current_epoch()
        if epoch < current:
            raise StaleEpochError(
                f"epoch {epoch} is fenced: leader epoch is {current}")

    # -- job registry --------------------------------------------------------
    def _job_meta(self, job_id: str) -> str:
        return f"job-{job_id}.json"

    def _job_blob(self, job_id: str) -> str:
        return self._path(f"job-{job_id}.pkl")

    def register_job(self, job_id: str, payload: Any, epoch: int) -> None:
        """Persist a job's plan payload under the acting epoch.  The
        pickle publishes first, its CRC'd meta record LAST — a job entry
        is visible iff both landed."""
        with self._lock:
            self.check_epoch(epoch)
            existing = self._read_record(self._job_meta(job_id))
            if existing is not None and int(existing.get("epoch", 0)) > epoch:
                raise StaleEpochError(
                    f"job {job_id!r} already registered at epoch "
                    f"{existing['epoch']} > {epoch}")
            blob = pickle.dumps(payload, protocol=4)
            tmp = self._job_blob(job_id) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._job_blob(job_id))
            self._write_record(self._job_meta(job_id), {
                "job_id": job_id, "epoch": epoch,
                "crc32": zlib.crc32(blob), "size": len(blob)})

    def load_job(self, job_id: str) -> Any:
        """The registered payload, CRC-verified; raises ``KeyError`` for
        an unknown/torn entry (the meta record is written last, so a
        half-written registration reads as absent)."""
        with self._lock:
            meta = self._read_record(self._job_meta(job_id))
            if meta is None:
                raise KeyError(f"job {job_id!r} not registered")
            try:
                with open(self._job_blob(job_id), "rb") as f:
                    blob = f.read()
            except OSError:
                raise KeyError(f"job {job_id!r}: payload missing")
            if len(blob) != meta.get("size") or \
                    zlib.crc32(blob) != meta.get("crc32"):
                raise KeyError(f"job {job_id!r}: payload corrupt "
                               f"(size/CRC mismatch)")
            return pickle.loads(blob)

    def job_ids(self) -> List[str]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("job-") and name.endswith(".json"):
                meta = self._read_record(name)
                if meta is not None:
                    out.append(str(meta["job_id"]))
        return sorted(out)

    # -- completed-checkpoint pointer ----------------------------------------
    def _ckpt_file(self, job_id: str) -> str:
        return f"ckpt-{job_id}.json"

    def set_completed_checkpoint(self, job_id: str, checkpoint_id: int,
                                 epoch: int) -> None:
        """THE zombie fence: advance the job's completed-checkpoint
        pointer under ``epoch``.  Re-verifies the store's leader epoch at
        write time — a zombie ex-leader (whose own workers still share
        its epoch and happily ack) fails HERE, before any notify-complete
        fans out, so its checkpoint never completes and its 2PC epochs
        never commit.  The pointer itself is monotone in (epoch,
        checkpoint_id): a stale racer can never roll it backwards."""
        with self._lock:
            self.check_epoch(epoch)
            prev = self._read_record(self._ckpt_file(job_id))
            if prev is not None:
                if int(prev.get("epoch", 0)) > epoch:
                    raise StaleEpochError(
                        f"job {job_id!r} pointer already at epoch "
                        f"{prev['epoch']} > {epoch}")
                if int(prev.get("epoch", 0)) == epoch and \
                        int(prev.get("checkpoint_id", -1)) > checkpoint_id:
                    return          # same leader, older cut: keep newest
            self._write_record(self._ckpt_file(job_id), {
                "job_id": job_id, "checkpoint_id": int(checkpoint_id),
                "epoch": int(epoch)})

    def completed_checkpoint(self, job_id: str) -> Optional[Dict[str, int]]:
        rec = self._read_record(self._ckpt_file(job_id))
        if rec is None:
            return None
        return {"checkpoint_id": int(rec["checkpoint_id"]),
                "epoch": int(rec["epoch"])}


class LeaseRenewer:
    """Background renewal loop for a held lease (``ttl / 3`` cadence by
    default).  A failed renewal — superseded, torn write, store gone —
    invokes ``on_lost`` exactly once and stops: the loud-demotion seam
    both coordinators hang their standing-down logic on."""

    def __init__(self, store: FileHaStore, lease: Lease, ttl_s: float,
                 interval_s: Optional[float] = None,
                 on_lost: Optional[Callable[[Exception], None]] = None):
        self.store = store
        self.ttl_s = ttl_s
        self.interval_s = interval_s if interval_s is not None else ttl_s / 3.0
        self.on_lost = on_lost
        self._lease = lease
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.lost: Optional[Exception] = None
        self._thread = threading.Thread(target=self._loop,
                                        name="ha-lease-renew", daemon=True)

    @property
    def lease(self) -> Lease:
        with self._lock:
            return self._lease

    def start(self) -> "LeaseRenewer":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                renewed = self.store.renew(self.lease, self.ttl_s)
                with self._lock:
                    self._lease = renewed
            except Exception as e:  # noqa: BLE001 — any renew failure demotes
                self.lost = e
                if self.on_lost is not None:
                    try:
                        self.on_lost(e)
                    except Exception:  # noqa: BLE001
                        pass
                return

    def stop(self) -> None:
        """Stop renewing WITHOUT releasing the lease (a killed
        coordinator stops exactly like this: its lease times out and a
        standby takes over at epoch + 1)."""
        self._stop.set()

    def join(self, timeout: float = 5.0) -> None:
        self._thread.join(timeout)


def resolve_restore(store: Optional[FileHaStore], job_id: str,
                    checkpoint_storage: Any,
                    log: Optional[Callable[[str], None]] = None
                    ) -> Tuple[Optional[Dict[str, Any]], str]:
    """New-leader restore resolution: the HA completed-checkpoint pointer
    is TRUTH; the storage directory scan (``load_latest``) is a logged
    fallback only — the split-brain fix for a stale leader's concurrent
    retention pass racing the scan.  Increment chains resolve inside
    ``checkpoint_storage.load``.  Returns ``(snapshot_or_None, source)``
    with source one of ``"ha-pointer"``, ``"scan-fallback"``, ``"none"``."""
    say = log if log is not None else (lambda msg: None)
    pointer = store.completed_checkpoint(job_id) if store is not None else None
    if pointer is not None and checkpoint_storage is not None:
        cid = pointer["checkpoint_id"]
        try:
            return checkpoint_storage.load(cid), "ha-pointer"
        except Exception as e:  # noqa: BLE001 — corrupt/missing cut
            say(f"HA pointer checkpoint {cid} unloadable "
                f"({type(e).__name__}: {e}); falling back to directory scan")
    if checkpoint_storage is not None:
        try:
            snap = checkpoint_storage.load_latest()
        except Exception as e:  # noqa: BLE001
            say(f"load_latest scan failed ({type(e).__name__}: {e})")
            snap = None
        if snap is not None:
            if pointer is not None:
                say("restored from directory scan despite an HA pointer "
                    "(pointer cut unloadable)")
            return snap, "scan-fallback"
    return None, "none"


def job_id_for(job_ref: str) -> str:
    """A filesystem-safe HA job id from a ``module:function`` job ref."""
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in job_ref)
