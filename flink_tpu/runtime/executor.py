"""Local pipeline executor — the MiniCluster/mailbox analog.

Runs an ``ExecutionPlan`` in one process: every vertex is a single-writer
operator instance (the structural race-avoidance of the reference's mailbox
model, ``MailboxProcessor.java:66``); sources are drained split-by-split in
round-robin (pipeline parallelism across vertices comes from the dataflow
itself); watermarks from multiple inputs are aligned with a per-vertex
min-valve (``StatusWatermarkValve.java:38``); bounded input ends with
MAX_WATERMARK + ``end_input`` cascade in topological order, mirroring the
reference's end-of-input flushing.

Elements are delivered depth-first: an operator's emissions reach downstream
*before* the element that caused them is forwarded — the same ordering the
reference gets from in-band control flow, and the property checkpoint barrier
alignment relies on.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, MAX_WATERMARK, CheckpointBarrier,
                                  RecordBatch, StreamElement, StreamStatus,
                                  TaggedBatch, Watermark)
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.graph.stream_graph import ExecutionPlan, PlanVertex
from flink_tpu.operators.base import StreamOperator


class WatermarkValve:
    """Min-across-inputs watermark alignment (``StatusWatermarkValve``).

    Idleness (``StreamStatus``, ``StatusWatermarkValve.java`` markIdle
    semantics): an IDLE input is excluded from the min, so one stalled
    source cannot freeze event time for the whole pipeline; when every
    input is idle no watermark advances (nothing can be proven)."""

    def __init__(self, num_inputs: int):
        self.per_input = [LONG_MIN] * max(1, num_inputs)
        self.idle = [False] * max(1, num_inputs)
        self.current = LONG_MIN
        self._last_combined = False  # last combined status forwarded

    def _advance(self) -> Optional[int]:
        active = [wm for wm, idl in zip(self.per_input, self.idle)
                  if not idl]
        if not active:
            return None
        new_min = min(active)
        if new_min > self.current:
            self.current = new_min
            return new_min
        return None

    def record_activity(self, input_index: int) -> Optional[bool]:
        """Any element on an idle channel reactivates it; returns the new
        COMBINED status iff it changed (the caller forwards it downstream —
        the reference forwards ACTIVE on any reactivating element)."""
        if not self.idle[input_index]:
            return None
        self.idle[input_index] = False
        combined = all(self.idle)
        if combined != self._last_combined:
            self._last_combined = combined
            return combined
        return None

    def input_watermark(self, input_index: int, ts: int) -> Optional[int]:
        # a watermark is proof of activity; idleness-aware callers invoke
        # record_activity FIRST to forward the transition — this fallback
        # keeps the combined memory consistent for everyone else
        if self.idle[input_index]:
            self.idle[input_index] = False
            self._last_combined = all(self.idle)
        if ts > self.per_input[input_index]:
            self.per_input[input_index] = ts
        return self._advance()

    def input_status(self, input_index: int, idle: bool) -> Optional[int]:
        """Mark a channel idle/active; going idle can UNBLOCK the min."""
        self.idle[input_index] = idle
        return self._advance()

    def status_update(self, input_index: int,
                      idle: bool) -> Tuple[Optional[int], bool, bool]:
        """One StreamStatus arrival: returns (advanced watermark or None,
        combined idle status, whether the combined status CHANGED — the
        reference forwards status only on change)."""
        adv = self.input_status(input_index, idle)
        combined = all(self.idle)
        changed = combined != self._last_combined
        self._last_combined = combined
        return adv, combined, changed

    # -- snapshot (idle flags must survive recovery: a restored subtask
    # will never be re-sent an idle channel's status) --------------------
    def snapshot(self) -> Dict[str, Any]:
        return {"per_input": list(self.per_input), "idle": list(self.idle),
                "current": self.current, "combined": self._last_combined}

    def restore(self, snap) -> None:
        if isinstance(snap, dict):
            self.per_input = list(snap["per_input"])
            self.idle = list(snap.get("idle", [False] * len(self.per_input)))
            self.current = snap.get("current", LONG_MIN)
            self._last_combined = snap.get("combined", False)
        else:  # legacy list-only snapshots
            self.per_input = list(snap)
            self.idle = [False] * len(self.per_input)
            self.current = min(self.per_input)
        active = [wm for wm, idl in zip(self.per_input, self.idle)
                  if not idl]
        if active:
            self.current = max(self.current, min(active))


@dataclass
class RunningVertex:
    vertex: PlanVertex
    operator: StreamOperator
    valve: WatermarkValve
    # (target RunningVertex, input index at target)
    targets: List[Tuple["RunningVertex", int]] = field(default_factory=list)
    ended_inputs: int = 0
    num_inputs: int = 0
    io: Any = None  # OperatorIOMetrics


@dataclass
class JobExecutionResult:
    job_name: str
    net_runtime_ms: float
    records_emitted: int = 0
    accumulators: Dict[str, float] = field(default_factory=dict)

    def get_accumulator_result(self, name: str) -> float:
        """``JobExecutionResult.getAccumulatorResult`` analog."""
        return self.accumulators[name]


class LocalExecutor:
    """Single-process executor (reference analog: ``LocalExecutor`` +
    ``MiniCluster`` running a job with real operator semantics in one JVM)."""

    def __init__(self, checkpoint_interval_ms: int = 0,
                 checkpoint_storage=None,
                 listeners: Optional[List[Callable[[str, Any], None]]] = None,
                 max_records: Optional[int] = None,
                 max_wall_ms: Optional[int] = None,
                 metric_registry=None, config=None):
        self.checkpoint_interval_ms = checkpoint_interval_ms
        self.checkpoint_storage = checkpoint_storage
        self.listeners = listeners or []
        self.max_records = max_records      # unbounded-source record budget
        self.max_wall_ms = max_wall_ms      # unbounded-source wall budget
        self.metric_registry = metric_registry
        self.config = config
        self._cancelled = False
        self._records = 0

    def cancel(self) -> None:
        """Cooperative cancellation (``JobMaster.cancel`` analog): the source
        loop stops at the next batch boundary and flushes bounded-end path."""
        self._cancelled = True

    # ------------------------------------------------------------- wiring
    def _build(self, plan: ExecutionPlan,
               restore: Optional[Dict[str, Any]] = None) -> Dict[int, RunningVertex]:
        from flink_tpu.metrics import (MetricRegistry, OperatorIOMetrics,
                                       task_metric_group)

        if self.metric_registry is None:
            self.metric_registry = MetricRegistry()
        # local execution = one slot: every operator shares this slot's
        # managed-memory accountant (budgeted components reserve from it)
        from flink_tpu.runtime.memory import memory_manager_for
        slot_memory = memory_manager_for(self.config)
        running: Dict[int, RunningVertex] = {}
        for v in plan.vertices:
            op = v.build_operator()
            group = task_metric_group(self.metric_registry, plan.job_name,
                                      v.name, 0)
            ctx = RuntimeContext(task_name=v.name, subtask_index=0, parallelism=1,
                                 max_parallelism=v.max_parallelism,
                                 metrics=group, memory_manager=slot_memory)
            op.open(ctx)
            if restore and v.uid in restore:
                op.restore_state(restore[v.uid])
            rv = RunningVertex(v, op, WatermarkValve(0))
            rv.io = OperatorIOMetrics(group)
            running[v.id] = rv
        # wire edges by the target's declared logical input port
        in_counts: Dict[int, int] = {v.id: 0 for v in plan.vertices}
        for v in plan.vertices:
            for e in v.out_edges:
                tgt = running[e.target_id]
                in_counts[e.target_id] += 1
                running[v.id].targets.append((tgt, e.input_index))
        for v in plan.vertices:
            rv = running[v.id]
            rv.num_inputs = max(1, in_counts[v.id])
            rv.valve = WatermarkValve(rv.num_inputs)
        return running

    # ----------------------------------------------------------- delivery
    def _route(self, rv: RunningVertex, elements: List[StreamElement]) -> None:
        for el in elements:
            if isinstance(el, RecordBatch):
                self._records += len(el)
                if rv.io is not None:
                    rv.io.records_out.inc(len(el))
            for tgt, idx in rv.targets:
                self._deliver(tgt, idx, el)

    def _deliver(self, rv: RunningVertex, input_index: int,
                 el: StreamElement) -> None:
        op = rv.operator
        if isinstance(el, RecordBatch):
            if len(el):
                st = rv.valve.record_activity(input_index)
                if st is not None:
                    self._route(rv, [StreamStatus(st)])
                if rv.io is not None:
                    rv.io.records_in.inc(len(el))
                if getattr(op, "is_two_input", False):
                    self._route(rv, op.process_batch2(el, input_index))
                else:
                    self._route(rv, op.process_batch(el))
        elif isinstance(el, Watermark):
            st = rv.valve.record_activity(input_index)
            if st is not None:
                self._route(rv, [StreamStatus(st)])
            advanced = rv.valve.input_watermark(input_index, el.timestamp)
            if advanced is not None:
                if rv.io is not None:
                    rv.io.watermark.set(advanced)
                wm = Watermark(advanced)
                self._route(rv, op.process_watermark(wm))
                if op.forwards_watermarks:
                    self._route(rv, [wm])
        elif isinstance(el, CheckpointBarrier):
            # single-input-per-vertex local mode: barrier alignment is trivial;
            # snapshot on first arrival, forward once all inputs delivered it.
            self._on_barrier(rv, input_index, el)
        elif isinstance(el, TaggedBatch):
            # side-output routing: only the matching SideOutputOperator
            # consumes it; every other vertex drops it
            if getattr(op, "accepts_tag", None) == el.tag:
                self._route(rv, op.process_tagged(el.batch))
        elif isinstance(el, StreamStatus):
            # idleness: excluding the idle channel can itself advance the
            # min watermark (StatusWatermarkValve.markIdle)
            advanced, combined, changed = rv.valve.status_update(
                input_index, el.idle)
            if advanced is not None:
                wm = Watermark(advanced)
                self._route(rv, op.process_watermark(wm))
                if op.forwards_watermarks:
                    self._route(rv, [wm])
            if changed:  # vertex's COMBINED status, forwarded on change
                self._route(rv, [StreamStatus(combined)])
        else:
            self._route(rv, [el])

    # barrier handling is installed by the checkpointing runtime (see
    # flink_tpu/runtime/checkpoint/coordinator.py) — default: pass through.
    def _on_barrier(self, rv: RunningVertex, input_index: int,
                    barrier: CheckpointBarrier) -> None:
        self._route(rv, [barrier])


    @staticmethod
    def _close_all(plan, running) -> None:
        """Close every operator even when one close() raises (a pipelined
        operator surfaces parked hot-stage errors at close): remaining
        operators must still release their threads/spill files/native
        handles.  The FIRST error wins and re-raises after the sweep."""
        first: Optional[BaseException] = None
        for v in plan.vertices:
            try:
                running[v.id].operator.close()
            except BaseException as e:  # noqa: BLE001 — collected, re-raised
                if first is None:
                    first = e
        if first is not None:
            raise first


    # ---------------------------------------------------------------- run
    def execute(self, plan: ExecutionPlan,
                restore: Optional[Dict[str, Any]] = None,
                drain: bool = True) -> JobExecutionResult:
        t0 = time.monotonic()
        running = self._build(plan, restore)
        self.running = running
        self.plan = plan
        source_vertices = [running[v.id] for v in plan.sources]

        # split readers, round-robin (SourceReaderBase poll loop analog);
        # stateful sources (open_split + reader.position) resume from the
        # checkpointed position — FLIP-27 SourceReader.snapshotState analog
        restored_positions = (restore or {}).get("__sources__", {})
        readers: List[Tuple[RunningVertex, Any]] = []
        self._split_readers: List[Tuple[str, str, Any]] = []  # (uid, split_id, reader)
        for rv in source_vertices:
            src = rv.vertex.chain[0].source
            positions = restored_positions.get(rv.vertex.uid, {})
            for split in src.create_splits(rv.vertex.parallelism):
                from flink_tpu.connectors.sources import split_id_of
                split_id = split_id_of(split)
                if hasattr(src, "open_split"):
                    reader = src.open_split(split, positions.get(split_id))
                else:
                    reader = split.read()
                readers.append((rv, reader))
                self._split_readers.append((rv.vertex.uid, split_id, reader))

        # checkpoint cadence through the injectable clock seam, clamped
        # monotone: a chaos ClockSkew backward step must not stall the
        # periodic trigger (nor a forward jump double-fire after recovery)
        from flink_tpu.utils.clock import MonotoneElapsed
        ckpt_timer = MonotoneElapsed()
        ckpt_id = 0
        while readers and not self._cancelled:
            if self.max_records is not None and self._records >= self.max_records:
                break
            if (self.max_wall_ms is not None
                    and (time.monotonic() - t0) * 1000 >= self.max_wall_ms):
                break
            self._advance_processing_time(running)
            still: List[Tuple[RunningVertex, Any]] = []
            for rv, it in readers:
                try:
                    el = next(it)
                except StopIteration:
                    # source exhausted: this vertex goes quiet until the
                    # bounded-end cascade — flush pipelined operators now
                    # so their in-flight hot stages don't idle undispatched
                    flush = getattr(rv.operator, "flush_pipeline", None)
                    if flush is not None:
                        self._route(rv, flush())
                    continue
                # a source vertex's chain may include chained operators:
                # feed the element through its own operator first
                if isinstance(el, RecordBatch):
                    self._route(rv, rv.operator.process_batch(el))
                elif isinstance(el, Watermark):
                    adv = rv.valve.input_watermark(0, el.timestamp)
                    if adv is not None:
                        wm = Watermark(adv)
                        self._route(rv, rv.operator.process_watermark(wm))
                        if rv.operator.forwards_watermarks:
                            self._route(rv, [wm])
                else:
                    self._route(rv, [el])
                still.append((rv, it))
            readers = still
            if (self.checkpoint_interval_ms and self.checkpoint_storage and
                    ckpt_timer.ms() >= self.checkpoint_interval_ms):
                ckpt_id += 1
                self.trigger_checkpoint(ckpt_id)
                ckpt_timer = MonotoneElapsed()

        # bounded end: MAX_WATERMARK from sources, then end_input in topo
        # order.  drain=False (stop-with-savepoint --no-drain analog) keeps
        # in-progress windows unfired so a restore continues them.
        if not drain:
            self._close_all(plan, running)
            return JobExecutionResult(plan.job_name,
                                      (time.monotonic() - t0) * 1000.0,
                                      self._records,
                                      self._collect_accumulators(running))
        for rv in source_vertices:
            adv = rv.valve.input_watermark(0, MAX_WATERMARK)
            if adv is not None:
                wm = Watermark(adv)
                self._route(rv, rv.operator.process_watermark(wm))
                self._route(rv, [wm])
        for v in plan.vertices:
            rv = running[v.id]
            self._route(rv, rv.operator.end_input())
        self._close_all(plan, running)
        return JobExecutionResult(plan.job_name,
                                  (time.monotonic() - t0) * 1000.0,
                                  self._records,
                                  self._collect_accumulators(running))

    def _collect_accumulators(self, running) -> Dict[str, float]:
        """Merge per-subtask user counters (reference: accumulators shipped
        with the final task state and merged on the JobMaster)."""
        out: Dict[str, float] = {}
        for rv in running.values():
            ctx = getattr(rv.operator, "ctx", None)
            for name, v in (ctx.accumulator_results() if ctx else {}).items():
                out[name] = out.get(name, 0.0) + v
        return out

    def _advance_processing_time(self, running: Dict[int, RunningVertex]) -> None:
        """Fire due processing-time timers on every vertex (the
        ``ProcessingTimeService`` tick; local mode polls wall clock between
        source rounds — same granularity as the mailbox checking its mail).

        Reads through the injectable clock seam (``utils/clock.py``) and
        clamps MONOTONE at this boundary: a backward-stepped wall clock
        (chaos ``ClockSkew``, NTP) must never rewind processing time —
        the reference's ``ProcessingTimeService`` is monotone by contract,
        so timers can neither re-fire nor fire early on a step back."""
        from flink_tpu.utils import clock
        now_ms = max(clock.now_ms(), getattr(self, "_proc_time_ms", 0))
        self._proc_time_ms = now_ms
        for rv in running.values():
            out = rv.operator.on_processing_time(now_ms)
            if out:
                self._route(rv, out)

    # ------------------------------------------------------- checkpointing
    def trigger_checkpoint(self, checkpoint_id: int) -> Dict[str, Any]:
        """Synchronous aligned checkpoint of all vertices (local mode: the
        depth-first delivery order means no in-flight data exists between
        vertices at this point — alignment is implicit)."""
        from flink_tpu.operators.base import snapshot_scope

        # pre-barrier drain: async emissions reach downstream BEFORE the
        # snapshot (AbstractPythonFunctionOperator.prepareSnapshotPreBarrier
        # analog) — topo order so drained elements flow through the plan
        plan = getattr(self, "plan", None)
        for v in plan.vertices if plan is not None else []:
            rv = self.running.get(v.id)
            if rv is None:
                continue
            prep = getattr(rv.operator, "prepare_snapshot_pre_barrier",
                           None)
            drained = prep() if prep is not None else []
            if drained:
                self._route(rv, drained)
        with snapshot_scope(checkpoint_id):
            snapshot = {rv.vertex.uid: rv.operator.snapshot_state()
                        for rv in self.running.values()}
        sources: Dict[str, Dict[str, Any]] = {}
        for uid, split_id, reader in getattr(self, "_split_readers", []):
            pos = getattr(reader, "position", None)
            if pos is not None:
                sources.setdefault(uid, {})[split_id] = pos
        if sources:
            snapshot["__sources__"] = sources
        if self.checkpoint_storage is not None:
            self.checkpoint_storage.store(checkpoint_id, snapshot)
            # checkpoint durable -> commit side effects (CheckpointListener.
            # notifyCheckpointComplete: two-phase-commit sinks publish here)
            for rv in self.running.values():
                rv.operator.notify_checkpoint_complete(checkpoint_id)
        return snapshot
