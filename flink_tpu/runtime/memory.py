"""Per-slot managed memory accounting (``MemoryManager.java`` analog).

The reference gives every task slot a fixed budget of *managed memory*
(``taskmanager.memory.managed.size`` split over the slots); memory-hungry
operators — sort buffers, hash tables, the RocksDB tier, python UDF
workers — RESERVE fractions of it up front and fail fast (or spill
earlier) instead of OOM-ing the process mid-job
(``MemoryManager.java:1``, ``computeMemorySize``, FLIP-49/53 weights).

Same role here: a :class:`MemoryManager` per slot, handed to operators
via ``RuntimeContext.memory_manager``.  Budgeted components consult it:
the spill-tier keyed backend reserves its resident-byte budget, external
sort/shuffle buffers can size themselves from
:meth:`MemoryManager.compute_operator_share`, and an over-committed slot
raises :class:`MemoryReservationError` at reserve time — deployment
failure surfaces at schedule time, not as a mid-job OOM.

Reservations are plain accounting (Python/numpy own the actual bytes —
there is no Unsafe to wrap); what the manager provides is the CONTRACT:
a slot's operators cannot collectively claim more than the slot's share.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class MemoryReservationError(MemoryError):
    """A reservation exceeded the slot's remaining managed memory."""


class MemoryReservation:
    """One owner's claim on a slice of a slot's managed memory."""

    __slots__ = ("manager", "owner", "nbytes", "_released")

    def __init__(self, manager: "MemoryManager", owner: str, nbytes: int):
        self.manager = manager
        self.owner = owner
        self.nbytes = int(nbytes)
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self.manager._release(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class MemoryManager:
    """Byte-accounted managed memory for ONE slot."""

    def __init__(self, total_bytes: int):
        if total_bytes < 0:
            raise ValueError("total_bytes must be >= 0")
        self.total = int(total_bytes)
        self._used = 0
        self._by_owner: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- accounting ---------------------------------------------------------
    def reserve(self, owner: str, nbytes: int) -> MemoryReservation:
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("nbytes must be >= 0")
        with self._lock:
            if self._used + nbytes > self.total:
                raise MemoryReservationError(
                    f"{owner!r} requested {nbytes} managed bytes; only "
                    f"{self.total - self._used} of {self.total} remain "
                    f"(held: {dict(self._by_owner)})")
            self._used += nbytes
            self._by_owner[owner] = self._by_owner.get(owner, 0) + nbytes
        return MemoryReservation(self, owner, nbytes)

    def _release(self, res: MemoryReservation) -> None:
        with self._lock:
            # clamp to the owner's live bytes: a reservation released after
            # release_all(owner) must not double-decrement (negative _used
            # would silently void the over-commit invariant)
            freed = min(res.nbytes, self._by_owner.get(res.owner, 0))
            self._used -= freed
            left = self._by_owner.get(res.owner, 0) - freed
            if left > 0:
                self._by_owner[res.owner] = left
            else:
                self._by_owner.pop(res.owner, None)

    def release_all(self, owner: str) -> int:
        """Drop every reservation of ``owner`` (task teardown); returns the
        bytes freed."""
        with self._lock:
            freed = self._by_owner.pop(owner, 0)
            self._used -= freed
            return freed

    def available(self) -> int:
        with self._lock:
            return self.total - self._used

    def used(self) -> int:
        with self._lock:
            return self._used

    def usage_by_owner(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._by_owner)

    # -- fraction splitting (computeMemorySize / FLIP-53 weights) -----------
    def compute_operator_share(self, weights: Dict[str, float],
                               owner: str) -> int:
        """``owner``'s byte share of this slot's TOTAL managed memory when
        the slot's operators declare relative ``weights`` (the reference
        splits a slot's managed memory by declared use-case weights rather
        than first-come-first-served)."""
        total_w = sum(w for w in weights.values() if w > 0)
        if total_w <= 0 or weights.get(owner, 0) <= 0:
            return 0
        return int(self.total * weights[owner] / total_w)


def slot_memory_managers(total_bytes: int,
                         num_slots: int) -> List[MemoryManager]:
    """Split a task executor's managed memory evenly over its slots
    (``taskmanager.memory.managed.size`` / ``numberOfTaskSlots``)."""
    if num_slots <= 0:
        raise ValueError("num_slots must be > 0")
    share = int(total_bytes) // num_slots
    return [MemoryManager(share) for _ in range(num_slots)]


def memory_manager_for(config=None,
                       num_slots: Optional[int] = None) -> MemoryManager:
    """One slot's manager from configuration (None config = defaults;
    ``num_slots`` None reads ``taskmanager.numberOfTaskSlots``)."""
    from flink_tpu.config.config_option import Configuration
    from flink_tpu.config.options import TaskManagerOptions

    cfg = config if config is not None else Configuration()
    total = cfg.get(TaskManagerOptions.MANAGED_MEMORY_SIZE)
    if num_slots is None:
        num_slots = cfg.get(TaskManagerOptions.NUM_TASK_SLOTS)
    return MemoryManager(int(total) // max(1, int(num_slots)))


class SlotMemoryPool:
    """A task executor's fixed slot managers, assigned round-robin — the
    aggregate managed memory of every subtask in the process is bounded by
    ``taskmanager.memory.managed.size``, however many subtasks launch (or
    relaunch) over the executor's lifetime."""

    def __init__(self, config=None):
        from flink_tpu.config.config_option import Configuration
        from flink_tpu.config.options import TaskManagerOptions

        cfg = config if config is not None else Configuration()
        n = max(1, int(cfg.get(TaskManagerOptions.NUM_TASK_SLOTS)))
        total = int(cfg.get(TaskManagerOptions.MANAGED_MEMORY_SIZE))
        self.slots = slot_memory_managers(total, n)
        self._next = 0
        self._lock = threading.Lock()

    def assign(self) -> MemoryManager:
        with self._lock:
            mm = self.slots[self._next % len(self.slots)]
            self._next += 1
            return mm
