"""Keyed timer service, batched.

Analog of ``InternalTimerServiceImpl.java:43``: per-key event-time and
processing-time timers with (key, namespace, timestamp) identity, fired in
timestamp order when the watermark / processing clock advances.  Re-designed
batched: registrations arrive as **arrays of (slot, namespace, ts)** per
micro-batch (one numpy append + one dedup at fire time instead of a
key-grouped priority-queue poll per timer), which is the only shape the
batched operators produce anyway.

Fire order matches the reference: ascending timestamp, and each fired batch
is handed back as arrays so the operator can run its ``on_timer`` logic
vectorized over every key firing at the same watermark advance.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import LONG_MIN


class _TimerTable:
    """Append-only (slot, namespace, ts) triples with lazy dedup/compaction."""

    def __init__(self):
        self._slots = np.zeros(0, np.int64)
        self._ns = np.zeros(0, np.int64)
        self._ts = np.zeros(0, np.int64)

    def __len__(self) -> int:
        return self._slots.size

    def register(self, slots, timestamps, namespaces=None) -> None:
        slots = np.asarray(slots, np.int64)
        if slots.size == 0:
            return
        ts = np.asarray(timestamps, np.int64)
        ns = (np.zeros(slots.size, np.int64) if namespaces is None
              else np.asarray(namespaces, np.int64))
        self._slots = np.concatenate([self._slots, slots])
        self._ns = np.concatenate([self._ns, ns])
        self._ts = np.concatenate([self._ts, ts])

    def delete(self, slots, timestamps, namespaces=None) -> None:
        """``deleteEventTimeTimer`` analog: drop matching (slot, ns, ts)."""
        if self._slots.size == 0:
            return
        slots = np.asarray(slots, np.int64)
        ts = np.asarray(timestamps, np.int64)
        ns = (np.zeros(slots.size, np.int64) if namespaces is None
              else np.asarray(namespaces, np.int64))
        # structured view for row-wise membership
        mine = self._pack()
        kill = _pack3(slots, ns, ts)
        keep = ~np.isin(mine, kill)
        self._keep(keep)

    def _pack(self) -> np.ndarray:
        return _pack3(self._slots, self._ns, self._ts)

    def _keep(self, mask: np.ndarray) -> None:
        self._slots = self._slots[mask]
        self._ns = self._ns[mask]
        self._ts = self._ts[mask]

    def pop_due(self, up_to_inclusive: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Remove and return all unique timers with ts <= bound, sorted by
        (ts, slot) — the reference's queue-poll order."""
        if self._slots.size == 0:
            return (np.zeros(0, np.int64),) * 3
        due = self._ts <= up_to_inclusive
        if not due.any():
            return (np.zeros(0, np.int64),) * 3
        s, n, t = self._slots[due], self._ns[due], self._ts[due]
        self._keep(~due)
        # dedup (registration is idempotent in the reference)
        packed = _pack3(s, n, t)
        _, first = np.unique(packed, return_index=True)
        first = np.sort(first)
        s, n, t = s[first], n[first], t[first]
        order = np.lexsort((s, t))
        return s[order], n[order], t[order]

    def min_timestamp(self) -> Optional[int]:
        return int(self._ts.min()) if self._ts.size else None

    def snapshot(self) -> Dict[str, np.ndarray]:
        packed = self._pack()
        _, first = np.unique(packed, return_index=True)
        return {"slots": self._slots[first].copy(),
                "ns": self._ns[first].copy(),
                "ts": self._ts[first].copy()}

    def restore(self, snap: Dict[str, np.ndarray]) -> None:
        self._slots = np.asarray(snap["slots"], np.int64).copy()
        self._ns = np.asarray(snap["ns"], np.int64).copy()
        self._ts = np.asarray(snap["ts"], np.int64).copy()


def _pack3(a: np.ndarray, b: np.ndarray, c: np.ndarray) -> np.ndarray:
    """Row-wise identity of (slot, ns, ts) triples via a void view."""
    m = np.empty((a.size, 3), np.int64)
    m[:, 0], m[:, 1], m[:, 2] = a, b, c
    return np.ascontiguousarray(m).view([("", np.int64)] * 3).ravel()


class InternalTimerService:
    """Event + processing time timers for one keyed operator
    (``InternalTimerServiceImpl`` analog, snapshotted with operator state)."""

    def __init__(self):
        self.event_timers = _TimerTable()
        self.proc_timers = _TimerTable()
        self.current_watermark: int = LONG_MIN
        #: high-water processing time: the service is MONOTONE even when
        #: the driving clock is not (chaos ClockSkew / NTP step-back) —
        #: ``ProcessingTimeService`` contract.  A backward step can
        #: neither re-fire popped timers (they left the table) nor fire
        #: pending ones early; a forward jump fires everything due at once
        #: (no stuck timers).
        self.current_processing_time: int = LONG_MIN

    # -- registration (batched) ---------------------------------------------
    def register_event_time(self, slots, timestamps, namespaces=None) -> None:
        self.event_timers.register(slots, timestamps, namespaces)

    def register_processing_time(self, slots, timestamps, namespaces=None) -> None:
        self.proc_timers.register(slots, timestamps, namespaces)

    def delete_event_time(self, slots, timestamps, namespaces=None) -> None:
        self.event_timers.delete(slots, timestamps, namespaces)

    def delete_processing_time(self, slots, timestamps, namespaces=None) -> None:
        self.proc_timers.delete(slots, timestamps, namespaces)

    # -- advance -------------------------------------------------------------
    def advance_watermark(self, watermark: int):
        """Returns (slots, namespaces, timestamps) of event-time timers due at
        this watermark, in fire order (``advanceWatermark`` analog)."""
        self.current_watermark = watermark
        return self.event_timers.pop_due(watermark)

    def advance_processing_time(self, now_ms: int):
        self.current_processing_time = max(self.current_processing_time,
                                           now_ms)
        return self.proc_timers.pop_due(self.current_processing_time)

    def next_processing_time(self) -> Optional[int]:
        """Earliest pending processing-time timer (executor wakeup hint)."""
        return self.proc_timers.min_timestamp()

    # -- snapshot ------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {"event": self.event_timers.snapshot(),
                "proc": self.proc_timers.snapshot(),
                "watermark": self.current_watermark,
                "proc_time": self.current_processing_time}

    def restore(self, snap: Dict[str, Any]) -> None:
        self.event_timers.restore(snap["event"])
        self.proc_timers.restore(snap["proc"])
        self.current_watermark = int(snap.get("watermark", LONG_MIN))
        self.current_processing_time = int(snap.get("proc_time", LONG_MIN))
