"""Shuffle SPI: pluggable result-partition services for batch exchanges.

The reference decouples how task outputs reach consumers behind a shuffle
SPI (``ShuffleServiceFactory`` / ``ShuffleMaster`` /
``ShuffleEnvironment``, ``flink-runtime/.../shuffle/``), with two
first-party implementations: pipelined in-memory partitions for streaming
and the **sort-merge blocking partition** for batch
(``SortMergeResultPartition.java:65`` + ``PartitionSortedBuffer`` +
``PartitionedFileWriter``) — records are clustered by target subpartition
in a bounded memory buffer, spilled as sequential *regions* of one shared
data file, and served to consumers AFTER the producer finishes, so batch
consumers can start late, re-read after restarts, and never backpressure
the producer.

Same split here, TPU-host flavored:

- :class:`PipelinedShuffleService` — in-memory subpartition queues;
  consumers may read while the producer writes (the streaming default —
  the live job edges additionally ride the credit-based channels in
  ``cluster/channels.py``/``cluster/net.py``).
- :class:`SortMergeShuffleService` — the blocking batch service.  The
  writer appends batches into a byte-budgeted buffer keyed by
  subpartition; at budget it flushes one REGION: every subpartition's
  pending batches written contiguously (the "sort" is this clustering)
  to the single partition data file, with (offset, length) per
  subpartition recorded in the index.  ``finish()`` writes the index and
  atomically publishes a marker — only then is the partition readable.
  Readers stream their subpartition's byte ranges region by region
  (sequential IO per region), decode via the framework codec (CRC'd FTB
  blocks), and never hold more than one batch.  Partitions are plain
  files: they outlive the producer process, serve any number of
  consumers, and survive consumer restarts — the batch failover property
  blocking partitions exist for.

Service choice is configuration (``shuffle.service``), the SPI contract
is the three-method surface below, and ``register_shuffle_service``
admits third-party implementations — the pluggability the reference's
SPI provides.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import struct
import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch
from flink_tpu.native.codec import decode_batch, encode_batch


class ShuffleWriter:
    """Producer handle for one result partition."""

    def emit(self, subpartition: int, batch: RecordBatch) -> None:
        raise NotImplementedError

    def finish(self) -> None:
        """Seal the partition: after this, readers see the full data."""
        raise NotImplementedError

    def abort(self) -> None:
        """Discard everything written (producer failure)."""
        raise NotImplementedError


class ShuffleService:
    """SPI: how one task's partitioned output reaches consumer tasks."""

    #: True when readers must wait for the producer's finish() (batch
    #: blocking partitions); False when they may consume concurrently
    blocking: bool = False

    def create_partition(self, partition_id: str,
                         num_subpartitions: int) -> ShuffleWriter:
        raise NotImplementedError

    def open_reader(self, partition_id: str,
                    subpartition: int) -> Iterator[RecordBatch]:
        raise NotImplementedError

    def release_partition(self, partition_id: str) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# pipelined (in-memory) service
# ---------------------------------------------------------------------------


class _PipelinedPartition:
    def __init__(self, n: int):
        self.queues: List[List[RecordBatch]] = [[] for _ in range(n)]
        self.finished = False
        self.cond = threading.Condition()


class _PipelinedWriter(ShuffleWriter):
    def __init__(self, part: _PipelinedPartition):
        self._p = part

    def emit(self, subpartition: int, batch: RecordBatch) -> None:
        with self._p.cond:
            self._p.queues[subpartition].append(batch)
            self._p.cond.notify_all()

    def finish(self) -> None:
        with self._p.cond:
            self._p.finished = True
            self._p.cond.notify_all()

    def abort(self) -> None:
        with self._p.cond:
            self._p.queues = [[] for _ in self._p.queues]
            self._p.finished = True
            self._p.cond.notify_all()


class PipelinedShuffleService(ShuffleService):
    """In-memory subpartition queues; readers consume while the producer
    writes (streaming semantics)."""

    blocking = False

    def __init__(self):
        self._parts: Dict[str, _PipelinedPartition] = {}
        self._lock = threading.Lock()

    def create_partition(self, partition_id: str,
                         num_subpartitions: int) -> ShuffleWriter:
        with self._lock:
            if partition_id in self._parts:
                raise ValueError(f"partition {partition_id} already exists")
            part = self._parts[partition_id] = _PipelinedPartition(
                num_subpartitions)
        return _PipelinedWriter(part)

    def open_reader(self, partition_id: str,
                    subpartition: int) -> Iterator[RecordBatch]:
        with self._lock:
            part = self._parts[partition_id]
        i = 0
        while True:
            with part.cond:
                while len(part.queues[subpartition]) <= i \
                        and not part.finished:
                    part.cond.wait(timeout=10.0)
                if len(part.queues[subpartition]) <= i:
                    return
                batch = part.queues[subpartition][i]
            i += 1
            yield batch

    def release_partition(self, partition_id: str) -> None:
        with self._lock:
            self._parts.pop(partition_id, None)


# ---------------------------------------------------------------------------
# sort-merge blocking service
# ---------------------------------------------------------------------------

_FRAME = struct.Struct(">i")  # per-batch length prefix inside a region


class _SortMergeWriter(ShuffleWriter):
    """Byte-budgeted clustering buffer + region spiller
    (``PartitionSortedBuffer`` + ``PartitionedFileWriter`` analog)."""

    def __init__(self, service: "SortMergeShuffleService", pid: str,
                 n: int):
        self._svc = service
        self.pid = pid
        self.n = n
        self._pending: List[List[bytes]] = [[] for _ in range(n)]
        self._pending_bytes = 0
        self._regions: List[Dict[str, List[int]]] = []
        self._data = open(service._data_path(pid) + ".inprogress", "wb")
        self._done = False

    def emit(self, subpartition: int, batch: RecordBatch) -> None:
        if self._done:
            raise ValueError("writer is finished")
        if not 0 <= subpartition < self.n:
            raise IndexError(f"subpartition {subpartition} out of range")
        blob = encode_batch(batch)
        self._pending[subpartition].append(blob)
        self._pending_bytes += len(blob)
        if self._pending_bytes >= self._svc.memory_budget_bytes:
            self._flush_region()

    def _flush_region(self) -> None:
        if self._pending_bytes == 0:
            return
        offsets = [0] * self.n
        lengths = [0] * self.n
        counts = [0] * self.n
        for s in range(self.n):
            offsets[s] = self._data.tell()
            for blob in self._pending[s]:
                self._data.write(_FRAME.pack(len(blob)))
                self._data.write(blob)
            lengths[s] = self._data.tell() - offsets[s]
            counts[s] = len(self._pending[s])
            self._pending[s] = []
        self._pending_bytes = 0
        self._regions.append({"offsets": offsets, "lengths": lengths,
                              "counts": counts})

    def finish(self) -> None:
        if self._done:
            return
        self._flush_region()
        self._data.flush()
        os.fsync(self._data.fileno())
        self._data.close()
        self._done = True
        os.replace(self._svc._data_path(self.pid) + ".inprogress",
                   self._svc._data_path(self.pid))
        index = {"num_subpartitions": self.n, "regions": self._regions}
        tmp = self._svc._index_path(self.pid) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(index, f)
        # atomic publish: the index IS the finished marker
        os.replace(tmp, self._svc._index_path(self.pid))

    def abort(self) -> None:
        if not self._done:
            self._data.close()
            self._done = True
        for p in (self._svc._data_path(self.pid) + ".inprogress",
                  self._svc._data_path(self.pid),
                  self._svc._index_path(self.pid)):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass


class SortMergeShuffleService(ShuffleService):
    """Spilled, clustered, blocking result partitions
    (``SortMergeResultPartition.java:65`` analog).  Files under
    ``directory`` named by partition id; readable only once finished."""

    blocking = True

    def __init__(self, directory: str,
                 memory_budget_bytes: int = 32 << 20):
        self.directory = directory
        self.memory_budget_bytes = int(memory_budget_bytes)
        os.makedirs(directory, exist_ok=True)

    def _safe(self, pid: str) -> str:
        return re.sub(r"[^\w.-]", "_", pid)

    def _data_path(self, pid: str) -> str:
        return os.path.join(self.directory, self._safe(pid) + ".shuffle")

    def _index_path(self, pid: str) -> str:
        return os.path.join(self.directory, self._safe(pid) + ".index")

    def create_partition(self, partition_id: str,
                         num_subpartitions: int) -> ShuffleWriter:
        if os.path.exists(self._index_path(partition_id)):
            raise ValueError(f"partition {partition_id} already finished")
        return _SortMergeWriter(self, partition_id, num_subpartitions)

    def is_finished(self, partition_id: str) -> bool:
        return os.path.exists(self._index_path(partition_id))

    def open_reader(self, partition_id: str,
                    subpartition: int) -> Iterator[RecordBatch]:
        if not self.is_finished(partition_id):
            raise ValueError(
                f"blocking partition {partition_id} is not finished — "
                "consumers of a sort-merge partition start after the "
                "producer completes")
        with open(self._index_path(partition_id)) as f:
            index = json.load(f)
        if not 0 <= subpartition < index["num_subpartitions"]:
            raise IndexError(f"subpartition {subpartition} out of range")
        with open(self._data_path(partition_id), "rb") as data:
            for region in index["regions"]:
                data.seek(region["offsets"][subpartition])
                remaining = region["lengths"][subpartition]
                while remaining > 0:
                    (ln,) = _FRAME.unpack(data.read(_FRAME.size))
                    yield decode_batch(data.read(ln))
                    remaining -= _FRAME.size + ln

    def release_partition(self, partition_id: str) -> None:
        for p in (self._data_path(partition_id),
                  self._index_path(partition_id),
                  self._data_path(partition_id) + ".inprogress"):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass

    def release_all(self) -> None:
        shutil.rmtree(self.directory, ignore_errors=True)
        os.makedirs(self.directory, exist_ok=True)


# ---------------------------------------------------------------------------
# registry (the pluggable part of the SPI)
# ---------------------------------------------------------------------------

_FACTORIES: Dict[str, Callable[..., ShuffleService]] = {}


def register_shuffle_service(name: str,
                             factory: Callable[..., ShuffleService]) -> None:
    """Admit a service implementation under a ``shuffle.service`` name
    (``ShuffleServiceFactory`` discovery analog)."""
    _FACTORIES[name] = factory


register_shuffle_service("pipelined", lambda **kw: PipelinedShuffleService())
register_shuffle_service(
    "sort-merge",
    lambda directory=None, memory_budget_bytes=32 << 20, **kw:
        SortMergeShuffleService(
            directory or os.path.join(
                os.environ.get("TMPDIR", "/tmp"),
                f"flink-tpu-shuffle-{os.getpid()}"),
            memory_budget_bytes))


def shuffle_service_for(config=None, **overrides) -> ShuffleService:
    """Instantiate the configured service (``shuffle.service``; defaults
    to sort-merge for batch exchanges, matching the reference's batch
    default)."""
    from flink_tpu.config.options import ShuffleOptions

    name = overrides.pop("name", None)
    kw = dict(overrides)
    if config is not None:
        name = name or config.get(ShuffleOptions.SERVICE)
        kw.setdefault("directory", config.get(ShuffleOptions.DIRECTORY))
        kw.setdefault("memory_budget_bytes",
                      config.get(ShuffleOptions.MEMORY_BUDGET_BYTES))
    name = name or "sort-merge"
    if name not in _FACTORIES:
        raise ValueError(f"unknown shuffle.service {name!r}; registered: "
                         f"{sorted(_FACTORIES)}")
    kw = {k: v for k, v in kw.items() if v is not None}
    return _FACTORIES[name](**kw)


def hash_subpartition(key: np.ndarray, n: int) -> np.ndarray:
    """Record -> subpartition routing used by hash exchanges: the same
    murmur-based spread as the key-group formula (``hash_keys``) so batch
    and streaming route identically."""
    from flink_tpu.core.keygroups import hash_keys

    return (hash_keys(np.asarray(key)).astype(np.int64)
            % np.int64(n)).astype(np.int64)
