from flink_tpu.runtime.checkpoint.failure import (
    CheckpointFailureManager,
    CheckpointFailureReason,
)
from flink_tpu.runtime.checkpoint.storage import (
    CorruptCheckpointError,
    FileCheckpointStorage,
    InMemoryCheckpointStorage,
    RetryingCheckpointStorage,
    read_savepoint,
    write_savepoint,
)

__all__ = [
    "CheckpointFailureManager",
    "CheckpointFailureReason",
    "CorruptCheckpointError",
    "FileCheckpointStorage",
    "InMemoryCheckpointStorage",
    "RetryingCheckpointStorage",
    "read_savepoint",
    "write_savepoint",
]
