from flink_tpu.runtime.checkpoint.storage import (
    FileCheckpointStorage,
    InMemoryCheckpointStorage,
    read_savepoint,
    write_savepoint,
)

__all__ = [
    "FileCheckpointStorage",
    "InMemoryCheckpointStorage",
    "read_savepoint",
    "write_savepoint",
]
