"""Task-local state store: a worker-side SECONDARY copy of that worker's
own subtask snapshots.

Analog of ``TaskLocalStateStoreImpl``
(``flink-runtime/src/main/java/org/apache/flink/runtime/state/
TaskLocalStateStoreImpl.java:54``) and the
``flink-local-recovery-and-allocation-test`` e2e: every checkpoint ack ALSO
writes the snapshot to a worker-local directory; on a same-worker restart
the restore reads the local copy and touches the remote (primary)
checkpoint storage only for states the local store lacks — recovery cost
stops scaling with remote-storage bandwidth.

The primary store (``FileCheckpointStorage`` / object store) stays the
source of truth: local copies are best-effort (``confirm`` prunes
everything older than the last completed checkpoint; a missing or corrupt
local entry silently falls back to the shipped remote state).
"""

from __future__ import annotations

import os
import pickle
import re
import shutil
import urllib.parse
from typing import Any, Dict, List, Optional


class TaskLocalStateStore:
    """Per-worker local snapshot directory:
    ``<base>/worker-<idx>/chk-<cid>/<uid>-<subtask>.pkl``."""

    def __init__(self, base_dir: str, worker_index: int):
        self.dir = os.path.join(base_dir, f"worker-{worker_index}")
        os.makedirs(self.dir, exist_ok=True)

    def _chk_dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.dir, f"chk-{checkpoint_id}")

    def _path(self, checkpoint_id: int, uid: str, subtask: int) -> str:
        safe = urllib.parse.quote(uid, safe="")
        return os.path.join(self._chk_dir(checkpoint_id),
                            f"{safe}-{subtask}.pkl")

    def store(self, checkpoint_id: int, uid: str, subtask: int,
              snapshot: Dict[str, Any]) -> None:
        """Best-effort local write (never fails the checkpoint ack: the
        primary copy rides the ack to the coordinator regardless).

        Incremental checkpoints (ISSUE-16): an increment-bearing snapshot
        is stored RAW with a ``.delta`` marker next to it — ``load``
        resolves the chain by walking older local entries, and ``confirm``
        keeps every entry a live chain still reaches back to."""
        try:
            os.makedirs(self._chk_dir(checkpoint_id), exist_ok=True)
            path = self._path(checkpoint_id, uid, subtask)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(snapshot, f, protocol=pickle.HIGHEST_PROTOCOL)
            from flink_tpu.runtime.checkpoint import delta
            if delta.tree_has_increment(snapshot):
                with open(path + ".delta", "wb"):
                    pass
            else:
                # a full cut ends any previous chain under this name
                try:
                    os.unlink(path + ".delta")
                except OSError:
                    pass
            os.replace(tmp, path)
        except OSError:
            pass

    def _read(self, checkpoint_id: int, uid: str,
              subtask: int) -> Optional[Dict[str, Any]]:
        path = self._path(checkpoint_id, uid, subtask)
        try:
            with open(path, "rb") as f:
                return pickle.load(f)
        except (OSError, pickle.PickleError, EOFError):
            return None        # fall back to the remote copy

    def load(self, checkpoint_id: int, uid: str,
             subtask: int) -> Optional[Dict[str, Any]]:
        """The subtask's snapshot at ``checkpoint_id``, increment chains
        resolved against older local entries.  Any gap in the chain (a
        pruned, missing or unreadable link) returns None — the restore
        silently falls back to the coordinator-shipped remote state."""
        snap = self._read(checkpoint_id, uid, subtask)
        if snap is None:
            return None
        from flink_tpu.runtime.checkpoint import delta
        if not delta.tree_has_increment(snap):
            return snap
        chain = [snap]
        older = [i for i in self.checkpoint_ids() if i < checkpoint_id]
        while delta.tree_has_increment(chain[-1]):
            if not older:
                return None          # chain base pruned: remote fallback
            prev = self._read(older.pop(), uid, subtask)
            if prev is None:
                return None
            chain.append(prev)
        try:
            resolved = chain.pop()
            while chain:
                resolved = delta.apply_increments(resolved, chain.pop())
            return resolved
        except delta.IncrementChainError:
            return None

    def _chain_floor(self, checkpoint_id: int, ids: List[int]) -> int:
        """Oldest checkpoint id any of ``checkpoint_id``'s entries still
        chains back to (walks the cheap ``.delta`` markers, no unpickling);
        ``checkpoint_id`` itself when every entry is self-contained."""
        floor = checkpoint_id
        try:
            names = os.listdir(self._chk_dir(checkpoint_id))
        except OSError:
            return floor
        for name in names:
            if not name.endswith(".pkl"):
                continue
            cur = checkpoint_id
            while os.path.exists(os.path.join(self._chk_dir(cur), name)
                                 + ".delta"):
                prev = [i for i in ids if i < cur]
                if not prev:
                    break
                cur = max(prev)
            floor = min(floor, cur)
        return floor

    def confirm(self, checkpoint_id: int) -> None:
        """Checkpoint ``checkpoint_id`` completed: local copies no live
        increment chain reaches any more can never be restored from again
        — prune them (``TaskLocalStateStoreImpl.pruneCheckpoints``; with
        full snapshots the floor is simply ``checkpoint_id``)."""
        ids = self.checkpoint_ids()
        floor = (self._chain_floor(checkpoint_id, ids)
                 if checkpoint_id in ids else checkpoint_id)
        for cid in ids:
            if cid < floor:
                shutil.rmtree(self._chk_dir(cid), ignore_errors=True)

    def checkpoint_ids(self) -> List[int]:
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for n in names:
            m = re.fullmatch(r"chk-(\d+)", n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)
