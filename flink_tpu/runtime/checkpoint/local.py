"""Task-local state store: a worker-side SECONDARY copy of that worker's
own subtask snapshots.

Analog of ``TaskLocalStateStoreImpl``
(``flink-runtime/src/main/java/org/apache/flink/runtime/state/
TaskLocalStateStoreImpl.java:54``) and the
``flink-local-recovery-and-allocation-test`` e2e: every checkpoint ack ALSO
writes the snapshot to a worker-local directory; on a same-worker restart
the restore reads the local copy and touches the remote (primary)
checkpoint storage only for states the local store lacks — recovery cost
stops scaling with remote-storage bandwidth.

The primary store (``FileCheckpointStorage`` / object store) stays the
source of truth: local copies are best-effort (``confirm`` prunes
everything older than the last completed checkpoint; a missing or corrupt
local entry silently falls back to the shipped remote state).
"""

from __future__ import annotations

import os
import pickle
import re
import shutil
import urllib.parse
from typing import Any, Dict, List, Optional


class TaskLocalStateStore:
    """Per-worker local snapshot directory:
    ``<base>/worker-<idx>/chk-<cid>/<uid>-<subtask>.pkl``."""

    def __init__(self, base_dir: str, worker_index: int):
        self.dir = os.path.join(base_dir, f"worker-{worker_index}")
        os.makedirs(self.dir, exist_ok=True)

    def _chk_dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.dir, f"chk-{checkpoint_id}")

    def _path(self, checkpoint_id: int, uid: str, subtask: int) -> str:
        safe = urllib.parse.quote(uid, safe="")
        return os.path.join(self._chk_dir(checkpoint_id),
                            f"{safe}-{subtask}.pkl")

    def store(self, checkpoint_id: int, uid: str, subtask: int,
              snapshot: Dict[str, Any]) -> None:
        """Best-effort local write (never fails the checkpoint ack: the
        primary copy rides the ack to the coordinator regardless)."""
        try:
            os.makedirs(self._chk_dir(checkpoint_id), exist_ok=True)
            path = self._path(checkpoint_id, uid, subtask)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(snapshot, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        except OSError:
            pass

    def load(self, checkpoint_id: int, uid: str,
             subtask: int) -> Optional[Dict[str, Any]]:
        path = self._path(checkpoint_id, uid, subtask)
        try:
            with open(path, "rb") as f:
                return pickle.load(f)
        except (OSError, pickle.PickleError, EOFError):
            return None        # fall back to the remote copy

    def confirm(self, checkpoint_id: int) -> None:
        """Checkpoint ``checkpoint_id`` completed: local copies of OLDER
        checkpoints can never be restored from again — prune them
        (``TaskLocalStateStoreImpl.pruneCheckpoints``)."""
        for cid in self.checkpoint_ids():
            if cid < checkpoint_id:
                shutil.rmtree(self._chk_dir(cid), ignore_errors=True)

    def checkpoint_ids(self) -> List[int]:
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for n in names:
            m = re.fullmatch(r"chk-(\d+)", n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)
