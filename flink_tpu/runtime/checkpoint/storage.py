"""Checkpoint storage: durable snapshot persistence + metadata.

Analog of the reference's checkpoint storage stack
(``CheckpointStorageCoordinatorView`` / ``FsCheckpointStorageAccess`` +
versioned metadata ``runtime/checkpoint/Checkpoints.java`` and
``metadata/MetadataSerializer``): a checkpoint is a directory
``chk-{id}/`` holding one ``.npz`` per operator uid (numpy trees, pickled
object leaves for key dictionaries) plus ``_metadata.json`` (version, id,
uids, timestamp).  Savepoints are the same format at a user-chosen path —
rescalable and inspectable offline (state-processor analog reads them back).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.testing import chaos

METADATA_FILE = "_metadata.json"
FORMAT_VERSION = 1


class CorruptCheckpointError(ValueError):
    """A checkpoint on disk failed its integrity check (torn write,
    truncated file, checksum mismatch, unreadable metadata).  Retrying
    cannot help — recovery must fall back to an older checkpoint, which
    is exactly what ``load_latest`` does."""


class InMemoryCheckpointStorage:
    """Test/local storage (``MemoryStateBackend``-style): deep-copied trees."""

    def __init__(self, retain: int = 3):
        self.retain = retain
        self._store: Dict[int, Dict[str, Any]] = {}

    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        chaos.fire("checkpoint.store", checkpoint_id=checkpoint_id)
        self._store[checkpoint_id] = pickle.loads(pickle.dumps(snapshot))
        while len(self._store) > self.retain:
            del self._store[min(self._store)]

    def checkpoint_ids(self) -> List[int]:
        return sorted(self._store)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        chaos.fire("checkpoint.load", checkpoint_id=checkpoint_id)
        return pickle.loads(pickle.dumps(self._store[checkpoint_id]))

    def load_latest(self) -> Optional[Dict[str, Any]]:
        ids = self.checkpoint_ids()
        return self.load(ids[-1]) if ids else None


class FileCheckpointStorage:
    """Filesystem checkpoint storage (``FsStateBackend`` analog).

    Hardened commit protocol: operator files are written into a
    ``chk-N.inprogress`` staging dir with a CRC32 + size per file
    recorded in ``_metadata.json``, then published by one atomic
    ``os.replace``.  A crash mid-write leaves only an ignored staging dir;
    a torn file that survives anyway (lost data blocks after the rename)
    fails its checksum at ``load`` and is *skipped* by ``load_latest``,
    which falls back to the newest intact checkpoint.  ``fsync=True``
    additionally syncs every file before the publish for power-loss
    durability — off by default because it multiplies store latency and
    the checksum gate already catches whatever a crash tears."""

    def __init__(self, base_dir: str, retain: int = 3, fsync: bool = False):
        self.base_dir = base_dir
        self.retain = retain
        self.fsync = fsync
        #: coordinator HA (ISSUE-20): optional zero-arg callable returning
        #: a checkpoint id retention must NEVER evict (or None) — re-read
        #: FRESH at every cleanup pass, so the HA completed-checkpoint
        #: pointer stays restorable even when a stale leader's concurrent
        #: retention runs against the same directory
        self.pin_provider: Optional[Callable[[], Optional[int]]] = None
        os.makedirs(base_dir, exist_ok=True)

    def _dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.base_dir, f"chk-{checkpoint_id}")

    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        chaos.fire("checkpoint.store", checkpoint_id=checkpoint_id)
        d = self._dir(checkpoint_id)
        tmp = d + ".inprogress"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        uids = []
        for uid, op_snap in snapshot.items():
            fname = f"op-{len(uids)}.pkl"
            payload = pickle.dumps(_to_numpy(op_snap), protocol=4)
            uids.append({"uid": uid, "file": fname,
                         "crc32": zlib.crc32(payload), "size": len(payload)})
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(payload)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
        meta = {"version": FORMAT_VERSION, "checkpoint_id": checkpoint_id,
                "timestamp_ms": int(time.time() * 1000), "operators": uids}
        with open(os.path.join(tmp, METADATA_FILE), "w") as f:
            json.dump(meta, f, indent=2)
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        if self.fsync:
            # the rename is only durable once the directory entries are:
            # sync the staging dir's entries, then (below) the parent so
            # the publish itself survives power loss
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        if os.path.exists(d):
            shutil.rmtree(d)
        os.replace(tmp, d)  # atomic publish (reference: finalize + rename)
        if self.fsync:
            fd = os.open(self.base_dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        self._cleanup()

    def _cleanup(self):
        ids = self.checkpoint_ids()
        pinned = None
        if self.pin_provider is not None:
            try:
                pinned = self.pin_provider()
            except Exception:  # noqa: BLE001 — pin source unreadable:
                pinned = None  # fall back to plain retention
        for cid in ids[: max(0, len(ids) - self.retain)]:
            if pinned is not None and cid == pinned:
                continue
            shutil.rmtree(self._dir(cid), ignore_errors=True)

    def checkpoint_ids(self) -> List[int]:
        out = []
        for name in os.listdir(self.base_dir):
            # skip leftover chk-N.inprogress dirs from a crash mid-publish
            if not (name.startswith("chk-") and name[4:].isdigit()):
                continue
            if os.path.isfile(os.path.join(self.base_dir, name, METADATA_FILE)):
                out.append(int(name[4:]))
        return sorted(out)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        chaos.fire("checkpoint.load", checkpoint_id=checkpoint_id)
        d = self._dir(checkpoint_id)
        try:
            with open(os.path.join(d, METADATA_FILE)) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            raise CorruptCheckpointError(
                f"chk-{checkpoint_id}: unreadable metadata ({e})") from e
        if meta["version"] > FORMAT_VERSION:
            raise ValueError(f"checkpoint format {meta['version']} too new")
        out: Dict[str, Any] = {}
        for entry in meta["operators"]:
            try:
                with open(os.path.join(d, entry["file"]), "rb") as f:
                    payload = f.read()
            except OSError as e:
                raise CorruptCheckpointError(
                    f"chk-{checkpoint_id}/{entry['file']}: {e}") from e
            # integrity gate: size first (cheap torn-write detector), then
            # CRC32 — only checkpoints written before checksums existed
            # (no "crc32" key) skip verification
            if "size" in entry and len(payload) != entry["size"]:
                raise CorruptCheckpointError(
                    f"chk-{checkpoint_id}/{entry['file']}: torn write "
                    f"({len(payload)} bytes, expected {entry['size']})")
            if "crc32" in entry and zlib.crc32(payload) != entry["crc32"]:
                raise CorruptCheckpointError(
                    f"chk-{checkpoint_id}/{entry['file']}: checksum mismatch")
            try:
                out[entry["uid"]] = pickle.loads(payload)
            except Exception as e:  # noqa: BLE001 — any unpickle error
                raise CorruptCheckpointError(
                    f"chk-{checkpoint_id}/{entry['file']}: undecodable "
                    f"({e})") from e
        return out

    def load_latest(self) -> Optional[Dict[str, Any]]:
        """Newest INTACT checkpoint: corrupt/torn ones are skipped (never
        served), falling back to the next older id."""
        for cid in reversed(self.checkpoint_ids()):
            try:
                return self.load(cid)
            except CorruptCheckpointError:
                continue
        return None

    def metadata(self, checkpoint_id: int) -> Dict[str, Any]:
        with open(os.path.join(self._dir(checkpoint_id), METADATA_FILE)) as f:
            return json.load(f)


class RetryingCheckpointStorage:
    """Bounded-exponential-backoff retry wrapper around any storage backend
    (``RetryingExecutor`` / s3 retry-policy analog): transient store/load
    errors are retried up to ``max_attempts`` with
    ``initial_backoff_ms * multiplier^k`` sleeps capped at
    ``max_backoff_ms``.  :class:`CorruptCheckpointError` is NOT retried —
    a bad checksum never heals; ``load_latest`` already falls back.

    ``sleep`` is injectable so tests assert the backoff sequence without
    wall-clock waits."""

    def __init__(self, inner, max_attempts: int = 3,
                 initial_backoff_ms: int = 10, multiplier: float = 2.0,
                 max_backoff_ms: int = 1000,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.inner = inner
        self.max_attempts = max_attempts
        self.initial_backoff_ms = initial_backoff_ms
        self.multiplier = multiplier
        self.max_backoff_ms = max_backoff_ms
        self._sleep = sleep
        #: attempts beyond the first, across all operations (retry metric)
        self.retries = 0

    def _retry(self, fn: Callable, *args):
        backoff_ms = float(self.initial_backoff_ms)
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args)
            except CorruptCheckpointError:
                raise
            except Exception:
                if attempt >= self.max_attempts:
                    raise
                self.retries += 1
                self._sleep(min(backoff_ms, self.max_backoff_ms) / 1000.0)
                backoff_ms *= self.multiplier

    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        self._retry(self.inner.store, checkpoint_id, snapshot)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        return self._retry(self.inner.load, checkpoint_id)

    def load_latest(self) -> Optional[Dict[str, Any]]:
        return self._retry(self.inner.load_latest)

    def checkpoint_ids(self) -> List[int]:
        return self._retry(self.inner.checkpoint_ids)

    def __getattr__(self, name):
        # metadata() and backend-specific extras pass through un-retried
        return getattr(self.inner, name)


def _to_numpy(tree: Any) -> Any:
    """Device arrays -> host numpy throughout a snapshot tree."""
    if isinstance(tree, dict):
        return {k: _to_numpy(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        t = [_to_numpy(v) for v in tree]
        return tuple(t) if isinstance(tree, tuple) else t
    if hasattr(tree, "__array__") and not isinstance(tree, np.ndarray):
        return np.asarray(tree)
    return tree


def write_savepoint(path: str, snapshot: Dict[str, Any]) -> str:
    """User-triggered rescalable savepoint (``Savepoint`` analog)."""
    storage = FileCheckpointStorage(path, retain=1_000_000)
    sid = (max(storage.checkpoint_ids()) + 1) if storage.checkpoint_ids() else 1
    storage.store(sid, snapshot)
    return os.path.join(path, f"chk-{sid}")


def read_savepoint(path: str) -> Dict[str, Any]:
    """Load a savepoint directory written by ``write_savepoint`` (accepts the
    ``chk-N`` dir itself or its parent)."""
    if os.path.isfile(os.path.join(path, METADATA_FILE)):
        parent, name = os.path.split(path.rstrip("/"))
        return FileCheckpointStorage(parent).load(int(name[4:]))
    storage = FileCheckpointStorage(path)
    snap = storage.load_latest()
    if snap is None:
        raise FileNotFoundError(f"no checkpoints under {path}")
    return snap
