"""Checkpoint storage: durable snapshot persistence + metadata.

Analog of the reference's checkpoint storage stack
(``CheckpointStorageCoordinatorView`` / ``FsCheckpointStorageAccess`` +
versioned metadata ``runtime/checkpoint/Checkpoints.java`` and
``metadata/MetadataSerializer``): a checkpoint is a directory
``chk-{id}/`` holding one ``.npz`` per operator uid (numpy trees, pickled
object leaves for key dictionaries) plus ``_metadata.json`` (version, id,
uids, timestamp).  Savepoints are the same format at a user-chosen path —
rescalable and inspectable offline (state-processor analog reads them back).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional

import numpy as np

METADATA_FILE = "_metadata.json"
FORMAT_VERSION = 1


class InMemoryCheckpointStorage:
    """Test/local storage (``MemoryStateBackend``-style): deep-copied trees."""

    def __init__(self, retain: int = 3):
        self.retain = retain
        self._store: Dict[int, Dict[str, Any]] = {}

    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        self._store[checkpoint_id] = pickle.loads(pickle.dumps(snapshot))
        while len(self._store) > self.retain:
            del self._store[min(self._store)]

    def checkpoint_ids(self) -> List[int]:
        return sorted(self._store)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        return pickle.loads(pickle.dumps(self._store[checkpoint_id]))

    def load_latest(self) -> Optional[Dict[str, Any]]:
        ids = self.checkpoint_ids()
        return self.load(ids[-1]) if ids else None


class FileCheckpointStorage:
    """Filesystem checkpoint storage (``FsStateBackend`` analog)."""

    def __init__(self, base_dir: str, retain: int = 3):
        self.base_dir = base_dir
        self.retain = retain
        os.makedirs(base_dir, exist_ok=True)

    def _dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.base_dir, f"chk-{checkpoint_id}")

    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        d = self._dir(checkpoint_id)
        tmp = d + ".inprogress"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        uids = []
        for uid, op_snap in snapshot.items():
            fname = f"op-{len(uids)}.pkl"
            uids.append({"uid": uid, "file": fname})
            with open(os.path.join(tmp, fname), "wb") as f:
                pickle.dump(_to_numpy(op_snap), f, protocol=4)
        meta = {"version": FORMAT_VERSION, "checkpoint_id": checkpoint_id,
                "timestamp_ms": int(time.time() * 1000), "operators": uids}
        with open(os.path.join(tmp, METADATA_FILE), "w") as f:
            json.dump(meta, f, indent=2)
        if os.path.exists(d):
            shutil.rmtree(d)
        os.replace(tmp, d)  # atomic publish (reference: finalize + rename)
        self._cleanup()

    def _cleanup(self):
        ids = self.checkpoint_ids()
        for cid in ids[: max(0, len(ids) - self.retain)]:
            shutil.rmtree(self._dir(cid), ignore_errors=True)

    def checkpoint_ids(self) -> List[int]:
        out = []
        for name in os.listdir(self.base_dir):
            # skip leftover chk-N.inprogress dirs from a crash mid-publish
            if not (name.startswith("chk-") and name[4:].isdigit()):
                continue
            if os.path.isfile(os.path.join(self.base_dir, name, METADATA_FILE)):
                out.append(int(name[4:]))
        return sorted(out)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        d = self._dir(checkpoint_id)
        with open(os.path.join(d, METADATA_FILE)) as f:
            meta = json.load(f)
        if meta["version"] > FORMAT_VERSION:
            raise ValueError(f"checkpoint format {meta['version']} too new")
        out: Dict[str, Any] = {}
        for entry in meta["operators"]:
            with open(os.path.join(d, entry["file"]), "rb") as f:
                out[entry["uid"]] = pickle.load(f)
        return out

    def load_latest(self) -> Optional[Dict[str, Any]]:
        ids = self.checkpoint_ids()
        return self.load(ids[-1]) if ids else None

    def metadata(self, checkpoint_id: int) -> Dict[str, Any]:
        with open(os.path.join(self._dir(checkpoint_id), METADATA_FILE)) as f:
            return json.load(f)


def _to_numpy(tree: Any) -> Any:
    """Device arrays -> host numpy throughout a snapshot tree."""
    if isinstance(tree, dict):
        return {k: _to_numpy(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        t = [_to_numpy(v) for v in tree]
        return tuple(t) if isinstance(tree, tuple) else t
    if hasattr(tree, "__array__") and not isinstance(tree, np.ndarray):
        return np.asarray(tree)
    return tree


def write_savepoint(path: str, snapshot: Dict[str, Any]) -> str:
    """User-triggered rescalable savepoint (``Savepoint`` analog)."""
    storage = FileCheckpointStorage(path, retain=1_000_000)
    sid = (max(storage.checkpoint_ids()) + 1) if storage.checkpoint_ids() else 1
    storage.store(sid, snapshot)
    return os.path.join(path, f"chk-{sid}")


def read_savepoint(path: str) -> Dict[str, Any]:
    """Load a savepoint directory written by ``write_savepoint`` (accepts the
    ``chk-N`` dir itself or its parent)."""
    if os.path.isfile(os.path.join(path, METADATA_FILE)):
        parent, name = os.path.split(path.rstrip("/"))
        return FileCheckpointStorage(parent).load(int(name[4:]))
    storage = FileCheckpointStorage(path)
    snap = storage.load_latest()
    if snap is None:
        raise FileNotFoundError(f"no checkpoints under {path}")
    return snap
