"""Object-store checkpoint storage: snapshots behind an S3-shaped service.

The reference persists checkpoints to pluggable remote filesystems
(``flink-filesystems/flink-s3-fs-base``, ``FsCheckpointStorageAccess``);
this module provides the same seam against an HTTP object store — a
standalone :class:`ObjectStoreServer` process (``python -m flink_tpu
objectstore``) speaking a minimal S3-like protocol, and
:class:`ObjectStoreCheckpointStorage` implementing the exact storage
interface of ``FileCheckpointStorage`` (store/load/load_latest/
checkpoint_ids/metadata) over it.

Wire protocol:
  - ``PUT    /o/{key}``          store object (atomic: temp + rename)
  - ``GET    /o/{key}``          fetch object
  - ``GET    /list?prefix=P``    JSON list of keys
  - ``DELETE /o/{key}``          remove object

The server also exposes **TTL leases with fencing tokens** (the etcd-lease /
ZooKeeper-ephemeral-node analog, ``ZooKeeperLeaderElectionDriver``):
  - ``POST /lease/{name}/acquire``  body {holder, ttl_ms} ->
        {acquired, holder, token, expires_in_ms}; a lease is granted when
        free or expired; every new grant bumps the monotone fencing token
  - ``POST /lease/{name}/renew``    body {holder, token, ttl_ms}
  - ``POST /lease/{name}/release``  body {holder, token}
  - ``GET  /lease/{name}``          current state
Cross-HOST leader election (``cluster/ha.py`` LeaseLeaderElection) rides
these endpoints — any number of pods on any machines contend through one
object-store service, with fencing tokens guarding split-brain writers.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from flink_tpu.runtime.checkpoint.storage import (FORMAT_VERSION, _to_numpy)
from flink_tpu.testing import chaos


class ObjectStoreServer:
    """Minimal durable object store over HTTP (keys -> files on disk)."""

    def __init__(self, directory: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        #: lease table: name -> {holder, token, expires (monotonic)}
        self._leases: Dict[str, Dict[str, Any]] = {}
        self._lease_lock = threading.Lock()
        self._token_path = os.path.join(directory, "_lease_tokens.json")
        try:
            with open(self._token_path) as f:
                payload = json.load(f)
            self._next_token = int(payload["next"])
            #: per-election LAST granted token (persisted): fencing after a
            #: restart must compare against the election's own newest
            #: grant, not the shared counter
            self._last_grant: Dict[str, int] = {
                k: int(v) for k, v in payload.get("last", {}).items()}
        except (OSError, ValueError, KeyError):
            self._next_token = 1
            self._last_grant = {}
        store = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _path(self, key: str) -> str:
                safe = urllib.parse.quote(key, safe="")
                return os.path.join(store.directory, safe)

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 3 and parts[0] == "lease":
                    ln = int(self.headers.get("Content-Length", 0))
                    try:
                        req = json.loads(self.rfile.read(ln) or b"{}")
                    except ValueError:
                        return self._json(400, {"error": "bad json"})
                    name, verb = parts[1], parts[2]
                    if verb == "acquire":
                        return self._json(200, store.lease_acquire(
                            name, str(req.get("holder", "")),
                            int(req.get("ttl_ms", 10_000))))
                    if verb == "renew":
                        return self._json(200, store.lease_renew(
                            name, str(req.get("holder", "")),
                            int(req.get("token", -1)),
                            int(req.get("ttl_ms", 10_000))))
                    if verb == "release":
                        return self._json(200, store.lease_release(
                            name, str(req.get("holder", "")),
                            int(req.get("token", -1))))
                self._json(404, {"error": "not found"})

            def do_PUT(self):
                if not self.path.startswith("/o/"):
                    self.send_error(404)
                    return
                key = urllib.parse.unquote(self.path[3:])
                ln = int(self.headers["Content-Length"])
                data = self.rfile.read(ln)
                # fenced writes: a writer presenting a fencing token older
                # than the election's latest grant is a DEPOSED leader —
                # reject (the split-brain guard the lease tokens exist for)
                election = self.headers.get("X-Fencing-Election")
                if election is not None:
                    try:
                        tok = int(self.headers.get("X-Fencing-Token", -1))
                    except ValueError:
                        tok = -1
                    if not store.fencing_valid(election, tok):
                        return self._json(
                            412, {"error": "fencing token superseded",
                                  "election": election})
                path = self._path(key)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if len(parts) == 2 and parts[0] == "lease":
                    return self._json(200, store.lease_state(parts[1]))
                if self.path.startswith("/o/"):
                    key = urllib.parse.unquote(self.path[3:])
                    path = self._path(key)
                    if not os.path.exists(path):
                        self.send_error(404)
                        return
                    with open(path, "rb") as f:
                        data = f.read()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if self.path.startswith("/list"):
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    prefix = q.get("prefix", [""])[0]
                    keys = sorted(
                        urllib.parse.unquote(n)
                        for n in os.listdir(store.directory)
                        if not n.endswith(".tmp")
                        and urllib.parse.unquote(n).startswith(prefix))
                    body = json.dumps(keys).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_error(404)

            def do_DELETE(self):
                if not self.path.startswith("/o/"):
                    self.send_error(404)
                    return
                key = urllib.parse.unquote(self.path[3:])
                try:
                    os.remove(self._path(key))
                except FileNotFoundError:
                    pass
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="object-store", daemon=True)

    # -- lease primitives (single authority, like an etcd leader) ---------
    def lease_acquire(self, name: str, holder: str,
                      ttl_ms: int) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lease_lock:
            cur = self._leases.get(name)
            if cur is not None and cur["expires"] > now \
                    and cur["holder"] != holder:
                return {"acquired": False, "holder": cur["holder"],
                        "expires_in_ms": int((cur["expires"] - now) * 1000)}
            if cur is not None and cur["holder"] == holder \
                    and cur["expires"] > now:
                cur["expires"] = now + ttl_ms / 1000.0
                return {"acquired": True, "holder": holder,
                        "token": cur["token"], "expires_in_ms": ttl_ms}
            token = self._next_token
            self._next_token += 1
            self._last_grant[name] = token
            tmp = self._token_path + ".tmp"
            with open(tmp, "w") as f:  # tokens survive server restarts
                json.dump({"next": self._next_token,
                           "last": self._last_grant}, f)
            os.replace(tmp, self._token_path)
            self._leases[name] = {"holder": holder, "token": token,
                                  "expires": now + ttl_ms / 1000.0}
            return {"acquired": True, "holder": holder, "token": token,
                    "expires_in_ms": ttl_ms}

    def lease_renew(self, name: str, holder: str, token: int,
                    ttl_ms: int) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lease_lock:
            cur = self._leases.get(name)
            if cur is None or cur["holder"] != holder \
                    or cur["token"] != token or cur["expires"] <= now:
                return {"renewed": False}
            cur["expires"] = now + ttl_ms / 1000.0
            return {"renewed": True, "token": token}

    def lease_release(self, name: str, holder: str,
                      token: int) -> Dict[str, Any]:
        with self._lease_lock:
            cur = self._leases.get(name)
            if cur is not None and cur["holder"] == holder \
                    and cur["token"] == token:
                del self._leases[name]
                return {"released": True}
            return {"released": False}

    def fencing_valid(self, election: str, token: int) -> bool:
        """A presented token is valid unless a NEWER grant exists for the
        election (the write may proceed even if the lease lapsed, as long
        as nobody else was granted since — standard fencing semantics)."""
        with self._lease_lock:
            cur = self._leases.get(election)
            if cur is not None:
                return token >= cur["token"]
            # no live record (e.g. after a server restart): only THIS
            # election's latest historical grant can still be valid —
            # older ones are deposed by construction; elections that were
            # never granted reject everything (fail closed)
            last = self._last_grant.get(election)
            return last is not None and token == last

    def lease_state(self, name: str) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lease_lock:
            cur = self._leases.get(name)
            if cur is None or cur["expires"] <= now:
                return {"held": False}
            return {"held": True, "holder": cur["holder"],
                    "token": cur["token"],
                    "expires_in_ms": int((cur["expires"] - now) * 1000)}

    def start(self) -> "ObjectStoreServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def serve_forever(self) -> None:
        self._httpd.serve_forever()


class ObjectStoreClient:
    def __init__(self, url: str, timeout_s: float = 60.0):
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s

    def _req(self, method: str, path: str, body: Optional[bytes] = None,
             headers: Optional[Dict[str, str]] = None):
        req = urllib.request.Request(self.url + path, data=body,
                                     method=method, headers=headers or {})
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    def put(self, key: str, data: bytes,
            fencing: Optional[tuple] = None) -> None:
        """``fencing=(election, token)``: the server rejects the write with
        412 when a newer fencing token was granted for that election — a
        deposed leader cannot corrupt shared state."""
        headers = {}
        if fencing is not None:
            headers = {"X-Fencing-Election": str(fencing[0]),
                       "X-Fencing-Token": str(fencing[1])}
        self._req("PUT", "/o/" + urllib.parse.quote(key, safe=""),
                  data, headers).read()

    def get(self, key: str) -> bytes:
        with self._req("GET", "/o/" + urllib.parse.quote(key, safe="")) as r:
            return r.read()

    def list(self, prefix: str = "") -> List[str]:
        with self._req("GET", "/list?prefix="
                       + urllib.parse.quote(prefix)) as r:
            return json.loads(r.read())

    def delete(self, key: str) -> None:
        self._req("DELETE", "/o/"
                  + urllib.parse.quote(key, safe="")).read()


class ObjectStoreCheckpointStorage:
    """Checkpoint storage against the object store — same interface (and
    key layout) as ``FileCheckpointStorage``: ``{prefix}chk-{id}/op-{j}.pkl``
    objects plus a ``_metadata.json`` published LAST (readers only trust
    checkpoints whose metadata object exists — the atomic-rename analog)."""

    def __init__(self, url: str, prefix: str = "", retain: int = 3,
                 client=None):
        """``client``: any object with put/get/list/delete — the same
        layout+metadata protocol then runs over other stores (e.g. the S3
        dialect, ``filesystems/s3.py``)."""
        self.client = client if client is not None else ObjectStoreClient(url)
        self.prefix = prefix
        self.retain = retain

    def _meta_key(self, cid: int) -> str:
        return f"{self.prefix}chk-{cid}/_metadata.json"

    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        chaos.fire("checkpoint.store", checkpoint_id=checkpoint_id)
        uids = []
        for uid, op_snap in snapshot.items():
            fname = f"op-{len(uids)}.pkl"
            uids.append({"uid": uid, "file": fname})
            self.client.put(f"{self.prefix}chk-{checkpoint_id}/{fname}",
                            pickle.dumps(_to_numpy(op_snap), protocol=4))
        meta = {"version": FORMAT_VERSION, "checkpoint_id": checkpoint_id,
                "timestamp_ms": int(time.time() * 1000), "operators": uids}
        # metadata LAST: its presence publishes the checkpoint
        self.client.put(self._meta_key(checkpoint_id),
                        json.dumps(meta).encode())
        self._cleanup()

    def _cleanup(self) -> None:
        ids = self.checkpoint_ids()
        for cid in ids[: max(0, len(ids) - self.retain)]:
            for key in self.client.list(f"{self.prefix}chk-{cid}/"):
                self.client.delete(key)

    def checkpoint_ids(self) -> List[int]:
        out = []
        for key in self.client.list(self.prefix):
            tail = key[len(self.prefix):]
            if tail.endswith("/_metadata.json") and tail.startswith("chk-"):
                cid = tail[4:].split("/", 1)[0]
                if cid.isdigit():
                    out.append(int(cid))
        return sorted(out)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        chaos.fire("checkpoint.load", checkpoint_id=checkpoint_id)
        meta = json.loads(self.client.get(self._meta_key(checkpoint_id)))
        if meta["version"] > FORMAT_VERSION:
            raise ValueError(f"checkpoint format {meta['version']} too new")
        out: Dict[str, Any] = {}
        for entry in meta["operators"]:
            out[entry["uid"]] = pickle.loads(self.client.get(
                f"{self.prefix}chk-{checkpoint_id}/{entry['file']}"))
        return out

    def load_latest(self) -> Optional[Dict[str, Any]]:
        ids = self.checkpoint_ids()
        return self.load(ids[-1]) if ids else None

    def metadata(self, checkpoint_id: int) -> Dict[str, Any]:
        return json.loads(self.client.get(self._meta_key(checkpoint_id)))
