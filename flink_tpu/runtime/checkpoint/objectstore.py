"""Object-store checkpoint storage: snapshots behind an S3-shaped service.

The reference persists checkpoints to pluggable remote filesystems
(``flink-filesystems/flink-s3-fs-base``, ``FsCheckpointStorageAccess``);
this module provides the same seam against an HTTP object store — a
standalone :class:`ObjectStoreServer` process (``python -m flink_tpu
objectstore``) speaking a minimal S3-like protocol, and
:class:`ObjectStoreCheckpointStorage` implementing the exact storage
interface of ``FileCheckpointStorage`` (store/load/load_latest/
checkpoint_ids/metadata) over it.

Wire protocol:
  - ``PUT    /o/{key}``          store object (atomic: temp + rename)
  - ``GET    /o/{key}``          fetch object
  - ``GET    /list?prefix=P``    JSON list of keys
  - ``DELETE /o/{key}``          remove object
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from flink_tpu.runtime.checkpoint.storage import (FORMAT_VERSION, _to_numpy)


class ObjectStoreServer:
    """Minimal durable object store over HTTP (keys -> files on disk)."""

    def __init__(self, directory: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        store = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _path(self, key: str) -> str:
                safe = urllib.parse.quote(key, safe="")
                return os.path.join(store.directory, safe)

            def do_PUT(self):
                if not self.path.startswith("/o/"):
                    self.send_error(404)
                    return
                key = urllib.parse.unquote(self.path[3:])
                ln = int(self.headers["Content-Length"])
                data = self.rfile.read(ln)
                path = self._path(key)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                if self.path.startswith("/o/"):
                    key = urllib.parse.unquote(self.path[3:])
                    path = self._path(key)
                    if not os.path.exists(path):
                        self.send_error(404)
                        return
                    with open(path, "rb") as f:
                        data = f.read()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if self.path.startswith("/list"):
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    prefix = q.get("prefix", [""])[0]
                    keys = sorted(
                        urllib.parse.unquote(n)
                        for n in os.listdir(store.directory)
                        if not n.endswith(".tmp")
                        and urllib.parse.unquote(n).startswith(prefix))
                    body = json.dumps(keys).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_error(404)

            def do_DELETE(self):
                if not self.path.startswith("/o/"):
                    self.send_error(404)
                    return
                key = urllib.parse.unquote(self.path[3:])
                try:
                    os.remove(self._path(key))
                except FileNotFoundError:
                    pass
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="object-store", daemon=True)

    def start(self) -> "ObjectStoreServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def serve_forever(self) -> None:
        self._httpd.serve_forever()


class ObjectStoreClient:
    def __init__(self, url: str, timeout_s: float = 60.0):
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s

    def _req(self, method: str, path: str, body: Optional[bytes] = None):
        req = urllib.request.Request(self.url + path, data=body,
                                     method=method)
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    def put(self, key: str, data: bytes) -> None:
        self._req("PUT", "/o/" + urllib.parse.quote(key, safe=""),
                  data).read()

    def get(self, key: str) -> bytes:
        with self._req("GET", "/o/" + urllib.parse.quote(key, safe="")) as r:
            return r.read()

    def list(self, prefix: str = "") -> List[str]:
        with self._req("GET", "/list?prefix="
                       + urllib.parse.quote(prefix)) as r:
            return json.loads(r.read())

    def delete(self, key: str) -> None:
        self._req("DELETE", "/o/"
                  + urllib.parse.quote(key, safe="")).read()


class ObjectStoreCheckpointStorage:
    """Checkpoint storage against the object store — same interface (and
    key layout) as ``FileCheckpointStorage``: ``{prefix}chk-{id}/op-{j}.pkl``
    objects plus a ``_metadata.json`` published LAST (readers only trust
    checkpoints whose metadata object exists — the atomic-rename analog)."""

    def __init__(self, url: str, prefix: str = "", retain: int = 3):
        self.client = ObjectStoreClient(url)
        self.prefix = prefix
        self.retain = retain

    def _meta_key(self, cid: int) -> str:
        return f"{self.prefix}chk-{cid}/_metadata.json"

    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        uids = []
        for uid, op_snap in snapshot.items():
            fname = f"op-{len(uids)}.pkl"
            uids.append({"uid": uid, "file": fname})
            self.client.put(f"{self.prefix}chk-{checkpoint_id}/{fname}",
                            pickle.dumps(_to_numpy(op_snap), protocol=4))
        meta = {"version": FORMAT_VERSION, "checkpoint_id": checkpoint_id,
                "timestamp_ms": int(time.time() * 1000), "operators": uids}
        # metadata LAST: its presence publishes the checkpoint
        self.client.put(self._meta_key(checkpoint_id),
                        json.dumps(meta).encode())
        self._cleanup()

    def _cleanup(self) -> None:
        ids = self.checkpoint_ids()
        for cid in ids[: max(0, len(ids) - self.retain)]:
            for key in self.client.list(f"{self.prefix}chk-{cid}/"):
                self.client.delete(key)

    def checkpoint_ids(self) -> List[int]:
        out = []
        for key in self.client.list(self.prefix):
            tail = key[len(self.prefix):]
            if tail.endswith("/_metadata.json") and tail.startswith("chk-"):
                cid = tail[4:].split("/", 1)[0]
                if cid.isdigit():
                    out.append(int(cid))
        return sorted(out)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        meta = json.loads(self.client.get(self._meta_key(checkpoint_id)))
        if meta["version"] > FORMAT_VERSION:
            raise ValueError(f"checkpoint format {meta['version']} too new")
        out: Dict[str, Any] = {}
        for entry in meta["operators"]:
            out[entry["uid"]] = pickle.loads(self.client.get(
                f"{self.prefix}chk-{checkpoint_id}/{entry['file']}"))
        return out

    def load_latest(self) -> Optional[Dict[str, Any]]:
        ids = self.checkpoint_ids()
        return self.load(ids[-1]) if ids else None

    def metadata(self, checkpoint_id: int) -> Dict[str, Any]:
        return json.loads(self.client.get(self._meta_key(checkpoint_id)))
