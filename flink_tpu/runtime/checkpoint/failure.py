"""Checkpoint failure policy: tolerate-then-failover.

Analog of ``runtime/checkpoint/CheckpointFailureManager.java``: declined,
timed-out and storage-failed checkpoints increment a *continuous* failure
counter that resets on every successful checkpoint; once the counter
exceeds ``tolerable_failed_checkpoints``
(``execution.checkpointing.tolerable-failed-checkpoints``) the job fails
over through its restart strategy.  Pre-trigger declines ("busy", sources
already finished) are NOT counted, matching the reference's ignored
``CHECKPOINT_COORDINATOR_*`` reasons — only checkpoints that were actually
in flight count against the budget.
"""

from __future__ import annotations

from typing import Optional

from flink_tpu.metrics.core import Counter


class CheckpointFailureReason:
    """Counted failure reasons (``CheckpointFailureReason.java`` subset)."""

    DECLINED = "declined"            # a task declined (snapshot error)
    TIMEOUT = "expired"              # alignment/acks not done in time
    STORAGE = "storage"              # completed-checkpoint store failed


class CheckpointFailureManager:
    """Continuous-failure accounting + the failover decision.

    Thread-safety is the CALLER's: both runtimes invoke this under their
    coordinator lock, exactly like the reference calls it from the
    CheckpointCoordinator's timer/IO thread with coordinator-wide
    ordering."""

    UNLIMITED = -1

    def __init__(self, tolerable_failed_checkpoints: int = 0):
        if tolerable_failed_checkpoints < self.UNLIMITED:
            raise ValueError("tolerable_failed_checkpoints must be >= -1 "
                             f"(got {tolerable_failed_checkpoints})")
        self.tolerable = tolerable_failed_checkpoints
        self._continuous = 0
        #: lifetime counters (numberOfFailedCheckpoints /
        #: numberOfCompletedCheckpoints metric analogs)
        self.failed_counter = Counter()
        self.completed_counter = Counter()
        self.last_failure_reason: Optional[str] = None
        self.last_failure_checkpoint_id: Optional[int] = None

    # -- events ------------------------------------------------------------
    def on_checkpoint_success(self, checkpoint_id: int) -> None:
        self._continuous = 0
        self.completed_counter.inc()

    def on_checkpoint_failure(self, reason: str,
                              checkpoint_id: Optional[int] = None) -> bool:
        """Record one in-flight checkpoint failure; True = the tolerable
        budget is exhausted and the job must fail over."""
        self._continuous += 1
        self.failed_counter.inc()
        self.last_failure_reason = reason
        self.last_failure_checkpoint_id = checkpoint_id
        if self.tolerable == self.UNLIMITED:
            return False
        return self._continuous > self.tolerable

    def on_job_restart(self) -> None:
        """A failover wipes in-flight checkpoint attempts: the continuous
        window restarts with the new execution (lifetime counters keep
        accumulating for observability)."""
        self._continuous = 0

    # -- introspection -----------------------------------------------------
    @property
    def continuous_failures(self) -> int:
        return self._continuous

    def num_failed(self) -> int:
        return self.failed_counter.get_count()

    def num_completed(self) -> int:
        return self.completed_counter.get_count()

    def status(self) -> dict:
        """REST-facing summary (job_status() embeds this)."""
        return {
            "tolerable_failed_checkpoints": self.tolerable,
            "continuous_failed_checkpoints": self._continuous,
            "failed_checkpoints": self.num_failed(),
            "last_failure_reason": self.last_failure_reason,
            "last_failure_checkpoint_id": self.last_failure_checkpoint_id,
        }
