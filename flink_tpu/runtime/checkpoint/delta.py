"""Increment (delta) checkpoint nodes: schema, detection, resolution.

Analog of the reference's incremental checkpoint handles
(``IncrementalRemoteKeyedStateHandle``) + the FLIP-158 changelog handle
(``ChangelogStateBackendHandle``): an operator that tracked its own
mutations since the last *confirmed* checkpoint snapshots a small
self-describing **increment dict** instead of its full dense state.  A
restore resolves ``base + ordered increment replay`` back to the exact
full-snapshot tree — bit-identical, so everything downstream of restore
(redistribute/rescale, SavepointWriter, queryable replicas) keeps
consuming the dense gid-indexed interchange unchanged.

Increment nodes carry ABSOLUTE values (last-writer-wins): each dirty
cell/row ships its current contents, so replaying an increment that
covers a superset of the exact delta (operators ship the union of all
unconfirmed dirt — crash consistency) is harmless.

Two increment kinds:

``window_delta``
    WindowAggOperator pane-granular delta: dirty ``(gid, pane)`` cell
    rows + the append-only key-index tail + changed count/value
    baselines, against the dense ``{counts [n,m], leaves [n,m,...]}``
    layout.
``changelog``
    ChangelogKeyedStateBackend mutation-log suffix beyond the confirmed
    log position (same materialization epoch), plus overwritten extras
    (timers).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

#: marker key: a dict carrying it is an increment node, not full state
INCREMENT_KEY = "__increment__"


class IncrementChainError(RuntimeError):
    """An increment node has no base to apply against (broken chain)."""


def is_increment(node: Any) -> bool:
    return isinstance(node, dict) and node.get(INCREMENT_KEY) is not None


def tree_has_increment(tree: Any) -> bool:
    """True if any node anywhere in the snapshot tree is an increment."""
    if isinstance(tree, dict):
        if tree.get(INCREMENT_KEY) is not None:
            return True
        return any(tree_has_increment(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return any(tree_has_increment(v) for v in tree)
    return False


# --------------------------------------------------------------- resolution
def apply_increments(prev: Any, raw: Any) -> Any:
    """Resolve one raw checkpoint tree against the previous RESOLVED tree.

    Structural walk: increment nodes apply onto the node at the same path
    in ``prev`` (chains' ``op{i}`` nesting and subtask lists included);
    full nodes/leaves are taken from ``raw`` verbatim.  Returns a fully
    resolved tree; never mutates ``prev`` (appliers copy what they touch).
    """
    if is_increment(raw):
        kind = raw.get("kind")
        if kind == "window_delta":
            return apply_window_delta(prev, raw)
        if kind == "changelog":
            return apply_changelog(prev, raw)
        raise IncrementChainError(f"unknown increment kind {kind!r}")
    if isinstance(raw, dict):
        if not tree_has_increment(raw):
            return raw
        pd = prev if isinstance(prev, dict) else {}
        return {k: apply_increments(pd.get(k), v) for k, v in raw.items()}
    if isinstance(raw, (list, tuple)):
        if not tree_has_increment(raw):
            return raw
        pl = prev if isinstance(prev, (list, tuple)) else []
        out = [apply_increments(pl[i] if i < len(pl) else None, v)
               for i, v in enumerate(raw)]
        return tuple(out) if isinstance(raw, tuple) else out
    return raw


def resolve_chain(raws: List[Any]) -> Any:
    """Resolve an ordered chain ``[full base, inc_1, ..., inc_k]`` (ascending
    checkpoint order; the first element must be increment-free)."""
    if not raws:
        raise IncrementChainError("empty increment chain")
    if tree_has_increment(raws[0]):
        raise IncrementChainError(
            "increment chain does not start at a full base")
    resolved = raws[0]
    for raw in raws[1:]:
        resolved = apply_increments(resolved, raw)
    return resolved


# --------------------------------------------------------- window_delta apply
def _concat_reverse(prev_reverse: np.ndarray, tail: np.ndarray,
                    base_n: int, n: int) -> np.ndarray:
    prev_reverse = np.asarray(prev_reverse)
    if prev_reverse.shape[0] < base_n:
        raise IncrementChainError(
            f"key-index base too short: prev has {prev_reverse.shape[0]} "
            f"keys, increment expects >= {base_n}")
    tail = np.asarray(tail)
    if tail.shape[0] == 0:
        # avoid np.concatenate dtype promotion against an empty default-
        # dtype array (would corrupt int/object key arrays)
        out = prev_reverse[:base_n].copy()
    else:
        out = np.concatenate([prev_reverse[:base_n], tail])
    if out.shape[0] != n:
        raise IncrementChainError(
            f"key-index tail mismatch: resolved {out.shape[0]} keys, "
            f"increment says {n}")
    return out


def apply_window_delta(prev: Optional[Dict[str, Any]],
                       inc: Dict[str, Any]) -> Dict[str, Any]:
    """base + one WindowAggOperator pane-granular delta -> dense snapshot.

    The base may be a mesh per-shard-slice snapshot (increments bypass
    shard slicing); it is densified first so the result is always the
    dense gid-indexed interchange format.
    """
    if prev is None:
        raise IncrementChainError("window_delta increment without a base")
    from flink_tpu.state.shard_layout import densify_keyed_snapshot
    prev = densify_keyed_snapshot(prev)

    meta = inc["meta"]
    n = int(inc["n"])
    base_n = int(inc["base_n"])
    snap: Dict[str, Any] = dict(meta)   # pane_base/max_pane/... absolutes

    # -- key index: append-only reverse array + shipped tail
    if inc.get("key_tail") is not None or "key_index" in prev:
        tail = inc.get("key_tail")
        if tail is None:
            tail = np.asarray([])[:0]
        prev_rev = prev.get("key_index", {}).get(
            "reverse", np.asarray(tail)[:0])
        snap["key_index"] = {
            "reverse": _concat_reverse(prev_rev, tail, base_n, n)}
        snap["key_index_kind"] = inc["key_index_kind"]

    pane_base = meta["pane_base"]
    max_pane = meta["max_pane"]
    leaf_meta = inc["leaf_meta"]   # [(init ndarray, dtype str, trailing shape)]
    has_grid = inc.get("has_grid",
                       pane_base is not None and (n > 0 or inc["cells"]))
    if has_grid:
        panes = np.arange(pane_base, max_pane + 1, dtype=np.int64)
        m = panes.size
        counts = np.zeros((n, m), np.int32)
        leaves = []
        for init, dtype, trailing in leaf_meta:
            fill = np.broadcast_to(
                np.asarray(init, np.dtype(dtype)),
                (n, m) + tuple(trailing)).copy()
            leaves.append(fill)
        # copy the intersecting base columns (rows [0:base rows])
        prev_panes = np.asarray(prev.get("panes", np.asarray([], np.int64)),
                                np.int64)
        prev_counts = prev.get("counts")
        if prev_counts is not None and prev_panes.size:
            rows = min(int(prev_counts.shape[0]), n)
            prev_col = {int(p): j for j, p in enumerate(prev_panes.tolist())}
            prev_leaves = prev.get("leaves", [])
            for j, p in enumerate(panes.tolist()):
                pj = prev_col.get(int(p))
                if pj is None:
                    continue
                counts[:rows, j] = np.asarray(prev_counts)[:rows, pj]
                for dst, src in zip(leaves, prev_leaves):
                    dst[:rows, j] = np.asarray(src)[:rows, pj]
        # scatter the dirty cell rows (absolute values)
        col = {int(p): j for j, p in enumerate(panes.tolist())}
        for cell in inc["cells"]:
            j = col.get(int(cell["pane"]))
            if j is None:
                continue        # pane expired between marking and the cut
            gids = np.asarray(cell["gids"], np.int64)
            counts[gids, j] = cell["counts"]
            for dst, src in zip(leaves, cell["leaves"]):
                dst[gids, j] = src
        snap["panes"] = panes
        snap["counts"] = counts
        snap["leaves"] = leaves
        snap["leaf_schema"] = inc["leaf_schema"]
    if inc.get("paging_stats") is not None:
        snap["paging_stats"] = inc["paging_stats"]

    # -- count/value baselines: drop-then-set, unchanged carried from base
    cb = {w: np.asarray(b).copy()
          for w, b in prev.get("count_baselines", {}).items()}
    for w in inc.get("cb_drops", ()):
        cb.pop(w, None)
    cb.update(inc.get("count_baselines", {}))
    # pad carried-over baselines to n: the full-snapshot format pads them
    # to the key count, and restore digests must match it exactly
    for w, b in list(cb.items()):
        if b.shape[0] < n:
            grown = np.zeros(n, b.dtype)
            grown[:b.shape[0]] = b
            cb[w] = grown
        elif b.shape[0] > n:
            cb[w] = b[:n].copy()
    if cb:
        snap["count_baselines"] = cb
    vb = {w: [np.asarray(l).copy() for l in ls]
          for w, ls in prev.get("value_baselines", {}).items()}
    for w in inc.get("vb_drops", ()):
        vb.pop(w, None)
    vb.update(inc.get("value_baselines", {}))
    if vb:
        snap["value_baselines"] = vb
    return snap


# ----------------------------------------------------------- changelog apply
def apply_changelog(prev: Optional[Dict[str, Any]],
                    inc: Dict[str, Any]) -> Dict[str, Any]:
    """base + one changelog-suffix increment -> full backend snapshot.

    The previous resolved node holds the full mutation log up to its cut;
    the increment ships only the suffix beyond the confirmed position
    (same materialization epoch), so ``prev_log[:log_base] + suffix`` is
    exactly the backend's current log."""
    if prev is None:
        raise IncrementChainError("changelog increment without a base")
    log_base = int(inc["log_base"])
    prev_log = list(prev.get("changelog", []))
    if len(prev_log) < log_base:
        raise IncrementChainError(
            f"changelog base too short: prev has {len(prev_log)} entries, "
            f"increment resumes at {log_base}")
    snap = {k: v for k, v in prev.items()}
    snap["changelog"] = prev_log[:log_base] + list(inc["log_suffix"])
    snap["changelog_backend"] = True
    for k, v in inc.get("extras", {}).items():
        snap[k] = v
    return snap


# ------------------------------------------------------------------ sizing
def state_size(tree: Any) -> int:
    """Approximate byte size of a snapshot tree (array leaves dominate)."""
    if isinstance(tree, np.ndarray):
        return tree.nbytes
    if isinstance(tree, dict):
        return sum(state_size(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(state_size(v) for v in tree)
    if isinstance(tree, (bytes, bytearray, str)):
        return len(tree)
    return 8
