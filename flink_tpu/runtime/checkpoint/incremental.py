"""Incremental checkpoints: content-addressed shared state + refcounting.

Analog of the reference's incremental RocksDB checkpoints
(``RocksIncrementalSnapshotStrategy.java:83``: previously-uploaded SST files
are re-referenced, not re-uploaded) + ``SharedStateRegistry`` (refcounts
shared artifacts across retained checkpoints, deletes on last release).

Redesigned for array state: every large numpy leaf in a snapshot tree is
content-hashed; the blob is uploaded once into ``shared/`` and later
checkpoints that contain the identical array just reference the hash.  A
registry file tracks ``hash -> [checkpoint ids]``; retention eviction
releases references and deletes unreferenced blobs.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

METADATA_FILE = "_metadata.json"


@dataclass(frozen=True)
class BlobRef:
    """Placeholder for a deduplicated array leaf."""

    digest: str
    shape: Tuple[int, ...]
    dtype: str


class IncrementalCheckpointStorage:
    """Durable checkpoint storage with cross-checkpoint blob dedup."""

    def __init__(self, directory: str, retain: int = 3,
                 min_blob_bytes: int = 4096):
        self.directory = directory
        self.retain = retain
        self.min_blob_bytes = min_blob_bytes
        self.shared_dir = os.path.join(directory, "shared")
        os.makedirs(self.shared_dir, exist_ok=True)
        self._registry_path = os.path.join(directory, "_registry.json")
        self._registry: Dict[str, List[int]] = {}
        if os.path.exists(self._registry_path):
            with open(self._registry_path) as f:
                self._registry = {k: list(v) for k, v in json.load(f).items()}

    # -- tree walk -----------------------------------------------------------
    def _dedup(self, obj: Any, cid: int, new_blobs: Dict[str, np.ndarray]) -> Any:
        if isinstance(obj, np.ndarray) and obj.dtype != object and \
                obj.nbytes >= self.min_blob_bytes:
            arr = np.ascontiguousarray(obj)
            digest = hashlib.sha256(arr.tobytes()).hexdigest()[:32]
            if digest not in self._registry:
                new_blobs[digest] = arr
            self._registry.setdefault(digest, [])
            if cid not in self._registry[digest]:
                self._registry[digest].append(cid)
            return BlobRef(digest, tuple(arr.shape), arr.dtype.str)
        if isinstance(obj, dict):
            return {k: self._dedup(v, cid, new_blobs) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [self._dedup(v, cid, new_blobs) for v in obj]
            return type(obj)(out) if isinstance(obj, tuple) else out
        return obj

    def _resolve(self, obj: Any) -> Any:
        if isinstance(obj, BlobRef):
            path = os.path.join(self.shared_dir, obj.digest + ".blob")
            arr = np.fromfile(path, np.dtype(obj.dtype))
            return arr.reshape(obj.shape)
        if isinstance(obj, dict):
            return {k: self._resolve(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [self._resolve(v) for v in obj]
            return type(obj)(out) if isinstance(obj, tuple) else out
        return obj

    # -- storage interface ---------------------------------------------------
    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        new_blobs: Dict[str, np.ndarray] = {}
        deduped = self._dedup(snapshot, checkpoint_id, new_blobs)
        for digest, arr in new_blobs.items():
            tmp = os.path.join(self.shared_dir, f".{digest}.tmp")
            arr.tofile(tmp)
            os.replace(tmp, os.path.join(self.shared_dir, digest + ".blob"))
        cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
        os.makedirs(cdir, exist_ok=True)
        with open(os.path.join(cdir, "snapshot.pkl"), "wb") as f:
            pickle.dump(deduped, f, protocol=4)
        with open(os.path.join(cdir, METADATA_FILE), "w") as f:
            json.dump({"checkpoint_id": checkpoint_id,
                       "incremental": True,
                       "new_blobs": len(new_blobs),
                       "referenced_blobs": self._count_refs(deduped)}, f)
        self._save_registry()
        self._evict()

    def _count_refs(self, obj: Any) -> int:
        if isinstance(obj, BlobRef):
            return 1
        if isinstance(obj, dict):
            return sum(self._count_refs(v) for v in obj.values())
        if isinstance(obj, (list, tuple)):
            return sum(self._count_refs(v) for v in obj)
        return 0

    def checkpoint_ids(self) -> List[int]:
        ids = []
        for d in os.listdir(self.directory):
            if d.startswith("chk-"):
                try:
                    ids.append(int(d[4:]))
                except ValueError:
                    continue
        return sorted(ids)

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
        with open(os.path.join(cdir, "snapshot.pkl"), "rb") as f:
            return self._resolve(pickle.load(f))

    def load_latest(self) -> Optional[Dict[str, Any]]:
        ids = self.checkpoint_ids()
        return self.load(ids[-1]) if ids else None

    def metadata(self, checkpoint_id: int) -> Dict[str, Any]:
        cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
        with open(os.path.join(cdir, METADATA_FILE)) as f:
            return json.load(f)

    # -- retention / registry ------------------------------------------------
    def _evict(self) -> None:
        ids = self.checkpoint_ids()
        while len(ids) > self.retain:
            victim = ids.pop(0)
            self.release(victim)

    def release(self, checkpoint_id: int) -> None:
        """Drop a checkpoint and delete blobs nothing references anymore
        (``SharedStateRegistry.unregisterUnusedState`` analog)."""
        import shutil

        cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
        if os.path.isdir(cdir):
            shutil.rmtree(cdir)
        dead = []
        for digest, refs in self._registry.items():
            if checkpoint_id in refs:
                refs.remove(checkpoint_id)
            if not refs:
                dead.append(digest)
        for digest in dead:
            del self._registry[digest]
            path = os.path.join(self.shared_dir, digest + ".blob")
            if os.path.exists(path):
                os.remove(path)
        self._save_registry()

    def _save_registry(self) -> None:
        tmp = self._registry_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._registry, f)
        os.replace(tmp, self._registry_path)

    def shared_blob_count(self) -> int:
        return len([f for f in os.listdir(self.shared_dir)
                    if f.endswith(".blob")])
