"""Incremental checkpoints: content-addressed shared state + refcounting.

Analog of the reference's incremental RocksDB checkpoints
(``RocksIncrementalSnapshotStrategy.java:83``: previously-uploaded SST files
are re-referenced, not re-uploaded) + ``SharedStateRegistry`` (refcounts
shared artifacts across retained checkpoints, deletes on last release).

Redesigned for array state: every large numpy leaf in a snapshot tree is
content-hashed; the blob is uploaded once into ``shared/`` and later
checkpoints that contain the identical array just reference the hash.  A
registry file tracks ``hash -> [checkpoint ids]``; retention eviction
releases references and deletes unreferenced blobs.

This storage is additionally the durable format for **increment chains**
(``runtime/checkpoint/delta.py``): a stored tree may contain increment
nodes; ``load`` walks back to the newest increment-free base and resolves
``base + ordered increment replay`` before returning, so callers always
receive the dense full-snapshot interchange.  Retention never evicts a
checkpoint that a retained checkpoint's chain still walks through, and a
background compaction thread re-bases (rewrites the newest checkpoint
self-contained) once a chain grows past ``max_increments_per_base`` —
crash-safe by construction: the compacted pickle publishes by one atomic
rename; a crash mid-compaction leaves an ignored tmp file and the old
chain still resolves.

Crash-consistency hardening (parity with ``FileCheckpointStorage``):
``snapshot.pkl`` is staged + atomically renamed with its CRC32/size
recorded in ``_metadata.json`` (written last — ``checkpoint_ids`` ignores
half-written directories), blobs carry CRC32/size in their
:class:`BlobRef`, and every verification failure raises
:class:`CorruptCheckpointError` so ``load_latest`` (and the coordinators'
restart recovery) falls back to an older intact base.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import zlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.runtime.checkpoint import delta
from flink_tpu.runtime.checkpoint.storage import CorruptCheckpointError
from flink_tpu.testing import chaos

METADATA_FILE = "_metadata.json"


@dataclass(frozen=True)
class BlobRef:
    """Placeholder for a deduplicated array leaf.  ``crc32``/``nbytes``
    default to None so pickles written before the hardening still load
    (verification is skipped for them)."""

    digest: str
    shape: Tuple[int, ...]
    dtype: str
    crc32: Optional[int] = None
    nbytes: Optional[int] = None


class IncrementalCheckpointStorage:
    """Durable checkpoint storage with cross-checkpoint blob dedup and
    increment-chain resolution."""

    #: coordinators store RAW increment trees here (this storage resolves
    #: chains itself at load); plain storages receive pre-resolved trees
    supports_increments = True

    def __init__(self, directory: str, retain: int = 3,
                 min_blob_bytes: int = 4096,
                 max_increments_per_base: int = 8,
                 compact_in_background: bool = True):
        self.directory = directory
        self.retain = retain
        self.min_blob_bytes = min_blob_bytes
        self.max_increments_per_base = max_increments_per_base
        self.compact_in_background = compact_in_background
        self.shared_dir = os.path.join(directory, "shared")
        os.makedirs(self.shared_dir, exist_ok=True)
        self._registry_path = os.path.join(directory, "_registry.json")
        self._registry: Dict[str, List[int]] = {}
        self._lock = threading.RLock()
        self._compact_thread: Optional[threading.Thread] = None
        #: compactions performed (observability + tests)
        self.compactions = 0
        #: coordinator HA (ISSUE-20): optional zero-arg callable returning
        #: a checkpoint id retention must never evict (or None).  Re-read
        #: FRESH per eviction pass, and the pinned cut's WHOLE increment
        #: chain is kept — the HA completed-checkpoint pointer stays
        #: restorable even under a stale leader's concurrent retention.
        self.pin_provider = None
        if os.path.exists(self._registry_path):
            with open(self._registry_path) as f:
                self._registry = {k: list(v) for k, v in json.load(f).items()}

    # -- tree walk -----------------------------------------------------------
    def _dedup(self, obj: Any, cid: int, new_blobs: Dict[str, np.ndarray]) -> Any:
        if isinstance(obj, np.ndarray) and obj.dtype != object and \
                obj.nbytes >= self.min_blob_bytes:
            arr = np.ascontiguousarray(obj)
            payload = arr.tobytes()
            digest = hashlib.sha256(payload).hexdigest()[:32]
            if digest not in self._registry:
                new_blobs[digest] = arr
            self._registry.setdefault(digest, [])
            if cid not in self._registry[digest]:
                self._registry[digest].append(cid)
            return BlobRef(digest, tuple(arr.shape), arr.dtype.str,
                           zlib.crc32(payload), arr.nbytes)
        if isinstance(obj, dict):
            return {k: self._dedup(v, cid, new_blobs) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [self._dedup(v, cid, new_blobs) for v in obj]
            return type(obj)(out) if isinstance(obj, tuple) else out
        return obj

    def _resolve(self, obj: Any) -> Any:
        if isinstance(obj, BlobRef):
            path = os.path.join(self.shared_dir, obj.digest + ".blob")
            try:
                payload = open(path, "rb").read()
            except OSError as e:
                raise CorruptCheckpointError(
                    f"missing shared blob {obj.digest}: {e}") from e
            if obj.nbytes is not None and len(payload) != obj.nbytes:
                raise CorruptCheckpointError(
                    f"shared blob {obj.digest} is {len(payload)} bytes, "
                    f"expected {obj.nbytes} (torn write)")
            if obj.crc32 is not None and zlib.crc32(payload) != obj.crc32:
                raise CorruptCheckpointError(
                    f"shared blob {obj.digest} failed CRC32 verification")
            arr = np.frombuffer(payload, np.dtype(obj.dtype))
            return arr.reshape(obj.shape).copy()
        if isinstance(obj, dict):
            return {k: self._resolve(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [self._resolve(v) for v in obj]
            return type(obj)(out) if isinstance(obj, tuple) else out
        return obj

    # -- storage interface ---------------------------------------------------
    def store(self, checkpoint_id: int, snapshot: Dict[str, Any]) -> None:
        chaos.fire("checkpoint.store", checkpoint_id=checkpoint_id)
        has_delta = delta.tree_has_increment(snapshot)
        with self._lock:
            new_blobs: Dict[str, np.ndarray] = {}
            deduped = self._dedup(snapshot, checkpoint_id, new_blobs)
            for digest, arr in new_blobs.items():
                tmp = os.path.join(self.shared_dir, f".{digest}.tmp")
                arr.tofile(tmp)
                os.replace(tmp, os.path.join(self.shared_dir,
                                             digest + ".blob"))
            cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
            os.makedirs(cdir, exist_ok=True)
            payload = pickle.dumps(deduped, protocol=4)
            keep = len(payload)
            if has_delta:
                # fault point on the increment-append write: a TruncatedWrite
                # schedule tears the published record short (post-rename data
                # loss); the CRC gate below catches it at load and recovery
                # falls back past the torn increment to an older base
                keep = chaos.truncated("checkpoint.increment_append",
                                       len(payload),
                                       checkpoint_id=checkpoint_id)
            tmp = os.path.join(cdir, ".snapshot.pkl.tmp")
            with open(tmp, "wb") as f:
                f.write(payload[:keep])
            os.replace(tmp, os.path.join(cdir, "snapshot.pkl"))
            meta = {"checkpoint_id": checkpoint_id,
                    "incremental": True,
                    "delta": has_delta,
                    "new_blobs": len(new_blobs),
                    "referenced_blobs": self._count_refs(deduped),
                    "snapshot_crc32": zlib.crc32(payload),
                    "snapshot_size": len(payload)}
            mtmp = os.path.join(cdir, "." + METADATA_FILE + ".tmp")
            with open(mtmp, "w") as f:
                json.dump(meta, f)
            os.replace(mtmp, os.path.join(cdir, METADATA_FILE))
            self._save_registry()
            self._evict()
        self._maybe_compact(checkpoint_id)

    def _count_refs(self, obj: Any) -> int:
        if isinstance(obj, BlobRef):
            return 1
        if isinstance(obj, dict):
            return sum(self._count_refs(v) for v in obj.values())
        if isinstance(obj, (list, tuple)):
            return sum(self._count_refs(v) for v in obj)
        return 0

    def checkpoint_ids(self) -> List[int]:
        ids = []
        for d in os.listdir(self.directory):
            if not d.startswith("chk-"):
                continue
            # half-written directories (crash between snapshot.pkl and the
            # metadata publish) are invisible: metadata is written LAST
            if not os.path.exists(os.path.join(self.directory, d,
                                               METADATA_FILE)):
                continue
            try:
                ids.append(int(d[4:]))
            except ValueError:
                continue
        return sorted(ids)

    def _load_raw(self, checkpoint_id: int) -> Dict[str, Any]:
        """One checkpoint's stored tree, blob-resolved and verified but NOT
        increment-resolved (may contain increment nodes)."""
        cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
        spath = os.path.join(cdir, "snapshot.pkl")
        try:
            payload = open(spath, "rb").read()
        except OSError as e:
            raise CorruptCheckpointError(
                f"checkpoint {checkpoint_id}: unreadable snapshot.pkl: "
                f"{e}") from e
        meta = self.metadata(checkpoint_id)
        if "snapshot_size" in meta and len(payload) != meta["snapshot_size"]:
            raise CorruptCheckpointError(
                f"checkpoint {checkpoint_id}: snapshot.pkl is "
                f"{len(payload)} bytes, expected {meta['snapshot_size']} "
                f"(torn write)")
        if "snapshot_crc32" in meta and \
                zlib.crc32(payload) != meta["snapshot_crc32"]:
            raise CorruptCheckpointError(
                f"checkpoint {checkpoint_id}: snapshot.pkl failed CRC32 "
                f"verification")
        try:
            tree = pickle.loads(payload)
        except Exception as e:  # noqa: BLE001 — any unpickle error = corrupt
            raise CorruptCheckpointError(
                f"checkpoint {checkpoint_id}: undecodable snapshot.pkl: "
                f"{type(e).__name__}: {e}") from e
        return self._resolve(tree)

    def _chain_ids(self, checkpoint_id: int,
                   ids: Optional[List[int]] = None) -> List[int]:
        """The stored checkpoint ids whose increments resolve
        ``checkpoint_id``, ascending — every stored id from the newest
        increment-free base up to and including ``checkpoint_id`` (each
        may carry dirt the next increment's union no longer re-ships)."""
        if ids is None:
            ids = self.checkpoint_ids()
        if checkpoint_id not in ids:
            raise CorruptCheckpointError(
                f"checkpoint {checkpoint_id} not stored")
        chain = []
        for cid in sorted((i for i in ids if i <= checkpoint_id),
                          reverse=True):
            chain.append(cid)
            if not self._is_delta(cid):
                return list(reversed(chain))
        raise CorruptCheckpointError(
            f"checkpoint {checkpoint_id}: no increment-free base retained "
            f"below it")

    def _is_delta(self, checkpoint_id: int) -> bool:
        try:
            return bool(self.metadata(checkpoint_id).get("delta"))
        except (OSError, ValueError):
            return False

    def load(self, checkpoint_id: int) -> Dict[str, Any]:
        chaos.fire("checkpoint.load", checkpoint_id=checkpoint_id)
        with self._lock:
            raws = [self._load_raw(cid)
                    for cid in self._chain_ids(checkpoint_id)]
        try:
            return delta.resolve_chain(raws)
        except delta.IncrementChainError as e:
            raise CorruptCheckpointError(
                f"checkpoint {checkpoint_id}: broken increment chain: "
                f"{e}") from e

    def load_latest(self) -> Optional[Dict[str, Any]]:
        """Newest restorable checkpoint: a corrupt snapshot/blob/increment
        anywhere in the newest chain falls back to the next-older
        checkpoint whose chain is intact."""
        for cid in sorted(self.checkpoint_ids(), reverse=True):
            try:
                return self.load(cid)
            except CorruptCheckpointError:
                continue
        return None

    def metadata(self, checkpoint_id: int) -> Dict[str, Any]:
        cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
        with open(os.path.join(cdir, METADATA_FILE)) as f:
            return json.load(f)

    def chain_length(self, checkpoint_id: int) -> int:
        """Number of stored checkpoints (base included) resolving this one."""
        with self._lock:
            return len(self._chain_ids(checkpoint_id))

    # -- compaction ----------------------------------------------------------
    def _maybe_compact(self, checkpoint_id: int) -> None:
        """Re-base once the newest chain outgrows ``max_increments_per_base``:
        rewrite ``checkpoint_id`` self-contained (resolved tree, deduped
        against the registry) so restores stop replaying long chains and
        retention can release the old bases.  Runs on a daemon thread by
        default — never on the ack/store path's critical section."""
        with self._lock:
            try:
                if not self._is_delta(checkpoint_id) or \
                        len(self._chain_ids(checkpoint_id)) - 1 \
                        <= self.max_increments_per_base:
                    return
            except CorruptCheckpointError:
                return
        if not self.compact_in_background:
            self._compact(checkpoint_id)
            return
        t = threading.Thread(target=self._compact, args=(checkpoint_id,),
                             daemon=True, name=f"chk-compact-{checkpoint_id}")
        with self._lock:
            self._compact_thread = t
        t.start()

    def _compact(self, checkpoint_id: int) -> None:
        try:
            resolved = self.load(checkpoint_id)
            chaos.fire("checkpoint.compact", checkpoint_id=checkpoint_id)
            with self._lock:
                if checkpoint_id not in self.checkpoint_ids():
                    return                      # evicted while resolving
                new_blobs: Dict[str, np.ndarray] = {}
                deduped = self._dedup(resolved, checkpoint_id, new_blobs)
                for digest, arr in new_blobs.items():
                    tmp = os.path.join(self.shared_dir, f".{digest}.tmp")
                    arr.tofile(tmp)
                    os.replace(tmp, os.path.join(self.shared_dir,
                                                 digest + ".blob"))
                cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
                payload = pickle.dumps(deduped, protocol=4)
                tmp = os.path.join(cdir, ".snapshot.pkl.tmp")
                with open(tmp, "wb") as f:
                    f.write(payload)
                meta = self.metadata(checkpoint_id)
                meta.update({"delta": False, "compacted": True,
                             "snapshot_crc32": zlib.crc32(payload),
                             "snapshot_size": len(payload),
                             "referenced_blobs": self._count_refs(deduped)})
                # pickle first, metadata second — a crash between the two
                # leaves a self-contained pickle whose metadata still says
                # "delta": resolution walks one chain link too many, which
                # is harmless (absolute values, full tree overwrites)
                os.replace(tmp, os.path.join(cdir, "snapshot.pkl"))
                mtmp = os.path.join(cdir, "." + METADATA_FILE + ".tmp")
                with open(mtmp, "w") as f:
                    json.dump(meta, f)
                os.replace(mtmp, os.path.join(cdir, METADATA_FILE))
                self._save_registry()
                self.compactions += 1
                self._evict()   # old bases may now be releasable
        except (CorruptCheckpointError, chaos.InjectedFault, OSError):
            # compaction is best-effort: a crash/fault mid-compaction leaves
            # the old chain fully intact (tmp files are ignored) — restore
            # still resolves base + replay
            return

    def wait_for_compaction(self, timeout: float = 30.0) -> None:
        """Join any in-flight background compaction (tests/benchmarks)."""
        with self._lock:
            t = self._compact_thread
        if t is not None:
            t.join(timeout)

    # -- retention / registry ------------------------------------------------
    def _needed_ids(self, ids: List[int]) -> set:
        """Checkpoints retention must keep: the newest ``retain`` heads
        plus every chain member a retained head still resolves through —
        and the HA-pinned cut's whole chain, when a pin provider is set."""
        heads = list(ids[-self.retain:]) if self.retain else []
        if self.pin_provider is not None:
            try:
                pinned = self.pin_provider()
            except Exception:  # noqa: BLE001 — pin source unreadable
                pinned = None
            if pinned is not None and pinned in ids and pinned not in heads:
                heads.append(pinned)
        needed = set()
        for head in heads:
            try:
                needed.update(self._chain_ids(head, ids))
            except CorruptCheckpointError:
                needed.add(head)
        return needed

    def _evict(self) -> None:
        ids = self.checkpoint_ids()
        if len(ids) <= self.retain:
            return
        needed = self._needed_ids(ids)
        for victim in ids:
            if len(self.checkpoint_ids()) <= self.retain:
                break
            if victim in needed:
                continue
            self.release(victim)

    def release(self, checkpoint_id: int) -> None:
        """Drop a checkpoint and delete blobs nothing references anymore
        (``SharedStateRegistry.unregisterUnusedState`` analog)."""
        import shutil

        with self._lock:
            cdir = os.path.join(self.directory, f"chk-{checkpoint_id}")
            if os.path.isdir(cdir):
                shutil.rmtree(cdir)
            dead = []
            for digest, refs in self._registry.items():
                if checkpoint_id in refs:
                    refs.remove(checkpoint_id)
                if not refs:
                    dead.append(digest)
            for digest in dead:
                del self._registry[digest]
                path = os.path.join(self.shared_dir, digest + ".blob")
                if os.path.exists(path):
                    os.remove(path)
            self._save_registry()

    def _save_registry(self) -> None:
        tmp = self._registry_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._registry, f)
        os.replace(tmp, self._registry_path)

    def shared_blob_count(self) -> int:
        return len([f for f in os.listdir(self.shared_dir)
                    if f.endswith(".blob")])
